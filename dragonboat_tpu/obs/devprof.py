"""Device capacity & profiling plane (ISSUE 15 tentpole).

The obs stack attributes latency end-to-end on the host (``trace.py``),
across hosts (``replattr.py``) and over time (``health.py``) — but the
device plane that does the actual work was a black box: nobody could
answer "how many HBM bytes does a G=100k coordinator hold", "what does
each warmed fused program cost", or "how much of a dispatch's wall is
device execution vs host dispatch overhead".  ROADMAP items 2 and 3
(devsm scale-out past ``n_kv_ents``, 1M+ groups sharded across a mesh)
are capacity-planning problems that start from exactly this ledger.
Four pillars:

- **HBM memory ledger** (:meth:`DevProf.hbm_ledger`): walks the
  engine's resident state — the ``ops/state.py`` quorum tensors, the
  pending-read ctx slots, the devsm ``kv_value``/``kv_ent_*`` slabs and
  the in-flight pipelined dispatch's egress accumulators (the
  staged-round double buffer) — and publishes
  ``dragonboat_devprof_hbm_bytes{plane,artifact}`` gauges.  Every
  artifact is priced from the live arrays' own ``nbytes`` (pure
  metadata, no transfer), so the ledger can never drift from what is
  actually allocated.

- **Capacity model** (:func:`predict_bytes` /
  :meth:`DevProf.capacity_model`): extrapolates resident bytes for any
  ``(G, P, S, V, E)`` geometry from a ``jax.eval_shape`` walk of the
  SAME ``make_state`` constructor the engine allocates through (a new
  state field can't escape the model), plus the per-dispatch transient
  upload term at a given fused K bucket (mirroring
  ``engine.upload_nbytes`` over the fused argument tuple).  Asserted
  against actually-allocated bytes (tests/bench: within 10%) and
  against ``device.memory_stats()`` where the backend provides one —
  the sizing input for ROADMAP items 2/3.

- **Program registry** (:meth:`DevProf.collect_programs`): walks the
  warm set (``BatchedQuorumEngine.warm_plan`` — K buckets × reads ×
  votes × kv variants, the same enumeration ``warmup_fused`` /
  ``warmup_devsm`` compile) and records each program's
  ``lower().compile().cost_analysis()`` / ``memory_analysis()`` —
  flops, bytes accessed, peak temp allocation, compile wall (cache-hot
  compiles deserialize via the persistent compilation cache).  Rendered
  as the perf ledger's "Device programs" table.

- **Device-time estimator** (:meth:`DevProf.note_dispatch`, called from
  the engine's dispatch sites behind the ``_devprof is not None``
  latch): 1-in-N dispatches measure a post-launch
  ``block_until_ready`` delta — the device-execution estimate the
  FlightRecorder's host walls (``dispatch_ms``/``egress_ms``) do not
  separate — feeding the ``dragonboat_devprof_device_ms`` histogram, a
  duty-cycle gauge, and fused **padding-waste** accounting (padded
  program K minus live/ticked rounds is provable no-op device work).
  The sampled delta is also stamped onto the dispatch's recorder span
  as ``device_ms``.

On-demand ``jax.profiler`` capture windows
(:meth:`DevProf.capture` ← ``NodeHost.profile_device``) land their
artifacts beside the ``dump_trace``/``debug_dump`` outputs (the node
host dir), and the read-only ``/debug/devprof`` handler on the existing
MetricsServer serves :meth:`DevProf.to_json` so trace sessions and
device profiles are collected from one place.

Overhead contract (the ``_obs is not None`` latch precedent): OFF by
default.  ``NodeHostConfig.device_profile = 0`` constructs nothing —
the engine keeps ``_devprof = None`` and a bit-identical host path —
and with the plane on, per-dispatch cost is a few counter bumps under
one micro-lock; the sampled ``block_until_ready`` runs 1-in-N
(``sample_every``, default 16) and is priced by the bench devprof axis
(<5% + 2·SEM asserted).
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..logger import get_logger
from ..ops.state import field_plane, state_layout
# nearest-rank percentile, shared with the health plane (one
# implementation — divergent copies would make device_ms percentiles
# incomparable with the health plane's latency percentiles)
from .health import _pctile

plog = get_logger("devprof")

#: device-time sampling stride (1-in-N dispatches pay a blocking
#: block_until_ready); NodeHostConfig.device_profile overrides
DEFAULT_SAMPLE_EVERY = 16

#: HBM-ledger gauge refresh cadence (rides the sampling tick — the walk
#: is pure array metadata, but republishing ~30 gauges per dispatch
#: would be registry traffic for nothing)
LEDGER_REFRESH_S = 1.0

#: bounded device-time sample window for the estimator percentiles
_SAMPLE_WINDOW = 512


def predict_bytes(
    n_groups: int,
    n_peers: int,
    n_read_slots: Optional[int] = None,
    n_kv_slots: Optional[int] = None,
    n_kv_ents: Optional[int] = None,
    n_kv_reads: Optional[int] = None,
    k_bucket: int = 0,
    include_reads: bool = False,
    include_kv: bool = False,
) -> dict:
    """The capacity model: predicted device-resident bytes for a group
    geometry, decomposed per plane, plus the transient per-dispatch
    upload term at fused bucket ``k_bucket`` (0 = no dispatch term).

    The resident half walks ``jax.eval_shape`` over the engine's own
    ``make_state`` (``ops.state.state_layout``), so it is exact by
    construction and every field scales linearly with the group axis:
    ``bytes_per_group = state_bytes / n_groups``.  The dispatch half
    mirrors the fused ``quorum_multiround`` argument tuple the engine
    ships (``upload_nbytes`` semantics, dummies included) — the read/kv
    stage tensors only count when those planes are live, exactly like
    the engine's ``has_reads``/``has_kv`` statics.
    """
    layout = state_layout(
        n_groups, n_peers,
        n_read_slots=n_read_slots,
        n_kv_slots=n_kv_slots,
        n_kv_ents=n_kv_ents,
    )
    planes: Dict[str, int] = {}
    for field in layout.values():
        planes[field["plane"]] = planes.get(field["plane"], 0) + field["nbytes"]
    state_bytes = sum(planes.values())
    out = {
        "n_groups": n_groups,
        "n_peers": n_peers,
        "state_bytes": state_bytes,
        "planes": planes,
        "bytes_per_group": state_bytes / max(1, n_groups),
        "dispatch_bytes": 0,
    }
    if k_bucket > 0:
        from ..ops.state import KV_ENT_SLOTS, KV_READ_SLOTS, READ_SLOTS

        # the value-slot width (V) does not ride the dispatch — only
        # the entry/read stage tensors do
        g, p, k = n_groups, n_peers, k_bucket
        s = READ_SLOTS if n_read_slots is None else n_read_slots
        e = KV_ENT_SLOTS if n_kv_ents is None else n_kv_ents
        rk = KV_READ_SLOTS if n_kv_reads is None else n_kv_reads
        # the fused argument tuple: ack_max (K,G,P) i32, vote dummy
        # (1,1,1) i8, four churn dummies (1,1) i32, tick_mask (K,) bool
        d = k * g * p * 4 + 1 + 4 * 4 + k
        if include_reads:
            # stage_idx/stage_cnt (K,G,S) i32 + echo (K,G,S,P) bool
            d += k * g * s * 8 + k * g * s * p
        if include_kv:
            # kv_ei/kv_ek/kv_ev (K,G,E) i32 + kv_rk (K,G,R) i32
            d += k * g * e * 12 + k * g * rk * 4
        out["dispatch_bytes"] = d
        out["k_bucket"] = k
    out["total_bytes"] = state_bytes + out["dispatch_bytes"]
    return out




def _spec_nbytes(args) -> int:
    """Total bytes of a tuple of ``ShapeDtypeStruct`` stand-ins (``None``
    entries skipped) — the abstract twin of ``engine.upload_nbytes``."""
    import numpy as np

    return int(sum(
        int(np.prod(a.shape, dtype=np.int64)) * np.dtype(a.dtype).itemsize
        for a in args if a is not None
    ))


class DevProf:
    """The device capacity & profiling plane for one engine.

    Constructed by NodeHost when ``device_profile > 0`` (or directly by
    tests/bench), bound to a :class:`BatchedQuorumEngine` via
    :meth:`bind_engine` — which flips the engine's ``_devprof`` latch.
    ``registry=None`` keeps everything local (no families registered);
    with a registry the :class:`~.instruments.DevProfObs` families
    publish on the estimator's flush cadence.
    """

    def __init__(
        self,
        registry=None,
        recorder=None,
        sample_every: int = DEFAULT_SAMPLE_EVERY,
        artifact_dir: Optional[str] = None,
        ledger_refresh_s: float = LEDGER_REFRESH_S,
    ):
        if sample_every < 1:
            raise ValueError("devprof sample_every must be >= 1")
        self.engine = None
        self.coord = None  # optional: set by TpuQuorumCoordinator wiring
        self.recorder = recorder
        self.sample_every = int(sample_every)
        self.artifact_dir = artifact_dir
        self.ledger_refresh_s = float(ledger_refresh_s)
        self._obs = None
        if registry is not None:
            from .instruments import DevProfObs

            self._obs = DevProfObs(registry=registry)
        self._mu = threading.Lock()
        # estimator state (all under _mu; flushed totals track what the
        # registry has seen so counter families only receive deltas)
        self._dispatches = 0
        self._sampled = 0
        self._padded = 0
        self._wasted = 0
        self._since_sample = self.sample_every - 1  # sample the 1st
        self._flushed = {"dispatches": 0, "sampled": 0, "padded": 0,
                         "wasted": 0}
        self._device_ms: deque = deque(maxlen=_SAMPLE_WINDOW)
        self._duty = 0.0
        self._win_t0 = time.monotonic()
        self._ledger_mono = 0.0
        self._last_ledger: Optional[dict] = None
        # predict_bytes is an invariant of the engine geometry + the
        # plane latches: cache it per latch combination so the ~1s
        # ledger refresh on the dispatch thread never re-traces
        # make_state through eval_shape (review-caught)
        self._predict_cache: Dict[Tuple[bool, bool], dict] = {}
        # program registry (compiled lazily, guarded by its own lock —
        # a collect must not block the estimator's micro-lock)
        self._prog_mu = threading.Lock()
        self._programs: Optional[List[dict]] = None
        # capture windows.  _mu only guards the STATE (the active-window
        # slot); the actual jax.profiler start/stop calls — which can
        # spend seconds serializing the artifact — run under this
        # dedicated lock so note_dispatch's micro-lock never waits on
        # profiler I/O (review-caught: stop_trace under _mu froze the
        # round loop for the whole artifact write)
        self._prof_mu = threading.Lock()
        self._capture: Optional[dict] = None
        # the window being torn down right now: claimed out of _capture
        # but its stop_trace/artifact write still in flight —
        # capture_active stays True (and new windows refuse) until the
        # profiler is genuinely free again
        self._stopping: Optional[dict] = None
        self._captures: List[dict] = []
        self._capture_seq = 0  # disambiguates same-second window dirs

    # ------------------------------------------------------------------
    # wiring
    # ------------------------------------------------------------------

    def bind_engine(self, engine) -> None:
        """Attach to the engine (flips its ``_devprof`` latch) and take
        the first ledger snapshot so the families are live — a scrape
        distinguishes "devprof off" (families absent) from "on, idle"."""
        self.engine = engine
        self._predict_cache.clear()
        engine.enable_devprof(self)
        try:
            self.refresh_ledger()
        except Exception:
            plog.exception("initial devprof ledger refresh failed")

    def unbind(self) -> None:
        eng, self.engine = self.engine, None
        if eng is not None and eng._devprof is self:
            eng.disable_devprof()

    # ------------------------------------------------------------------
    # pillar 3: device-time estimator + padding waste (engine hook)
    # ------------------------------------------------------------------

    def note_dispatch(
        self, kind: str, leaf, *, rounds: int, live_rounds: int, span=None
    ) -> None:
        """Engine dispatch hook (behind the ``_devprof is not None``
        latch).  Unsampled dispatches pay a few counter bumps under one
        micro-lock; every ``sample_every``-th dispatch blocks on
        ``leaf`` (post-launch → completion, the device-execution
        estimate including queueing) and flushes the accumulated
        counters + window gauges to the registry."""
        with self._mu:
            self._dispatches += 1
            if kind == "fused":
                # padding waste is a FUSED-path metric (only padded
                # K-batched programs ship no-op rounds); counting the
                # single-round sparse/dense dispatches into the base
                # would dilute the ratio toward 0 on quiet clusters
                self._padded += rounds
                if rounds > live_rounds:
                    self._wasted += rounds - live_rounds
            self._since_sample += 1
            if self._since_sample < self.sample_every:
                return
            self._since_sample = 0
        t0 = time.perf_counter()
        ms = None
        try:
            import jax

            jax.block_until_ready(leaf)
            ms = (time.perf_counter() - t0) * 1e3
        except Exception as e:
            # a device fault during the sampled wait is the single most
            # interesting event this plane can see — surface it, and
            # still flush the accumulated counters below (swallowing it
            # silently stalled dispatches_total until the next sample)
            plog.warning("devprof sampled block_until_ready failed: %r", e)
        if ms is not None and span is not None:
            # producer-thread span mutation (the recorder's egress-field
            # pattern): the estimator's delta lands on the very span the
            # FlightRecorder holds for this dispatch
            span["device_ms"] = round(ms, 4)
        with self._mu:
            self._sampled += 1
            if ms is not None:
                self._device_ms.append(ms)
            now = time.monotonic()
            wall_ms = (now - self._win_t0) * 1e3
            # duty estimate over the stride window: the sampled
            # dispatch's device time extrapolated across the stride,
            # over the wall the stride spanned (clamped — it IS an
            # extrapolation, documented as such)
            if ms is not None and wall_ms > 0:
                self._duty = min(1.0, (ms * self.sample_every) / wall_ms)
            self._win_t0 = now
            deltas = {
                k: getattr(self, "_" + k) - self._flushed[k]
                for k in self._flushed
            }
            for k in self._flushed:
                self._flushed[k] = getattr(self, "_" + k)
            waste_ratio = self._wasted / self._padded if self._padded else 0.0
            duty = self._duty
        obs = self._obs
        if obs is not None:
            if ms is not None:
                obs.device_ms(ms)
            obs.flush_dispatch(
                dispatches=deltas["dispatches"],
                sampled=deltas["sampled"],
                padded=deltas["padded"],
                wasted=deltas["wasted"],
                waste_ratio=waste_ratio,
                duty_cycle=duty,
            )
        if time.monotonic() - self._ledger_mono >= self.ledger_refresh_s:
            try:
                self.refresh_ledger()
            except Exception:
                plog.exception("devprof ledger refresh failed")

    def estimator_stats(self) -> dict:
        with self._mu:
            samples = list(self._device_ms)
            padded, wasted = self._padded, self._wasted
            out = {
                "dispatches": self._dispatches,
                "sampled": self._sampled,
                "sample_every": self.sample_every,
                "padded_rounds": padded,
                "wasted_rounds": wasted,
                "padding_waste_ratio": (
                    round(wasted / padded, 4) if padded else 0.0
                ),
                "duty_cycle": round(self._duty, 4),
            }
        if samples:
            out["device_ms"] = {
                "n": len(samples),
                "p50": round(_pctile(samples, 50), 4),
                "p99": round(_pctile(samples, 99), 4),
                "max": round(max(samples), 4),
            }
        return out

    # ------------------------------------------------------------------
    # pillar 1: the HBM memory ledger
    # ------------------------------------------------------------------

    @staticmethod
    def _engine_artifacts(eng) -> Dict[Tuple[str, str], int]:
        """Price ONE engine's resident device state: the quorum state
        tensors plus the in-flight pipelined dispatch's egress
        accumulators (live ``nbytes`` — pure metadata, no transfer)."""
        artifacts: Dict[Tuple[str, str], int] = {}
        for name, arr in eng._dev._asdict().items():
            artifacts[(field_plane(name), name)] = int(arr.nbytes)
        inflight = eng._inflight
        if inflight is not None:
            import jax

            out = inflight[0]
            extra = sum(
                int(leaf.nbytes)
                for leaf in jax.tree_util.tree_leaves((
                    getattr(out, "committed", None),
                    getattr(out, "won", None),
                    getattr(out, "lost", None),
                    getattr(out, "flags", None),
                    getattr(out, "read_done_count", None),
                    getattr(out, "read_done_index", None),
                    getattr(out, "kv_read_val", None),
                    getattr(out, "kv_read_index", None),
                    getattr(out, "kv_applied", None),
                ))
            )
            # the double buffer: out.state already IS eng._dev (donated
            # chain) so only the egress accumulators are extra residency
            artifacts[("dispatch", "inflight_egress")] = extra
        return artifacts

    def hbm_ledger(self) -> dict:
        """Walk the engine's resident device state and price every
        artifact, plus the in-flight pipelined dispatch's egress
        accumulators.  Also publishes the ledger gauges and the
        capacity-model summary.

        On a mesh-sharded facade (``ops/mesh.py``) every per-shard
        engine is walked: the top-level artifacts/planes aggregate
        across shards (residency totals stay comparable with the
        single-device ledger), a ``shards`` section itemizes each
        shard's residency, and the gauges publish BOTH the aggregate
        rows and per-shard ``dragonboat_devprof_hbm_bytes{shard}``
        rows."""
        eng = self.engine
        if eng is None:
            return {}
        shards = getattr(eng, "shards", None)
        shard_rows: Optional[list] = None
        shard_artifacts: Optional[list] = None
        if shards:
            artifacts = {}
            shard_rows, shard_artifacts = [], []
            for s in shards:
                arts = self._engine_artifacts(s)
                arts.setdefault(("dispatch", "inflight_egress"), 0)
                shard_artifacts.append(arts)
                splanes: Dict[str, int] = {}
                for (plane, art), nbytes in arts.items():
                    artifacts[(plane, art)] = (
                        artifacts.get((plane, art), 0) + nbytes
                    )
                    splanes[plane] = splanes.get(plane, 0) + nbytes
                shard_rows.append({
                    "planes": splanes,
                    "state_bytes": sum(
                        b for (p, _), b in arts.items() if p != "dispatch"
                    ),
                    "total_bytes": sum(splanes.values()),
                })
        else:
            artifacts = self._engine_artifacts(eng)
        planes: Dict[str, int] = {}
        for (plane, _), nbytes in artifacts.items():
            planes[plane] = planes.get(plane, 0) + nbytes
        state_bytes = sum(
            b for (plane, _), b in artifacts.items() if plane != "dispatch"
        )
        ledger = {
            "artifacts": {
                plane: {
                    art: b
                    for (pl, art), b in sorted(artifacts.items())
                    if pl == plane
                }
                for plane in sorted(planes)
            },
            "planes": planes,
            "state_bytes": state_bytes,
            "total_bytes": sum(planes.values()),
        }
        if shard_rows is not None:
            ledger["shards"] = shard_rows
        model = self.capacity_model(ledger_state_bytes=state_bytes)
        ledger["capacity"] = model
        obs = self._obs
        if obs is not None:
            # the GAUGE set always carries the dispatch artifact — a
            # harvested inflight must rewrite its gauge to 0, or the
            # exposition keeps advertising residency that no longer
            # exists (review-caught: hbm_bytes disagreed with the
            # zeroed hbm_plane_bytes forever after one pipelined block)
            gauge_artifacts = dict(artifacts)
            gauge_artifacts.setdefault(("dispatch", "inflight_egress"), 0)
            obs.ledger(
                artifacts=gauge_artifacts,
                planes=planes,
                bytes_per_group=model["bytes_per_group"],
                capacity_groups=model.get("max_groups") or 0,
                model_error_pct=model.get("model_error_pct"),
                shard_artifacts=shard_artifacts,
            )
        with self._mu:
            self._ledger_mono = time.monotonic()
            self._last_ledger = ledger
        return ledger

    def refresh_ledger(self) -> dict:
        return self.hbm_ledger()

    # ------------------------------------------------------------------
    # pillar 1b: the capacity model
    # ------------------------------------------------------------------

    def capacity_model(
        self,
        budget_bytes: Optional[int] = None,
        ledger_state_bytes: Optional[int] = None,
    ) -> dict:
        """Predict resident bytes for the bound engine's geometry and
        extrapolate max groups per device.  ``budget_bytes`` overrides
        the device's own ``memory_stats()['bytes_limit']`` (absent on
        backends that don't report one, e.g. cpu — ``max_groups`` is
        then None unless a budget is passed).

        On a mesh-sharded facade the geometry half models ONE SHARD
        (each per-shard engine is an independent single-device
        allocation) and the capacity answer multiplies by mesh size:
        ``max_groups_per_device`` from the tightest per-device budget,
        ``max_groups`` = that × ``mesh_shards``."""
        eng = self.engine
        if eng is None:
            return {}
        from ..ops.engine import WARM_K_BUCKETS

        shards = getattr(eng, "shards", None)
        # geometry donor: one shard's engine on a mesh (per-device
        # residency), the engine itself otherwise
        geng = shards[0] if shards else eng
        n_shards = len(shards) if shards else 1
        key = (bool(eng._read_plane_used), bool(eng._devsm_used))
        base = self._predict_cache.get(key)
        if base is None:
            k = max(WARM_K_BUCKETS)
            base = predict_bytes(
                geng.n_groups, geng.n_peers,
                n_read_slots=geng.n_read_slots,
                n_kv_slots=geng.n_kv_slots,
                n_kv_ents=geng.n_kv_ents,
                n_kv_reads=geng.n_kv_reads,
                k_bucket=k,
                include_reads=key[0],
                include_kv=key[1],
            )
            # with a live engine, the dispatch term is DERIVED from the
            # same abstract argument spec the warmup/lowering builder
            # produces — structurally incapable of drifting from the
            # tensors a fused dispatch actually ships (predict_bytes's
            # closed form is the engine-less twin; the test suite
            # asserts the two agree on every plane combination)
            _, args, _ = geng._variant_args(
                "fused", k, key[0], key[1], abstract=True
            )
            base["dispatch_bytes"] = _spec_nbytes(args)
            base["total_bytes"] = base["state_bytes"] + base["dispatch_bytes"]
            self._predict_cache[key] = base
        # shallow copy: the measured/budget fields below are per-call,
        # the cached geometry half is immutable
        pred = dict(base)
        if ledger_state_bytes is None:
            engines = shards if shards else [eng]
            ledger_state_bytes = sum(
                int(arr.nbytes)
                for e in engines
                for arr in e._dev._asdict().values()
            )
        measured = ledger_state_bytes
        predicted_state = pred["state_bytes"] * n_shards
        if measured:
            pred["measured_state_bytes"] = measured
            pred["model_error_pct"] = round(
                (predicted_state - measured) / measured * 100.0, 4
            )
        per_device_budgets = None
        if budget_bytes is None:
            budget_bytes, per_device_budgets = self._device_budget()
        pred["budget_bytes"] = budget_bytes
        # every term scales linearly with G, so one division extrapolates:
        # resident bytes/group plus the fused dispatch's per-group upload
        per_group = (
            pred["bytes_per_group"]
            + pred["dispatch_bytes"] / max(1, geng.n_groups)
        )
        pred["bytes_per_group_with_dispatch"] = per_group
        per_dev = int(budget_bytes // per_group) if budget_bytes else None
        if n_shards > 1:
            pred["mesh_shards"] = n_shards
            pred["state_bytes_total"] = predicted_state
            pred["total_bytes_total"] = pred["total_bytes"] * n_shards
            if per_device_budgets is not None:
                pred["device_budgets"] = per_device_budgets
            pred["max_groups_per_device"] = per_dev
            pred["max_groups"] = (
                per_dev * n_shards if per_dev is not None else None
            )
        else:
            pred["max_groups"] = per_dev
        return pred

    def _device_budget(self) -> Tuple[Optional[int], Optional[list]]:
        """The backend-reported memory budget of the device(s) holding
        the engine state: ``(per_device_budget, per_shard_budgets)``.
        On a mesh the per-device budget is the TIGHTEST shard's (a
        capacity plan must fit the worst device); per_shard_budgets
        lists them all.  ``(None, None)`` where the backend has no
        ``memory_stats`` — the cpu client."""
        eng = self.engine
        shards = getattr(eng, "shards", None)
        engines = shards if shards else [eng]
        budgets: list = []
        for e in engines:
            try:
                dev = next(iter(e._dev.committed.devices()))
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if not stats:
                budgets.append(None)
                continue
            budgets.append(
                stats.get("bytes_limit")
                or stats.get("bytes_reservable_limit")
            )
        known = [b for b in budgets if b]
        if not known:
            return None, None
        if shards:
            return min(known), budgets
        return known[0], None

    # ------------------------------------------------------------------
    # pillar 2: the program registry
    # ------------------------------------------------------------------

    def collect_programs(
        self, include_kv: Optional[bool] = None, force: bool = False
    ) -> List[dict]:
        """AOT-analyze the engine's warm set: one
        ``lower().compile()`` per warm-plan variant (the SAME
        enumeration and shapes the warmup compiled —
        ``engine.warm_plan`` / ``_variant_args``), recording
        cost-analysis flops / bytes accessed, memory-analysis peak temp
        and argument/output bytes, and compile wall.  Cached after the
        first collection (``force`` re-runs); ``include_kv=None``
        follows the engine's devsm state."""
        with self._mu:
            if self._programs is not None and not force:
                return list(self._programs)
        with self._prog_mu:  # serializes COLLECTORS only — readers
            # (the programs property, to_json, /debug/devprof) take the
            # cheap _mu and never wait out a multi-second compile loop
            with self._mu:
                if self._programs is not None and not force:
                    return list(self._programs)
            eng = self.engine
            if eng is None:
                return []
            if include_kv is None:
                include_kv = bool(eng._devsm_used or eng.kv_fused_ready)
            rows: List[dict] = []
            for kind, arg, hr, kv in eng.warm_plan(include_kv=include_kv):
                label = eng.variant_label(kind, arg, hr, kv)
                t0 = time.perf_counter()
                try:
                    compiled = eng.lower_variant(kind, arg, hr, kv).compile()
                except Exception as e:  # a variant failing must not
                    # hide the rest of the table
                    plog.warning("devprof lower/compile %s: %r", label, e)
                    rows.append({"variant": label, "error": repr(e)})
                    continue
                compile_ms = (time.perf_counter() - t0) * 1e3
                row = {
                    "variant": label,
                    "kind": kind,
                    "compile_ms": round(compile_ms, 2),
                }
                try:
                    ca = compiled.cost_analysis()
                    if isinstance(ca, (list, tuple)):
                        ca = ca[0] if ca else {}
                    ca = ca or {}
                    row["flops"] = float(ca.get("flops", 0.0))
                    row["bytes_accessed"] = float(
                        ca.get("bytes accessed", 0.0)
                    )
                except Exception as e:
                    row["cost_error"] = repr(e)
                try:
                    ma = compiled.memory_analysis()
                    if ma is not None:
                        row["temp_bytes"] = int(ma.temp_size_in_bytes)
                        row["argument_bytes"] = int(
                            ma.argument_size_in_bytes
                        )
                        row["output_bytes"] = int(ma.output_size_in_bytes)
                        row["code_bytes"] = int(
                            ma.generated_code_size_in_bytes
                        )
                except Exception as e:
                    row["memory_error"] = repr(e)
                rows.append(row)
                obs = self._obs
                if obs is not None and "flops" in row:
                    obs.program(
                        variant=label,
                        flops=row["flops"],
                        bytes_accessed=row.get("bytes_accessed", 0.0),
                        temp_bytes=row.get("temp_bytes", 0),
                        compile_ms=compile_ms,
                    )
            with self._mu:
                self._programs = rows
        obs = self._obs
        if obs is not None:
            obs.programs_done(len(rows))
        return rows

    @property
    def programs(self) -> Optional[List[dict]]:
        """The collected registry (None until :meth:`collect_programs`
        ran — reading never triggers compiles NOR waits on one)."""
        with self._mu:
            return list(self._programs) if self._programs is not None else None

    # ------------------------------------------------------------------
    # pillar 4: on-demand jax.profiler capture windows
    # ------------------------------------------------------------------

    def capture(self, ms: float = 1000.0, path: Optional[str] = None) -> str:
        """Open one ``jax.profiler`` capture window for ``ms``
        milliseconds (stopped by a background timer, or early via
        :meth:`stop_capture`).  Returns the artifact directory —
        default: a timestamped ``devprof-*`` dir beside the
        ``dump_trace``/``debug_dump`` artifacts.  One window at a time:
        the profiler is process-global."""
        import jax

        base = self.artifact_dir
        if not base or base == ":memory:":
            import tempfile

            base = tempfile.gettempdir()
        with self._mu:
            self._capture_seq += 1
            seq = self._capture_seq
        # the sequence suffix keeps back-to-back short windows from
        # landing in one same-second directory and interleaving their
        # profiles in a single Perfetto session
        d = path or os.path.join(
            base, time.strftime("devprof-%Y%m%d-%H%M%S") + f"-{seq}"
        )
        rec = {"dir": d, "started": time.time(), "ms": float(ms),
               "stopped": None}
        with self._mu:
            if self._capture is not None or self._stopping is not None:
                raise RuntimeError(
                    "a device profile capture window is already active"
                )
            self._capture = rec  # claim the slot; profiler I/O runs
            self._captures.append(rec)  # outside the estimator lock
        try:
            with self._prof_mu:
                os.makedirs(d, exist_ok=True)
                jax.profiler.start_trace(d)
        except Exception:
            with self._mu:  # roll the claim back — nothing started
                if self._capture is rec:
                    self._capture = None
                self._captures.remove(rec)
            raise
        obs = self._obs
        if obs is not None:
            obs.capture(active=True)
        if self.recorder is not None:
            self.recorder.record("devprof", window_ms=float(ms), dir=d)
        t = threading.Thread(
            target=self._capture_deadline, args=(rec, ms),
            name="devprof-capture", daemon=True,
        )
        t.start()
        return d

    def _capture_deadline(self, rec: dict, ms: float) -> None:
        time.sleep(max(0.0, ms) / 1e3)
        self._stop_capture(rec)

    def stop_capture(self) -> Optional[str]:
        """Stop the active capture window early (None when idle);
        returns its artifact directory."""
        with self._mu:
            rec = self._capture
        if rec is None:
            return None
        self._stop_capture(rec)
        return rec["dir"]

    def _stop_capture(self, rec: dict) -> None:
        import jax

        with self._mu:
            if self._capture is not rec:  # already stopped (early stop
                return  # raced the deadline timer)
            self._capture = None  # claim atomically; the artifact
            self._stopping = rec  # write below must not hold _mu but
            # the window is not OVER until it lands (capture_active)
        with self._prof_mu:
            try:
                jax.profiler.stop_trace()
            except Exception:
                plog.exception("jax.profiler.stop_trace failed")
            rec["stopped"] = time.time()
        obs = self._obs
        if obs is not None:
            obs.capture(active=False)
        with self._mu:
            self._stopping = None
        plog.info("device profile capture written to %s", rec["dir"])

    @property
    def capture_active(self) -> bool:
        with self._mu:
            return self._capture is not None or self._stopping is not None

    def captures(self) -> List[dict]:
        with self._mu:
            return [dict(c) for c in self._captures]

    # ------------------------------------------------------------------
    # introspection (/debug/devprof, debug dumps, bench artifacts)
    # ------------------------------------------------------------------

    def to_json(self) -> dict:
        """Read-only JSON snapshot (never triggers compiles or
        captures): the ledger + capacity model (refreshed), estimator
        stats, any already-collected program registry, capture history
        and — when the coordinator wired a devsm plane — its shadow
        residency."""
        out = {
            "sample_every": self.sample_every,
            "estimator": self.estimator_stats(),
            "ledger": self.hbm_ledger(),
            "programs": self.programs,
            "captures": self.captures(),
        }
        coord = self.coord
        devsm = getattr(coord, "devsm", None) if coord is not None else None
        if devsm is not None:
            try:
                out["devsm"] = devsm.devprof_snapshot()
            except Exception:
                plog.exception("devsm devprof snapshot failed")
        return out

    def stop(self) -> None:
        """Detach from the engine and close any open capture window
        (NodeHost.stop).  Blocks until the stop lands: the deadline
        thread may have claimed the window and still be inside the
        profiler's artifact write — returning before it finishes would
        let NodeHost tear the engine down (or the process exit) under a
        live capture and truncate the profile."""
        self.stop_capture()
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            with self._mu:
                if self._capture is None and self._stopping is None:
                    break
            time.sleep(0.01)
        self.unbind()
