"""Cluster health plane: continuous per-group health sampling, anomaly
detectors with recovery-time attribution, and a live scrape endpoint
(ISSUE 13 tentpole).

The trace ledger answers "where does one request's latency go"; nothing
answered "is group 412 healthy right now" — per-group raft state,
host-plane depth and worker liveness were only visible through on-demand
dumps (SIGUSR2, ``dump_trace``, file-based ``write_health_metrics``).
This module closes that gap with three layers:

- :class:`HealthSampler` — on a low-rate cadence driven off the NodeHost
  tick worker it snapshots, per group, the raft plane (state/term/
  leader/commitIndex/appliedIndex, device commit watermark, devsm
  binding + release floor, lease validity + hit ratio, reachable voters)
  plus the host plane (staging-ring occupancy, WAL mode/flush window,
  hostproc worker heartbeat age and restart count, apply/egress queue
  depths) into a fixed-size rolling timeseries ring mirroring the
  :class:`~dragonboat_tpu.obs.recorder.FlightRecorder` shape (bounded
  memory, JSON dump on demand).

- **Detectors** run over consecutive samples and emit structured
  open/close health events:

  ==================  ==================================================
  detector            opens when
  ==================  ==================================================
  ``commit_stall``    commitIndex flat across ``commit_stall_samples``
                      consecutive samples while proposals are pending
  ``apply_lag``       committed − applied exceeds ``apply_lag_entries``
                      (closes at half the threshold — hysteresis)
  ``quorum_at_risk``  reachable voters ≤ quorum on a check-quorum
                      leader for ``quorum_risk_samples`` samples (one
                      more loss breaks the group); closes when every
                      voter is reachable again
  ``leader_flap``     ≥ ``leader_flap_changes`` leader changes inside
                      ``flap_window_s``; closes after a quiet window
  ``worker_flap``     hostproc workers alive < spawned; closes when the
                      monitor's respawn restores the full set
  ``lease_thrash``    ≥ ``lease_thrash_events`` grant/expiry
                      transitions inside the window; closes on a quiet
                      window with the lease held
  ``devsm_rebind``    ≥ ``devsm_rebind_binds`` device-plane rebinds of
                      one group inside the window (a bind/unbind loop)
  ``shard_imbalance`` mesh-sharded engine (ops/mesh.py): per-shard
                      dispatch-cost EMA skew above
                      ``shard_imbalance_ratio`` (or group-count skew
                      > 1) across ``shard_imbalance_samples``
                      consecutive samples — the facade's own
                      rebalancer fires at a LOWER ratio, so an open
                      event means placement is failing to converge
                      (e.g. every hot group is migration-ineligible);
                      closes when a migration or load shift rebalances
  ==================  ==================================================

  Every open/close publishes ``dragonboat_health_*`` families, records a
  ``health`` span into the flight recorder (when one is attached), and
  the open→close duration lands in the per-detector
  ``dragonboat_health_recovery_seconds`` histogram — the
  **recovery-time attribution** ROADMAP item 5 (BlackWater churn soak)
  wants in the perf ledger: ``leader_flap`` durations are failover
  recoveries, ``worker_flap`` durations are worker respawns,
  ``devsm_rebind`` durations are device-plane rebind loops.
  :meth:`NodeHost.health_report` aggregates the verdict.

- :class:`MetricsServer` — a tiny stdlib HTTP endpoint
  (``NodeHostConfig.metrics_addr``, default off) serving ``/metrics``
  (the existing Prometheus exposition, live-scrapeable at last),
  ``/healthz`` (the aggregated detector verdict; 503 while any detector
  is open) and ``/debug/health`` + ``/debug/trace`` JSON dumps.  It
  binds loopback unless the operator explicitly configures otherwise
  (the exposition names clusters and addresses — see docs/overview.md's
  security note).

Overhead contract (the ``_obs is not None`` / ``trace=None`` latch
precedent): the health plane is OFF by default.
``NodeHostConfig.health_sample_ms = 0`` constructs nothing — no sampler,
no server, no registry families — and the only hot-path residue is the
``Node._health_track`` latch check inside ``offload_commit`` (one
attribute load under an already-held lock, asserted structurally in
``tests/test_health.py``).  Sampling itself is bounded: one pass per
cadence over the group set with a non-blocking-ish ``raft_mu`` acquire
(a contended group reports ``busy`` instead of stalling the tick
worker), measured by the bench health axis (<5% asserted).
"""
from __future__ import annotations

import io
import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..logger import get_logger

plog = get_logger("health")

DEFAULT_CAPACITY = 256

#: detector vocabulary — instrument families zero-register per detector
#: so a scrape distinguishes "health off" (families absent) from
#: "healthy" (families at zero)
DETECTORS = (
    "commit_stall",
    "apply_lag",
    "quorum_at_risk",
    "leader_flap",
    "worker_flap",
    "lease_thrash",
    "devsm_rebind",
    "shard_imbalance",
)

#: recovery-attribution aliases for :meth:`NodeHost.health_report` /
#: the perf ledger: which detector's open→close durations measure which
#: recovery class
ATTRIBUTION = {
    "failover": "leader_flap",
    "worker_respawn": "worker_flap",
    "devsm_rebind": "devsm_rebind",
    "shard_rebalance": "shard_imbalance",
}


def _pctile(vals: List[float], q: float) -> Optional[float]:
    if not vals:
        return None
    vs = sorted(vals)
    i = min(len(vs) - 1, max(0, int(round(q / 100.0 * (len(vs) - 1)))))
    return vs[i]


class HealthSampler:
    """Rolling per-group/host health samples + anomaly detectors.

    Built by NodeHost when ``health_sample_ms > 0``; :meth:`maybe_sample`
    rides the tick worker (the tracer ``check_stalls`` precedent), so no
    extra thread exists and a stopped host stops sampling with it.
    ``nh=None`` (unit tests) skips live sampling — :meth:`ingest` feeds
    hand-built samples straight to the detectors.
    """

    def __init__(
        self,
        nh=None,
        sample_ms: float = 250.0,
        capacity: int = DEFAULT_CAPACITY,
        registry=None,
        recorder=None,
        # detector knobs (docs/overview.md table; tests shrink them)
        commit_stall_samples: int = 3,
        apply_lag_entries: int = 512,
        quorum_risk_samples: int = 2,
        leader_flap_changes: int = 3,
        lease_thrash_events: int = 4,
        devsm_rebind_binds: int = 3,
        flap_window_s: float = 10.0,
        shard_imbalance_samples: int = 3,
        shard_imbalance_ratio: float = 3.0,
        aggregate: bool = False,
    ):
        if capacity < 1:
            raise ValueError("health ring capacity must be >= 1")
        self.nh = nh
        self.sample_ms = float(sample_ms)
        self.capacity = capacity
        self.recorder = recorder
        self._obs = None
        if registry is not None:
            from .instruments import HealthObs

            self._obs = HealthObs(registry=registry, detectors=DETECTORS)
        # aggregate sampling mode (ISSUE 20): device-backed groups are
        # covered by the engine's telemetry fold (kernels.telem_fold) at
        # O(shards) host cost per pass; only the fold's top-K worst
        # groups, groups with an open per-group event, and non-device
        # groups take the per-group raft_mu walk.  False keeps the
        # historical walk-everything pass bit-identical.
        self.aggregate = bool(aggregate)
        self._telem_obs = None
        if aggregate and registry is not None:
            from .instruments import TelemObs

            self._telem_obs = TelemObs(registry=registry)
        # aggregate-detector memory: folds are evaluated once per seq (an
        # idle engine re-serves the same snapshot — stale folds must
        # neither extend streaks nor close events)
        self._telem_last_seq = -1
        self._telem_stall_streak = 0
        # non-device drill-down set, cached on the membership signature
        # (len(nodes), device group count) so the aggregate pass never
        # rebuilds an O(G) set while membership is stable
        self._nondev_sig = None
        self._nondev: frozenset = frozenset()
        # sampler degradation (ISSUE 20 satellite): raft_mu-budget busy
        # rows per pass, surfaced as dragonboat_health_busy_rows_total
        # and report()'s sampler_degraded
        self.busy_rows_total = 0
        self._last_busy = 0
        self.commit_stall_samples = commit_stall_samples
        self.apply_lag_entries = apply_lag_entries
        self.quorum_risk_samples = quorum_risk_samples
        self.leader_flap_changes = leader_flap_changes
        self.lease_thrash_events = lease_thrash_events
        self.devsm_rebind_binds = devsm_rebind_binds
        self.flap_window_s = flap_window_s
        self.shard_imbalance_samples = shard_imbalance_samples
        self.shard_imbalance_ratio = shard_imbalance_ratio
        # sample ring (the FlightRecorder shape: bounded, lock-light)
        self._buf: List[Optional[dict]] = [None] * capacity
        self._n = 0
        self._mu = threading.Lock()
        self._last_mono = 0.0
        # detector state
        self._open: Dict[Tuple[str, str], dict] = {}
        self._closed: deque = deque(maxlen=1024)
        self._recoveries: Dict[str, List[float]] = {d: [] for d in DETECTORS}
        self.opened: Dict[str, int] = {d: 0 for d in DETECTORS}
        # per-group evaluation memory
        self._prev: Dict[int, dict] = {}
        self._stall_streak: Dict[int, int] = {}
        self._risk_streak: Dict[int, int] = {}
        self._heal_streak: Dict[int, int] = {}
        self._leader_changes: Dict[int, deque] = {}
        self._lease_events: Dict[int, deque] = {}
        self._devsm_binds: Dict[int, deque] = {}
        self._prev_hostproc: Optional[dict] = None
        self._imbalance_streak = 0
        # detector-event subscribers (ISSUE 17): ``None`` until the
        # first registration — the same latch discipline as ``_obs``,
        # so an unsubscribed sampler pays one attribute load per event
        # and nothing else
        self._subs: Optional[Dict[str, list]] = None

    # ------------------------------------------------------------------
    # detector-event subscription (ISSUE 17)
    # ------------------------------------------------------------------

    def on_open(self, cb) -> None:
        """Register ``cb(event: dict)`` for detector OPEN transitions.

        The callback runs on the sampling thread (the NodeHost tick
        worker in live mode) AFTER the event is recorded — metrics
        bumped, flight-recorder span written — and receives a copy of
        the event dict (``detector``/``key``/``detail``/``opened_*``).
        Callbacks are exception-guarded: a failing subscriber is logged
        and never breaks sampling.  Subscribers must not block — hand
        work to your own thread (the RecoveryController queues).
        """
        if self._subs is None:
            self._subs = {"open": [], "close": []}
        self._subs["open"].append(cb)

    def on_close(self, cb) -> None:
        """Register ``cb(event: dict)`` for detector CLOSE transitions.

        Runs after the open→close duration has been appended to the
        recovery attribution (``recovery_stats`` already includes it
        when the callback observes the event — ordering asserted in
        tests/test_health.py); the event copy carries ``duration_s``.
        Same exception guard and non-blocking contract as :meth:`on_open`.
        """
        if self._subs is None:
            self._subs = {"open": [], "close": []}
        self._subs["close"].append(cb)

    def _dispatch(self, kind: str, ev: dict) -> None:
        subs = self._subs
        if subs is None:
            return
        for cb in subs[kind]:
            try:
                cb(dict(ev))
            except Exception:
                # a failing subscriber must never break sampling
                plog.exception(
                    "health %s subscriber failed for %s %s",
                    kind, ev.get("detector"), ev.get("key"),
                )

    # ------------------------------------------------------------------
    # sampling (tick worker)
    # ------------------------------------------------------------------

    def maybe_sample(self) -> Optional[dict]:
        """Take one sample when the cadence elapsed (tick-worker hook);
        cheap two-float compare otherwise."""
        now = time.monotonic()
        if (now - self._last_mono) * 1e3 < self.sample_ms:
            return None
        self._last_mono = now
        try:
            return self.sample()
        except Exception:
            # the sampler must never hurt the tick worker
            plog.exception("health sample failed")
            return None

    def sample(self) -> dict:
        """Snapshot the group walk set + the host planes, append to the
        ring, run the detectors, publish the sample metrics.

        In aggregate mode (ISSUE 20) the walk set shrinks from every
        group to the drill-down set — the telemetry fold's top-K worst
        groups, groups with an open per-group event (hysteresis must
        never depend on staying in the top-K), and non-device groups —
        while the fold covers the rest at O(shards) host cost; with no
        fold harvested yet (engine warming, nothing dispatched) the
        pass falls back to the full walk."""
        nh = self.nh
        if nh is None:
            raise RuntimeError("sampler has no NodeHost (unit mode)")
        t0 = time.perf_counter()
        groups: Dict[int, dict] = {}
        _, nodes = nh._get_nodes()
        qc = nh.quorum_coordinator
        tel = None
        walk = nodes
        if self.aggregate and qc is not None:
            tel = qc.telem_snapshot()
            if tel is not None:
                sig = (len(nodes), tel.get("groups"))
                if sig != self._nondev_sig:
                    reg = qc.registered_cids()
                    self._nondev = frozenset(
                        c for c in nodes if c not in reg
                    )
                    self._nondev_sig = sig
                drill = set(self._nondev)
                for cid, _lag in tel.get("topk") or ():
                    drill.add(cid)
                for _det, key in self._open:
                    if key.startswith("group:"):
                        try:
                            drill.add(int(key[6:]))
                        except ValueError:
                            pass
                walk = {c: nodes[c] for c in drill if c in nodes}
        # whole-PASS lock budget: the per-group raft_mu timeout shrinks
        # as the deadline approaches, so a host full of contended
        # groups costs one bounded stall total (busy rows past it),
        # never n_groups × timeout on the tick worker
        deadline = t0 + min(0.2, self.sample_ms / 1e3 / 2.0)
        for cid, node in walk.items():
            try:
                remaining = deadline - time.perf_counter()
                groups[cid] = node.health_snapshot(
                    lock_timeout=min(0.05, remaining)
                )
            except Exception:
                groups[cid] = {"error": True}
        host: Dict[str, Optional[dict]] = {}
        host["coord"] = qc.health_snapshot() if qc is not None else None
        hp = nh.hostplane
        host["hostplane"] = hp.health_snapshot() if hp is not None else None
        hpp = nh.hostproc
        host["hostproc"] = hpp.health_snapshot() if hpp is not None else None
        wall_ms = (time.perf_counter() - t0) * 1e3
        sample = {
            "ts": time.time(),
            "mono": time.monotonic(),
            "tick": nh.tick_count,
            "wall_ms": round(wall_ms, 4),
            "groups": groups,
            "host": host,
        }
        if tel is not None:
            sample["aggregate"] = True
            sample["telem"] = tel
            # gone detection needs full membership (the walk set is a
            # subset): resolved HERE, where the nodes dict gives O(1)
            # lookups over the small _prev set — _evaluate must not
            # treat mere absence from the walk as group removal
            sample["gone_cids"] = [
                c for c in self._prev if c not in walk and c not in nodes
            ]
        self.ingest(sample)
        return sample

    def ingest(self, sample: dict) -> None:
        """Append one sample (live or hand-built) and evaluate the
        detectors against it."""
        with self._mu:
            sample["seq"] = self._n
            self._buf[self._n % self.capacity] = sample
            self._n += 1
        # sampler degradation (ISSUE 20 satellite): rows the raft_mu
        # budget forced to report busy this pass — counted even in unit
        # mode so hand-built samples exercise the same path
        busy = sum(
            1 for g in (sample.get("groups") or {}).values()
            if g.get("busy")
        )
        self.busy_rows_total += busy
        self._last_busy = busy
        self._evaluate(sample)
        obs = self._obs
        if obs is not None:
            obs.sample(
                wall_ms=sample.get("wall_ms", 0.0),
                groups=len(sample.get("groups") or {}),
            )
            obs.busy_rows(busy)

    # ------------------------------------------------------------------
    # detectors
    # ------------------------------------------------------------------

    def _evaluate(self, sample: dict) -> None:
        now = sample.get("mono", time.monotonic())
        groups = sample.get("groups") or {}
        for cid, g in groups.items():
            if g.get("busy") or g.get("error"):
                continue
            prev = self._prev.get(cid)
            self._eval_commit_stall(cid, g, prev, now)
            self._eval_apply_lag(cid, g, now)
            self._eval_quorum_risk(cid, g, now)
            self._eval_leader_flap(cid, g, prev, now)
            self._eval_lease_thrash(cid, g, prev, now)
            self._eval_devsm_rebind(cid, g, prev, now)
            self._prev[cid] = g
        # groups that disappeared (stop_cluster) close their events AND
        # drop every per-cid evaluation memory: a leftover flap deque
        # would charge a restarted incarnation with the old one's
        # changes, and under long-running group churn the dicts would
        # grow without bound
        if sample.get("aggregate"):
            # aggregate samples walk only the drill-down set: absence
            # from the walk is NOT removal — closing on it would flap
            # every per-group detector as the top-K churns.  sample()
            # resolved true membership into gone_cids.
            gone = [
                c for c in sample.get("gone_cids") or ()
                if c in self._prev
            ]
        else:
            gone = [c for c in self._prev if c not in groups]
        for cid in gone:
            del self._prev[cid]
            for d in (self._stall_streak, self._risk_streak,
                      self._heal_streak, self._leader_changes,
                      self._lease_events, self._devsm_binds):
                d.pop(cid, None)
            for det in DETECTORS:
                self._set(det, f"group:{cid}", False, now, {})
        tel = sample.get("telem")
        if tel is not None:
            self._eval_telem(tel, now)
        hostproc = (sample.get("host") or {}).get("hostproc")
        self._eval_worker_flap(hostproc, now)
        coord = (sample.get("host") or {}).get("coord")
        self._eval_shard_imbalance(coord, now)

    def _eval_commit_stall(self, cid, g, prev, now) -> None:
        flat = (
            prev is not None
            and g.get("committed") == prev.get("committed")
            and g.get("pending_proposals")
            and prev.get("pending_proposals")
        )
        streak = self._stall_streak.get(cid, 0) + 1 if flat else 0
        self._stall_streak[cid] = streak
        self._set(
            "commit_stall", f"group:{cid}",
            streak >= self.commit_stall_samples, now,
            {"cluster_id": cid, "committed": g.get("committed"),
             "samples": streak},
        )

    def _eval_apply_lag(self, cid, g, now) -> None:
        committed, applied = g.get("committed"), g.get("applied")
        if committed is None or applied is None:
            return
        lag = committed - applied
        key = ("apply_lag", f"group:{cid}")
        # hysteresis: open past the threshold, close at half of it
        threshold = (
            self.apply_lag_entries // 2
            if key in self._open else self.apply_lag_entries
        )
        self._set(
            "apply_lag", f"group:{cid}", lag > threshold, now,
            {"cluster_id": cid, "lag": lag},
        )

    def _eval_quorum_risk(self, cid, g, now) -> None:
        reachable = g.get("reachable")
        voters, quorum = g.get("voters"), g.get("quorum")
        if reachable is None or not voters or voters <= quorum:
            # not a check-quorum leader sample, or a group (1-2 voters)
            # that is ALWAYS one loss from quorum — no signal.  An OPEN
            # event closes here: this replica stopped being the group's
            # check-quorum leader (deposed/transferred), so its risk
            # assessment ended — the new leader's host re-opens if the
            # risk persists
            self._risk_streak.pop(cid, None)
            self._heal_streak.pop(cid, None)
            self._set("quorum_at_risk", f"group:{cid}", False, now, {})
            return
        if reachable <= quorum:
            self._risk_streak[cid] = self._risk_streak.get(cid, 0) + 1
            self._heal_streak.pop(cid, None)
        else:
            self._risk_streak.pop(cid, None)
            self._heal_streak[cid] = self._heal_streak.get(cid, 0) + 1
        key = ("quorum_at_risk", f"group:{cid}")
        if key in self._open:
            # close only on a debounced full-reachability window — the
            # check-quorum flag clear makes single samples optimistic
            active = not (
                reachable >= voters
                and self._heal_streak.get(cid, 0) >= self.quorum_risk_samples
            )
        else:
            active = self._risk_streak.get(cid, 0) >= self.quorum_risk_samples
        self._set(
            "quorum_at_risk", f"group:{cid}", active, now,
            {"cluster_id": cid, "reachable": reachable, "voters": voters,
             "quorum": quorum,
             # actuation targeting (ISSUE 17): which voters the
             # check-quorum leader cannot reach right now
             "unreachable_ids": list(g.get("unreachable_ids") or ())},
        )

    def _eval_leader_flap(self, cid, g, prev, now) -> None:
        dq = self._leader_changes.setdefault(
            cid, deque(maxlen=max(8, self.leader_flap_changes * 2))
        )
        if prev is not None and g.get("leader_id") != prev.get("leader_id"):
            # (when, who) — the leader ids seen inside the flap window
            # are actuation targeting (ISSUE 17): transfer AWAY from the
            # hosts that participated in the flap
            dq.append((now, g.get("leader_id")))
        while dq and now - dq[0][0] > self.flap_window_s:
            dq.popleft()
        recent = []
        for _, lid in dq:
            if lid and lid not in recent:
                recent.append(lid)
        self._set(
            "leader_flap", f"group:{cid}",
            len(dq) >= self.leader_flap_changes, now,
            {"cluster_id": cid, "changes": len(dq),
             "leader_id": g.get("leader_id"),
             "recent_leaders": recent},
        )

    def _eval_lease_thrash(self, cid, g, prev, now) -> None:
        lease, please = g.get("lease"), (prev or {}).get("lease")
        if lease is None:
            return
        dq = self._lease_events.setdefault(cid, deque(maxlen=64))
        if please is not None:
            delta = (
                lease.get("grants", 0) + lease.get("expiries", 0)
                - please.get("grants", 0) - please.get("expiries", 0)
            )
            for _ in range(max(0, delta)):
                dq.append(now)
        while dq and now - dq[0] > self.flap_window_s:
            dq.popleft()
        active = len(dq) >= self.lease_thrash_events
        key = ("lease_thrash", f"group:{cid}")
        if key in self._open and not active:
            # close only once the lease is actually HELD again: a
            # thrash that settled into permanently-expired has not
            # recovered, even after the event window ages out — closing
            # there would flip /healthz back to ok and record a bogus
            # recovery duration while the lease is still down
            active = not lease.get("held", False)
        self._set(
            "lease_thrash", f"group:{cid}", active, now,
            {"cluster_id": cid, "events": len(dq),
             "held": lease.get("held")},
        )

    def _eval_devsm_rebind(self, cid, g, prev, now) -> None:
        dv, pdv = g.get("devsm"), (prev or {}).get("devsm")
        if dv is None:
            return
        dq = self._devsm_binds.setdefault(cid, deque(maxlen=32))
        if pdv is not None:
            for _ in range(max(0, dv.get("binds", 0) - pdv.get("binds", 0))):
                dq.append(now)
        while dq and now - dq[0] > self.flap_window_s:
            dq.popleft()
        self._set(
            "devsm_rebind", f"group:{cid}",
            len(dq) >= self.devsm_rebind_binds, now,
            {"cluster_id": cid, "binds": len(dq), "bound": dv.get("bound")},
        )

    @staticmethod
    def _lag_tail_bucket(threshold: int) -> int:
        """First histogram bucket whose lags are all >= ``threshold``
        (the fold's exact integer log2 bucketing: bucket 0 = lag 0,
        bucket i covers [2^(i-1), 2^i), top bucket capped)."""
        b = 1
        while (1 << (b - 1)) < threshold:
            b += 1
        return b

    def _eval_telem(self, tel: dict, now) -> None:
        """Aggregate-mode detectors (ISSUE 20): ``commit_stall`` and
        ``apply_lag`` run on the device fold itself — the stalled-group
        count and the commit-lag histogram tail — under ``aggregate``
        keys, naming the top-K identities in the detail so operators
        (and the recovery plane) can drill down to specific groups.
        Only a FRESH fold advances the evaluation: an idle engine
        re-serves the same snapshot, which must neither extend streaks
        nor close open events (the partial-sample hysteresis
        contract)."""
        seq = tel.get("seq")
        if seq == self._telem_last_seq:
            return
        self._telem_last_seq = seq
        if self._telem_obs is not None:
            self._telem_obs.fold(tel)
        topk = [list(p) for p in (tel.get("topk") or ())]
        stalled = int(tel.get("stalled", 0))
        streak = self._telem_stall_streak + 1 if stalled > 0 else 0
        self._telem_stall_streak = streak
        self._set(
            "commit_stall", "aggregate",
            streak >= self.commit_stall_samples, now,
            {"stalled": stalled, "samples": streak, "topk": topk},
        )
        # histogram tail at/above the apply-lag threshold (device commit
        # lag, last_index − committed); same hysteresis rule as the
        # per-group path — an open event closes at half the threshold
        hist = list(tel.get("lag_hist") or ())
        key = ("apply_lag", "aggregate")
        threshold = (
            self.apply_lag_entries // 2
            if key in self._open else self.apply_lag_entries
        )
        tail = 0
        if hist:
            b = min(self._lag_tail_bucket(threshold), len(hist) - 1)
            tail = int(sum(hist[b:]))
        self._set(
            "apply_lag", "aggregate", tail > 0, now,
            {"groups_over": tail, "threshold": threshold, "topk": topk},
        )

    def _eval_worker_flap(self, hostproc: Optional[dict], now) -> None:
        if hostproc is None:
            return
        alive, workers = hostproc.get("alive", 0), hostproc.get("workers", 0)
        restarts = hostproc.get("restarts", 0)
        prev = self._prev_hostproc
        self._prev_hostproc = hostproc
        # a kill -9'd worker can die AND respawn inside one monitor tick
        # — faster than any sampling cadence — so a restart-counter bump
        # between samples opens the event even when liveness never dipped
        # in a sample; it closes on the next healthy sample (duration =
        # the observed outage window, lower-bounded by the cadence)
        bumped = prev is not None and restarts > prev.get("restarts", 0)
        self._set(
            "worker_flap", "host", alive < workers or bumped, now,
            {"alive": alive, "workers": workers, "restarts": restarts},
        )

    def _eval_shard_imbalance(self, coord: Optional[dict], now) -> None:
        shards = (coord or {}).get("shards")
        if not shards or len(shards) < 2:
            # single-device / non-mesh coordinator: no placement to skew
            self._imbalance_streak = 0
            self._set("shard_imbalance", "host", False, now, {})
            return
        counts = [s.get("groups", 0) for s in shards]
        loads = [float(s.get("load_ms", 0.0)) for s in shards]
        hot, cool = max(loads), min(loads)
        # cost skew needs real load on the hot shard (the EMA idles at
        # ~0 and a 0.002ms/0.0005ms ratio is noise, not imbalance);
        # count skew of a single group is the rebalancer's own dead band
        cost_skew = (
            hot >= 1e-3
            and hot > self.shard_imbalance_ratio * max(cool, 1e-6)
        )
        count_skew = max(counts) - min(counts) > 1
        streak = (
            self._imbalance_streak + 1 if (cost_skew or count_skew) else 0
        )
        self._imbalance_streak = streak
        self._set(
            "shard_imbalance", "host",
            streak >= self.shard_imbalance_samples, now,
            {"groups": counts, "load_ms": loads,
             "migrations": (coord or {}).get("migrations"),
             "samples": streak},
        )

    # ------------------------------------------------------------------
    # open/close event plumbing
    # ------------------------------------------------------------------

    def _set(self, detector: str, key: str, active: bool,
             mono: Optional[float], detail: dict) -> None:
        now = mono if mono is not None else time.monotonic()
        k = (detector, key)
        ev = self._open.get(k)
        obs = self._obs
        if active:
            if ev is None:
                ev = {
                    "detector": detector,
                    "key": key,
                    "opened_ts": time.time(),
                    "opened_mono": now,
                    "closed_ts": None,
                    "duration_s": None,
                    "detail": dict(detail),
                }
                self._open[k] = ev
                self.opened[detector] += 1
                plog.warning("health OPEN %s %s %s", detector, key, detail)
                if obs is not None:
                    obs.event_open(detector, open_count=self._open_count(detector))
                if self.recorder is not None:
                    self.recorder.record(
                        "health", detector=detector, key=key, state="open",
                        **{f"d_{k_}": v for k_, v in detail.items()},
                    )
                self._dispatch("open", ev)
            else:
                ev["detail"] = dict(detail)  # refresh while open
            return
        if ev is None:
            return
        del self._open[k]
        dur = max(0.0, now - ev["opened_mono"])
        ev["closed_ts"] = time.time()
        ev["duration_s"] = round(dur, 4)
        ev["detail"] = dict(detail) or ev["detail"]
        self._closed.append(ev)
        self._recoveries[detector].append(dur)
        plog.warning(
            "health CLOSE %s %s after %.3fs", detector, key, dur
        )
        if obs is not None:
            obs.event_close(
                detector, duration_s=dur,
                open_count=self._open_count(detector),
            )
        if self.recorder is not None:
            self.recorder.record(
                "health", detector=detector, key=key, state="close",
                recovery_ms=round(dur * 1e3, 3),
            )
        # close subscribers observe the event AFTER the duration landed
        # in the recovery attribution (ordering asserted in tests)
        self._dispatch("close", ev)

    def _open_count(self, detector: str) -> int:
        return sum(1 for d, _ in self._open if d == detector)

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------

    def __len__(self) -> int:
        return min(self._n, self.capacity)

    def samples(self) -> List[dict]:
        """Recorded samples, oldest → newest."""
        with self._mu:
            n = self._n
            if n <= self.capacity:
                return [s for s in self._buf[:n]]
            return [
                self._buf[i % self.capacity]
                for i in range(n - self.capacity, n)
            ]

    def open_events(self) -> List[dict]:
        return [dict(e) for e in self._open.values()]

    def closed_events(self) -> List[dict]:
        return [dict(e) for e in self._closed]

    def recovery_durations(self) -> Dict[str, List[float]]:
        """Raw per-detector open→close durations (seconds).  The churn
        soak merges these across hosts and recomputes fleet-level
        percentiles — per-host percentiles cannot be merged."""
        return {d: list(v) for d, v in self._recoveries.items() if v}

    def recovery_stats(self) -> Dict[str, dict]:
        """Per-detector open→close duration percentiles (seconds)."""
        out = {}
        for det, durs in self._recoveries.items():
            if not durs:
                continue
            out[det] = {
                "n": len(durs),
                "p50_s": round(_pctile(durs, 50), 4),
                "p99_s": round(_pctile(durs, 99), 4),
                "max_s": round(max(durs), 4),
            }
        return out

    def report(self) -> dict:
        """The aggregated verdict ``NodeHost.health_report`` /
        ``/healthz`` serve: ``ok`` unless any detector is open."""
        open_evs = self.open_events()
        recov = self.recovery_stats()
        attribution = {}
        for alias, det in ATTRIBUTION.items():
            if det in recov:
                attribution[f"{alias}_p50_s"] = recov[det]["p50_s"]
                attribution[f"{alias}_p99_s"] = recov[det]["p99_s"]
        return {
            "status": "degraded" if open_evs else "ok",
            "open": open_evs,
            "detectors": {
                d: {
                    "opened": self.opened[d],
                    "closed": len(self._recoveries[d]),
                    "open": self._open_count(d),
                }
                for d in DETECTORS
            },
            "recovery": recov,
            "attribution": attribution,
            "samples": self._n,
            "sample_ms": self.sample_ms,
            "aggregate": self.aggregate,
            # sampler degradation (ISSUE 20 satellite): a pass that hit
            # the raft_mu budget left busy rows — the O(G) blowup the
            # aggregate mode exists to prevent is itself detectable
            "busy_rows": self.busy_rows_total,
            "sampler_degraded": self._last_busy > 0,
        }

    def to_json(self, limit: Optional[int] = None) -> dict:
        """JSON snapshot of the ring + events (``/debug/health``, the
        bench health axis artifact, ``NodeHost.debug_dump``)."""
        samples = self.samples()
        if limit is not None and len(samples) > limit:
            samples = samples[-limit:]
        return {
            "capacity": self.capacity,
            "count": self._n,
            "sample_ms": self.sample_ms,
            "report": self.report(),
            "closed": self.closed_events(),
            "samples": samples,
        }


# ---------------------------------------------------------------------------
# live scrape endpoint
# ---------------------------------------------------------------------------


class MetricsServer:
    """Stdlib HTTP endpoint over one NodeHost (``metrics_addr``):

    ==================  ================================================
    path                serves
    ==================  ================================================
    ``/metrics``        the Prometheus text exposition, streamed as
                        chunked transfer one family at a time
                        (``iter_health_metrics``, ~16KB coalesced
                        chunks) so a high-cardinality scrape never
                        materializes the whole exposition on the
                        serving thread; HTTP/1.0 scrapers get the
                        buffered form
    ``/healthz``        the aggregated detector verdict as JSON; HTTP
                        200 while ok, 503 while any detector is open
    ``/debug/health``   the health sample ring + events (404 while the
                        sampler is off)
    ``/debug/trace``    the Chrome-trace export (404 while tracing is
                        off)
    ``/debug/devprof``  the device capacity & profiling snapshot —
                        HBM ledger, capacity model, estimator stats,
                        collected program registry (404 while devprof
                        is off)
    ``/debug/telem``    the latest device telemetry fold — lag
                        histogram, state counts, stalled count, top-K
                        worst groups (404 while the fold is off)
    ==================  ================================================

    Serves on daemon threads (``ThreadingHTTPServer``); request handling
    only READS (registry snapshot, ring copy) so a slow scraper can
    never stall the host.  Port 0 binds an ephemeral port; ``port``
    exposes the bound one (tests).  Binding a non-loopback address logs
    a warning — the exposition names clusters and peer addresses.
    """

    def __init__(self, nh, addr: str):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        host, _, port_s = addr.rpartition(":")
        if not host:
            raise ValueError(f"metrics_addr needs host:port, got {addr!r}")
        nh_ref = nh

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # no stderr chatter per scrape
                pass

            def do_GET(self):
                try:
                    _serve(nh_ref, self)
                except BrokenPipeError:
                    pass
                except Exception:
                    plog.exception("metrics endpoint request failed")
                    try:
                        self.send_error(500)
                    except Exception:
                        pass

        self._srv = ThreadingHTTPServer((host, int(port_s)), _Handler)
        self.host = self._srv.server_address[0]
        self.port = self._srv.server_address[1]
        if not (host.startswith("127.") or host in ("localhost", "::1")):
            plog.warning(
                "metrics endpoint bound to non-loopback %s:%d — the "
                "exposition names clusters and addresses; front it with "
                "auth or keep it loopback + a local scraper",
                self.host, self.port,
            )
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="dbtpu-metrics", daemon=True
        )
        self._thread.start()
        plog.info("metrics endpoint serving on %s:%d", self.host, self.port)

    def stop(self) -> None:
        try:
            self._srv.shutdown()
            self._srv.server_close()
        except Exception:
            pass
        self._thread.join(timeout=2.0)


#: coalesce streamed exposition families into chunks around this size —
#: one syscall per ~16KB instead of one per family, still never the
#: whole exposition in one string
_METRICS_CHUNK = 16384


def _serve(nh, handler) -> None:
    path = handler.path.split("?", 1)[0]
    if path == "/metrics":
        reg = getattr(getattr(nh, "raft_events", None), "registry", None)
        if reg is None or handler.request_version < "HTTP/1.1":
            # no registry handle (test doubles expose only
            # write_health_metrics) or an HTTP/1.0 scraper that cannot
            # parse chunked framing: serve the buffered form
            buf = io.StringIO()
            nh.write_health_metrics(buf)
            body = buf.getvalue().encode("utf-8")
            handler.send_response(200)
            handler.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
            return
        # streamed exposition (ISSUE 20 satellite): one family at a
        # time off the registry generator, coalesced to ~16KB chunks —
        # at high group/shard cardinality the historical single join
        # was a latency spike on the serving thread
        handler.protocol_version = "HTTP/1.1"
        handler.send_response(200)
        handler.send_header(
            "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
        )
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()
        w = handler.wfile
        pending: list = []
        size = 0
        for part in reg.iter_health_metrics():
            pending.append(part)
            size += len(part)
            if size >= _METRICS_CHUNK:
                data = "".join(pending).encode("utf-8")
                w.write(b"%x\r\n%s\r\n" % (len(data), data))
                pending, size = [], 0
        if pending:
            data = "".join(pending).encode("utf-8")
            w.write(b"%x\r\n%s\r\n" % (len(data), data))
        w.write(b"0\r\n\r\n")
        return
    if path == "/healthz":
        report = nh.health_report()
        body = json.dumps(report, default=str).encode("utf-8")
        handler.send_response(200 if report.get("status") == "ok" else 503)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
        return
    if path == "/debug/health":
        sampler = nh.health
        if sampler is None:
            handler.send_error(404, "health sampling is off")
            return
        body = json.dumps(sampler.to_json(), default=str).encode("utf-8")
        handler.send_response(200)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
        return
    if path == "/debug/trace":
        tracer = nh.tracer
        if tracer is None:
            handler.send_error(404, "tracing is off")
            return
        body = json.dumps(
            tracer.export_chrome(), default=str
        ).encode("utf-8")
        handler.send_response(200)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
        return
    if path == "/debug/devprof":
        devprof = getattr(nh, "devprof", None)
        if devprof is None:
            handler.send_error(404, "device profiling is off")
            return
        # read-only by contract: to_json never triggers compiles or
        # capture windows — a scraper can poll this freely
        body = json.dumps(devprof.to_json(), default=str).encode("utf-8")
        handler.send_response(200)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
        return
    if path == "/debug/telem":
        qc = getattr(nh, "quorum_coordinator", None)
        if qc is None or not getattr(qc, "telem_enabled", False):
            handler.send_error(404, "device telemetry is off")
            return
        # read-only by contract: telem_snapshot is the latest harvested
        # fold (None until the first telem-on dispatch lands) — a
        # scraper can poll this freely, it never triggers a dispatch
        body = json.dumps(
            {"enabled": True, "snapshot": qc.telem_snapshot()},
            default=str,
        ).encode("utf-8")
        handler.send_response(200)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
        return
    handler.send_error(404)
