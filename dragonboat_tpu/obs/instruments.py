"""Engine/coordinator instruments: device-plane metric families.

``EngineObs`` and ``CoordObs`` hold a :class:`FlightRecorder` plus the
:class:`dragonboat_tpu.events.MetricsRegistry` the metrics publish into
(default: the process registry ``events.DEFAULT_REGISTRY``, the same one
``write_health_metrics`` exposes).  Every family is zero-registered at
construction, so the exposition shows the device plane the moment obs is
enabled — a scrape distinguishes "obs off" (families absent) from "obs
on, idle" (families at zero).

Families (device plane, published by ``EngineObs``):

- ``dragonboat_device_dispatch_total`` — device dispatches launched
- ``dragonboat_device_rounds_total`` — scanned rounds across dispatches
- ``dragonboat_device_dispatch_latency_ms`` — host stage+launch wall
  time histogram
- ``dragonboat_device_egress_latency_ms`` — blocking egress wall time
  histogram
- ``dragonboat_device_acks_staged_total`` / ``…votes_staged_total`` —
  events ingested
- ``dragonboat_device_recycles_total`` — in-program membership recycles
- ``dragonboat_device_reads_staged_total`` / ``…read_echoes_total`` /
  ``…reads_released_total`` — read-plane traffic
- ``dragonboat_device_upload_bytes_total`` — host→device event tensors
- ``dragonboat_device_egress_rows_total`` — rows whose commit advanced
- ``dragonboat_device_multidev_wait_ms_total`` — multi-device dispatch
  lock wait (zero on single-device / mesh-sharded engines)
- ``dragonboat_device_stalls_total`` — watchdog-flagged spans
- ``dragonboat_device_warmup_seconds`` / ``…warmup_programs_total`` —
  AOT warm-compile wall time and programs warmed (ISSUE 7)
- gauges: ``dragonboat_device_staged_rounds`` (egress/dispatch queue
  depth), ``dragonboat_device_read_slots_in_use``
- ``dragonboat_devsm_ops_staged_total`` / ``…applied_total`` /
  ``…reads_staged_total`` / ``…reads_served_total`` + gauge
  ``…slot_occupancy`` — device state machine traffic (ISSUE 11), spanned
  by the ``apply_kernel`` flight-recorder kind

Coordinator plane (``CoordObs``): ``dragonboat_coord_rounds_total``,
``…round_latency_ms`` (histogram), ``…ops_drained_total``,
``…tick_deficit_total``, ``…commits_offloaded_total``,
``…reads_confirmed_total``, ``…fused_dispatch_total`` /
``…fused_rounds_total`` (adaptive-K live batching); gauges
``…staged_depth``, ``…read_fallbacks``.  Node offload application
counts under ``dragonboat_node_offload_applied_total{kind=…}``
(node.py).
"""
from __future__ import annotations

from typing import Optional

from ..events import DEFAULT_BUCKETS, DEFAULT_REGISTRY, MetricsRegistry
from .recorder import FlightRecorder

#: log-spaced dispatch/egress/round latency buckets (ms): the live
#: coordinator's single-round dispatches sit near the bottom decade, a
#: first-use XLA compile or a wedged tunnel at the top.  ONE geometry,
#: shared with the registry default — histogram bucket sets are
#: first-declare-wins, so a second copy that drifted would be silently
#: ignored for already-declared families.
LATENCY_BUCKETS_MS = DEFAULT_BUCKETS

_DEV = "dragonboat_device_"
_COORD = "dragonboat_coord_"
_HOST = "dragonboat_host_"
_HPROC = "dragonboat_hostproc_"
_DEVSM = "dragonboat_devsm_"
_HEALTH = "dragonboat_health_"
_REPL = "dragonboat_repl_"
_DEVPROF = "dragonboat_devprof_"
_MESH = "dragonboat_mesh_"
_RECOV = "dragonboat_recovery_"
_TELEM = "dragonboat_telem_"

#: recovery-duration buckets (seconds): a worker respawn lands near the
#: bottom, a failover around election timeouts, a wedged rebind loop or
#: an unhealed netsplit at the top
RECOVERY_BUCKETS_S = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

#: ``# HELP`` text per family (ISSUE 9 satellite: the exposition was
#: ``# TYPE``-only).  Families not listed fall back to the registry's
#: deterministic placeholder.
_HELP = {
    _HPROC + "workers_alive": "host-plane worker processes currently "
    "alive (spawned minus crashed/stopped)",
    _HPROC + "worker_restarts_total": "worker processes respawned after "
    "a crash/exit (bounded per worker; exhausted lanes stay in-process)",
    _HPROC + "ring_depth": "bytes staged across every shared-memory "
    "ring (request + response), sampled by the monitor",
    _HPROC + "ring_full_total": "ring pushes that stayed full past the "
    "busy window and raised SystemBusy, by role",
    _HPROC + "fallbacks_total": "stage executions that fell back "
    "in-process (worker gone/busy), by role",
    _HPROC + "calls_total": "completed worker round trips, by role",
    _HPROC + "worker_wall_ms": "worker-side execution wall time per "
    "round trip (the stage work done off the serving process), by role",
    _DEV + "dispatch_total": "device programs launched",
    _DEV + "rounds_total": "scanned rounds across device dispatches",
    _DEV + "acks_staged_total": "replicate acks ingested by dispatches",
    _DEV + "votes_staged_total": "votes ingested by dispatches",
    _DEV + "recycles_total": "in-program membership recycles",
    _DEV + "reads_staged_total": "ReadIndex batches staged on device",
    _DEV + "read_echoes_total": "heartbeat read-echoes staged on device",
    _DEV + "reads_released_total": "client reads released by confirmed slots",
    _DEV + "upload_bytes_total": "host-to-device event tensor bytes",
    _DEV + "egress_rows_total": "rows whose commit watermark advanced",
    _DEV + "multidev_wait_ms_total": "milliseconds waiting on the "
    "engine's multi-device dispatch lock (zero on single-device and "
    "mesh-sharded engines)",
    _DEV + "stalls_total": "stall-watchdog-flagged dispatch spans",
    _DEV + "warmup_seconds": "wall seconds spent AOT warm-compiling",
    _DEV + "warmup_programs_total": "device programs AOT warm-compiled",
    _DEV + "staged_rounds": "egress/dispatch round queue depth",
    _DEV + "read_slots_in_use": "pending-read engine slots occupied",
    _DEV + "dispatch_latency_ms": "host stage+launch wall time per dispatch",
    _DEV + "egress_latency_ms": "blocking device-to-host egress wall time",
    _COORD + "rounds_total": "coordinator rounds dispatched",
    _COORD + "round_latency_ms": "whole-round wall time",
    _COORD + "ops_drained_total": "staged ops drained into the engine",
    _COORD + "tick_deficit_total": "host ticks replayed by rounds",
    _COORD + "commits_offloaded_total": "group commits offloaded to nodes",
    _COORD + "reads_confirmed_total": "ReadIndex ctxs confirmed on device",
    _COORD + "fused_dispatch_total": "rounds served by one fused dispatch",
    _COORD + "fused_rounds_total": "rounds carried by fused dispatches",
    _COORD + "staged_depth": "ops staged for the next round",
    _COORD + "read_fallbacks": "read echoes tallied scalar-side",
    _HOST + "ingress_submitted_total": "commands accepted into ingress rings",
    _HOST + "ingress_drains_total": "ingress batcher drain cycles",
    _HOST + "ingress_drained_total": "commands drained by the batcher",
    _HOST + "ingress_ring_depth": "commands still ringed after a drain",
    _HOST + "wal_flushes_total": "group-commit WAL flush cycles",
    _HOST + "wal_riders_total": "committer submissions merged into cycles",
    _HOST + "wal_updates_total": "raft updates persisted by the WAL tier",
    _HOST + "wal_amortization": "committer submissions per fsync cycle",
    _HOST + "wal_flush_latency_ms": "merged save+fsync wall time",
    _HOST + "apply_batches_total": "decoupled apply executor wakeups",
    _HOST + "apply_groups_total": "groups covered by apply batches",
    _HOST + "egress_notified_total": "client completions delivered off-worker",
    # device state machine (devsm, ISSUE 11)
    _DEVSM + "ops_staged_total": "KV entry ops staged into device buffers",
    _DEVSM + "applied_total": "KV ops applied by the in-program fold",
    _DEVSM + "reads_staged_total": "KV reads staged for device capture",
    _DEVSM + "reads_served_total": "KV reads served from device state",
    _DEVSM + "slot_occupancy": "entry-buffer slots holding unapplied ops",
    # cluster health plane (obs/health.py, ISSUE 13)
    _HEALTH + "samples_total": "health samples taken by the tick-worker "
    "cadence",
    _HEALTH + "sample_ms": "wall milliseconds one health sample cost "
    "(the sampler-overhead evidence)",
    _HEALTH + "groups": "raft groups covered by the last health sample",
    _HEALTH + "events_total": "health detector OPEN events, by detector",
    _HEALTH + "open": "health events currently open, by detector",
    _HEALTH + "recovery_seconds": "open-to-close durations per detector "
    "(leader_flap = failover, worker_flap = worker respawn, "
    "devsm_rebind = device rebind — the recovery-time attribution)",
    # replication attribution (obs/replattr.py, ISSUE 14)
    _REPL + "ack_rtt_seconds": "sampled replication send-to-ack round "
    "trip per peer (leader clock), labeled by latency class",
    _REPL + "stage_seconds": "quorum-closing path's stage decomposition "
    "(wire_out / follower_append / follower_fsync / ack_send / "
    "wire_back), clock-offset corrected so stages sum to the RTT",
    _REPL + "quorum_close_seconds": "replicate fan-out to quorum close "
    "per sampled commit (the kth voter's ack, try_commit's own "
    "kth_largest rule)",
    _REPL + "quorum_closer_total": "sampled commits whose quorum this "
    "peer's ack closed, by peer and latency class",
    _REPL + "laggard_total": "sampled commits this peer had NOT acked "
    "when the quorum closed, by peer and latency class",
    _REPL + "commits_attributed_total": "sampled commits closed with a "
    "full attribution record",
    _REPL + "records_dropped_total": "attribution records dropped "
    "before closing (term change, transition reset, overflow, expiry)",
    _REPL + "clock_offset_ms": "latest NTP-style ack-pair clock-offset "
    "estimate per peer (follower minus leader milliseconds)",
    # device capacity & profiling plane (obs/devprof.py, ISSUE 15)
    _DEVPROF + "hbm_bytes": "device-resident bytes per state artifact "
    "(the HBM ledger), by plane and artifact",
    _DEVPROF + "hbm_plane_bytes": "device-resident bytes per plane "
    "(quorum / read / devsm / dispatch)",
    _DEVPROF + "bytes_per_group": "resident state bytes one group row "
    "costs (the capacity model's extrapolation base)",
    _DEVPROF + "capacity_groups": "predicted max groups per device from "
    "the capacity model (0 = no memory budget known for this backend)",
    _DEVPROF + "model_error_pct": "capacity-model prediction vs "
    "actually-allocated resident bytes, percent",
    _DEVPROF + "device_ms": "sampled post-launch block_until_ready "
    "delta per dispatch — the device-execution estimate the host "
    "dispatch wall does not separate",
    _DEVPROF + "duty_cycle": "estimated device busy fraction over the "
    "last sampling window (sampled device time x stride / wall, "
    "clamped to 1)",
    _DEVPROF + "dispatches_total": "dispatches seen by the profiling "
    "plane",
    _DEVPROF + "sampled_total": "dispatches whose device time was "
    "sampled (1-in-N block_until_ready)",
    _DEVPROF + "padded_rounds_total": "rounds shipped inside fused "
    "K-batched programs (padded program K)",
    _DEVPROF + "wasted_rounds_total": "provable no-op padding rounds "
    "(padded K minus live/ticked rounds) — measurable wasted device work",
    _DEVPROF + "padding_waste_ratio": "wasted over padded rounds across "
    "the plane's lifetime",
    _DEVPROF + "programs": "warm-set programs analyzed by the registry",
    _DEVPROF + "program_compile_ms": "AOT lower+compile wall per "
    "analyzed program (cache-hot compiles deserialize)",
    _DEVPROF + "program_flops": "XLA cost-analysis flops per warmed "
    "program, by variant",
    _DEVPROF + "program_bytes": "XLA cost-analysis bytes accessed per "
    "warmed program, by variant",
    _DEVPROF + "program_temp_bytes": "XLA peak temp allocation per "
    "warmed program, by variant",
    _DEVPROF + "captures_total": "on-demand jax.profiler capture "
    "windows started",
    _DEVPROF + "capture_active": "1 while a capture window is recording",
    # mesh dispatch plane (ops/mesh.py, ISSUE 16)
    _MESH + "shards": "per-shard engines behind the mesh dispatch plane",
    _MESH + "groups": "raft groups currently placed on the shard, by "
    "shard (the live group-to-shard assignment table)",
    _MESH + "migrations_total": "groups migrated between shards by the "
    "cost-driven placement pass (stage-out/stage-in, watermarks "
    "preserved)",
    _MESH + "migration_ms": "stage-out to stage-in wall time per group "
    "migration",
    _MESH + "dispatch_concurrency": "shard dispatch streams observed "
    "simultaneously in flight per fan-out (the no-global-mutex "
    "evidence: >1 means two shards dispatched concurrently)",
    # closed-loop recovery plane (obs/recovery.py, ISSUE 17)
    _RECOV + "actions_total": "remediations the RecoveryController "
    "executed, by detector and action (evict_dead / promote_standby / "
    "transfer_leader / devsm_release / fastlane_redrive)",
    _RECOV + "dryrun_total": "remediations the controller WOULD have "
    "executed but only logged (dry-run mode), by detector and action",
    _RECOV + "skipped_total": "open events the controller declined to "
    "act on, by reason (not_leader / rate_limited / cooldown / "
    "suppressed / observe_only / no_target)",
    _RECOV + "suppressed_keys": "detector keys currently flap-damped "
    "(an action re-opened its detector max_reopens times), by detector",
    _RECOV + "failures_total": "remediations that raised or timed out, "
    "by detector and action",
    _RECOV + "action_seconds": "wall seconds one executed remediation "
    "took (decide-to-commit, e.g. config-change round trip), by action",
    # device telemetry fold (ops/kernels.py telem_fold, ISSUE 20)
    _TELEM + "folds_total": "device telemetry aggregates published to "
    "the health sampler (one fixed-size fold per harvested dispatch)",
    _TELEM + "groups": "live device-backed groups per raft state in the "
    "last fold, by state (follower / candidate / leader / observer / "
    "witness)",
    _TELEM + "stalled_groups": "groups whose commit watermark stayed "
    "flat since the previous fold despite pending appended entries",
    _TELEM + "commit_lag": "groups per log2 commit-lag bucket "
    "(last_index minus committed) in the last fold, by bucket lower "
    "bound",
    _TELEM + "worst_lag": "largest commit lag across live groups in the "
    "last fold (the top-K drill-down's first row)",
    _TELEM + "read_slots": "engine read-plane slots occupied in the "
    "last fold",
    _TELEM + "kv_ents": "devsm entry-buffer slots holding unapplied ops "
    "in the last fold",
    _HEALTH + "busy_rows_total": "per-group sample rows skipped because "
    "the raft_mu walk hit its budget mid-pass (nonzero means the "
    "sampler is degrading at this group count — the silent-O(G) "
    "blowup detector)",
}


def _describe(registry: MetricsRegistry, names) -> None:
    for name in names:
        text = _HELP.get(name)
        if text:
            registry.describe(name, text)


class EngineObs:
    """Device-plane instruments for one ``BatchedQuorumEngine``.

    The engine keeps ``self._obs = None`` until ``enable_obs``; every
    hot-path call site is gated on that ``is not None`` check, so the
    obs-off host path stays bit-identical (module docstring contract).
    """

    __slots__ = ("recorder", "registry", "shard")

    _COUNTERS = (
        _DEV + "dispatch_total",
        _DEV + "rounds_total",
        _DEV + "acks_staged_total",
        _DEV + "votes_staged_total",
        _DEV + "recycles_total",
        _DEV + "reads_staged_total",
        _DEV + "read_echoes_total",
        _DEV + "reads_released_total",
        _DEV + "upload_bytes_total",
        _DEV + "egress_rows_total",
        _DEV + "multidev_wait_ms_total",
        _DEV + "stalls_total",
        # AOT warm-compile (ISSUE 7): wall seconds spent pre-compiling
        # device programs and how many were warmed — the "warm-enable
        # cost" column of the perf ledger reads these
        _DEV + "warmup_seconds",
        _DEV + "warmup_programs_total",
        # device state machine (devsm, ISSUE 11): staged vs applied KV
        # entry ops and the reads the plane served — applied/staged
        # converging is the "apply rides the commit dispatch" invariant,
        # reads_served is the zero-host-apply read traffic
        _DEVSM + "ops_staged_total",
        _DEVSM + "applied_total",
        _DEVSM + "reads_staged_total",
        _DEVSM + "reads_served_total",
    )

    def __init__(
        self,
        recorder: FlightRecorder,
        registry: Optional[MetricsRegistry] = None,
        shard: Optional[int] = None,
    ):
        self.recorder = recorder
        self.registry = registry or DEFAULT_REGISTRY
        #: shard index when the engine is one shard of a mesh dispatch
        #: plane — stamped into dispatch spans so the ring shows which
        #: stream launched what (the span-overlap evidence keys on it)
        self.shard = shard
        r = self.registry
        _describe(r, self._COUNTERS + (
            _DEV + "staged_rounds", _DEV + "read_slots_in_use",
            _DEV + "dispatch_latency_ms", _DEV + "egress_latency_ms",
            _DEVSM + "slot_occupancy",
        ))
        for name in self._COUNTERS:
            r.counter_add(name, 0)
        r.gauge_set(_DEV + "staged_rounds", 0)
        r.gauge_set(_DEV + "read_slots_in_use", 0)
        r.gauge_set(_DEVSM + "slot_occupancy", 0)
        r.histogram_declare(
            _DEV + "dispatch_latency_ms", buckets=LATENCY_BUCKETS_MS
        )
        r.histogram_declare(
            _DEV + "egress_latency_ms", buckets=LATENCY_BUCKETS_MS
        )

    def warmup(self, *, variant: str, seconds: float) -> dict:
        """One AOT-warmed device program (engine ``_warmup_main``):
        accumulate ``dragonboat_device_warmup_seconds`` and record a
        ``warmup`` span.  The compile wall time deliberately lands in a
        field the stall watchdog does NOT inspect (``compile_ms``) — a
        multi-second warm compile is the expected out-of-band cost, not
        a stall, and must not trigger an auto-dump."""
        r = self.registry
        r.counter_add(_DEV + "warmup_seconds", seconds)
        r.counter_add(_DEV + "warmup_programs_total")
        return self.recorder.record(
            "warmup",
            variant=variant,
            compile_ms=round(seconds * 1e3, 4),
        )

    def apply_kernel(
        self, *, ops: int, reads: int, rounds: int, slot_occupancy: int
    ) -> dict:
        """One dispatch's devsm work launched (the ``apply_kernel`` span
        kind, ISSUE 11): staged entry ops and KV reads riding the fused
        program, plus the host view of entry-buffer occupancy.  The
        applied/served counts land at harvest via :meth:`devsm_egress` —
        the fold runs inside the same program as the commit advancement,
        so the span brackets exactly the apply stage the host no longer
        runs."""
        r = self.registry
        if ops:
            r.counter_add(_DEVSM + "ops_staged_total", ops)
        if reads:
            r.counter_add(_DEVSM + "reads_staged_total", reads)
        r.gauge_set(_DEVSM + "slot_occupancy", slot_occupancy)
        return self.recorder.record(
            "apply_kernel",
            ops=ops,
            reads=reads,
            rounds=rounds,
            slot_occupancy=slot_occupancy,
        )

    def devsm_egress(self, span: dict, *, applied: int, reads_served: int) -> None:
        """Close an ``apply_kernel`` span at harvest: what the fold
        applied and how many KV reads came back captured."""
        r = self.registry
        if applied:
            r.counter_add(_DEVSM + "applied_total", applied)
        if reads_served:
            r.counter_add(_DEVSM + "reads_served_total", reads_served)
        self.recorder.update(
            span, applied=applied, reads_served=reads_served
        )

    def dispatch(
        self,
        kind: str,
        *,
        rounds: int,
        acks: int,
        votes: int,
        recycles: int,
        reads: int,
        echoes: int,
        upload_bytes: int,
        dispatch_ms: float,
        gate: str,
        k_rounds: Optional[int] = None,
        mu_wait_ms: float = 0.0,
        pending_rounds: int = 0,
        read_slots_in_use: Optional[int] = None,
        n_dispatches: int = 1,
    ) -> dict:
        """One logical step's device work launched: publish counters +
        latency, and open its span (egress fields land via
        :meth:`egress`).  ``n_dispatches`` counts the actual device
        programs — an oversized sparse backlog chunks into several per
        step — so ``dispatch_total`` tracks programs, not steps.
        ``k_rounds`` is the LIVE round count of the block (real staged
        rounds, or ticked rounds when a deficit replay ticks into the
        padding) vs ``rounds``, the padded program K."""
        r = self.registry
        r.counter_add(_DEV + "dispatch_total", n_dispatches)
        r.counter_add(_DEV + "rounds_total", rounds)
        if acks:
            r.counter_add(_DEV + "acks_staged_total", acks)
        if votes:
            r.counter_add(_DEV + "votes_staged_total", votes)
        if recycles:
            r.counter_add(_DEV + "recycles_total", recycles)
        if reads:
            r.counter_add(_DEV + "reads_staged_total", reads)
        if echoes:
            r.counter_add(_DEV + "read_echoes_total", echoes)
        if upload_bytes:
            r.counter_add(_DEV + "upload_bytes_total", upload_bytes)
        if mu_wait_ms:
            r.counter_add(_DEV + "multidev_wait_ms_total", mu_wait_ms)
        r.histogram_observe(
            _DEV + "dispatch_latency_ms", dispatch_ms,
            buckets=LATENCY_BUCKETS_MS,
        )
        r.gauge_set(_DEV + "staged_rounds", pending_rounds)
        if read_slots_in_use is not None:
            r.gauge_set(_DEV + "read_slots_in_use", read_slots_in_use)
        stalls = self.recorder.stalls
        extra = {"dispatches": n_dispatches} if n_dispatches > 1 else {}
        if k_rounds is not None:
            extra["k_rounds"] = k_rounds
        if self.shard is not None:
            extra["shard"] = self.shard
        span = self.recorder.record(
            kind,
            gate=gate,
            rounds=rounds,
            **extra,
            acks=acks,
            votes=votes,
            recycles=recycles,
            reads=reads,
            echoes=echoes,
            upload_bytes=upload_bytes,
            dispatch_ms=round(dispatch_ms, 4),
            mu_wait_ms=round(mu_wait_ms, 4),
        )
        if self.recorder.stalls != stalls:
            r.counter_add(_DEV + "stalls_total")
        return span

    def egress(
        self, span: dict, *, egress_ms: float, egress_rows: int,
        reads_released: int,
    ) -> None:
        """Close a dispatch span at harvest: blocking egress wall time
        plus what the block released."""
        r = self.registry
        r.histogram_observe(
            _DEV + "egress_latency_ms", egress_ms, buckets=LATENCY_BUCKETS_MS
        )
        if egress_rows:
            r.counter_add(_DEV + "egress_rows_total", egress_rows)
        if reads_released:
            r.counter_add(_DEV + "reads_released_total", reads_released)
        stalls = self.recorder.stalls
        self.recorder.update(
            span,
            egress_ms=round(egress_ms, 4),
            egress_rows=egress_rows,
            reads_released=reads_released,
        )
        if self.recorder.stalls != stalls:
            r.counter_add(_DEV + "stalls_total")


class HostObs:
    """Compartmentalized host-plane instruments (hostplane.py, ISSUE 8).

    Families (``dragonboat_host_*``):

    - ``ingress_submitted_total`` / ``ingress_drains_total`` /
      ``ingress_drained_total`` — ring traffic; drained/drains is the
      drain batch size (the batcher's amortization)
    - gauge ``ingress_ring_depth`` — staged commands still ringed at the
      end of a drain
    - ``wal_flushes_total`` / ``wal_riders_total`` /
      ``wal_updates_total`` — group-commit flusher cycles, committer
      submissions merged per cycle (riders/flushes = the fsync
      amortization factor, published as gauge ``wal_amortization``) and
      raft updates persisted
    - histogram ``wal_flush_latency_ms`` — merged save+fsync wall time
    - ``apply_batches_total`` / ``apply_groups_total`` — decoupled apply
      executor wakeups and the groups they covered
    - ``egress_notified_total`` — client completions delivered off the
      apply workers

    Stage spans land in the shared flight recorder (``ingress_drain`` /
    ``wal_flush`` kinds) next to the device-plane spans; the same
    ``is not None`` latch keeps the obs-off host plane bit-identical.
    """

    __slots__ = ("recorder", "registry")

    _COUNTERS = (
        _HOST + "ingress_submitted_total",
        _HOST + "ingress_drains_total",
        _HOST + "ingress_drained_total",
        _HOST + "wal_flushes_total",
        _HOST + "wal_riders_total",
        _HOST + "wal_updates_total",
        _HOST + "apply_batches_total",
        _HOST + "apply_groups_total",
        _HOST + "egress_notified_total",
    )

    def __init__(
        self,
        recorder: Optional[FlightRecorder] = None,
        registry: Optional[MetricsRegistry] = None,
    ):
        from . import default_recorder

        self.recorder = recorder or default_recorder()
        self.registry = registry or DEFAULT_REGISTRY
        r = self.registry
        _describe(r, self._COUNTERS + (
            _HOST + "ingress_ring_depth", _HOST + "wal_amortization",
            _HOST + "wal_flush_latency_ms",
        ))
        for name in self._COUNTERS:
            r.counter_add(name, 0)
        r.gauge_set(_HOST + "ingress_ring_depth", 0)
        r.gauge_set(_HOST + "wal_amortization", 0)
        r.histogram_declare(
            _HOST + "wal_flush_latency_ms", buckets=LATENCY_BUCKETS_MS
        )

    def ingress_submit(self, n: int) -> None:
        self.registry.counter_add(_HOST + "ingress_submitted_total", n)

    def ingress_drain(
        self, *, groups: int, cmds: int, wall_ms: float, ring_depth: int
    ) -> dict:
        r = self.registry
        r.counter_add(_HOST + "ingress_drains_total")
        if cmds:
            r.counter_add(_HOST + "ingress_drained_total", cmds)
        r.gauge_set(_HOST + "ingress_ring_depth", ring_depth)
        return self.recorder.record(
            "ingress_drain",
            groups=groups,
            cmds=cmds,
            wall_ms=round(wall_ms, 4),
        )

    def wal_flush(
        self, *, riders: int, updates: int, wall_ms: float,
        amortization: float,
    ) -> dict:
        r = self.registry
        r.counter_add(_HOST + "wal_flushes_total")
        r.counter_add(_HOST + "wal_riders_total", riders)
        if updates:
            r.counter_add(_HOST + "wal_updates_total", updates)
        r.gauge_set(_HOST + "wal_amortization", round(amortization, 3))
        r.histogram_observe(
            _HOST + "wal_flush_latency_ms", wall_ms,
            buckets=LATENCY_BUCKETS_MS,
        )
        return self.recorder.record(
            "wal_flush",
            riders=riders,
            updates=updates,
            wall_ms=round(wall_ms, 4),
        )

    def apply_batch(self, *, groups: int) -> None:
        r = self.registry
        r.counter_add(_HOST + "apply_batches_total")
        if groups:
            r.counter_add(_HOST + "apply_groups_total", groups)

    def egress_batch(self, n: int) -> None:
        if n:
            self.registry.counter_add(_HOST + "egress_notified_total", n)


class HostProcObs:
    """Multi-process host-tier instruments (hostproc/, ISSUE 12).

    Families (``dragonboat_hostproc_*``):

    - gauge ``workers_alive`` — worker processes currently alive
    - ``worker_restarts_total`` — crash respawns (the monitor's bounded
      restart path)
    - gauge ``ring_depth`` — bytes staged across all shared-memory
      rings, sampled by the monitor thread
    - ``ring_full_total{role}`` — sustained-full pushes that raised
      SystemBusy
    - ``fallbacks_total{role}`` — stage executions that fell back
      in-process (worker gone/busy)
    - ``calls_total{role}`` — completed worker round trips
    - histogram ``worker_wall_ms{role}`` — worker-side execution wall
      per round trip (the per-stage worker wall the latency attribution
      table wants next to the ``ipc`` trace stage)

    Same ``is not None`` latch contract as every other plane: obs off
    keeps the hostproc hot path bit-identical.
    """

    __slots__ = ("registry",)

    _ROLES = ("encode", "wal", "apply")

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry or DEFAULT_REGISTRY
        r = self.registry
        _describe(r, (
            _HPROC + "workers_alive", _HPROC + "worker_restarts_total",
            _HPROC + "ring_depth", _HPROC + "ring_full_total",
            _HPROC + "fallbacks_total", _HPROC + "calls_total",
            _HPROC + "worker_wall_ms",
        ))
        r.gauge_set(_HPROC + "workers_alive", 0)
        r.gauge_set(_HPROC + "ring_depth", 0)
        r.counter_add(_HPROC + "worker_restarts_total", 0)
        for role in self._ROLES:
            labels = {"role": role}
            r.counter_add(_HPROC + "ring_full_total", 0, labels=labels)
            r.counter_add(_HPROC + "fallbacks_total", 0, labels=labels)
            r.counter_add(_HPROC + "calls_total", 0, labels=labels)
            r.histogram_declare(
                _HPROC + "worker_wall_ms", buckets=LATENCY_BUCKETS_MS,
                labels=labels,
            )

    def workers_alive(self, n: int) -> None:
        self.registry.gauge_set(_HPROC + "workers_alive", n)

    def restart(self) -> None:
        self.registry.counter_add(_HPROC + "worker_restarts_total")

    def ring_depth(self, n: int) -> None:
        self.registry.gauge_set(_HPROC + "ring_depth", n)

    def ring_full(self, role: str) -> None:
        self.registry.counter_add(
            _HPROC + "ring_full_total", labels={"role": role}
        )

    def fallback(self, role: str) -> None:
        self.registry.counter_add(
            _HPROC + "fallbacks_total", labels={"role": role}
        )

    def call(self, role: str, wall_ms: float) -> None:
        labels = {"role": role}
        r = self.registry
        r.counter_add(_HPROC + "calls_total", labels=labels)
        r.histogram_observe(
            _HPROC + "worker_wall_ms", wall_ms,
            buckets=LATENCY_BUCKETS_MS, labels=labels,
        )


class HealthObs:
    """Cluster-health-plane instruments (obs/health.py, ISSUE 13).

    Families (``dragonboat_health_*``):

    - ``samples_total`` + histogram ``sample_ms`` — sampling cadence and
      per-sample wall cost (the overhead evidence next to the bench
      axis's <5% assertion)
    - gauge ``groups`` — groups covered by the last sample
    - ``events_total{detector}`` — detector OPEN events
    - gauge ``open{detector}`` — events currently open (the ``/healthz``
      verdict is "degraded" whenever any is nonzero)
    - histogram ``recovery_seconds{detector}`` — open→close durations:
      the recovery-time attribution (failover / worker-respawn /
      devsm-rebind p99s the perf ledger publishes)
    - ``busy_rows_total`` — per-group rows skipped by the raft_mu
      budget mid-walk (ISSUE 20 satellite: sampler degradation must be
      itself detectable, not silent)

    Same ``is not None`` latch contract as every other plane: health off
    registers none of this.
    """

    __slots__ = ("registry",)

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 detectors=()):
        self.registry = registry or DEFAULT_REGISTRY
        r = self.registry
        _describe(r, (
            _HEALTH + "samples_total", _HEALTH + "sample_ms",
            _HEALTH + "groups", _HEALTH + "events_total",
            _HEALTH + "open", _HEALTH + "recovery_seconds",
            _HEALTH + "busy_rows_total",
        ))
        r.counter_add(_HEALTH + "samples_total", 0)
        r.counter_add(_HEALTH + "busy_rows_total", 0)
        r.gauge_set(_HEALTH + "groups", 0)
        r.histogram_declare(_HEALTH + "sample_ms", buckets=LATENCY_BUCKETS_MS)
        for det in detectors:
            labels = {"detector": det}
            r.counter_add(_HEALTH + "events_total", 0, labels=labels)
            r.gauge_set(_HEALTH + "open", 0, labels=labels)
            r.histogram_declare(
                _HEALTH + "recovery_seconds", buckets=RECOVERY_BUCKETS_S,
                labels=labels,
            )

    def sample(self, *, wall_ms: float, groups: int) -> None:
        r = self.registry
        r.counter_add(_HEALTH + "samples_total")
        r.gauge_set(_HEALTH + "groups", groups)
        r.histogram_observe(
            _HEALTH + "sample_ms", wall_ms, buckets=LATENCY_BUCKETS_MS
        )

    def busy_rows(self, n: int) -> None:
        if n:
            self.registry.counter_add(_HEALTH + "busy_rows_total", n)

    def event_open(self, detector: str, *, open_count: int) -> None:
        labels = {"detector": detector}
        r = self.registry
        r.counter_add(_HEALTH + "events_total", labels=labels)
        r.gauge_set(_HEALTH + "open", open_count, labels=labels)

    def event_close(self, detector: str, *, duration_s: float,
                    open_count: int) -> None:
        labels = {"detector": detector}
        r = self.registry
        r.gauge_set(_HEALTH + "open", open_count, labels=labels)
        r.histogram_observe(
            _HEALTH + "recovery_seconds", duration_s,
            buckets=RECOVERY_BUCKETS_S, labels=labels,
        )


class TelemObs:
    """Device-telemetry-fold instruments (ops/kernels.py ``telem_fold``,
    ISSUE 20).

    Families (``dragonboat_telem_*``), all refreshed from the latest
    harvested aggregate — snapshots of the device fold, not host-side
    accumulation:

    - ``folds_total`` — aggregates published to the sampler
    - gauge ``groups{state}`` — live groups per raft state
    - gauge ``stalled_groups`` — commit watermark flat with pending work
    - gauge ``commit_lag{bucket}`` — log2 lag histogram, labeled by the
      bucket's lower bound (``0``, ``1``, ``2``, ``4`` … capped top)
    - gauge ``worst_lag`` — the top-K drill-down's first row
    - gauge ``read_slots`` / ``kv_ents`` — plane slot occupancy

    Same ``is not None`` latch contract as every other plane: aggregate
    sampling off registers none of this.
    """

    __slots__ = ("registry", "_bucket_labels")

    _STATES = ("follower", "candidate", "leader", "observer", "witness")

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 buckets: int = 16):
        self.registry = registry or DEFAULT_REGISTRY
        r = self.registry
        _describe(r, (
            _TELEM + "folds_total", _TELEM + "groups",
            _TELEM + "stalled_groups", _TELEM + "commit_lag",
            _TELEM + "worst_lag", _TELEM + "read_slots",
            _TELEM + "kv_ents",
        ))
        r.counter_add(_TELEM + "folds_total", 0)
        r.gauge_set(_TELEM + "stalled_groups", 0)
        r.gauge_set(_TELEM + "worst_lag", 0)
        r.gauge_set(_TELEM + "read_slots", 0)
        r.gauge_set(_TELEM + "kv_ents", 0)
        for s in self._STATES:
            r.gauge_set(_TELEM + "groups", 0, labels={"state": s})
        # bucket i counts lags in [2^(i-1), 2^i) (bucket 0 = lag 0;
        # top bucket capped) — label by the inclusive lower bound
        self._bucket_labels = tuple(
            {"bucket": str(0 if i == 0 else 1 << (i - 1))}
            for i in range(buckets)
        )
        for lbl in self._bucket_labels:
            r.gauge_set(_TELEM + "commit_lag", 0, labels=lbl)

    def fold(self, snap: dict) -> None:
        """Publish one harvested aggregate (the ``telem_snapshot``
        dict) into the registry."""
        r = self.registry
        r.counter_add(_TELEM + "folds_total")
        for s, n in zip(self._STATES, snap.get("state_counts", ())):
            r.gauge_set(_TELEM + "groups", n, labels={"state": s})
        r.gauge_set(_TELEM + "stalled_groups", snap.get("stalled", 0))
        topk = snap.get("topk") or ()
        r.gauge_set(_TELEM + "worst_lag", topk[0][1] if topk else 0)
        r.gauge_set(_TELEM + "read_slots", snap.get("read_slots", 0))
        r.gauge_set(_TELEM + "kv_ents", snap.get("kv_ents", 0))
        for lbl, n in zip(self._bucket_labels, snap.get("lag_hist", ())):
            r.gauge_set(_TELEM + "commit_lag", n, labels=lbl)


class RecoveryObs:
    """Closed-loop recovery instruments (obs/recovery.py, ISSUE 17).

    Families (``dragonboat_recovery_*``):

    - ``actions_total{detector,action}`` — remediations executed
    - ``dryrun_total{detector,action}`` — remediations logged-only
      (dry-run mode)
    - ``skipped_total{reason}`` — open events declined (not leader on
      this host, rate limit, cooldown, flap-suppressed, observe-only
      detector, no viable target)
    - gauge ``suppressed_keys{detector}`` — keys currently flap-damped
    - ``failures_total{detector,action}`` — remediations that raised
    - histogram ``action_seconds{action}`` — decide-to-commit wall per
      executed remediation

    Zero-registered per detector/action at construction (the HealthObs
    precedent: a scrape distinguishes "recovery off" — families absent
    — from "on but idle" — families at zero).  Same ``is not None``
    latch contract as every other plane.
    """

    __slots__ = ("registry",)

    #: skip-reason vocabulary (zero-registered)
    SKIP_REASONS = (
        "not_leader", "rate_limited", "cooldown", "suppressed",
        "observe_only", "no_target", "stopped",
    )

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 matrix=()):
        """``matrix`` — iterable of ``(detector, action)`` pairs to
        zero-register (the controller's actuation matrix)."""
        self.registry = registry or DEFAULT_REGISTRY
        r = self.registry
        _describe(r, (
            _RECOV + "actions_total", _RECOV + "dryrun_total",
            _RECOV + "skipped_total", _RECOV + "suppressed_keys",
            _RECOV + "failures_total", _RECOV + "action_seconds",
        ))
        for det, action in matrix:
            labels = {"detector": det, "action": action}
            r.counter_add(_RECOV + "actions_total", 0, labels=labels)
            r.counter_add(_RECOV + "dryrun_total", 0, labels=labels)
            r.counter_add(_RECOV + "failures_total", 0, labels=labels)
            r.gauge_set(
                _RECOV + "suppressed_keys", 0, labels={"detector": det}
            )
            r.histogram_declare(
                _RECOV + "action_seconds", buckets=RECOVERY_BUCKETS_S,
                labels={"action": action},
            )
        for reason in self.SKIP_REASONS:
            r.counter_add(
                _RECOV + "skipped_total", 0, labels={"reason": reason}
            )

    def action(self, detector: str, action: str, *,
               duration_s: float) -> None:
        r = self.registry
        labels = {"detector": detector, "action": action}
        r.counter_add(_RECOV + "actions_total", labels=labels)
        r.histogram_observe(
            _RECOV + "action_seconds", duration_s,
            buckets=RECOVERY_BUCKETS_S, labels={"action": action},
        )

    def dryrun(self, detector: str, action: str) -> None:
        self.registry.counter_add(
            _RECOV + "dryrun_total",
            labels={"detector": detector, "action": action},
        )

    def skipped(self, reason: str) -> None:
        self.registry.counter_add(
            _RECOV + "skipped_total", labels={"reason": reason}
        )

    def failure(self, detector: str, action: str) -> None:
        self.registry.counter_add(
            _RECOV + "failures_total",
            labels={"detector": detector, "action": action},
        )

    def suppressed(self, detector: str, count: int) -> None:
        self.registry.gauge_set(
            _RECOV + "suppressed_keys", count,
            labels={"detector": detector},
        )


class DevProfObs:
    """Device capacity & profiling instruments (obs/devprof.py, ISSUE 15).

    Families (``dragonboat_devprof_*``):

    - gauges ``hbm_bytes{plane,artifact}`` / ``hbm_plane_bytes{plane}``
      — the HBM ledger: every resident device artifact priced by bytes
    - gauges ``bytes_per_group`` / ``capacity_groups`` /
      ``model_error_pct`` — the capacity model (max groups per device;
      prediction vs actually-allocated bytes)
    - histogram ``device_ms`` + gauge ``duty_cycle`` — the sampled
      device-time estimator (block_until_ready deltas, 1-in-N)
    - ``dispatches_total`` / ``sampled_total`` /
      ``padded_rounds_total`` / ``wasted_rounds_total`` + gauge
      ``padding_waste_ratio`` — fused padding-waste accounting
    - gauge ``programs`` + histogram ``program_compile_ms`` + gauges
      ``program_{flops,bytes,temp_bytes}{variant}`` — the warm-set
      program registry (XLA cost/memory analysis per program)
    - ``captures_total`` + gauge ``capture_active`` — on-demand
      ``jax.profiler`` capture windows

    Same ``is not None`` latch contract as every other plane: devprof
    off registers none of this.
    """

    __slots__ = ("registry",)

    _PLANES = ("quorum", "read", "devsm", "dispatch")

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry or DEFAULT_REGISTRY
        r = self.registry
        _describe(r, (
            _DEVPROF + "hbm_bytes", _DEVPROF + "hbm_plane_bytes",
            _DEVPROF + "bytes_per_group", _DEVPROF + "capacity_groups",
            _DEVPROF + "model_error_pct", _DEVPROF + "device_ms",
            _DEVPROF + "duty_cycle", _DEVPROF + "dispatches_total",
            _DEVPROF + "sampled_total", _DEVPROF + "padded_rounds_total",
            _DEVPROF + "wasted_rounds_total",
            _DEVPROF + "padding_waste_ratio", _DEVPROF + "programs",
            _DEVPROF + "program_compile_ms", _DEVPROF + "program_flops",
            _DEVPROF + "program_bytes", _DEVPROF + "program_temp_bytes",
            _DEVPROF + "captures_total", _DEVPROF + "capture_active",
        ))
        for name in (
            "dispatches_total", "sampled_total", "padded_rounds_total",
            "wasted_rounds_total", "captures_total",
        ):
            r.counter_add(_DEVPROF + name, 0)
        for name in (
            "bytes_per_group", "capacity_groups", "model_error_pct",
            "duty_cycle", "padding_waste_ratio", "programs",
            "capture_active",
        ):
            r.gauge_set(_DEVPROF + name, 0)
        for plane in self._PLANES:
            r.gauge_set(
                _DEVPROF + "hbm_plane_bytes", 0, labels={"plane": plane}
            )
        r.histogram_declare(
            _DEVPROF + "device_ms", buckets=LATENCY_BUCKETS_MS
        )
        r.histogram_declare(
            _DEVPROF + "program_compile_ms", buckets=LATENCY_BUCKETS_MS
        )

    def device_ms(self, ms: float) -> None:
        self.registry.histogram_observe(
            _DEVPROF + "device_ms", ms, buckets=LATENCY_BUCKETS_MS
        )

    def flush_dispatch(
        self, *, dispatches: int, sampled: int, padded: int, wasted: int,
        waste_ratio: float, duty_cycle: float,
    ) -> None:
        """Counter DELTAS accumulated since the last flush (the tracer's
        local-accumulate/periodic-flush discipline — a registry bump per
        dispatch would tax the round thread) plus the window gauges."""
        r = self.registry
        if dispatches:
            r.counter_add(_DEVPROF + "dispatches_total", dispatches)
        if sampled:
            r.counter_add(_DEVPROF + "sampled_total", sampled)
        if padded:
            r.counter_add(_DEVPROF + "padded_rounds_total", padded)
        if wasted:
            r.counter_add(_DEVPROF + "wasted_rounds_total", wasted)
        r.gauge_set(_DEVPROF + "padding_waste_ratio", round(waste_ratio, 4))
        r.gauge_set(_DEVPROF + "duty_cycle", round(duty_cycle, 4))

    def ledger(
        self, *, artifacts: dict, planes: dict, bytes_per_group: float,
        capacity_groups: int, model_error_pct: Optional[float],
        shard_artifacts: Optional[list] = None,
    ) -> None:
        """``shard_artifacts`` (mesh-sharded facade, ops/mesh.py): a list
        of per-shard artifact dicts — each publishes its own
        ``hbm_bytes{plane,artifact,shard}`` rows alongside the
        aggregated shard-less rows, so a scrape sees both the whole
        mesh's residency and each device's."""
        r = self.registry
        for (plane, artifact), nbytes in artifacts.items():
            r.gauge_set(
                _DEVPROF + "hbm_bytes", nbytes,
                labels={"plane": plane, "artifact": artifact},
            )
        if shard_artifacts:
            for i, per_shard in enumerate(shard_artifacts):
                for (plane, artifact), nbytes in per_shard.items():
                    r.gauge_set(
                        _DEVPROF + "hbm_bytes", nbytes,
                        labels={"plane": plane, "artifact": artifact,
                                "shard": str(i)},
                    )
        for plane in self._PLANES:
            r.gauge_set(
                _DEVPROF + "hbm_plane_bytes", planes.get(plane, 0),
                labels={"plane": plane},
            )
        r.gauge_set(_DEVPROF + "bytes_per_group", round(bytes_per_group, 1))
        r.gauge_set(_DEVPROF + "capacity_groups", capacity_groups)
        if model_error_pct is not None:
            r.gauge_set(
                _DEVPROF + "model_error_pct", round(model_error_pct, 3)
            )

    def program(
        self, *, variant: str, flops: float, bytes_accessed: float,
        temp_bytes: int, compile_ms: float,
    ) -> None:
        r = self.registry
        labels = {"variant": variant}
        r.gauge_set(_DEVPROF + "program_flops", flops, labels=labels)
        r.gauge_set(_DEVPROF + "program_bytes", bytes_accessed, labels=labels)
        r.gauge_set(
            _DEVPROF + "program_temp_bytes", temp_bytes, labels=labels
        )
        r.histogram_observe(
            _DEVPROF + "program_compile_ms", compile_ms,
            buckets=LATENCY_BUCKETS_MS,
        )

    def programs_done(self, n: int) -> None:
        self.registry.gauge_set(_DEVPROF + "programs", n)

    def capture(self, active: bool) -> None:
        r = self.registry
        if active:
            r.counter_add(_DEVPROF + "captures_total")
        r.gauge_set(_DEVPROF + "capture_active", 1 if active else 0)


#: dispatch-concurrency buckets: how many shard streams were in flight
#: at once (mesh sizes are small powers of two; >1 is the headline)
CONCURRENCY_BUCKETS = (1, 2, 4, 8, 16, 32)


class MeshObs:
    """Mesh-dispatch-plane instruments (ops/mesh.py, ISSUE 16).

    Families (``dragonboat_mesh_*``):

    - gauge ``shards`` — per-shard engines behind the facade
    - gauge ``groups{shard}`` — the live group→shard assignment table
    - ``migrations_total`` + histogram ``migration_ms`` — cost-driven
      placement moves and their stage-out→stage-in wall time
    - histogram ``dispatch_concurrency`` — shard dispatch streams
      simultaneously in flight per fan-out; any observation above 1 is
      the direct "two shards dispatched concurrently" evidence the old
      global mutex made impossible

    Holds the SHARED recorder the per-shard ``EngineObs`` publish into
    (one ring, so per-shard dispatch spans interleave and overlap is
    assertable from span timestamps alone) — same ``recorder`` /
    ``registry`` surface as ``EngineObs`` so the coordinator's obs
    wiring is facade-agnostic.
    """

    __slots__ = ("recorder", "registry", "n_shards")

    def __init__(
        self,
        recorder: FlightRecorder,
        registry: Optional[MetricsRegistry] = None,
        n_shards: int = 1,
    ):
        self.recorder = recorder
        self.registry = registry or DEFAULT_REGISTRY
        self.n_shards = n_shards
        r = self.registry
        _describe(r, (
            _MESH + "shards", _MESH + "groups",
            _MESH + "migrations_total", _MESH + "migration_ms",
            _MESH + "dispatch_concurrency",
        ))
        r.gauge_set(_MESH + "shards", n_shards)
        for i in range(n_shards):
            r.gauge_set(_MESH + "groups", 0, labels={"shard": str(i)})
        r.counter_add(_MESH + "migrations_total", 0)
        r.histogram_declare(
            _MESH + "migration_ms", buckets=LATENCY_BUCKETS_MS
        )
        r.histogram_declare(
            _MESH + "dispatch_concurrency", buckets=CONCURRENCY_BUCKETS
        )

    def placement(self, counts) -> None:
        """Publish the live assignment table (groups per shard)."""
        r = self.registry
        for i, n in enumerate(counts):
            r.gauge_set(_MESH + "groups", n, labels={"shard": str(i)})

    def migration(self, cluster_id, src, dst, wall_ms, counts) -> dict:
        r = self.registry
        r.counter_add(_MESH + "migrations_total")
        r.histogram_observe(
            _MESH + "migration_ms", wall_ms, buckets=LATENCY_BUCKETS_MS
        )
        self.placement(counts)
        return self.recorder.record(
            "mesh_migration",
            cluster_id=cluster_id,
            src_shard=src,
            dst_shard=dst,
            wall_ms=round(wall_ms, 4),
        )

    def concurrency(self, peak: int) -> None:
        """One fan-out's high-water mark of simultaneously in-flight
        shard dispatch streams."""
        if peak > 0:
            self.registry.histogram_observe(
                _MESH + "dispatch_concurrency", peak,
                buckets=CONCURRENCY_BUCKETS,
            )


class CoordObs:
    """Round-loop instruments for one ``TpuQuorumCoordinator``."""

    __slots__ = ("recorder", "registry")

    _COUNTERS = (
        _COORD + "rounds_total",
        _COORD + "ops_drained_total",
        _COORD + "tick_deficit_total",
        _COORD + "commits_offloaded_total",
        _COORD + "reads_confirmed_total",
        # adaptive K-round batching (ISSUE 7): rounds served by ONE fused
        # multi-round dispatch, and the fused rounds they carried — the
        # ratio to rounds_total is the live fused duty cycle
        _COORD + "fused_dispatch_total",
        _COORD + "fused_rounds_total",
    )

    def __init__(
        self, recorder: FlightRecorder, registry: Optional[MetricsRegistry] = None
    ):
        self.recorder = recorder
        self.registry = registry or DEFAULT_REGISTRY
        r = self.registry
        _describe(r, self._COUNTERS + (
            _COORD + "staged_depth", _COORD + "read_fallbacks",
            _COORD + "round_latency_ms",
        ))
        for name in self._COUNTERS:
            r.counter_add(name, 0)
        r.gauge_set(_COORD + "staged_depth", 0)
        r.gauge_set(_COORD + "read_fallbacks", 0)
        r.histogram_declare(
            _COORD + "round_latency_ms", buckets=LATENCY_BUCKETS_MS
        )

    def round(
        self,
        *,
        wall_ms: float,
        gate: str,
        ops: int,
        deficit: int,
        commits: int,
        reads_confirmed: int,
        read_fallbacks: int,
        staged_depth: int,
        k_rounds: int = 1,
        fused: bool = False,
        fuse_skip: Optional[str] = None,
    ) -> dict:
        """One dispatched coordinator round (quiet early-return rounds are
        not recorded).  The recorder's stall check on ``wall_ms`` IS the
        round-gate watchdog: a round outlasting ``stall_ms`` auto-dumps
        the ring with this span as the trigger.

        ``k_rounds`` is the adaptive K the round chose (1 = the
        single-round path); ``fused`` marks a fused multi-round dispatch;
        ``fuse_skip`` names why a K>1 backlog did NOT fuse
        (``"warmup"`` — programs still compiling, ``"votes"`` — an
        election rode this round, ``"churn"`` — unwarmed in-program
        recycles/pre-staged rounds in the backlog, ``"mesh_warmup"`` —
        a mesh coordinator's per-shard program sets still warming) so
        the warmup gate can assert proposals never blocked on
        compilation."""
        r = self.registry
        r.counter_add(_COORD + "rounds_total")
        if ops:
            r.counter_add(_COORD + "ops_drained_total", ops)
        if deficit:
            r.counter_add(_COORD + "tick_deficit_total", deficit)
        if commits:
            r.counter_add(_COORD + "commits_offloaded_total", commits)
        if reads_confirmed:
            r.counter_add(_COORD + "reads_confirmed_total", reads_confirmed)
        if fused:
            r.counter_add(_COORD + "fused_dispatch_total")
            r.counter_add(_COORD + "fused_rounds_total", k_rounds)
        r.gauge_set(_COORD + "staged_depth", staged_depth)
        r.gauge_set(_COORD + "read_fallbacks", read_fallbacks)
        r.histogram_observe(
            _COORD + "round_latency_ms", wall_ms, buckets=LATENCY_BUCKETS_MS
        )
        extra = {}
        if fused:
            extra["fused"] = True
        if fuse_skip:
            extra["fuse_skip"] = fuse_skip
        return self.recorder.record(
            "coord_round",
            gate=gate,
            wall_ms=round(wall_ms, 4),
            ops=ops,
            deficit=deficit,
            k_rounds=k_rounds,
            commits=commits,
            reads_confirmed=reads_confirmed,
            **extra,
        )
