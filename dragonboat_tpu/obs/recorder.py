"""The device-plane flight recorder: a fixed-size ring of span records.

One span per device dispatch (and per coordinator round): what was
staged, how long the host spent launching it, how long the blocking
egress took, and WHY the dispatch fired (its gate reason).  The ring is
bounded — memory is O(capacity) no matter the load — and recording is
lock-light: one micro-lock bump for the ring slot; span dicts are
mutated in place by their single producing thread afterwards (the
egress fields land at harvest time), so a dump taken mid-flight shows
the in-flight dispatch with its egress still pending — exactly the span
a stall investigation needs.

The stall watchdog rides the same records: any span whose wall fields
(``wall_ms`` / ``dispatch_ms`` / ``egress_ms`` / ``mu_wait_ms``) reach
``stall_ms`` is marked ``stalled`` and triggers an automatic dump —
logged, kept on ``last_dump``, and written to ``dump_path`` when set
(``DBTPU_OBS_DUMP``).  ``stall_ms <= 0`` disables the watchdog (the
bench overhead axis measures with it off).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional

from ..logger import get_logger

plog = get_logger("obs")

DEFAULT_CAPACITY = 512

#: span fields the stall watchdog inspects, in attribution order
_STALL_KEYS = ("wall_ms", "dispatch_ms", "egress_ms", "mu_wait_ms")


def _default_stall_ms() -> float:
    try:
        return float(os.environ.get("DBTPU_OBS_STALL_MS", "1000"))
    except ValueError:
        plog.warning("malformed DBTPU_OBS_STALL_MS; using 1000")
        return 1000.0


class FlightRecorder:
    """Bounded ring of span records with a stall watchdog.

    Span schema (all producers; absent fields simply weren't measured):

    ======================  ==================================================
    field                   meaning
    ======================  ==================================================
    ``seq``                 monotonically increasing record number
    ``kind``                ``"dispatch"`` (engine, single-round),
                            ``"fused"`` (engine, K-round block),
                            ``"coord_round"`` (tpuquorum round loop),
                            ``"warmup"`` (one AOT-warmed program)
    ``ts``                  wall-clock time the span was recorded
    ``gate``                why the dispatch fired: ``+``-joined subset of
                            ``tick``/``acks``/``reads``/``churn``/``dirty``,
                            or ``drain``
    ``rounds``              scanned rounds in the block (padded program K)
    ``k_rounds``            LIVE rounds: real staged rounds, or the
                            ticked count when a deficit replay ticks
                            into the padding (coord spans: the adaptive
                            K the round chose; 1 = single-round path)
    ``fused`` ``fuse_skip`` coord spans: this round used a fused
                            multi-round dispatch / why a K>1 backlog
                            did not (``warmup``/``votes``/``churn``)
    ``variant``             warmup spans: which program was warmed
    ``compile_ms``          warmup spans: compile wall time (NOT a
                            stall-watchdog field — warm compiles are
                            expected to be slow)
    ``acks`` ``votes``      staged event counts ingested by the dispatch
    ``recycles``            in-program membership recycles in the block
    ``reads`` ``echoes``    staged ReadIndex batches / heartbeat echoes
    ``upload_bytes``        host→device event-tensor bytes
    ``dispatch_ms``         host wall time staging + launching the program
    ``egress_ms``           blocking device→host egress wall time (set at
                            harvest; an in-flight span lacks it)
    ``egress_rows``         rows whose commit watermark advanced
    ``reads_released``      client reads released by confirmed slots
    ``mu_wait_ms``          time spent waiting on the engine's multi-
                            device dispatch lock (zero on single-device
                            and mesh-sharded engines)
    ``shard``               mesh shard index of the launching stream
                            (mesh-sharded engines only, ops/mesh.py)
    ``wall_ms``             whole-round wall time (coordinator spans)
    ``device_ms``           sampled post-launch ``block_until_ready``
                            delta (the devprof device-time estimator,
                            ISSUE 15; only on sampled dispatch spans —
                            deliberately NOT a stall-watchdog field,
                            the blocking sample is the measurement)
    ``stalled``             set by the watchdog: which field tripped
    ======================  ==================================================

    ``devprof`` spans mark on-demand ``jax.profiler`` capture windows
    (``window_ms``/``dir``, obs/devprof.py).
    """

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        stall_ms: Optional[float] = None,
        dump_path: Optional[str] = None,
    ):
        if capacity < 1:
            raise ValueError("recorder capacity must be >= 1")
        self.capacity = capacity
        self.stall_ms = (
            _default_stall_ms() if stall_ms is None else float(stall_ms)
        )
        self.dump_path = dump_path or os.environ.get("DBTPU_OBS_DUMP")
        self._buf: List[Optional[dict]] = [None] * capacity
        self._n = 0
        self._mu = threading.Lock()
        self.stalls = 0
        self.dumps = 0
        self.last_dump: Optional[dict] = None

    # ------------------------------------------------------------------
    # recording
    # ------------------------------------------------------------------

    def record(self, kind: str, **fields) -> dict:
        """Append a span; returns the (mutable) span dict so the producer
        can finalize it later (``update``)."""
        span = {"kind": kind, "ts": time.time()}
        span.update(fields)
        with self._mu:
            span["seq"] = self._n
            self._buf[self._n % self.capacity] = span
            self._n += 1
        self._stall_check(span)
        return span

    def update(self, span: dict, **fields) -> None:
        """Finalize a span in place (egress fields land at harvest)."""
        span.update(fields)
        self._stall_check(span)

    def _stall_check(self, span: dict) -> None:
        th = self.stall_ms
        if th <= 0 or span.get("stalled"):
            return
        over = [
            k for k in _STALL_KEYS if float(span.get(k) or 0.0) >= th
        ]
        if over:
            span["stalled"] = "+".join(over)
            self.stalls += 1
            self.dump(
                reason=f"stall:{span['stalled']} >= {th:g}ms", trigger=span
            )

    # ------------------------------------------------------------------
    # introspection / dumping
    # ------------------------------------------------------------------

    def __len__(self) -> int:
        return min(self._n, self.capacity)

    def spans(self) -> List[dict]:
        """Recorded spans, oldest → newest."""
        with self._mu:
            n = self._n
            if n <= self.capacity:
                return [s for s in self._buf[:n]]
            return [
                self._buf[i % self.capacity] for i in range(n - self.capacity, n)
            ]

    def to_json(self, limit: Optional[int] = None) -> dict:
        """JSON-serializable snapshot (``limit`` keeps only the newest N
        spans — artifact writers cap the payload)."""
        spans = self.spans()
        if limit is not None and len(spans) > limit:
            spans = spans[-limit:]
        return {
            "capacity": self.capacity,
            "count": self._n,
            "stall_ms": self.stall_ms,
            "stalls": self.stalls,
            "spans": spans,
        }

    def dump(self, reason: str = "on-demand", trigger: Optional[dict] = None) -> dict:
        """Snapshot the ring (plus the triggering span) — kept on
        ``last_dump``, logged, and written to ``dump_path`` when set.
        Called automatically by the stall watchdog; callers (bench rung
        watchdog, operators) may invoke it on demand."""
        d = {"reason": reason, "time": time.time(), "trigger": trigger}
        d.update(self.to_json())
        self.last_dump = d
        self.dumps += 1
        path = self.dump_path
        if path:
            try:
                with open(path, "w") as f:
                    json.dump(d, f, indent=1, default=str)
            except OSError as e:
                plog.warning("flight recorder dump to %s failed: %r", path, e)
        plog.warning(
            "flight recorder dump (%s): %d spans, trigger=%s%s",
            reason,
            len(d["spans"]),
            (trigger or {}).get("kind"),
            f" -> {path}" if path else "",
        )
        return d
