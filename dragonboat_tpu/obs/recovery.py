"""Closed-loop recovery plane: health detectors that actuate (ISSUE 17
tentpole, ROADMAP item 5).

The health plane (obs/health.py, ISSUE 13) measures every failure as a
detector open→close duration; this module closes the loop.  A
:class:`RecoveryController` subscribes to detector OPEN events
(``HealthSampler.on_open``) and drives guard-railed remediations through
the NodeHost's own public actuation surfaces:

==================  ====================================================
detector            remediation
==================  ====================================================
``quorum_at_risk``  **evict_dead** — REMOVE_NODE one unreachable voter
                    (the check-quorum leader's ``unreachable_ids``),
                    restoring the quorum safety margin immediately —
                    then **promote_standby** — ADD_NODE a standing
                    observer to voter (legal promotion: the raft core
                    moves the observer's tracked progress to the voter
                    set) or, with a configured standby pool and no
                    observer, ADD_WITNESS a fresh metadata-only voter.
                    That is the BlackWater move (PAPERS.md): durability
                    capacity on unreliable nodes is cheapest as
                    witnesses/observers promoted on demand — note the
                    reference core *forbids* in-place witness→full
                    promotion (``could not promote witness``), so
                    "promote a witness" is spelled observer-promotion
                    or fresh-witness-add, never ADD_NODE of a witness id
``leader_flap``     **transfer_leader** — leadership transferred to a
                    voter that did NOT appear in the flap window's
                    ``recent_leaders`` (away from the flapping hosts)
``devsm_rebind``    **devsm_release** — force-release the device
                    binding (``DevSMPlane.on_unbind``): a bind/unbind
                    loop stops burning uploads and reads fall back to
                    the gated host shadow until leadership settles
``commit_stall``    **fastlane_redrive** — re-drive the fast-lane
                    eject/re-enroll path (``Node.fast_eject`` +
                    ``set_step_ready``): a group wedged in the native
                    lane hands back to scalar raft, which runs the full
                    protocol
``worker_flap``     observe-and-attribute ONLY — the hostproc monitor
                    already respawns dead workers; a second respawn
                    here would double-actuate (asserted in tests: one
                    kill -9 = exactly one restart-counter bump)
==================  ====================================================

Every actuation is guard-railed:

- **rate limit per group** (``rate_limit_s``): minimum seconds between
  any two executed actions touching the same detector key (group/host)
- **cooldown per (detector, key)** (``cooldown_s``): after an action,
  that detector+key pair cannot actuate again until the cooldown ages
- **flap damping** (``max_reopens`` / ``reopen_window_s``): an action
  whose detector re-opens within the window earns a strike; at
  ``max_reopens`` strikes the key is suppressed — reported, counted in
  ``dragonboat_recovery_suppressed_keys``, no further actions until a
  full quiet window passes
- **dry-run** (``dry_run=True``): decisions run end to end and are
  logged + counted (``dragonboat_recovery_dryrun_total``) but nothing
  executes

Threading: detector callbacks (tick-worker context) only enqueue; a
small pool of daemon action threads executes remediations with bounded
sync timeouts, so a slow config change can never stall sampling.  An
action that finds this host is not the group's leader re-enqueues with
a short delay for a bounded number of attempts — under churn the leader
moves between detection and actuation, and some host in the group will
win the race.

Off contract (the ``_obs is not None`` latch precedent): the plane is
OFF by default.  ``NodeHostConfig.auto_recover = False`` constructs
nothing — no controller, no subscriber on the sampler, no registry
families — asserted structurally in tests/test_recovery.py.
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..logger import get_logger

plog = get_logger("recovery")

#: the actuation matrix — (detector, action) vocabulary, zero-registered
#: by RecoveryObs so a scrape distinguishes "off" from "idle"
MATRIX = (
    ("quorum_at_risk", "evict_dead"),
    ("quorum_at_risk", "promote_standby"),
    ("leader_flap", "transfer_leader"),
    ("devsm_rebind", "devsm_release"),
    ("commit_stall", "fastlane_redrive"),
)

#: detectors the controller attributes but never actuates: worker_flap
#: belongs to the hostproc monitor (double-actuation guard), the rest
#: self-correct (apply executor, lease plane, mesh rebalancer)
OBSERVE_ONLY = (
    "worker_flap", "apply_lag", "lease_thrash", "shard_imbalance",
)


class RecoveryController:
    """Guard-railed detector-driven remediation over one NodeHost.

    Built by NodeHost when ``auto_recover=True`` (requires the health
    plane); unit tests construct it directly over a hand-fed
    :class:`~dragonboat_tpu.obs.health.HealthSampler`.
    """

    def __init__(
        self,
        nh,
        sampler,
        *,
        dry_run: bool = False,
        registry=None,
        rate_limit_s: float = 2.0,
        cooldown_s: float = 5.0,
        max_reopens: int = 3,
        reopen_window_s: float = 60.0,
        action_timeout_s: float = 5.0,
        workers: int = 2,
        max_attempts: int = 3,
        retry_delay_s: float = 0.3,
        standby_witness_addrs: Tuple[str, ...] = (),
    ):
        self.nh = nh
        self.sampler = sampler
        self.dry_run = bool(dry_run)
        self.rate_limit_s = float(rate_limit_s)
        self.cooldown_s = float(cooldown_s)
        self.max_reopens = int(max_reopens)
        self.reopen_window_s = float(reopen_window_s)
        self.action_timeout_s = float(action_timeout_s)
        self.max_attempts = int(max_attempts)
        self.retry_delay_s = float(retry_delay_s)
        self.standby_witness_addrs = tuple(standby_witness_addrs)
        self._obs = None
        if registry is not None:
            from .instruments import RecoveryObs

            self._obs = RecoveryObs(registry=registry, matrix=MATRIX)
        self._mu = threading.Lock()
        # guardrail state, all keyed on the detector event key
        self._last_key_action: Dict[str, float] = {}           # rate limit
        self._last_det_action: Dict[Tuple[str, str], float] = {}  # cooldown
        self._strikes: Dict[Tuple[str, str], Tuple[int, float]] = {}
        self._suppressed: Dict[Tuple[str, str], float] = {}
        # attribution / introspection
        self.actions: Dict[Tuple[str, str], int] = {m: 0 for m in MATRIX}
        self.dryruns: Dict[Tuple[str, str], int] = {m: 0 for m in MATRIX}
        self.skips: Dict[str, int] = {}
        self.failures: Dict[Tuple[str, str], int] = {}
        self.observed: Dict[str, int] = {}
        self._recent: deque = deque(maxlen=64)
        self._next_witness_id: Dict[int, int] = {}
        self._stopped = threading.Event()
        self._q: "queue.Queue" = queue.Queue()
        self._threads: List[threading.Thread] = []
        for i in range(max(1, int(workers))):
            t = threading.Thread(
                target=self._worker_main, name=f"dbtpu-recover-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        sampler.on_open(self._on_open)
        sampler.on_close(self._on_close)
        plog.info(
            "recovery controller on (dry_run=%s rate_limit=%.1fs "
            "cooldown=%.1fs max_reopens=%d)",
            self.dry_run, self.rate_limit_s, self.cooldown_s,
            self.max_reopens,
        )

    # ------------------------------------------------------------------
    # detector callbacks (sampling-thread context: enqueue only)
    # ------------------------------------------------------------------

    def _on_open(self, ev: dict) -> None:
        if self._stopped.is_set():
            return
        det = ev.get("detector")
        with self._mu:
            self.observed[det] = self.observed.get(det, 0) + 1
            self._note_reopen(det, ev.get("key"), ev.get("opened_mono"))
        self._q.put((ev, 1))

    def _on_close(self, ev: dict) -> None:
        # nothing to actuate on close; the sampler already recorded the
        # MTTR attribution before this callback ran (ordering contract)
        pass

    def _note_reopen(self, det: str, key: str, mono) -> None:
        """Strike accounting (held under ``_mu``): an OPEN arriving
        within ``reopen_window_s`` of an executed action on the same
        (detector, key) means the action did not stick."""
        k = (det, key)
        now = mono if mono is not None else time.monotonic()
        acted = self._last_det_action.get(k)
        if acted is None or now - acted > self.reopen_window_s:
            return
        count, _ = self._strikes.get(k, (0, 0.0))
        count += 1
        self._strikes[k] = (count, now)
        if count >= self.max_reopens and k not in self._suppressed:
            self._suppressed[k] = now
            plog.warning(
                "recovery SUPPRESS %s %s after %d re-opens", det, key,
                count,
            )
            if self._obs is not None:
                self._obs.suppressed(
                    det,
                    sum(1 for d, _ in self._suppressed if d == det),
                )

    # ------------------------------------------------------------------
    # action workers
    # ------------------------------------------------------------------

    def _worker_main(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            ev, attempt = item
            try:
                self._handle(ev, attempt)
            except Exception:
                plog.exception(
                    "recovery handler failed for %s %s",
                    ev.get("detector"), ev.get("key"),
                )

    def _handle(self, ev: dict, attempt: int) -> None:
        det, key = ev.get("detector"), ev.get("key")
        if self._stopped.is_set():
            self._skip("stopped")
            return
        if det in OBSERVE_ONLY or det not in {m[0] for m in MATRIX}:
            self._skip("observe_only")
            return
        now = time.monotonic()
        k = (det, key)
        with self._mu:
            sup = self._suppressed.get(k)
            if sup is not None:
                count, last = self._strikes.get(k, (0, sup))
                if now - last <= self.reopen_window_s:
                    self._skip_locked("suppressed")
                    return
                # a full quiet window passed: lift the suppression
                del self._suppressed[k]
                self._strikes.pop(k, None)
                if self._obs is not None:
                    self._obs.suppressed(
                        det,
                        sum(1 for d, _ in self._suppressed if d == det),
                    )
            last_key = self._last_key_action.get(key)
            if last_key is not None and now - last_key < self.rate_limit_s:
                self._skip_locked("rate_limited")
                return
            last_det = self._last_det_action.get(k)
            if last_det is not None and now - last_det < self.cooldown_s:
                self._skip_locked("cooldown")
                return
        t0 = time.perf_counter()
        try:
            outcome = self._actuate(det, ev)
        except Exception as e:
            with self._mu:
                self.failures[(det, "?")] = (
                    self.failures.get((det, "?"), 0) + 1
                )
            if self._obs is not None:
                self._obs.failure(det, "?")
            plog.warning("recovery action failed %s %s: %r", det, key, e)
            return
        if outcome is None:
            self._skip("no_target")
            return
        if outcome == "not_leader":
            self._skip("not_leader")
            if attempt < self.max_attempts and not self._stopped.is_set():
                # the leader moved between detection and actuation —
                # retry shortly; some host in the group wins the race
                timer = threading.Timer(
                    self.retry_delay_s,
                    lambda: self._q.put((ev, attempt + 1)),
                )
                timer.daemon = True
                timer.start()
            return
        # outcome: list of (action, executed_detail) performed
        dur = time.perf_counter() - t0
        stamp = time.monotonic()
        with self._mu:
            self._last_key_action[key] = stamp
            self._last_det_action[k] = stamp
            for action, detail in outcome:
                m = (det, action)
                if self.dry_run:
                    self.dryruns[m] = self.dryruns.get(m, 0) + 1
                else:
                    self.actions[m] = self.actions.get(m, 0) + 1
                self._recent.append({
                    "ts": time.time(),
                    "detector": det,
                    "key": key,
                    "action": action,
                    "dry_run": self.dry_run,
                    "duration_s": round(dur, 4),
                    "detail": detail,
                })
        for action, detail in outcome:
            if self.dry_run:
                plog.warning(
                    "recovery DRY-RUN %s %s -> %s %s", det, key, action,
                    detail,
                )
                if self._obs is not None:
                    self._obs.dryrun(det, action)
            else:
                plog.warning(
                    "recovery ACT %s %s -> %s %s (%.3fs)", det, key,
                    action, detail, dur,
                )
                if self._obs is not None:
                    self._obs.action(det, action, duration_s=dur)

    def _skip(self, reason: str) -> None:
        with self._mu:
            self._skip_locked(reason)

    def _skip_locked(self, reason: str) -> None:
        self.skips[reason] = self.skips.get(reason, 0) + 1
        if self._obs is not None:
            self._obs.skipped(reason)

    # ------------------------------------------------------------------
    # the actuation matrix
    # ------------------------------------------------------------------

    def _actuate(self, det: str, ev: dict):
        """Dispatch one open event; returns ``None`` (no viable target),
        ``"not_leader"`` (retryable) or a list of (action, detail)."""
        detail = ev.get("detail") or {}
        cid = detail.get("cluster_id")
        if det == "quorum_at_risk":
            return self._act_quorum(cid, detail)
        if det == "leader_flap":
            return self._act_leader_flap(cid, detail)
        if det == "devsm_rebind":
            return self._act_devsm(cid, detail)
        if det == "commit_stall":
            return self._act_commit_stall(cid, detail)
        return None

    def _node(self, cid):
        if cid is None:
            return None
        try:
            return self.nh.get_node(cid)
        except Exception:
            return None  # group stopped since the event opened

    def _act_quorum(self, cid, detail):
        node = self._node(cid)
        if node is None:
            return None
        if not node.is_leader():
            return "not_leader"
        m = node.get_membership()
        dead = [
            nid for nid in detail.get("unreachable_ids") or ()
            if nid in m.addresses or nid in (m.witnesses or {})
        ]
        out = []
        if dead:
            # one eviction per actuation: dropping the unreachable voter
            # restores the quorum margin (and closes the detector); a
            # mass-evict under a transient partition would be the cure
            # worse than the disease
            victim = min(dead)
            out.append(("evict_dead", {
                "cluster_id": cid, "node_id": victim,
                "unreachable": sorted(dead),
            }))
            if not self.dry_run:
                self.nh.sync_request_delete_node(
                    cid, victim, timeout=self.action_timeout_s
                )
        # restore durability: promote a standing observer to voter
        # (the raft core carries its progress over), or add a fresh
        # witness from the standby pool — NEVER ADD_NODE a witness id
        # (the reference core rejects in-place witness promotion)
        observers = dict(m.observers or {})
        if observers:
            oid = min(observers)
            out.append(("promote_standby", {
                "cluster_id": cid, "node_id": oid,
                "address": observers[oid], "kind": "observer",
            }))
            if not self.dry_run:
                self.nh.sync_request_add_node(
                    cid, oid, observers[oid],
                    timeout=self.action_timeout_s,
                )
        elif self.standby_witness_addrs:
            used = set(m.addresses) | set(m.observers or {})
            used |= set(m.witnesses or {}) | set(m.removed or {})
            wid = max(
                self._next_witness_id.get(cid, 0), max(used, default=0) + 1
            )
            self._next_witness_id[cid] = wid + 1
            addr = self.standby_witness_addrs[
                cid % len(self.standby_witness_addrs)
            ]
            out.append(("promote_standby", {
                "cluster_id": cid, "node_id": wid, "address": addr,
                "kind": "witness",
            }))
            if not self.dry_run:
                self.nh.sync_request_add_witness(
                    cid, wid, addr, timeout=self.action_timeout_s
                )
        return out or None

    def _act_leader_flap(self, cid, detail):
        node = self._node(cid)
        if node is None:
            return None
        if not node.is_leader():
            return "not_leader"
        m = node.get_membership()
        recent = set(detail.get("recent_leaders") or ())
        if recent and node.node_id not in recent:
            # leadership already escaped the flapping set (e.g. another
            # host's controller landed it here): transferring again
            # would re-enter the churn this action exists to stop
            return None
        witnesses = set(m.witnesses or {})
        candidates = [
            nid for nid in sorted(m.addresses)
            if nid != node.node_id and nid not in witnesses
        ]
        targets = [nid for nid in candidates if nid not in recent]
        if not targets:
            # every voter participated in the flap: there is no stable
            # host to move to, and another transfer is itself a leader
            # change that resets the detector's quiet window — holding
            # leadership is the only move that lets the flap die out
            return None
        target = targets[0]
        if not self.dry_run:
            self.nh.request_leader_transfer(cid, target)
        return [("transfer_leader", {
            "cluster_id": cid, "target": target,
            "away_from": sorted(recent),
        })]

    def _act_devsm(self, cid, detail):
        node = self._node(cid)
        if node is None:
            return None
        coord = getattr(self.nh, "quorum_coordinator", None)
        if coord is not None:
            if self.dry_run:
                plane = coord.devsm
                if plane is None or not plane.tracks(cid):
                    return None
            elif not coord.devsm_force_release(cid):
                return None
        else:
            plane = node.devsm_plane
            if plane is None:
                return None
            if not self.dry_run:
                plane.on_unbind(cid)
        return [("devsm_release", {
            "cluster_id": cid, "binds": detail.get("binds"),
        })]

    def _act_commit_stall(self, cid, detail):
        node = self._node(cid)
        if node is None:
            return None
        if not node.fast_lane:
            return None  # the stall is not the native lane's
        if not self.dry_run:
            node.fast_eject()
            self.nh.engine.set_step_ready(cid)
        return [("fastlane_redrive", {"cluster_id": cid})]

    # ------------------------------------------------------------------
    # introspection / teardown
    # ------------------------------------------------------------------

    def report(self) -> dict:
        """Aggregated actuation report (``NodeHost.recovery_report``,
        the churn soak's RECOV capture)."""
        with self._mu:
            return {
                "enabled": True,
                "dry_run": self.dry_run,
                "actions": {
                    f"{d}:{a}": n
                    for (d, a), n in sorted(self.actions.items()) if n
                },
                "dryruns": {
                    f"{d}:{a}": n
                    for (d, a), n in sorted(self.dryruns.items()) if n
                },
                "skips": dict(self.skips),
                "failures": {
                    f"{d}:{a}": n
                    for (d, a), n in sorted(self.failures.items())
                },
                "observed": dict(self.observed),
                "suppressed": [
                    {"detector": d, "key": k}
                    for d, k in sorted(self._suppressed)
                ],
                "recent": list(self._recent),
                "guardrails": {
                    "rate_limit_s": self.rate_limit_s,
                    "cooldown_s": self.cooldown_s,
                    "max_reopens": self.max_reopens,
                    "reopen_window_s": self.reopen_window_s,
                },
            }

    def stop(self) -> None:
        """Stop the action workers; queued events are dropped.  The
        sampler keeps its subscriber entries (it is torn down with the
        host right after), but a stopped controller ignores callbacks."""
        self._stopped.set()
        for _ in self._threads:
            self._q.put(None)
        for t in self._threads:
            t.join(timeout=2.0)
