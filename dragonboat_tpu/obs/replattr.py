"""Per-commit replication attribution: which peer's ack closed the
quorum, and where its round trip went (ISSUE 14 tentpole).

The request tracer (obs/trace.py) prices every stage *inside* one
NodeHost — its chain jumps from ``raft_step`` straight to ``wal`` /
``device_round``, so the replication leg (leader send → wire → follower
append+fsync → ack → quorum close) that costs a full far-domain RTT per
commit was a black box.  This module is the leader-side half of the
cross-host tracer: for every **sampled** proposal (the tracer's 1-in-N)
it opens a commit record when the REPLICATE fan-out goes out, folds in
each peer's ack (with the follower's stage stamps riding back on the
REPLICATE_RESP's :class:`~dragonboat_tpu.wire.ReplTrace` context), and
closes the record when the commit watermark passes the proposal —
computing

- **per-peer ack RTT** (``t_ack_recv - t_send``, both leader-clock);
- **the quorum-closing ack**: commit advances when the *quorum-th*
  voter's match covers the index — the same ``kth_largest(match,
  quorum)`` reduction ``raft.try_commit`` (and the batched
  ``kernels.commit_quorum``) runs — so sorting the voters' ack times
  ascending and taking the quorum-th smallest names the peer whose ack
  closed the commit (the leader self-acks at send time: its own match
  already covers the index when the fan-out leaves, exactly how
  ``try_commit`` counts it);
- **laggard identity**: voters that had not acked when the quorum
  closed (the peers a domain-local sub-quorum — ROADMAP item 4 — would
  take off the commit path);
- **the closing path's stage decomposition**: wire-out, follower
  append, follower fsync, ack-send and wire-back, reconciled across the
  two hosts' clocks with the NTP-style ack-pair estimate
  ``offset = ((t_recv - t_send) + (t_ack - t_ack_recv)) / 2`` — the
  five deltas then sum to the measured RTT *exactly* (the estimate's
  residual error is the wire asymmetry, the classic NTP caveat,
  documented in docs/overview.md).

Everything publishes as ``dragonboat_repl_*`` families (per-peer ack
RTT histograms, quorum-close latency, closer/laggard counters with a
latency-class label from ``LatencyInjector.health_snapshot``), as
``repl_commit`` flight-recorder spans, and as a ``repl`` summary on the
sampled request's Trace (rendered by ``NodeHost.dump_trace`` and joined
across hosts by ``tools/trace_merge.py``).

Overhead contract (the ``trace=None`` latch precedent): the plane only
exists while tracing is on — ``Raft.replattr`` / ``Node.replattr`` stay
``None`` otherwise and every hook gates on a plain attribute check, so
the trace-off request paths are structurally bit-identical.  Records
are term-pinned: any leadership transition (``Raft.reset``) drops the
group's open records, so a mid-trace transfer can never attribute one
term's acks to another's commit (tests/test_repltrace.py).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..events import DEFAULT_REGISTRY, MetricsRegistry
from ..logger import get_logger

plog = get_logger("replattr")

_R = "dragonboat_repl_"

#: seconds-scale buckets shared with the request tracer's stage
#: histograms (trace.STAGE_BUCKETS_S) — one import direction only, the
#: tracer never imports this module
from .trace import STAGE_BUCKETS_S  # noqa: E402

#: the closing path's stage vocabulary, in pipeline order
STAGES = (
    "wire_out", "follower_append", "follower_fsync", "ack_send",
    "wire_back",
)


class _Record:
    """One sampled proposal's in-flight commit record on the leader."""

    __slots__ = (
        "cid", "index", "term", "tid", "trace", "t0",
        "sends", "acks", "span_seq", "closed", "expect", "t_closed",
        "voters",
    )

    def __init__(self, cid: int, index: int, term: int, tid: int,
                 trace, t0: float):
        self.cid = cid
        self.index = index
        self.term = term
        self.tid = tid
        self.trace = trace          # the sampled obs.trace.Trace (or None)
        self.t0 = t0                # leader wall clock at fan-out
        self.sends: Dict[int, float] = {}        # peer -> t_send
        self.acks: Dict[int, Tuple[float, object]] = {}  # peer -> (t, ctx)
        self.span_seq: Optional[int] = None      # device round linkage
        self.closed: Optional[dict] = None
        self.expect = 0             # non-self voters at close (straggler GC)
        self.t_closed = 0.0
        self.voters: frozenset = frozenset()     # voter set at close

    def voter_acks(self) -> int:
        """Acked VOTERS — observers/witnesses also ack sampled
        replications, so a raw len(acks) would end the straggler window
        before the lagging voter reported."""
        return sum(1 for p in self.acks if p in self.voters)


def _decompose(ctx, t_send: float, t_ack_recv: float):
    """Offset-corrected stage deltas for one full-context ack; returns
    ``(stages_dict, offset_seconds)`` or ``(None, None)`` when the
    follower stamps are incomplete (witness metadata leg, reject)."""
    if ctx is None or not (ctx.t_recv and ctx.t_append and ctx.t_ack):
        return None, None
    # pair against the stamp that actually rode the acked leg: a
    # retransmit/catch-up resend re-attaches a fresh context with its
    # own t_send, while the caller's record keeps the FIRST send (the
    # commit-relevant RTT) — offsetting against the first send would
    # absorb half the retransmit gap into the clock-offset estimate
    # and inflate wire_out by the whole gap
    if ctx.t_send:
        t_send = ctx.t_send
    t_fsync = ctx.t_fsync or ctx.t_append
    # NTP-style pairing: both legs measured, half the asymmetry each way
    off = ((ctx.t_recv - t_send) + (ctx.t_ack - t_ack_recv)) / 2.0
    stages = {
        "wire_out": (ctx.t_recv - off) - t_send,
        "follower_append": ctx.t_append - ctx.t_recv,
        "follower_fsync": t_fsync - ctx.t_append,
        "ack_send": ctx.t_ack - t_fsync,
        "wire_back": t_ack_recv - (ctx.t_ack - off),
    }
    return stages, off


class ReplAttr:
    """Leader-side replication attribution plane (one per NodeHost,
    constructed only when tracing is on)."""

    def __init__(
        self,
        host: str = "",
        registry: Optional[MetricsRegistry] = None,
        recorder=None,
        keep: int = 256,
        max_inflight: int = 512,
        expire_s: float = 60.0,
    ):
        self.host = host
        self.registry = registry or DEFAULT_REGISTRY
        self.recorder = recorder
        self.keep = keep
        self.max_inflight = max_inflight
        self.expire_s = expire_s
        self._mu = threading.Lock()
        self._by_cid: Dict[int, Dict[int, _Record]] = {}  # cid -> idx -> rec
        self._inflight = 0
        self._done: deque = deque(maxlen=max(1, keep))
        # per-peer-address clock-offset EWMA (follower_clock - leader_clock)
        self._offsets: Dict[str, float] = {}
        # bounded per-peer RTT samples for the bench/introspection table
        self._rtts: Dict[Tuple[int, int], deque] = {}
        self._closer: Dict[Tuple[int, int], int] = {}
        self._laggard: Dict[Tuple[int, int], int] = {}
        # wiring (NodeHost): peer (cid, nid) -> transport address, and
        # address -> latency class/domain label
        self.resolver: Optional[Callable[[int, int], Optional[str]]] = None
        self.class_of: Optional[Callable[[str], Optional[str]]] = None
        self.commits_attributed = 0
        self.records_dropped = 0
        r = self.registry
        from .instruments import _describe

        _describe(r, (
            _R + "ack_rtt_seconds", _R + "stage_seconds",
            _R + "quorum_close_seconds", _R + "quorum_closer_total",
            _R + "laggard_total", _R + "commits_attributed_total",
            _R + "records_dropped_total", _R + "clock_offset_ms",
        ))
        r.counter_add(_R + "commits_attributed_total", 0)
        r.histogram_declare(_R + "ack_rtt_seconds", buckets=STAGE_BUCKETS_S)
        r.histogram_declare(_R + "stage_seconds", buckets=STAGE_BUCKETS_S)
        r.histogram_declare(
            _R + "quorum_close_seconds", buckets=STAGE_BUCKETS_S
        )

    # ------------------------------------------------------------------
    # peer labels
    # ------------------------------------------------------------------

    def _addr(self, cid: int, peer: int) -> Optional[str]:
        res = self.resolver
        if res is None:
            return None
        try:
            return res(cid, peer)
        except Exception:
            return None

    def _labels(self, cid: int, peer: int) -> Dict[str, str]:
        addr = self._addr(cid, peer)
        cls = None
        if addr is not None and self.class_of is not None:
            try:
                cls = self.class_of(addr)
            except Exception:
                cls = None
        return {"peer": str(peer), "cls": cls or "unknown"}

    # ------------------------------------------------------------------
    # leader hooks (node/raft; every call site gates on `is not None`)
    # ------------------------------------------------------------------

    def attach_sends(self, cid: int, msgs, tracer) -> None:
        """Scan an update's outbound messages for REPLICATEs carrying
        sampled entries: attach one fresh :class:`ReplTrace` context per
        message (per peer — the contexts are stamped concurrently by
        different followers) and open/extend the per-index commit
        records.  Called from ``Node.send_replicate_messages`` before
        the fan-out leaves, under the step worker."""
        from ..wire import MessageType, ReplTrace

        by_key = tracer._by_key
        open_recs = self._by_cid.get(cid)
        if not by_key and not open_recs:
            return
        now = time.time()
        staged = []   # sampled-entry sends: (msg, trace, index)
        resends = []  # sends with no live sampled trace, for record catch-up
        for m in msgs:
            if m.type != MessageType.REPLICATE or not m.entries:
                continue
            best = None
            best_index = 0
            for e in m.entries:
                t = by_key.get(e.key)
                if t is not None and not t.done and e.index >= best_index:
                    best = t
                    best_index = e.index
            if best is not None:
                m.trace = ReplTrace(
                    tid=best.tid, origin=self.host, index=best_index,
                    t_send=now,
                )
                staged.append((m, best, best_index))
            elif open_recs:
                resends.append(m)
        if not staged and not resends:
            return
        with self._mu:
            recs = self._by_cid.setdefault(cid, {})
            for m, tr, index in staged:
                rec = recs.get(index)
                if rec is None:
                    if self._inflight >= self.max_inflight:
                        self._drop_locked(reason="overflow", n=1)
                        continue
                    rec = _Record(cid, index, m.term, tr.tid, tr, now)
                    recs[index] = rec
                    self._inflight += 1
                rec.sends.setdefault(m.to, now)
            for m in resends:
                # a lagging/paused peer's catch-up REPLICATE can carry a
                # sampled index whose trace already completed (the
                # leader committed on the fast peers long ago).  The
                # record is still open waiting on THIS peer — stamp its
                # send time so the late ack still prices the peer's RTT,
                # and re-attach the newest covered record's context so
                # the follower's stage stamps ride back too.
                lo = m.entries[0].index
                hi = m.entries[-1].index
                covered = None
                for index, rec in recs.items():
                    if lo <= index <= hi and m.to not in rec.sends:
                        rec.sends[m.to] = now
                        if covered is None or index > covered.index:
                            covered = rec
                if covered is not None and m.trace is None:
                    m.trace = ReplTrace(
                        tid=covered.tid, origin=self.host,
                        index=covered.index, t_send=now,
                    )
            if not recs:
                self._by_cid.pop(cid, None)

    def on_ack(self, cid: int, peer: int, match: int, term: int,
               ctx=None) -> None:
        """A REPLICATE_RESP advanced ``peer``'s match: fold the ack (and
        its follower stage stamps, when the context rode back) into
        every open record it covers.  Called from
        ``raft.handle_leader_replicate_resp`` under raftMu, BEFORE the
        commit advancement that may close the record."""
        recs = self._by_cid.get(cid)
        if not recs:
            return
        now = time.time()
        t_ack_recv = (
            ctx.t_ack_recv if ctx is not None and ctx.t_ack_recv else now
        )
        publish: List[Tuple[dict, float]] = []
        offset_label = None
        with self._mu:
            for index in [i for i in recs if i <= match]:
                rec = recs[index]
                if rec.term != term:
                    self._expire_locked(rec, reason="term")
                    continue
                if peer in rec.acks:
                    continue
                use_ctx = (
                    ctx if ctx is not None and ctx.index == rec.index
                    else None
                )
                rec.acks[peer] = (t_ack_recv, use_ctx)
                t_send = rec.sends.get(peer)
                if t_send is not None:
                    rtt = max(0.0, t_ack_recv - t_send)
                    labels = self._labels(cid, peer)
                    publish.append((labels, rtt))
                    self._rtts.setdefault(
                        (cid, peer), deque(maxlen=512)
                    ).append(rtt)
                    if rec.closed is not None:
                        # straggler window: the record already closed —
                        # this peer was its laggard; enrich the summary
                        # (and the sampled trace's repl table, the same
                        # dict) with the late ack's measured RTT.
                        # Copy-on-write: the summary is already published
                        # (Trace.repl / the _done ring) and a concurrent
                        # dump may be iterating "peers" — swap in a new
                        # dict instead of mutating the visible one
                        peers = dict(rec.closed["peers"])
                        peers[str(peer)] = {
                            "t_send": t_send,
                            "rtt_ms": round(rtt * 1e3, 4),
                            "cls": labels["cls"],
                            "addr": self._addr(cid, peer),
                            "acked": True,
                            "after_close_ms": round(
                                max(0.0, t_ack_recv - rec.t_closed) * 1e3,
                                4,
                            ),
                        }
                        rec.closed["peers"] = peers
                    if use_ctx is not None:
                        _stages, off = _decompose(
                            use_ctx, t_send, t_ack_recv
                        )
                        if off is not None:
                            addr = self._addr(cid, peer)
                            if addr is not None:
                                prev = self._offsets.get(addr)
                                self._offsets[addr] = (
                                    off if prev is None
                                    else prev * 0.8 + off * 0.2
                                )
                                offset_label = (labels["peer"], off)
                if (
                    rec.closed is not None
                    and rec.voter_acks() >= rec.expect
                ):
                    # every voter has now acked: the straggler window is
                    # over, drop the retained record
                    del recs[index]
                    self._inflight -= 1
            if not recs:
                self._by_cid.pop(cid, None)
        r = self.registry
        for labels, rtt in publish:
            r.histogram_observe(
                _R + "ack_rtt_seconds", rtt, labels=labels,
                buckets=STAGE_BUCKETS_S,
            )
        if offset_label is not None:
            r.gauge_set(
                _R + "clock_offset_ms", offset_label[1] * 1e3,
                labels={"peer": offset_label[0]},
            )

    def note_device_round(self, cid: int, span_seq: Optional[int]) -> None:
        """Device-plane linkage (tpuquorum): the staged-round ack block
        whose dispatch released this group's commit — the closed record
        then names the same recorder span the request trace links."""
        recs = self._by_cid.get(cid)
        if not recs or span_seq is None:
            return
        with self._mu:
            for rec in recs.values():
                rec.span_seq = span_seq

    def on_commit(self, cid: int, committed: int, term: int, voters,
                  quorum: int, self_id: int) -> None:
        """The group's commit watermark advanced: close every open
        record it covers and publish the quorum attribution.  Called
        under raftMu from the scalar commit site
        (``raft._note_commit``) and the device-plane apply
        (``node._apply_offload_effects``), so the voter set and quorum
        are read at exactly the commit's membership."""
        recs = self._by_cid.get(cid)
        if not recs:
            return
        now = time.time()
        voter_set = set(voters)
        closed: List[_Record] = []
        with self._mu:
            for index in [i for i in recs if i <= committed]:
                rec = recs[index]
                if rec.closed is not None:
                    continue  # already closed, riding its straggler window
                if rec.term != term:
                    del recs[index]
                    self._inflight -= 1
                    self._drop_locked(reason="term", n=1)
                    continue
                # mark closed under the lock; stay registered so late
                # (laggard) acks still fold their RTT into the summary
                rec.t_closed = now
                rec.voters = frozenset(voter_set)
                rec.expect = sum(1 for p in voter_set if p != self_id)
                closed.append(rec)
        for rec in closed:
            self._close(rec, now, voter_set, quorum, self_id)
        if closed:
            with self._mu:
                for rec in closed:
                    if (
                        rec.voter_acks() >= rec.expect
                        and recs.get(rec.index) is rec
                    ):
                        del recs[rec.index]
                        self._inflight -= 1
                if not recs:
                    self._by_cid.pop(cid, None)

    def _close(self, rec: _Record, now: float, voters, quorum: int,
               self_id: int) -> None:
        # ack times per voter: the leader counts at fan-out time (its own
        # match already covered the index when the REPLICATE left — the
        # same way try_commit's kth_largest counts it)
        times = [(rec.t0, self_id)]
        for peer, (t, _ctx) in rec.acks.items():
            if peer in voters:
                times.append((t, peer))
        times.sort()
        closer = None
        t_close = None
        if len(times) >= quorum:
            t_close, closer = times[quorum - 1]
        laggards = sorted(
            p for p in voters
            if p != self_id and p not in rec.acks
        )
        close_s = (
            max(0.0, t_close - rec.t0) if t_close is not None else None
        )
        stages = None
        offset = None
        if closer is not None and closer != self_id:
            t_ack_recv, ctx = rec.acks[closer]
            stages, offset = _decompose(
                ctx, rec.sends.get(closer, rec.t0), t_ack_recv
            )
        r = self.registry
        self.commits_attributed += 1
        r.counter_add(_R + "commits_attributed_total")
        if close_s is not None:
            r.histogram_observe(
                _R + "quorum_close_seconds", close_s,
                buckets=STAGE_BUCKETS_S,
            )
        if closer is not None:
            labels = self._labels(rec.cid, closer)
            r.counter_add(_R + "quorum_closer_total", labels=labels)
            with self._mu:
                k = (rec.cid, closer)
                self._closer[k] = self._closer.get(k, 0) + 1
        for p in laggards:
            labels = self._labels(rec.cid, p)
            r.counter_add(_R + "laggard_total", labels=labels)
            with self._mu:
                k = (rec.cid, p)
                self._laggard[k] = self._laggard.get(k, 0) + 1
        if stages is not None:
            for stage, v in stages.items():
                r.histogram_observe(
                    _R + "stage_seconds", max(0.0, v),
                    labels={"stage": stage}, buckets=STAGE_BUCKETS_S,
                )
        summary = {
            "tid": rec.tid,
            "cluster_id": rec.cid,
            "index": rec.index,
            "term": rec.term,
            "origin": self.host,
            "quorum": quorum,
            "close_ms": (
                round(close_s * 1e3, 4) if close_s is not None else None
            ),
            "closer": closer,
            "laggards": laggards,
            "span_seq": rec.span_seq,
            "offset_ms": (
                round(offset * 1e3, 4) if offset is not None else None
            ),
            "stages_ms": (
                {k: round(v * 1e3, 4) for k, v in stages.items()}
                if stages is not None else None
            ),
            "peers": {
                str(peer): {
                    "t_send": rec.sends.get(peer),
                    "rtt_ms": (
                        round((t - rec.sends[peer]) * 1e3, 4)
                        if peer in rec.sends else None
                    ),
                    "cls": self._labels(rec.cid, peer)["cls"],
                    "addr": self._addr(rec.cid, peer),
                    "acked": True,
                }
                for peer, (t, _c) in rec.acks.items()
            },
        }
        for p in laggards:
            summary["peers"].setdefault(
                str(p),
                {
                    "t_send": rec.sends.get(p),
                    "rtt_ms": None,
                    "cls": self._labels(rec.cid, p)["cls"],
                    "addr": self._addr(rec.cid, p),
                    "acked": False,
                },
            )
        rec.closed = summary
        with self._mu:
            self._done.append(rec)
        tr = rec.trace
        if tr is not None and not tr.done:
            # the quorum-close point lands in the sampled trace's stage
            # chain (rendered between wal and apply in the export) and
            # the per-peer table rides the trace into dump_trace
            tr.add("repl_quorum")
            tr.repl = summary
        elif tr is not None:
            tr.repl = summary
        if self.recorder is not None:
            self.recorder.record(
                "repl_commit",
                cluster_id=rec.cid,
                index=rec.index,
                tid=rec.tid,
                close_ms=summary["close_ms"],
                closer=closer,
                laggards=len(laggards),
                span_seq=rec.span_seq,
            )

    # ------------------------------------------------------------------
    # invalidation
    # ------------------------------------------------------------------

    def on_reset(self, cid: int) -> None:
        """Leadership transition (``raft.reset``): the quorum these
        records were tallied against is gone — drop them rather than
        attribute a stale term's acks to a later commit.  (Closed
        records riding their straggler window just end early; only
        records that never attributed count as dropped.)"""
        with self._mu:
            recs = self._by_cid.pop(cid, None)
            if recs:
                self._inflight -= len(recs)
                open_n = sum(1 for r in recs.values() if r.closed is None)
                if open_n:
                    self._drop_locked(reason="reset", n=open_n)

    def _expire_locked(self, rec: _Record, reason: str) -> None:
        recs = self._by_cid.get(rec.cid)
        if recs is not None and recs.get(rec.index) is rec:
            del recs[rec.index]
            self._inflight -= 1
        self._drop_locked(reason=reason, n=1)

    def _drop_locked(self, reason: str, n: int) -> None:
        self.records_dropped += n
        self.registry.counter_add(
            _R + "records_dropped_total", n, labels={"reason": reason}
        )

    def sweep(self) -> int:
        """Expire records that never committed (dropped proposals, lost
        quorums) — driven by the NodeHost tick worker next to the
        tracer's stall check.  Returns expired count."""
        if not self._by_cid:
            return 0
        now = time.time()
        n = 0
        with self._mu:
            for cid in list(self._by_cid):
                recs = self._by_cid[cid]
                for index in list(recs):
                    rec = recs[index]
                    if rec.closed is not None:
                        # attributed; the straggler window ends after 5s
                        # even if a laggard never acks (partition)
                        if now - rec.t_closed > 5.0:
                            del recs[index]
                            self._inflight -= 1
                    elif now - rec.t0 > self.expire_s:
                        del recs[index]
                        self._inflight -= 1
                        n += 1
                if not recs:
                    del self._by_cid[cid]
            if n:
                self._drop_locked(reason="expired", n=n)
        return n

    # ------------------------------------------------------------------
    # introspection (bench / tests / dump)
    # ------------------------------------------------------------------

    def offsets(self) -> Dict[str, float]:
        """Per-peer-address clock-offset estimates (seconds; follower
        minus leader) — ``tools/trace_merge.py`` shifts follower dumps
        onto the leader's clock with these."""
        with self._mu:
            return dict(self._offsets)

    def records(self) -> List[dict]:
        """Closed attribution records, oldest→newest."""
        with self._mu:
            return [r.closed for r in self._done if r.closed]

    def summary(self) -> dict:
        """Aggregate table for the bench/perf-ledger: per (cid, peer)
        ack RTT percentiles plus closer/laggard tallies, and the
        aggregate close-stage shares over the closed ring."""

        def pct(vals, q):
            vals = sorted(vals)
            i = min(
                len(vals) - 1,
                max(0, int(round(q / 100.0 * (len(vals) - 1)))),
            )
            return vals[i]

        with self._mu:
            rtts = {k: list(v) for k, v in self._rtts.items()}
            closer = dict(self._closer)
            laggard = dict(self._laggard)
            done = [r.closed for r in self._done if r.closed]
        peers: Dict[str, dict] = {}

        def row(cid, peer):
            return peers.setdefault(
                str(peer),
                {
                    "acks": 0, "rtt_p50_ms": None, "rtt_p99_ms": None,
                    "closer": 0, "laggard": 0,
                    "cls": self._labels(cid, peer)["cls"],
                },
            )

        for (cid, peer), vals in rtts.items():
            d = row(cid, peer)
            d["acks"] += len(vals)
            if vals:
                d["rtt_p50_ms"] = round(pct(vals, 50) * 1e3, 3)
                d["rtt_p99_ms"] = round(pct(vals, 99) * 1e3, 3)
        for (cid, peer), n in closer.items():
            row(cid, peer)["closer"] += n
        for (cid, peer), n in laggard.items():
            row(cid, peer)["laggard"] += n
        stage_sums: Dict[str, float] = {}
        closes = []
        for rec in done:
            if rec.get("close_ms") is not None:
                closes.append(rec["close_ms"])
            st = rec.get("stages_ms")
            if st:
                for k, v in st.items():
                    stage_sums[k] = stage_sums.get(k, 0.0) + max(0.0, v)
        total = sum(stage_sums.values()) or 1.0
        return {
            "commits_attributed": self.commits_attributed,
            "records_dropped": self.records_dropped,
            "peers": peers,
            "close_ms": {
                "p50": round(pct(closes, 50), 3) if closes else None,
                "p99": round(pct(closes, 99), 3) if closes else None,
                "n": len(closes),
            },
            "close_stage_share_pct": {
                k: round(v / total * 100.0, 1)
                for k, v in sorted(stage_sums.items())
            },
            "clock_offsets_ms": {
                a: round(o * 1e3, 4) for a, o in self.offsets().items()
            },
        }
