"""Cross-plane request tracing: follow one proposal (or read) through
every host-plane stage and device round (ISSUE 9 tentpole).

The host plane is a multi-stage pipeline (ingress rings → batcher →
raft step → group-commit WAL → device dispatch → apply pool → egress
sink), but per-stage aggregates cannot say which stage owns a given
request's tail latency.  This module adds the missing connective
tissue: a lightweight trace context allocated at ``propose`` /
``read`` time for a sampled 1-in-N of requests and carried through the
``RequestState`` future; each pipeline stage stamps the context as the
request passes, and the coordinator links the FlightRecorder span seq
of the device round that carried its commit.  The result is

- per-stage latency histograms ``dragonboat_trace_stage_seconds{stage}``
  (stage = time from the previous stamp to this one) plus an always-on
  end-to-end histogram ``dragonboat_trace_e2e_seconds`` fed by every
  request (non-sampled requests carry only a single monotonic enqueue
  timestamp — no allocation, no registration);
- an exportable Chrome-trace / Perfetto JSON (``NodeHost.dump_trace``)
  where one request renders as ONE flow across host threads and device
  rounds (flow events bind the stage slices; linked recorder spans are
  emitted on a ``device-plane`` track);
- a stage-level stall watchdog: a sampled request stuck longer than
  ``stall_ms`` in any one stage auto-dumps its partial trace PLUS the
  flight-recorder ring (the cross-plane twin of the recorder's own
  span watchdog).

Stage vocabulary (a request only carries the stages its path visits):

==============  =========================================================
stage           stamped when
==============  =========================================================
``propose``     the trace is allocated (t0; the enqueue timestamp)
``ipc``         the shared-memory handoff to the hostproc encode worker
                completed (ring enqueue → worker dequeue → encoded burst
                returned) — workers-on path only (ISSUE 12), so the
                latency attribution table can price the process handoff
``ingress``     the entry is staged for raft — after ``entry_q.add`` /
                the native fast-lane append on the direct path, after
                the batcher drain on the compartmentalized path (so the
                ring wait + drain time is the ingress stage)
``raft_step``   raft ingested the entry (``peer.propose_entries``); for
                reads: the ReadIndex ctx was submitted
``wal``         the update carrying the entry is fsynced (committer /
                group-commit WAL release)
``device_round``the coordinator round whose dispatch released the
                group's commit (tpu engine only; replace-style — the
                LAST such round before apply wins — and the recorder
                span seq is linked into ``Trace.spans``)
``read_confirm``the ReadIndex ctx was quorum-confirmed (reads only)
``lease_read``  the read was served locally under a valid leader lease
                (ISSUE 10) — replaces ``read_confirm``; no confirmation
                round ran, so the trace shows the short path
``apply``       the user SM applied the entry / the read's apply
                watermark was reached
``egress``      the client future was notified (trace completes)
==============  =========================================================

Overhead contract (the PR-5 ``is not None`` latch precedent): tracing
is OFF by default — ``NodeHost.tracer`` / ``Node.tracer`` /
``Engine.tracer`` / coordinator ``tracer`` stay ``None``,
``RequestState.trace`` stays ``None``, and every hot-path hook gates on
a plain attribute check, so the trace-off host path is bit-identical.
Trace-ON overhead is measured by the bench trace axis
(``bench_e2e.run_trace_axis``, <5% asserted on the fused host loop).
"""
from __future__ import annotations

import bisect
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..events import DEFAULT_REGISTRY, MetricsRegistry
from ..logger import get_logger

plog = get_logger("trace")

_T = "dragonboat_trace_"

#: seconds-scale stage/e2e histogram buckets: sub-ms direct-path stages
#: at the bottom, a wedged WAL or tunnel stall at the top
STAGE_BUCKETS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0,
)

#: newest-enabled tracer — introspection only (``active()``); every
#: request token carries its owning tracer, so completions never route
#: through this global.
_ACTIVE: Optional["Tracer"] = None


def _default_stall_ms() -> float:
    try:
        return float(os.environ.get("DBTPU_TRACE_STALL_MS", "1000"))
    except ValueError:
        plog.warning("malformed DBTPU_TRACE_STALL_MS; using 1000")
        return 1000.0


class Trace:
    """One sampled request's context: an append-only list of
    ``[stage, perf_counter_t, thread_name]`` stamps plus the recorder
    span seqs linked along the way.  Mutation is GIL-atomic appends from
    the pipeline threads; the tracer's lock guards only the in-flight
    index, never the stamp path."""

    __slots__ = (
        "tracer", "tid", "kind", "cluster_id", "key", "t0",
        "events", "spans", "outcome", "stalled", "done",
        "applied", "_round_ev", "repl",
    )

    def __init__(self, tracer: "Tracer", tid: int, kind: str,
                 cluster_id: int, key: int, t0: float):
        self.tracer = tracer
        self.tid = tid
        self.kind = kind
        self.cluster_id = cluster_id
        self.key = key
        self.t0 = t0
        self.events: List[list] = [["propose", t0, _tname()]]
        self.spans: List[int] = []
        self.outcome: Optional[str] = None
        self.stalled: Optional[str] = None
        self.done = False
        self.applied = False       # an "apply" stamp landed
        self._round_ev = None      # cached device_round event (replace)
        # per-commit quorum attribution summary (obs/replattr.py,
        # ISSUE 14): set by the leader's ReplAttr when the commit
        # covering this proposal closes — None until then / off-plane
        self.repl: Optional[dict] = None

    def add(self, stage: str) -> None:
        self.events.append([stage, time.perf_counter(), _tname()])
        if stage == "apply":
            self.applied = True

    def add_round(self, span_seq: Optional[int], now: float,
                  thread: str) -> None:
        """Replace-style ``device_round`` stamp: a request can sit through
        several coordinator rounds while waiting for apply — the LAST
        round before apply is the one whose dispatch released its commit,
        so later stamps overwrite earlier ones (every linked span seq is
        kept in ``spans`` for the flow export).  Runs once per in-flight
        trace per commit round — the caller hoists the timestamp/thread
        lookup so this is flag checks plus two list stores (a
        per-trace ``perf_counter`` here measured ~10% off the tpu e2e
        loop on the 1-vCPU box)."""
        if self.applied or self.done:
            # already applied: a later round touching this group can no
            # longer be the one that released this request
            return
        if span_seq is not None and (
            not self.spans or self.spans[-1] != span_seq
        ):
            self.spans.append(span_seq)
        ev = self._round_ev
        if ev is not None:
            ev[1] = now
            ev[2] = thread
        else:
            self._round_ev = ev = ["device_round", now, thread]
            self.events.append(ev)

    def to_dict(self) -> dict:
        """JSON-ready snapshot (stall dumps, SIGUSR2 debug dumps)."""
        t0 = self.t0
        return {
            "trace_id": self.tid,
            "kind": self.kind,
            "cluster_id": self.cluster_id,
            "key": self.key,
            "outcome": self.outcome,
            "stalled": self.stalled,
            "done": self.done,
            "spans": list(self.spans),
            "repl": self.repl,
            "events": [
                {
                    "stage": s,
                    "t_ms": round((t - t0) * 1e3, 4),
                    "thread": th,
                }
                for s, t, th in sorted(self.events, key=lambda e: e[1])
            ],
        }


def _tname() -> str:
    return threading.current_thread().name


class Tracer:
    """Sampling allocator + in-flight index + stage histogram publisher.

    ``sample_every=N`` traces 1 request in N (N=1 traces everything —
    tests and targeted debugging).  Hot-path cost for the other N-1:
    one float timestamp on the future and one e2e histogram observation
    at completion.  The in-flight index is keyed two ways: by entry key
    (``mark_entries``/``mark_updates`` — the raft-step and WAL hooks see
    entries, not futures) and by cluster id (``mark_clusters`` — the
    coordinator round fan-out sees groups)."""

    def __init__(
        self,
        sample_every: int = 64,
        registry: Optional[MetricsRegistry] = None,
        recorder=None,
        stall_ms: Optional[float] = None,
        keep: int = 256,
    ):
        if sample_every < 1:
            raise ValueError("sample_every must be >= 1")
        self.sample_every = int(sample_every)
        self.registry = registry or DEFAULT_REGISTRY
        self.recorder = recorder  # FlightRecorder or None
        self.stall_ms = (
            _default_stall_ms() if stall_ms is None else float(stall_ms)
        )
        self.dump_path = os.environ.get("DBTPU_TRACE_DUMP")
        self._mu = threading.Lock()
        self._n = 0          # requests seen (sampling counter)
        self._tid = 0        # trace ids
        self._by_key: Dict[int, Trace] = {}
        self._by_cluster: Dict[int, set] = {}
        self._done: deque = deque(maxlen=max(1, keep))
        self.sampled = 0
        self.completed = 0
        self.discarded = 0  # contexts whose submission was rejected
        self.stall_dumps = 0
        self.last_stall_dump: Optional[dict] = None
        # ---- replication tracing (ISSUE 14) --------------------------
        # this host's raft address (dump/merge identity) and the
        # leader-side attribution plane — both wired by NodeHost
        self.host = ""
        self.replattr = None
        # follower-leg records: a sampled REPLICATE from ANOTHER host's
        # leader stamped its stages here; the ack-send hook files the
        # completed leg so dump_trace renders the follower half of the
        # flow and tools/trace_merge.py can join it to the leader's
        self._repl_legs: deque = deque(maxlen=max(16, keep))
        # ---- local metric accumulators (hot-path cost control) -------
        # The propose/notify paths run at full request rate; a registry
        # histogram observe per completion (lock + label-key build)
        # measured ~20% off on the 1-vCPU e2e loop.  Observations land
        # in these plain lists under the tracer's own lock and flush to
        # the registry in ONE merge per tick (check_stalls) or when the
        # last in-flight trace completes — exposition lag <= one RTT.
        self._bk = STAGE_BUCKETS_S
        nb = len(self._bk) + 1
        self._e2e_acc = [[0] * nb, 0.0, 0]        # counts, sum, n
        self._stage_acc: Dict[str, list] = {}     # stage -> same shape
        self._pend_requests = 0
        self._pend_sampled = 0
        self._pend_completed = 0
        # clock anchor: stamps are perf_counter (monotonic); the export
        # maps them onto the wall clock the recorder spans already use
        self._wall0 = time.time()
        self._pc0 = time.perf_counter()
        r = self.registry
        r.describe(
            _T + "requests_total",
            "requests that entered the traced pipeline (sampled or not)",
        )
        r.describe(_T + "sampled_total", "requests allocated a full trace")
        r.describe(_T + "completed_total", "sampled traces completed")
        r.describe(
            _T + "stalls_total",
            "sampled requests stuck >stall_ms in one stage (auto-dumped)",
        )
        r.describe(_T + "inflight", "sampled traces currently in flight")
        r.describe(
            _T + "stage_seconds",
            "per-stage latency of sampled requests (time from the "
            "previous pipeline stamp to this stage's stamp)",
        )
        r.describe(
            _T + "e2e_seconds",
            "end-to-end request latency (enqueue to future notify), "
            "observed for EVERY request while tracing is on",
        )
        r.counter_add(_T + "requests_total", 0)
        r.counter_add(_T + "sampled_total", 0)
        r.counter_add(_T + "completed_total", 0)
        r.counter_add(_T + "stalls_total", 0)
        r.gauge_set(_T + "inflight", 0)
        r.histogram_declare(_T + "e2e_seconds", buckets=STAGE_BUCKETS_S)
        global _ACTIVE
        _ACTIVE = self

    # ------------------------------------------------------------------
    # allocation (propose / read time)
    # ------------------------------------------------------------------

    def attach_all(self, states, cluster_id: int, t0: float,
                   kind: str = "write") -> None:
        """Allocate contexts for a burst of freshly created futures:
        1-in-N gets a :class:`Trace` (registered by key + cluster), the
        rest share one ``(tracer, t0)`` token (the always-on enqueue
        timestamp feeding the e2e histogram at notify).  The common
        no-sample-in-this-burst case touches one lock and one attribute
        store per future — nothing else."""
        n = self.sample_every
        nstates = len(states)
        tok = (self, t0)  # ONE shared token per burst: non-sampled
        # futures carry (tracer, t0) so completion observes e2e into the
        # tracer that owns them (a module-global sink misattributed
        # multi-NodeHost processes), at zero per-request allocation
        with self._mu:
            base = self._n
            self._n = base + nstates
            self._pend_requests += nstates
            first = (-base) % n  # index of the first sampled slot
            if first >= nstates:
                for rs in states:
                    rs.trace = tok
                return
            sampled = []
            for i, rs in enumerate(states):
                if (i - first) % n == 0:
                    self._tid += 1
                    tr = Trace(self, self._tid, kind, cluster_id,
                               rs.key, t0)
                    rs.trace = tr
                    if rs.key:
                        self._by_key[rs.key] = tr
                    self._by_cluster.setdefault(cluster_id, set()).add(tr)
                    self.sampled += 1
                    self._pend_sampled += 1
                    sampled.append(rs)
                else:
                    rs.trace = tok
        # a future that completed before its context landed (the pipeline
        # can beat the attach on a hot box) must not leak in flight
        for rs in sampled:
            if rs.done():
                self.finish(rs.trace, rs.trace.outcome or "completed")

    def attach_one(self, rs, cluster_id: int, t0: float,
                   kind: str = "write") -> None:
        self.attach_all((rs,), cluster_id, t0, kind=kind)

    def discard(self, states) -> None:
        """Unregister contexts whose submission failed BEFORE the future
        could ever be notified (e.g. the ingress ring-cap SystemBusy
        raise happens after attach but before the futures reach any
        tracker — no notify will ever finish these, so they must not
        linger in flight for the stall watchdog to chase)."""
        with self._mu:
            for rs in states:
                t = rs.trace
                if t.__class__ is not Trace or t.done:
                    continue
                t.done = True
                t.outcome = "unsubmitted"
                if t.key:
                    self._by_key.pop(t.key, None)
                s = self._by_cluster.get(t.cluster_id)
                if s is not None:
                    s.discard(t)
                    if not s:
                        del self._by_cluster[t.cluster_id]
                # sampled_total is NOT decremented: the sample did
                # happen, and a tick flush may already have published it
                # — a negative delta would read as a Prometheus counter
                # reset.  sampled - completed - inflight = discarded.
                self.discarded += 1

    # ------------------------------------------------------------------
    # stage stamps (pipeline hooks)
    # ------------------------------------------------------------------

    @staticmethod
    def mark(rs, stage: str) -> None:
        """Stamp a stage on a future's trace (no-op for the non-sampled
        token, and for a COMPLETED trace — a burst's dropped tail
        finishes before the caller's post-staging mark loop runs, and a
        post-egress stamp would corrupt the time-sorted export).
        Callers gate on ``rs.trace is not None`` first."""
        t = rs.trace
        if t.__class__ is Trace and not t.done:
            t.add(stage)

    def mark_entries(self, entries, stage: str) -> None:
        """Stamp by entry key (raft-step hook: the staged entries are in
        hand, the futures are not)."""
        bk = self._by_key
        if not bk:
            return
        for e in entries:
            t = bk.get(e.key)
            if t is not None and not t.done:
                t.add(stage)

    def mark_updates(self, updates, stage: str) -> None:
        """Stamp every sampled entry carried by a persisted update batch
        (WAL hook, after the fsync)."""
        bk = self._by_key
        if not bk:
            return
        for ud in updates:
            for e in ud.entries_to_save:
                t = bk.get(e.key)
                if t is not None and not t.done:
                    t.add(stage)

    def mark_clusters(self, cids, span_seq: Optional[int] = None) -> None:
        """The coordinator round released commits/read-confirms for these
        groups: stamp ``device_round`` (replace-style) on every in-flight
        trace of those groups and link the dispatch span seq."""
        if not self._by_cluster:
            return
        now = time.perf_counter()
        thread = _tname()
        with self._mu:
            # stamp UNDER the lock: every set mutator (attach/finish/
            # discard) holds _mu too, so direct iteration is safe and
            # skips a per-round snapshot list — this runs on the
            # coordinator round thread, the tpu path's bottleneck, so
            # per-round allocations here are throughput (one lock per
            # ROUND, add_round is flag checks + two list stores)
            bc = self._by_cluster
            get = bc.get
            for cid in cids:
                for t in get(cid, ()):
                    t.add_round(span_seq, now, thread)

    # ------------------------------------------------------------------
    # replication legs (ISSUE 14, follower side)
    # ------------------------------------------------------------------

    def add_repl_leg(self, ctx) -> None:
        """File one completed follower leg of a sampled replication (the
        inbound REPLICATE's :class:`~dragonboat_tpu.wire.ReplTrace`
        stamps, recorded when the ack leaves this host).  The leg
        renders as ``follower_append`` / ``follower_fsync`` /
        ``ack_send`` slices in this host's Perfetto dump, carrying the
        LEADER's trace id + origin so ``tools/trace_merge.py`` can bind
        it into the leader's flow."""
        with self._mu:
            self._repl_legs.append({
                "tid": ctx.tid,
                "origin": ctx.origin,
                "index": ctx.index,
                "t_recv": ctx.t_recv,
                "t_append": ctx.t_append,
                "t_fsync": ctx.t_fsync,
                "t_ack": ctx.t_ack,
            })

    def repl_legs(self) -> List[dict]:
        with self._mu:
            return list(self._repl_legs)

    # ------------------------------------------------------------------
    # completion
    # ------------------------------------------------------------------

    def _acc(self, acc: list, seconds: float) -> None:
        """Accumulate one observation into a local [counts, sum, n]
        triple; caller holds ``_mu``."""
        acc[0][bisect.bisect_left(self._bk, seconds)] += 1
        acc[1] += seconds
        acc[2] += 1

    def observe_e2e(self, seconds: float) -> None:
        with self._mu:
            self._acc(self._e2e_acc, seconds)

    def finish(self, trace: Trace, outcome: str) -> None:
        """Trace completes (future notified): final ``egress`` stamp,
        stage + e2e observations (accumulated locally; flushed to the
        registry on the tick cadence), move to the completed ring."""
        with self._mu:
            # atomic claim: attach_all's already-done cleanup and the
            # notify thread's request_done can race here — exactly one
            # may run the completion half
            if trace.done:
                return
            trace.done = True
        trace.outcome = outcome
        trace.add("egress")
        evs = sorted(trace.events, key=lambda e: e[1])
        with self._mu:
            if trace.key:
                self._by_key.pop(trace.key, None)
            s = self._by_cluster.get(trace.cluster_id)
            if s is not None:
                s.discard(trace)
                if not s:
                    del self._by_cluster[trace.cluster_id]
            self._done.append(trace)
            prev = evs[0][1]
            for stage, t, _th in evs[1:]:
                acc = self._stage_acc.get(stage)
                if acc is None:
                    acc = self._stage_acc[stage] = [
                        [0] * (len(self._bk) + 1), 0.0, 0,
                    ]
                self._acc(acc, max(0.0, t - prev))
                prev = t
            self._acc(self._e2e_acc, max(0.0, evs[-1][1] - trace.t0))
            self._pend_completed += 1
            idle = not self._by_cluster
        self.completed += 1
        if idle:
            # the last in-flight trace just completed: flush now so a
            # quiet scrape (or a test right after the load) sees it —
            # under sustained load the tick-worker flush covers instead
            self.flush_metrics()

    def flush_metrics(self) -> None:
        """Publish the locally accumulated observations to the registry
        in one pass (called by the NodeHost tick worker via
        :meth:`check_stalls`, on going idle, and at :meth:`close`)."""
        with self._mu:
            e2e, self._e2e_acc = self._e2e_acc, [
                [0] * (len(self._bk) + 1), 0.0, 0,
            ]
            stages, self._stage_acc = self._stage_acc, {}
            reqs, self._pend_requests = self._pend_requests, 0
            samp, self._pend_sampled = self._pend_sampled, 0
            comp, self._pend_completed = self._pend_completed, 0
            inflight = sum(len(v) for v in self._by_cluster.values())
        reg = self.registry
        if reqs:
            reg.counter_add(_T + "requests_total", reqs)
        if samp:
            reg.counter_add(_T + "sampled_total", samp)
        if comp:
            reg.counter_add(_T + "completed_total", comp)
        if samp or comp:
            reg.gauge_set(_T + "inflight", inflight)
        if e2e[2]:
            reg.histogram_merge(
                _T + "e2e_seconds", e2e[0], e2e[1], e2e[2],
                buckets=self._bk,
            )
        for stage, acc in stages.items():
            reg.histogram_merge(
                _T + "stage_seconds", acc[0], acc[1], acc[2],
                labels={"stage": stage}, buckets=self._bk,
            )

    def close(self) -> None:
        """Flush and detach from the module-level e2e sink
        (NodeHost.stop)."""
        global _ACTIVE
        if _ACTIVE is self:
            _ACTIVE = None
        self.flush_metrics()

    # ------------------------------------------------------------------
    # stall watchdog (the host-stage extension of the recorder's)
    # ------------------------------------------------------------------

    def check_stalls(self) -> int:
        """Scan in-flight traces for one stuck longer than ``stall_ms``
        since its last stamp; each trips at most once and auto-dumps its
        partial trace plus the recorder ring.  Driven by the NodeHost
        tick worker (and callable on demand); returns newly stalled
        count.  Doubles as the metric-flush cadence.  The fast path —
        nothing sampled in flight, nothing pending — is a few
        truthiness checks."""
        if self._pend_requests or self._pend_completed or self._e2e_acc[2]:
            self.flush_metrics()
        if not self._by_cluster and not self._by_key:
            return 0
        th = self.stall_ms
        if th <= 0:
            return 0
        now = time.perf_counter()
        with self._mu:
            traces = {t for s in self._by_cluster.values() for t in s}
            traces.update(self._by_key.values())
        newly: List[Trace] = []
        for t in traces:
            if t.done or t.stalled:
                continue
            evs = t.events
            if not evs:
                continue
            last_stage, last_t, _ = max(evs, key=lambda e: e[1])
            if (now - last_t) * 1e3 >= th:
                t.stalled = last_stage
                newly.append(t)
        if newly:
            self.registry.counter_add(_T + "stalls_total", len(newly))
            # ONE aggregate dump per pass: a systemic stall trips many
            # sampled traces at once, and per-trace dumps would
            # serialize the recorder ring N times inline on the tick
            # worker — the thread driving raft timers — exactly when
            # the system is already degraded
            self._stall_dump(newly, now)
        return len(newly)

    def _stall_dump(self, stalled: List[Trace], now: float) -> None:
        head = stalled[0]
        last_t = max(e[1] for e in head.events)
        d = {
            "reason": (
                f"trace-stall: {len(stalled)} sampled request(s) stuck "
                f">= {self.stall_ms:g}ms in one stage (first: {head.kind} "
                f"trace {head.tid}, {(now - last_t) * 1e3:.0f}ms after "
                f"stage {head.stalled!r})"
            ),
            "time": time.time(),
            "trace": head.to_dict(),  # the first/triggering trace
            "traces": [t.to_dict() for t in stalled],
            "recorder": (
                self.recorder.to_json() if self.recorder is not None
                else None
            ),
        }
        self.last_stall_dump = d
        self.stall_dumps += 1
        path = self.dump_path
        if path:
            try:
                with open(path, "w") as f:
                    json.dump(d, f, indent=1, default=str)
            except OSError as e:
                plog.warning("trace stall dump to %s failed: %r", path, e)
        plog.warning("%s%s", d["reason"], f" -> {path}" if path else "")

    # ------------------------------------------------------------------
    # introspection / export
    # ------------------------------------------------------------------

    def reset_completed(self, keep: Optional[int] = None) -> None:
        """Clear the completed-trace ring (optionally resizing it) —
        bench phases scope an attribution measurement to one window this
        way; steady state keeps the bounded default."""
        with self._mu:
            self._done = deque(maxlen=max(1, keep or self._done.maxlen))

    def inflight(self) -> List[Trace]:
        with self._mu:
            s = {t for v in self._by_cluster.values() for t in v}
            s.update(self._by_key.values())
            return sorted(s, key=lambda t: t.tid)

    def traces(self) -> List[Trace]:
        """Completed (oldest→newest) then in-flight traces."""
        with self._mu:
            done = list(self._done)
        return done + [t for t in self.inflight() if t not in done]

    def to_json(self) -> dict:
        return {
            "sample_every": self.sample_every,
            "requests": self._n,
            "sampled": self.sampled,
            "completed": self.completed,
            "discarded": self.discarded,
            "stall_dumps": self.stall_dumps,
            "inflight": [t.to_dict() for t in self.inflight()],
            "traces": [t.to_dict() for t in self.traces() if t.done],
        }

    def stage_stats(self) -> dict:
        """Per-stage p50/p99 (ms) + share-of-e2e over the completed ring
        — the data behind the perf ledger's latency-attribution table."""
        with self._mu:
            done = list(self._done)
        return compute_stage_stats(done)

    def _wall_us(self, t_perf: float) -> float:
        return (self._wall0 + (t_perf - self._pc0)) * 1e6

    def export_chrome(self, include_recorder: bool = True,
                      limit: Optional[int] = None) -> dict:
        """Chrome-trace / Perfetto JSON: each sampled request is a chain
        of ``X`` slices (one per stage, on the thread that stamped it)
        bound into ONE flow by ``s``/``t``/``f`` events with
        ``id=trace_id``; linked recorder spans render on a
        ``device-plane`` track next to them.  Load in Perfetto / about:
        //tracing, or ship to teammates as-is."""
        events: List[dict] = []
        tids: Dict[str, int] = {}

        def tid_of(name: str) -> int:
            tid = tids.get(name)
            if tid is None:
                tid = tids[name] = len(tids) + 1
                events.append({
                    "ph": "M", "name": "thread_name", "pid": 1,
                    "tid": tid, "args": {"name": name},
                })
            return tid

        traces = self.traces()
        if limit is not None:
            traces = traces[-limit:]
        for t in traces:
            evs = sorted(t.events, key=lambda e: e[1])
            if len(evs) < 2:
                continue
            flow = []
            prev_t = evs[0][1]
            for stage, ts, thread in evs[1:]:
                tid = tid_of(thread)
                ev = {
                    "name": stage,
                    "cat": t.kind,
                    "ph": "X",
                    "pid": 1,
                    "tid": tid,
                    "ts": round(self._wall_us(prev_t), 1),
                    "dur": round(max(0.0, ts - prev_t) * 1e6, 1),
                    "args": {
                        "trace_id": t.tid,
                        "cluster_id": t.cluster_id,
                        "outcome": t.outcome,
                    },
                }
                if stage == "device_round" and t.spans:
                    ev["args"]["recorder_spans"] = list(t.spans)
                events.append(ev)
                flow.append((tid, prev_t))
                prev_t = ts
            flow.append((tid_of(evs[-1][2]), prev_t))
            for i, (tid, ts) in enumerate(flow):
                ph = "s" if i == 0 else ("f" if i == len(flow) - 1 else "t")
                ev = {
                    "name": f"{t.kind}-{t.tid}",
                    "cat": "request",
                    "ph": ph,
                    "id": t.tid,
                    "pid": 1,
                    "tid": tid,
                    "ts": round(self._wall_us(ts), 1),
                }
                if ph == "f":
                    ev["bp"] = "e"
                events.append(ev)
        # follower legs of OTHER hosts' sampled replications (ISSUE 14):
        # stage slices in this host's wall clock, flow-stepped under the
        # leader's trace id so a cross-host merge binds them into the
        # leader's request flow (tools/trace_merge.py)
        for leg in self.repl_legs():
            t_recv = leg["t_recv"]
            if not t_recv:
                continue
            leg_tid = tid_of("repl-follower")
            prev = t_recv
            for stage, key in (
                ("follower_append", "t_append"),
                ("follower_fsync", "t_fsync"),
                ("ack_send", "t_ack"),
            ):
                ts = leg[key]
                if not ts:
                    continue
                events.append({
                    "name": stage,
                    "cat": "repl",
                    "ph": "X",
                    "pid": 1,
                    "tid": leg_tid,
                    "ts": round(prev * 1e6, 1),
                    "dur": round(max(0.0, ts - prev) * 1e6, 1),
                    "args": {
                        "trace_id": leg["tid"],
                        "origin": leg["origin"],
                        "index": leg["index"],
                    },
                })
                prev = ts
            events.append({
                "name": f"write-{leg['tid']}",
                "cat": "request",
                "ph": "t",
                "id": leg["tid"],
                "pid": 1,
                "tid": leg_tid,
                "ts": round(t_recv * 1e6, 1),
                # the flow id is the LEADER's trace id — origin lets
                # tools/trace_merge.py remap ids per originating host so
                # two leaders' flows can never collide in a merged file
                "args": {"origin": leg["origin"]},
            })
        if include_recorder and self.recorder is not None:
            dev_tid = tid_of("device-plane")
            for span in self.recorder.spans():
                ts = span.get("ts")
                if ts is None:
                    continue
                dur_ms = span.get("wall_ms") or (
                    (span.get("dispatch_ms") or 0.0)
                    + (span.get("egress_ms") or 0.0)
                )
                events.append({
                    "name": span.get("kind", "span"),
                    "cat": "device",
                    "ph": "X",
                    "pid": 1,
                    "tid": dev_tid,
                    "ts": round(ts * 1e6, 1),
                    "dur": round(max(dur_ms, 0.001) * 1e3, 1),
                    "args": {
                        k: v for k, v in span.items()
                        if k not in ("ts",)
                    },
                })
        ra = self.replattr
        return {
            "displayTimeUnit": "ms",
            "traceEvents": events,
            "metadata": {
                "tracer": {
                    "sample_every": self.sample_every,
                    "requests": self._n,
                    "sampled": self.sampled,
                    "completed": self.completed,
                },
                # multi-host merge keys (ISSUE 14): this dump's host
                # identity plus its leader-side ack-pair clock-offset
                # estimates per peer address (follower − leader seconds)
                "host": self.host,
                "repl_offsets": ra.offsets() if ra is not None else {},
                "repl_legs": len(self._repl_legs),
            },
        }


def compute_stage_stats(traces) -> dict:
    """Per-stage p50/p99 (ms) + share-of-e2e over completed traces —
    ONE implementation serving both ``Tracer.stage_stats`` and the
    bench trace axis's cross-host merge (nearest-rank percentiles, so
    the two surfaces can never disagree on identical data)."""
    per: Dict[str, List[float]] = {}
    e2e: List[float] = []
    for t in traces:
        if not t.done:
            continue
        evs = sorted(t.events, key=lambda e: e[1])
        prev = evs[0][1]
        for stage, ts, _th in evs[1:]:
            per.setdefault(stage, []).append(max(0.0, ts - prev))
            prev = ts
        e2e.append(max(0.0, evs[-1][1] - t.t0))

    def pct(vals, q):
        vals = sorted(vals)
        i = min(len(vals) - 1, int(round(q / 100.0 * (len(vals) - 1))))
        return vals[i]

    total = sum(e2e) or 1.0
    out = {
        "traces": len(e2e),
        "e2e": {
            "p50_ms": round(pct(e2e, 50) * 1e3, 3),
            "p99_ms": round(pct(e2e, 99) * 1e3, 3),
        } if e2e else None,
        "stages": {},
    }
    for stage, vals in sorted(per.items()):
        out["stages"][stage] = {
            "p50_ms": round(pct(vals, 50) * 1e3, 3),
            "p99_ms": round(pct(vals, 99) * 1e3, 3),
            "share_pct": round(sum(vals) / total * 100.0, 1),
            "n": len(vals),
        }
    return out


# ----------------------------------------------------------------------
# completion hook (requests.RequestState.notify)
# ----------------------------------------------------------------------

#: outcome names derived from requests.RequestResultCode (lazily — the
#: requests module imports this one); a hand-copied literal table would
#: silently drift when a code is added
_OUTCOMES: Optional[Dict[int, str]] = None


def _outcome_name(result) -> str:
    global _OUTCOMES
    if _OUTCOMES is None:
        from ..requests import RequestResultCode

        _OUTCOMES = {int(c): c.name.lower() for c in RequestResultCode}
    return _OUTCOMES.get(int(getattr(result, "code", 1)), "completed")


def request_done(token, result) -> None:
    """Called by ``RequestState.notify`` when the future carries a trace
    token.  A ``(tracer, t0)`` tuple is the always-on enqueue timestamp
    of a non-sampled request: observe e2e into its owning tracer.  A
    :class:`Trace` completes into the tracer that allocated it."""
    if token.__class__ is Trace:
        token.tracer.finish(token, _outcome_name(result))
        return
    tracer, t0 = token
    tracer.observe_e2e(time.perf_counter() - t0)


def active() -> Optional[Tracer]:
    """The newest-enabled tracer (None when tracing is off)."""
    return _ACTIVE
