"""Batched quorum engine: the TPU-native heart of the framework.

The reference iterates thousands of Raft groups one at a time
(``execengine.go:923`` ``processSteps``; ``internal/raft/raft.go:861-909``
``tryCommit``; ``raft.go:1062-1080`` vote tally).  Here the per-group,
per-tick dense bookkeeping lives in ``(nGroups, nPeers)`` device arrays
stepped by ONE fused jit dispatch per tick (SURVEY.md §7), while rare
control-flow-heavy transitions (membership change, snapshot install, log
rejection backtracking) remain scalar on host and mask-update the tensors.

Modules:

* :mod:`.state`   — the ``QuorumState`` pytree layout + host<->device codec
* :mod:`.kernels` — pure jit kernels (commit quorum, vote tally, tick, ...)
* :mod:`.engine`  — ``BatchedQuorumEngine`` host driver (delta ingest,
  one dispatch per tick, egress of flags/commit advances)
* :mod:`.sharding` — device-mesh sharding of the group axis for multi-chip
"""

from .state import QuorumState, make_state, INDEX_MIN  # noqa: F401
from .kernels import (  # noqa: F401
    commit_quorum,
    vote_tally,
    check_quorum,
    tick_step,
    quorum_step,
    quorum_step_dense,
    quorum_multistep,
    quorum_multistep_dense,
)
from .engine import BatchedQuorumEngine  # noqa: F401
