"""Host driver for the batched quorum engine.

Replaces the reference's 16-worker per-group iteration
(``execengine.go:860-949``) with: host ingest (queues → compact event
batches) → ONE ``quorum_step`` device dispatch per round → host egress
(commit advances, election/heartbeat/step-down flags).  Rare transitions
(membership change, becoming leader/candidate, snapshot restore, index
rebase) mutate a numpy mirror row and are scattered onto the device arrays
before the next dispatch.

The group axis is shardable over a ``jax.sharding.Mesh`` (see
``sharding.py``): every kernel op is row-wise over groups, so XLA partitions
the whole step with zero collectives — groups are embarrassingly parallel,
exactly like the reference's ``clusterID % workers`` partitioning but over
chips instead of goroutines.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import nullcontext
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs as _obs
from ..logger import get_logger
from .kernels import TELEM_TOPK, quorum_step
from .state import (
    CANDIDATE,
    FOLLOWER,
    KV_ENT_SLOTS,
    KV_READ_SLOTS,
    KV_SLOTS,
    LEADER,
    OBSERVER,
    READ_SLOTS,
    VOTE_GRANT,
    VOTE_NONE,
    VOTE_REJECT,
    WITNESS,
    HostMirror,
    QuorumState,
)

elog = get_logger("ops.engine")

# Event batches are padded to fixed sizes so jit compiles once.
DEFAULT_EVENT_CAP = 4096

# Rebase a row when relative indexes pass this (well clear of int32 max).
REBASE_THRESHOLD = 1 << 30

#: padded fused-block sizes the live coordinator dispatches (and the
#: warmup pass pre-compiles): a K-round backlog pads up to the nearest
#: bucket, so the whole adaptive range is served by len(buckets) compiled
#: programs (the per-round tick mask makes padding rounds provable no-ops)
WARM_K_BUCKETS = (4, 16)


def k_bucket(k: int, buckets=WARM_K_BUCKETS) -> int:
    """Smallest warm bucket holding ``k`` rounds (the largest bucket for
    anything beyond — callers cap K at ``max(buckets)``)."""
    for b in buckets:
        if k <= b:
            return b
    return buckets[-1]


def upload_nbytes(*arrays) -> int:
    """Total bytes of the host tensors one dispatch ships (``None``
    entries — compiled-out optional planes — are skipped).  The ONE
    accounting point for host→device event-tensor volume: the flight
    recorder's ``upload_bytes`` span field, the
    ``dragonboat_device_upload_bytes_total`` counter and the devprof
    capacity model's per-dispatch term all read this, so the sum can
    never drift from the tensors actually passed to the kernel (ISSUE 15
    satellite — three hand-maintained per-site sums preceded it).
    Callers pass EXACTLY the argument tuple the kernel receives; the
    few-byte dummies of compiled-out planes are counted (they are
    genuinely uploaded)."""
    return int(sum(a.nbytes for a in arrays if a is not None))


# ----------------------------------------------------------------------
# persistent XLA compilation cache (ISSUE 7 tentpole)
# ----------------------------------------------------------------------
# jax's persistent compilation cache makes restarts skip XLA compilation
# entirely: the warmup pass's first run populates it, every later process
# deserializes the compiled executables in milliseconds.  The directory
# is VERSIONED by a hash of the kernel sources — a kernel change gets a
# fresh subdirectory instead of silently mixing stale executables (jax
# keys on the HLO, which would catch most but not all drift, e.g. a
# semantics change hidden behind an unchanged trace shape).

_CC_MU = threading.Lock()
_CC = {"dir": None, "hits": 0, "misses": 0, "listener": False,
       "read_patched": False}
#: serializes jax's compile-or-deserialize step process-wide once the
#: persistent cache is enabled: concurrent cache-hit deserialization on
#: the shared XLA CPU client corrupts the heap (reproduced 3/3 — three
#: engines warming from a hot cache in one process segfault in the warm
#: thread; a read-only lock around get_executable_and_time still wedged
#: or crashed 2/3, so the unsafe window spans the whole
#: compile_or_get_cached step).  Held only when a program is NOT in the
#: in-memory jit cache, so the dispatch hot path pays nothing.  RLock:
#: a compile may re-enter for subcomputations.
_CC_COMPILE_MU = threading.RLock()


def kernel_source_hash() -> str:
    """SHA-256 over the kernel-defining sources (kernels.py + state.py):
    the version key of the persistent compilation cache directory."""
    import hashlib

    h = hashlib.sha256()
    base = os.path.dirname(os.path.abspath(__file__))
    for fname in ("kernels.py", "state.py"):
        with open(os.path.join(base, fname), "rb") as f:
            h.update(f.read())
    return h.hexdigest()


def _cc_listener(event: str, **kwargs) -> None:
    # jax.monitoring fires for EVERY event; keep this O(1) cheap
    if event == "/jax/compilation_cache/cache_hits":
        _CC["hits"] += 1
    elif event == "/jax/compilation_cache/cache_misses":
        _CC["misses"] += 1


def enable_persistent_compilation_cache(cache_dir: str) -> str:
    """Point jax's persistent compilation cache at
    ``cache_dir/xla-<kernel-source-hash>`` and install the hit/miss
    counter.  Idempotent; returns the versioned directory.  Safe to call
    before or after backend init (the cache is consulted per compile).
    The min-compile-time/min-entry-size floors are zeroed so even the
    fast single-round programs persist — on the 1-2 vCPU boxes this
    targets, "fast" compiles are still hundreds of ms of stall."""
    versioned = os.path.join(cache_dir, "xla-" + kernel_source_hash()[:16])
    with _CC_MU:
        os.makedirs(versioned, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", versioned)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except AttributeError:  # older jax: flag absent, floor already 0
            pass
        # jax latches "cache in use?" at the FIRST compile of the process
        # (compilation_cache.is_cache_used's _cache_checked flag): enabling
        # the directory after anything has compiled — a NodeHost that
        # touched jax before the coordinator, a test suite with earlier
        # device work — would silently never engage the cache.  reset_cache
        # drops that latch (not the compiled executables) so the next
        # compile re-evaluates the config.
        try:
            from jax._src import compilation_cache as _jcc

            _jcc.reset_cache()
            # serialize compile-or-deserialize process-wide (see
            # _CC_COMPILE_MU): patching the single entry point covers
            # every engine, warm thread and round thread without
            # touching the execute fast path (already-jit-cached
            # programs never reach compiler.compile_or_get_cached)
            if not _CC["read_patched"]:
                from jax._src import compiler as _jcompiler

                _orig_cc = _jcompiler.compile_or_get_cached

                def _locked_cc(*a, **k):
                    with _CC_COMPILE_MU:
                        return _orig_cc(*a, **k)

                # pxla resolves this through the module attribute at
                # call time, so rebinding here covers every caller
                _jcompiler.compile_or_get_cached = _locked_cc
                _CC["read_patched"] = True
        except Exception:  # pragma: no cover - jax internals moved
            elog.warning(
                "compilation-cache latch reset/read-lock unavailable; a "
                "process that compiled before enabling the cache may not "
                "use it, and concurrent cache reads are unserialized"
            )
        if not _CC["listener"]:
            from jax import monitoring as _mon

            _mon.register_event_listener(_cc_listener)
            _CC["listener"] = True
        _CC["dir"] = versioned
    return versioned


def compilation_cache_stats() -> dict:
    """Persistent-cache telemetry: the versioned directory plus process-
    lifetime hit/miss counts (None dir = cache never enabled here)."""
    return {"dir": _CC["dir"], "hits": _CC["hits"], "misses": _CC["misses"]}


@dataclass
class GroupInfo:
    cluster_id: int
    row: int
    slots: Dict[int, int]            # node_id -> peer slot
    base: int = 0                    # uint64 absolute index of rel 0
    node_ids: List[int] = field(default_factory=list)


class StepResult:
    """Egress of one dispatch, in absolute-index / cluster-id terms.

    ``commit`` materializes lazily from the vectorized egress arrays: hot
    callers (the bench rungs, watermark probes) read the arrays or the
    engine's ``committed_view`` and never pay the per-row dict build."""

    __slots__ = (
        "won", "lost", "elect", "heartbeat", "demote",
        "_commit_cids", "_commit_abs", "_commit_dict",
        "read_cids", "read_slots", "read_index_abs", "read_counts",
        "_reads_list",
        "kv_cids", "kv_slots", "kv_vals", "kv_index_abs",
        "_kv_reads_list", "kv_applied_ops",
    )

    def __init__(self):
        self._commit_cids = None   # np (n,) int64 cluster ids, or None
        self._commit_abs = None    # np (n,) int64 absolute committed
        self._commit_dict: Optional[Dict[int, int]] = None
        self.won: List[int] = []
        self.lost: List[int] = []
        self.elect: List[int] = []
        self.heartbeat: List[int] = []
        self.demote: List[int] = []
        # confirmed-read egress, vectorized (None when the dispatch ran
        # read-free): per confirmed pending-read slot, the cluster, the
        # slot, the ABSOLUTE release index, and how many client reads
        # the batch carried.  Like the commit egress, hot callers read
        # the arrays; the list-of-tuples view materializes lazily.
        self.read_cids: Optional[np.ndarray] = None       # (n,) int64
        self.read_slots: Optional[np.ndarray] = None      # (n,) int64
        self.read_index_abs: Optional[np.ndarray] = None  # (n,) int64
        self.read_counts: Optional[np.ndarray] = None     # (n,) int64
        self._reads_list = None
        # devsm KV read egress (None when the dispatch ran kv-free): per
        # captured read slot, the cluster, the slot, the captured value
        # and the ABSOLUTE commit watermark the value reflects; plus the
        # total ops the apply fold consumed this dispatch.
        self.kv_cids: Optional[np.ndarray] = None         # (n,) int64
        self.kv_slots: Optional[np.ndarray] = None        # (n,) int64
        self.kv_vals: Optional[np.ndarray] = None         # (n,) int64
        self.kv_index_abs: Optional[np.ndarray] = None    # (n,) int64
        self._kv_reads_list = None
        self.kv_applied_ops: int = 0

    @property
    def commit(self) -> Dict[int, int]:
        """cluster_id -> new committed (abs); built on first access."""
        if self._commit_dict is None:
            if self._commit_cids is None or not len(self._commit_cids):
                self._commit_dict = {}
            else:
                self._commit_dict = dict(
                    zip(self._commit_cids.tolist(), self._commit_abs.tolist())
                )
        return self._commit_dict

    @property
    def reads(self) -> List[Tuple[int, int, int, int]]:
        """Confirmed reads as ``(cluster_id, slot, abs_index, count)``
        tuples; built on first access (vectorized twin: the
        ``read_*`` arrays)."""
        if self._reads_list is None:
            if self.read_cids is None or not len(self.read_cids):
                self._reads_list = []
            else:
                self._reads_list = list(
                    zip(
                        self.read_cids.tolist(),
                        self.read_slots.tolist(),
                        self.read_index_abs.tolist(),
                        self.read_counts.tolist(),
                    )
                )
        return self._reads_list

    @property
    def kv_reads(self) -> List[Tuple[int, int, int, int]]:
        """Captured devsm KV reads as ``(cluster_id, slot, value,
        abs_index)`` tuples; built on first access (vectorized twin: the
        ``kv_*`` arrays)."""
        if self._kv_reads_list is None:
            if self.kv_cids is None or not len(self.kv_cids):
                self._kv_reads_list = []
            else:
                self._kv_reads_list = list(
                    zip(
                        self.kv_cids.tolist(),
                        self.kv_slots.tolist(),
                        self.kv_vals.tolist(),
                        self.kv_index_abs.tolist(),
                    )
                )
        return self._kv_reads_list


class MultiRoundResult(StepResult):
    """Egress of one K-round fused dispatch (``step_rounds``).

    Adds the raw vectorized views on top of the StepResult interface:
    ``committed_rel`` is the device's final (G,) relative watermark vector
    and ``commit_rows`` the rows that advanced vs the pre-block host twin —
    both numpy, zero per-row Python.  Flags are OR-accumulated across the
    block's rounds (see ``kernels.quorum_multiround_impl`` on recycled-row
    attribution)."""

    __slots__ = ("rounds", "committed_rel", "commit_rows")

    def __init__(self, rounds: int):
        super().__init__()
        self.rounds = rounds
        self.committed_rel: Optional[np.ndarray] = None  # (G,) i32
        self.commit_rows: Optional[np.ndarray] = None    # (n,) changed rows


class _RoundBuf:
    """One closed ingest round awaiting the fused multi-round dispatch:
    epoch-filtered ack arrays, first-wins-deduped votes, and the round's
    leader-recycle records (applied at round start, device-side).
    ``cells`` optionally carries the precomputed flat (row·P + slot)
    index vector when the staging path shares one geometry across rounds
    (``ack_block_rounds``), sparing a per-round int64 conversion.
    ``reads`` / ``racks`` carry the round's staged ReadIndex batches
    ``(rows, slots, rels, counts)`` and heartbeat echoes
    ``(rows, rslots, peers)`` as flat arrays (None = none).
    ``kvents`` / ``kvreads`` carry the round's devsm entry ops
    ``(rows, slots, rels, keys, vals)`` and KV reads
    ``(rows, rslots, keys)`` the same way."""

    __slots__ = (
        "rows", "slots", "rels", "votes", "churn", "cells", "reads", "racks",
        "kvents", "kvreads",
    )

    def __init__(
        self, rows, slots, rels, votes, churn, cells=None,
        reads=None, racks=None, kvents=None, kvreads=None,
    ):
        self.rows = rows
        self.slots = slots
        self.rels = rels
        self.votes = votes   # list[(row, slot, grant)]
        self.churn = churn   # list[(row, term, term_start_rel, last_rel)]
        self.cells = cells   # np (n,) int64 row*P+slot, or None
        self.reads = reads   # (rows, slots, rels, counts) int32 arrays
        self.racks = racks   # (rows, rslots, peers) int32 arrays
        self.kvents = kvents    # (rows, slots, rels, keys, vals) int32 arrays
        self.kvreads = kvreads  # (rows, rslots, keys) int32 arrays


class BatchedQuorumEngine:
    """Device-resident quorum state for up to ``n_groups`` Raft groups.

    Usage::

        eng = BatchedQuorumEngine(n_groups=1024, n_peers=5)
        eng.add_group(cid, node_ids=[1,2,3], self_id=1, election_timeout=10)
        eng.set_leader(cid, term=1, term_start=1, last_index=1)
        eng.ack(cid, node_id=2, index=5)      # ReplicateResp ingest
        out = eng.step()                       # one device dispatch
        out.commit[cid]                        # -> advanced commit index
    """

    def __init__(
        self,
        n_groups: int,
        n_peers: int,
        event_cap: int = DEFAULT_EVENT_CAP,
        sharding=None,
        device_ticks: bool = True,
        dense_ingest: str | bool = "auto",
        n_read_slots: int = READ_SLOTS,
        n_kv_slots: int = KV_SLOTS,
        n_kv_ents: int = KV_ENT_SLOTS,
        n_kv_reads: int = KV_READ_SLOTS,
    ):
        self.n_groups = n_groups
        self.n_peers = n_peers
        self.n_read_slots = n_read_slots
        self.n_kv_slots = n_kv_slots
        self.n_kv_ents = n_kv_ents
        self.n_kv_reads = n_kv_reads
        self.event_cap = event_cap
        #: dense-ingestion policy: collapse a round's acks into a (G,P)
        #: max matrix and dispatch the scatter-free dense kernel (see
        #: kernels.quorum_step_dense_impl — ~7× at full occupancy on TPU).
        #: "auto" picks per dispatch by byte volume: dense uploads
        #: 6·G·P bytes vs ~13 per sparse event, so dense wins once the
        #: staged acks outnumber ~G·P/2.  True forces dense, False never.
        # identity checks: `1 in (True, ...)` would pass by int equality
        if not (
            dense_ingest is True
            or dense_ingest is False
            or dense_ingest == "auto"
        ):
            raise ValueError(
                f"dense_ingest must be True, False, or 'auto', got {dense_ingest!r}"
            )
        self.dense_ingest = dense_ingest
        self._dense_threshold = (n_groups * n_peers) // 2
        #: whether this engine EVER runs tick_step on device.  Contact
        #: events (leader_contact zero-acks) are one-shot, so a ticking
        #: engine must apply the election-clock reset on every round —
        #: including do_tick=False rounds that drain staged acks between
        #: host ticks.  Engines that never tick (host-driven clocks) skip
        #: the reset scatter entirely (it is dead work there).
        self.device_ticks = device_ticks
        self.mirror = HostMirror(
            n_groups, n_peers, n_read_slots, n_kv_slots, n_kv_ents
        )
        self.sharding = sharding
        n_dev = (
            len(getattr(sharding, "device_set", ())) if sharding is not None
            else 1
        )
        # Per-shard dispatch lock.  Engines whose state spans more than
        # one device (GSPMD-partitioned programs with collectives) hold
        # this lock from launch through the blocking egress: XLA's CPU
        # client runs each collective as an all-participant rendezvous on
        # a shared per-device thread pool, and two sharded programs of
        # the SAME engine launched from different threads could otherwise
        # interleave their per-device work and deadlock the rendezvous
        # (programs of one engine are normally ordered by their
        # donated-state data dependency; the lock makes that ordering
        # explicit across host threads).  This used to be a PROCESS-WIDE
        # class lock (`_MULTIDEV_MU`) because independent multi-device
        # engines in one process shared the rendezvous pool too; the mesh
        # dispatch plane (ops/mesh.py) now gives every shard its own
        # single-device engine — no collectives, no rendezvous — so the
        # global mutex died and each engine keeps only its own lock.
        # Reentrant on purpose: step -> step_rounds -> _harvest_inflight
        # all guard themselves.
        self._n_devices = n_dev
        self._dispatch_mu = threading.RLock() if n_dev > 1 else nullcontext()
        self._dev: QuorumState = self.mirror.to_device(sharding)
        self._cache_stale = False
        self.groups: Dict[int, GroupInfo] = {}
        self.rows: Dict[int, GroupInfo] = {}
        # vectorized row→(cluster_id, base) translation for egress: at
        # full occupancy tens of thousands of rows change per round, and
        # a per-row Python dict walk dominates the host loop
        self._row_cid = np.full((n_groups,), -1, np.int64)
        self._row_base = np.zeros((n_groups,), np.int64)
        #: host twin of dev.committed — device state changes only through
        #: _dispatch (whose egress refreshes this) and _upload_dirty
        #: (which syncs the dirty rows), so step() never needs a device
        #: readback just to learn the PREVIOUS watermarks (that readback
        #: was a full extra round trip per step on a network-attached TPU)
        self._committed_cache = np.zeros((n_groups,), np.int32)
        self._free = list(range(n_groups - 1, -1, -1))
        self._dirty: set[int] = set()
        # rows bulk-pulled from the device since the last dispatch
        # (sync_rows); invalidated whenever device state advances
        self._synced: set[int] = set()
        # per-row staging epoch: a state transition bumps it, and events
        # staged under an older epoch are filtered at dispatch.  This is
        # the O(1) replacement for scanning the whole event buffer on
        # every transition (measured 0.66ms per transition at 4k groups —
        # an election burst of 1,024 transitions cost a 680ms round).
        self._row_epoch = np.zeros((n_groups,), np.int32)
        # pending event buffers (grow unbounded host-side; chunked at
        # dispatch); entries carry the staging epoch as a 4th column
        self._acks: List[Tuple[int, int, int, int]] = []  # row, slot, rel, ep
        self._votes: List[Tuple[int, int, int, int]] = []  # row, slot, g, ep
        self._voted_cells: dict = {}  # (row, slot) -> staging epoch
        # vectorized bulk-ingest blocks (ack_block): (rows, slots, rels, eps)
        self._ack_blocks: List[Tuple[np.ndarray, ...]] = []
        # --- multi-round fused staging (ISSUE 1 tentpole) ---------------
        # closed ingest rounds awaiting ONE fused dispatch (begin_round /
        # step_rounds); each round's epoch filter resolves at close time,
        # so a later transition only purges rounds still open
        self._round_blocks: List[_RoundBuf] = []
        # leader-recycle records of the CURRENT open round (stage_recycle)
        self._churn: List[Tuple[int, int, int, int]] = []
        self._churn_rows: set = set()  # one recycle per row per round
        # rows with an UNDISPATCHED recycle anywhere in the backlog (open
        # round or closed blocks): their mirror rows are authoritative
        # (recycle_row already applied) and host reads must not consult
        # the pre-recycle device row; a rare-path mutation on such a row
        # collapses the recycle to pre-block ordering (_sync_row)
        self._churn_pending: set = set()
        # in-flight pipelined dispatch: (StepOutputs, prev_committed,
        # row_cid snapshot, row_base snapshot, n_rounds) — the ingest of
        # block i+1 overlaps the device execution of block i, and every
        # host read of device state harvests first (_harvest_inflight)
        self._inflight = None
        # --- device read plane staging (ISSUE 3 tentpole) ---------------
        # ReadIndex batches and heartbeat echoes of the CURRENT open
        # round; epoch columns filter events staged before a transition,
        # exactly like the ack/vote buffers
        self._read_stages: List[Tuple[int, int, int, int, int]] = []
        self._read_stage_blocks: List[Tuple[np.ndarray, ...]] = []
        self._read_echoes: List[Tuple[int, int, int, int]] = []
        self._read_echo_blocks: List[Tuple[np.ndarray, ...]] = []
        # host slot bookkeeping.  A slot is BUSY from stage until its
        # batch deterministically confirms: the device only ever sees
        # echoes this host staged, so once the staged echoes of a batch
        # reach quorum (counting self), the batch WILL confirm in its
        # round — the host predicts that without a readback and frees
        # the slot for rounds AFTER the current open one (a same-round
        # restage would overwrite the batch before its echoes land).
        # A batch whose echoes never reach quorum holds its slot until a
        # row transition purges it (the scalar path bounds the same case
        # with request timeouts, requests.py).
        self._read_busy = np.zeros((n_groups, n_read_slots), bool)
        self._read_echo_host = np.zeros(
            (n_groups, n_read_slots, n_peers), bool
        )
        self._read_next_slot = np.zeros((n_groups,), np.int32)
        # round seq of the moment a slot was predicted-confirmed: the
        # slot is reusable only in a LATER round
        self._read_freed_round = np.full((n_groups, n_read_slots), -1, np.int64)
        self._round_seq = 0
        # LATCH: set on the first read-plane ingress (stage/echo/cancel),
        # never reset.  Until it flips, the device read arrays are
        # provably all-zero — they mutate only inside has_reads dispatches,
        # which only staging triggers — and the mirror's are too (row
        # transitions merely re-zero them), so the rare-path row syncs
        # skip them (_sync_keys).  That is not dead-work avoidance: the
        # extra eager gather/scatter programs the read arrays add (incl. a
        # 3-D (rows,S,P) bool scatter) deadlocked XLA's CPU client when
        # several coordinator round threads first-compiled them while
        # other multi-device dispatches were in flight on the 8-virtual-
        # device mesh (test_full_stack_sharded_engine hung in
        # _upload_dirty).  A read-free engine keeps the exact eager
        # program set it had before the read plane existed.
        self._read_plane_used = False
        # --- device state machine staging (devsm, ISSUE 11) -------------
        # LATCH, same contract as _read_plane_used: until the first devsm
        # ingress (stage_kv_ops / stage_kv_read / kv_restore) the kv
        # arrays are provably at their reset values, every dispatch runs
        # has_kv=False, the rare-path row syncs skip the kv fields
        # (_sync_keys) and the recycle purge compiles out (purge_kv) — an
        # SM-free engine keeps today's host cost and eager-op set
        # bit-identical.
        self._devsm_used = False
        # --- hierarchical commit plane (hier, ISSUE 18) ------------------
        # LATCH, same contract as _read_plane_used/_devsm_used: until the
        # first enabling set_hier the near/sub_quorum arrays are provably
        # all-zero, every dispatch runs has_hier=False — the compiled
        # program set stays byte-identical to the pre-hier build — and
        # the rare-path row syncs skip the hier fields (_sync_keys).
        # Flipping the latch makes the next dispatch of each variant
        # compile its has_hier=True twin once (the late-devsm precedent);
        # hier deployments install domain geometry at registration /
        # first promotion, ahead of steady-state load.
        self._hier_used = False
        # --- device telemetry plane (telem, ISSUE 20) --------------------
        # LATCH, same contract as _hier_used: until enable_telem flips it,
        # telem_prev_committed is provably all-zero, every dispatch runs
        # has_telem=False — the compiled program set stays byte-identical
        # to the pre-telem build — and the rare-path row syncs skip the
        # telem field (_sync_keys).  Flip BEFORE warmup_fused (NodeHost
        # wires health_aggregate into the coordinator constructor for
        # exactly this) so the warmed programs carry the fold; a late
        # flip compiles each variant's has_telem=True twin on next use
        # (the late-devsm precedent).
        self._telem_used = False
        # static top-K width of the fold's drill-down egress; changing it
        # after programs compiled recompiles them, so it is ctor/enable
        # time configuration, not a per-dispatch knob
        self.n_telem_topk = TELEM_TOPK
        # last harvested aggregate: raw device arrays + the dispatch-time
        # row->cid capture, materialized into the snapshot dict LAZILY
        # (telem_snapshot) — per-dispatch harvest cost is one tuple
        # store, the numpy conversion runs at sampler cadence instead of
        # dispatch cadence
        self._last_telem = None
        self._telem_raw = None
        self._telem_seq = 0
        # host record of the rel index staged in each device entry-buffer
        # slot (-1 = free): slot ``rel % E`` is reusable once the
        # HARVESTED commit watermark has passed its tenant (the device
        # frees it the round the entry applies; the host learns at
        # harvest).  Ops whose slot is still occupied queue per row in
        # _kv_queue and drain — in log order — as harvests free slots.
        self._kv_ent_rel = np.full((n_groups, n_kv_ents), -1, np.int64)
        self._kv_queue: Dict[int, "deque"] = {}
        # staged-but-undispatched kv ops / reads of the CURRENT open
        # round, epoch-tagged like every other staging buffer:
        # (row, slot, rel, key, val, epoch) / (row, rslot, key, epoch)
        self._kv_stage: List[Tuple[int, int, int, int, int, int]] = []
        self._kv_read_stage: List[Tuple[int, int, int, int]] = []
        # a staged KV read captures in exactly its round, so its slot is
        # busy from stage until that dispatch's harvest reports the
        # capture (or a row transition purges it)
        self._kv_read_busy = np.zeros((n_groups, n_kv_reads), bool)
        # capture-egress callback (the devsm plane's read service): fired
        # with the StepResult of EVERY harvest that carried captures —
        # including rare-path internal harvests whose results the caller
        # never sees (a row sync forcing _harvest_inflight would
        # otherwise strand parked readers until their timeout)
        self.kv_egress_hook = None
        # --- device-plane observability (ISSUE 5 tentpole) --------------
        # OFF by default: self._obs stays None and every hot-path site
        # gates on a plain `is not None` check, so an obs-off engine keeps
        # a bit-identical host path and eager-op set (the _read_plane_used
        # precedent; parity asserted by bench._run_obs_axis).  The module
        # latch (obs.enable) flips newly built engines on; live wiring
        # goes through NodeHostConfig.enable_metrics -> the coordinator.
        self._obs = None
        self._obs_span = None      # span of the in-flight fused dispatch
        self._obs_kv_span = None   # apply_kernel span of the same dispatch
        self._obs_mu_wait = 0.0    # _dispatch_mu wait of the next dispatch
        self._obs_upload = 0       # upload bytes of the current dispatch
        # --- device capacity & profiling plane (ISSUE 15) ---------------
        # LATCH, same contract as _obs: None by default, every hot-path
        # site gates on `is not None`, so a profile-off engine keeps a
        # bit-identical host path.  Attached via enable_devprof (live
        # wiring: NodeHostConfig.device_profile → the coordinator).  The
        # attached DevProf samples 1-in-N dispatches with a
        # block_until_ready delta (the device-time estimator), accounts
        # fused padding waste, and walks self._dev for the HBM ledger.
        self._devprof = None
        # seq of the newest recorded dispatch span (-1 = none / obs off):
        # the request tracer links this into sampled traces' device_round
        # stage (ISSUE 9); written only inside the obs-gated branches
        self.last_span_seq = -1
        if _obs.enabled():
            self.enable_obs()
        # --- AOT warm-compile of the fused variants (ISSUE 7 tentpole) --
        # The latch gates the LIVE coordinator's fused dispatches: until
        # warmup has compiled the padded (K,G,P) program set, rounds fall
        # back to the already-compiled single-round path, so a proposal
        # never blocks behind a first-use XLA compile (0.5-4s measured on
        # the loaded 2-vCPU box).  Bulk drivers (bench ladder, native
        # control planes) may keep calling step_rounds without warmup —
        # they pay first-use compiles by construction and don't care.
        self._fused_ready = threading.Event()
        # devsm program readiness (set by a warmup that included the
        # has_kv variants, or by a later warmup_devsm): the coordinator
        # only FUSES kv-carrying blocks once these compiled — before
        # that they take the single-round dense path
        self._kv_fused_ready = threading.Event()
        self._warmup_thread: Optional[threading.Thread] = None
        self._kv_warmup_thread: Optional[threading.Thread] = None
        self._warmup_mu = threading.Lock()
        self._warmup_cancel = threading.Event()
        self.warmup_stats = {
            "seconds": 0.0, "programs": 0,
            "cache_hits": 0, "cache_misses": 0, "error": None,
        }

    def enable_obs(self, recorder=None, registry=None, shard=None):
        """Attach device-plane instruments (``obs.instruments.EngineObs``):
        per-dispatch flight-recorder spans plus the ``dragonboat_device_*``
        metric families in ``registry`` (default: the process registry
        ``events.DEFAULT_REGISTRY`` that ``write_health_metrics`` exposes).
        Returns the attached instruments.  A repeat call with no arguments
        is a no-op; passing ``recorder``/``registry`` REBINDS the
        instruments — an engine self-attached by the module latch must not
        swallow a later explicit wiring (NodeHost routing the families
        into ITS registry would otherwise silently publish to the default
        one and expose nothing).  ``shard`` tags this engine's dispatch
        spans with its mesh shard index (``ops/mesh.py`` wiring — all
        shards share ONE recorder, the tag tells their streams apart)."""
        if self._obs is not None and recorder is None and registry is None:
            return self._obs
        from ..obs.instruments import EngineObs

        # `is None`, not truthiness: an EMPTY recorder is falsy
        # (__len__ == 0) and must still be honored
        if recorder is None:
            recorder = (
                self._obs.recorder if self._obs is not None
                else _obs.default_recorder()
            )
        self._obs = EngineObs(recorder, registry=registry, shard=shard)
        return self._obs

    def disable_obs(self) -> None:
        self._obs = None

    def enable_devprof(self, devprof) -> None:
        """Attach a :class:`dragonboat_tpu.obs.devprof.DevProf` plane:
        sampled device-time estimation, fused padding-waste accounting
        and the HBM ledger all key off this latch (``is not None`` on
        every hot-path site — the ``_obs`` contract exactly)."""
        self._devprof = devprof

    def disable_devprof(self) -> None:
        self._devprof = None

    def enable_telem(self, topk: int | None = None) -> None:
        """Flip the device telemetry latch (ISSUE 20): every subsequent
        dispatch runs its ``has_telem=True`` variant, folding the shard's
        health aggregate (``kernels.telem_fold``) into the egress it
        already pays for.  One-way, like the other plane latches — the
        telem field starts participating in rare-path row syncs and
        recycle purges the moment it can be nonzero.  Call BEFORE
        ``warmup_fused`` to get the fold into the warmed program set; a
        later call recompiles each variant once on next use.  ``topk``
        sets the fold's static drill-down width (default
        ``kernels.TELEM_TOPK``); it must not change after programs
        compiled against it."""
        if topk is not None:
            self.n_telem_topk = int(topk)
        self._telem_used = True

    @property
    def telem_enabled(self) -> bool:
        return self._telem_used

    def telem_snapshot(self) -> dict | None:
        """The last harvested telemetry aggregate, or None before the
        first telem-carrying harvest (or while the plane is off).

        PASSIVE by design: the aggregate refreshes whenever a dispatch's
        egress is harvested — the plane adds no dispatches of its own,
        so an idle engine serves a stale snapshot.  Consumers read
        ``seq``/``mono`` for staleness; the health sampler's cadence
        rides the coordinator round loop, which dispatches every tick.

        LAZY materialization: the harvest stores the raw device arrays
        (one tuple assignment on the dispatch path); the numpy pull +
        dict build runs here, at CONSUMER cadence — the sampler reads
        ~once per 50ms while a loaded shard harvests hundreds of folds
        a second, and eager per-harvest conversion showed up as
        dispatch overhead in the telem bench axis."""
        raw = self._telem_raw
        if raw is not None:
            tel, row_cid, rounds, mono, seq = raw
            self._telem_raw = None
            self._ingest_telem(tel, row_cid, rounds, mono, seq)
        t = self._last_telem
        return dict(t) if t is not None else None

    def _stage_telem(self, tel, row_cid, rounds: int) -> None:
        """Record one harvested TelemAggregate for lazy materialization.
        ``row_cid`` must be the DISPATCH-TIME capture (copied), so a
        re-registration between dispatch and snapshot can't mislabel a
        drill-down row."""
        self._telem_seq += 1
        self._telem_raw = (
            tel, row_cid, rounds, time.monotonic(), self._telem_seq
        )

    def _ingest_telem(self, tel, row_cid, rounds, mono, seq) -> None:
        """Translate a TelemAggregate into the host snapshot dict."""
        state_counts = np.asarray(tel.state_counts, dtype=np.int64)
        rows = np.asarray(tel.topk_row)
        lags = np.asarray(tel.topk_lag)
        topk = [
            (int(row_cid[r]), int(lag))
            for r, lag in zip(rows, lags)
            if r >= 0 and row_cid[r] >= 0
        ]
        self._last_telem = {
            "seq": seq,
            "mono": mono,
            "rounds": int(rounds),
            "groups": int(state_counts.sum()),
            "lag_hist": [int(v) for v in np.asarray(tel.lag_hist)],
            "state_counts": [int(v) for v in state_counts],
            "stalled": int(tel.stalled),
            "read_slots": int(tel.read_slots),
            "kv_ents": int(tel.kv_ents),
            "topk": topk,
        }

    # ------------------------------------------------------------------
    # AOT warm-compile (ISSUE 7 tentpole)
    # ------------------------------------------------------------------

    @property
    def fused_ready(self) -> bool:
        """True once the warmup pass has compiled the fused live-path
        program set (the coordinator's gate for K>1 dispatches)."""
        return self._fused_ready.is_set()

    @property
    def kv_fused_ready(self) -> bool:
        """True once the devsm (has_kv) program variants compiled."""
        return self._kv_fused_ready.is_set()

    def warmup_fused(
        self,
        k_buckets=WARM_K_BUCKETS,
        include_reads: bool = True,
        include_single: bool = True,
        background: bool = True,
        include_kv: bool = False,
    ):
        """Pre-compile the live path's device programs against a THROWAWAY
        state of identical shapes/shardings, so first use on the live
        state hits the jit cache instead of stalling proposals 0.5-4s
        behind XLA.

        The set is small and closed: the fused ``quorum_multiround``
        variant per K bucket (reads on/off; votes stay OFF — the live
        coordinator routes vote-carrying rounds to the single-round path,
        elections want the fastest round, not a batched one), plus — with
        ``include_single`` — the sparse tick/no-tick single-round
        programs and the dense read-carrying ones the per-round fallback
        uses.  Warm dispatches run real (empty, all-rows-dead) programs,
        so the jit cache is populated by construction, and with the
        persistent compilation cache enabled
        (:func:`enable_persistent_compilation_cache`) a restarted process
        deserializes instead of compiling.

        ``background=True`` (default) runs on a niced daemon thread and
        returns it; the readiness latch (:attr:`fused_ready`) flips only
        after every fused variant compiled.  Repeat calls are no-ops.

        ``include_kv`` adds the devsm (``has_kv``) fused and dense
        variants — the coordinator passes it when a
        ``DeviceKVStateMachine`` group is expected; SM-free hosts keep
        the historical warm set and cost.  A devsm group registering
        AFTER warmup warms its variants separately
        (:meth:`warmup_devsm`).
        """
        args = (tuple(k_buckets), include_reads, include_single, include_kv)
        with self._warmup_mu:
            if self._warmup_thread is not None or self._fused_ready.is_set():
                return self._warmup_thread
            if background:
                t = threading.Thread(
                    target=self._warmup_main, args=args,
                    name="engine-warmup", daemon=True,
                )
                self._warmup_thread = t
                t.start()
                return t
        self._warmup_main(*args)
        return self.warmup_stats

    def warmup_devsm(self, k_buckets=WARM_K_BUCKETS, background: bool = True):
        """Warm ONLY the devsm (``has_kv``) program variants — the
        late-registration path: a ``DeviceKVStateMachine`` group joining
        a coordinator whose main warmup ran kv-free must not stall its
        first fused dispatch behind XLA.  Until :attr:`kv_fused_ready`
        flips, kv-carrying rounds take the single-round dense path."""
        args = (tuple(k_buckets),)
        with self._warmup_mu:
            if (
                self._kv_warmup_thread is not None
                or self._kv_fused_ready.is_set()
            ):
                return self._kv_warmup_thread
            if background:
                t = threading.Thread(
                    target=self._warmup_devsm_main, args=args,
                    name="engine-warmup-devsm", daemon=True,
                )
                self._kv_warmup_thread = t
                t.start()
                return t
        self._warmup_devsm_main(*args)
        return self.warmup_stats

    def _warmup_devsm_main(self, k_buckets) -> None:
        try:
            # same deprioritization as the main warm thread: these XLA
            # compiles run for tens of seconds and an un-niced compile
            # thread starves raft/transport on a core-starved box —
            # observed as leadership churn for the whole warm window
            if threading.current_thread() is self._kv_warmup_thread:
                try:
                    os.setpriority(
                        os.PRIO_PROCESS, threading.get_native_id(), 10
                    )
                except (OSError, AttributeError):
                    pass
            scratch = HostMirror(
                self.n_groups, self.n_peers, self.n_read_slots,
                self.n_kv_slots, self.n_kv_ents,
            ).to_device(self.sharding)
            for kind, a, hr, kv in self._kv_plan(k_buckets):
                if self._warmup_cancel.is_set():
                    return
                scratch = self._warm_one(scratch, kind, a, hr, kv)
                self.warmup_stats["programs"] += 1
            self._kv_fused_ready.set()
        except Exception as e:  # latch stays unset; dense path serves kv
            elog.warning("devsm warmup failed (kv stays single-round): %r", e)
            self.warmup_stats["error"] = repr(e)

    @staticmethod
    def _kv_plan(k_buckets):
        """The devsm program variants: fused per K bucket with and
        without the read plane riding along (a devsm round may carry
        ReadIndex echoes too), plus the dense single-round fallbacks."""
        plan = [
            ("fused", k, hr, True)
            for k in sorted({int(k) for k in k_buckets})
            for hr in (False, True)
        ]
        plan += [("dense", dt, hr, True) for dt in (True, False)
                 for hr in (False, True)]
        return plan

    def warm_plan(
        self,
        k_buckets=WARM_K_BUCKETS,
        include_reads: bool = True,
        include_single: bool = True,
        include_kv: bool = False,
    ):
        """The closed live-path program set as ``(kind, arg, has_reads,
        has_kv)`` tuples — the ONE enumeration both the warmup pass
        (``_warmup_main``) and the devprof program registry
        (``obs/devprof.py`` via :meth:`lower_variant`) walk, so the
        registry can never analyze a program the live path doesn't run
        nor miss one it does."""
        read_set = (False, True) if include_reads else (False,)
        plan = [
            ("fused", k, hr, False)
            for k in sorted({int(k) for k in k_buckets})
            for hr in read_set
        ]
        if include_single:
            plan += [("sparse", dt, False, False) for dt in (True, False)]
            # elections dispatch the vote-carrying sparse variant; warm
            # it so the first campaign after enable doesn't compile
            plan += [
                ("sparse_votes", dt, False, False) for dt in (True, False)
            ]
            if include_reads:
                plan += [("dense", dt, True, False) for dt in (True, False)]
        if include_kv:
            plan += self._kv_plan(k_buckets)
        return plan

    @staticmethod
    def variant_label(kind: str, arg, has_reads: bool, has_kv: bool) -> str:
        """Stable display name of a warm-plan variant (warmup spans and
        the devprof "Device programs" table share it)."""
        return (
            f"{kind}:k{arg}" if kind == "fused"
            else f"{kind}:{'tick' if arg else 'notick'}"
        ) + (":reads" if has_reads else "") + (":kv" if has_kv else "")

    def cancel_warmup(self) -> None:
        """Stop warming after the current variant (coordinator shutdown);
        a cancelled warmup leaves the latch unset — the fallback
        single-round path simply stays in effect."""
        self._warmup_cancel.set()

    def _warmup_main(
        self, k_buckets, include_reads, include_single, include_kv=False
    ) -> None:
        t0 = time.perf_counter()
        try:
            # same deprioritization as the coordinator round thread: a
            # multi-second XLA compile must not starve raft/transport
            # threads on a core-starved box (that contention was the
            # original reason the live path avoided fused variants).
            # ONLY on the dedicated warm thread — a foreground
            # (background=False) caller must not have its thread left
            # permanently niced.
            if threading.current_thread() is self._warmup_thread:
                try:
                    os.setpriority(
                        os.PRIO_PROCESS, threading.get_native_id(), 10
                    )
                except (OSError, AttributeError):
                    pass
            hits0, miss0 = _CC["hits"], _CC["misses"]
            scratch = HostMirror(
                self.n_groups, self.n_peers, self.n_read_slots,
                self.n_kv_slots, self.n_kv_ents,
            ).to_device(self.sharding)
            plan = self.warm_plan(
                k_buckets, include_reads, include_single, include_kv
            )
            for kind, a, hr, kv in plan:
                if self._warmup_cancel.is_set():
                    self.warmup_stats["error"] = "cancelled"
                    return
                tv = time.perf_counter()
                scratch = self._warm_one(scratch, kind, a, hr, kv)
                dt_s = time.perf_counter() - tv
                self.warmup_stats["programs"] += 1
                obs = self._obs  # re-read: may attach mid-warmup
                if obs is not None:
                    obs.warmup(
                        variant=self.variant_label(kind, a, hr, kv),
                        seconds=dt_s,
                    )
            self.warmup_stats["seconds"] = time.perf_counter() - t0
            self.warmup_stats["cache_hits"] = _CC["hits"] - hits0
            self.warmup_stats["cache_misses"] = _CC["misses"] - miss0
            self._fused_ready.set()
            if include_kv:
                self._kv_fused_ready.set()
            elog.info(
                "engine warmup: %d programs in %.2fs (cache: %d hits, "
                "%d misses)",
                self.warmup_stats["programs"], self.warmup_stats["seconds"],
                self.warmup_stats["cache_hits"],
                self.warmup_stats["cache_misses"],
            )
        except Exception as e:  # latch stays unset; live path unaffected
            self.warmup_stats["error"] = repr(e)
            self.warmup_stats["seconds"] = time.perf_counter() - t0
            elog.warning("engine warmup failed (fused path stays off): %r", e)

    def _variant_args(
        self, kind: str, arg, has_reads: bool, has_kv: bool = False,
        abstract: bool = False,
    ):
        """Kernel entry point, argument tensors (state excluded) and
        static kwargs for one warm-plan variant.  ``abstract=False``
        builds the concrete zero/fill tensors the warm dispatch runs
        (``_warm_one``); ``abstract=True`` builds
        :class:`jax.ShapeDtypeStruct` stand-ins for the devprof program
        registry's AOT ``lower().compile()`` (``lower_variant``) — ONE
        builder, so the registry analyzes byte-for-byte the programs the
        warmup compiled.  Shapes/statics must mirror the live call sites
        EXACTLY — a near-miss warms a program the live path never uses."""
        from .kernels import quorum_multiround, quorum_step_dense

        g, p, s = self.n_groups, self.n_peers, self.n_read_slots
        e, rk = self.n_kv_ents, self.n_kv_reads
        if abstract:
            def mk(shape, dtype, fill=0):
                del fill  # shape/dtype is all a lowering needs
                return jax.ShapeDtypeStruct(shape, dtype)
        else:
            def mk(shape, dtype, fill=0):
                if fill:
                    return jnp.full(shape, fill, dtype)
                return jnp.zeros(shape, dtype)

        def read_dims(*lead):
            return (
                mk(lead + (g, s), jnp.int32, -1),
                mk(lead + (g, s), jnp.int32),
                mk(lead + (g, s, p), bool),
            )

        def kv_dims(*lead):
            return (
                mk(lead + (g, e), jnp.int32, -1),
                mk(lead + (g, e), jnp.int32),
                mk(lead + (g, e), jnp.int32),
                mk(lead + (g, rk), jnp.int32, -1),
            )

        if kind == "fused":
            k = arg
            read_args = read_dims(k) if has_reads else (None, None, None)
            kv_args = kv_dims(k) if has_kv else (None, None, None, None)
            z11 = mk((1, 1), jnp.int32)
            args = (
                mk((k, g, p), jnp.int32, -1),
                mk((1, 1, 1), jnp.int8),
                z11, z11, z11, z11,
                mk((k,), bool),
            ) + read_args + kv_args
            statics = dict(
                do_tick=True,
                track_contact=True,
                has_votes=False,
                has_churn=False,
                has_reads=has_reads,
                purge_reads=False,
                has_kv=has_kv,
                purge_kv=False,
                has_hier=self._hier_used,
                has_telem=self._telem_used,
                purge_telem=False,
                telem_k=self.n_telem_topk,
            )
            return quorum_multiround, args, statics
        if kind == "dense":
            do_tick = arg
            read_args = read_dims() if has_reads else (None, None, None)
            kv_args = kv_dims() if has_kv else (None, None, None, None)
            args = (
                mk((g, p), jnp.int32),
                mk((g, p), bool),
                mk((1, 1), jnp.int8),
            ) + read_args + kv_args
            statics = dict(
                do_tick=do_tick,
                track_contact=self.device_ticks or do_tick,
                has_votes=False,
                has_reads=has_reads,
                has_kv=has_kv,
                has_hier=self._hier_used,
                has_telem=self._telem_used,
                telem_k=self.n_telem_topk,
            )
            return quorum_step_dense, args, statics
        # sparse single-round (the quiet-path workhorse)
        do_tick = arg
        cap = self.event_cap
        z32 = mk((cap,), jnp.int32)
        has_votes = kind == "sparse_votes"
        if has_votes:  # vote events pad to the full event cap
            vg = vp = z32
            vv = mk((cap,), jnp.int8)
            vvalid = mk((cap,), bool)
        else:
            vg = vp = mk((1,), jnp.int32)
            vv = mk((1,), jnp.int8)
            vvalid = mk((1,), bool)
        args = (z32, z32, z32, mk((cap,), bool), vg, vp, vv, vvalid)
        statics = dict(
            do_tick=do_tick,
            track_contact=self.device_ticks or do_tick,
            has_votes=has_votes,
            has_hier=self._hier_used,
            has_telem=self._telem_used,
            telem_k=self.n_telem_topk,
        )
        return quorum_step, args, statics

    def _warm_one(
        self, scratch: QuorumState, kind: str, arg, has_reads: bool,
        has_kv: bool = False,
    ):
        """Compile-and-run one variant against the scratch state (donated;
        the successor state is returned)."""
        fn, args, statics = self._variant_args(kind, arg, has_reads, has_kv)
        with self._dispatch_mu:  # multi-device programs take the lock
            out = fn(scratch, *args, **statics)
            jax.block_until_ready(out.committed)
        return out.state

    def lower_variant(
        self, kind: str, arg, has_reads: bool, has_kv: bool = False
    ):
        """AOT-lower one warm-plan variant against abstract shapes — no
        allocation, no dispatch.  ``.compile()`` on the result yields the
        XLA executable's ``cost_analysis()`` / ``memory_analysis()``:
        the devprof program registry's per-program flops/bytes/peak-temp
        figures (ISSUE 15).  With the persistent compilation cache
        enabled the compile step deserializes the warmed executable
        instead of recompiling."""
        fn, args, statics = self._variant_args(
            kind, arg, has_reads, has_kv, abstract=True
        )
        from .state import make_state

        st = jax.eval_shape(
            lambda: make_state(
                self.n_groups, self.n_peers, self.n_read_slots,
                self.n_kv_slots, self.n_kv_ents,
            )
        )
        if self.sharding is not None:
            # a mesh-sharded engine's live/warmed programs are GSPMD
            # partitions of the state — lowering unsharded here would
            # analyze an executable the cluster never runs (and miss
            # the persistent cache).  The event args stay unsharded,
            # matching the live call sites (host numpy → replication
            # decided by GSPMD, exactly as _warm_one dispatches them).
            st = jax.tree_util.tree_map(
                lambda s: jax.ShapeDtypeStruct(
                    s.shape, s.dtype, sharding=self.sharding
                ),
                st,
            )
        return fn.lower(st, *args, **statics)

    @staticmethod
    def _obs_gate(do_tick, acks, votes, recycles, reads, echoes) -> str:
        """Why the dispatch fired, for the span record."""
        parts = []
        if do_tick:
            parts.append("tick")
        if recycles:
            parts.append("churn")
        if acks or votes:
            parts.append("acks")
        if reads or echoes:
            parts.append("reads")
        return "+".join(parts) or "drain"

    @property
    def dev(self) -> QuorumState:
        return self._dev

    @dev.setter
    def dev(self, st: QuorumState) -> None:
        """External state assignment (hybrid direct-dispatch callers, e.g.
        the bench's staged multistep) — the host committed twin can no
        longer be trusted, so the next step() re-reads it from the device
        once instead of mis-reporting commit deltas."""
        self._harvest_inflight()
        self._dev = st
        self._cache_stale = True
        self._synced.clear()

    # ------------------------------------------------------------------
    # group lifecycle (rare path, host scalar)
    # ------------------------------------------------------------------

    def add_group(
        self,
        cluster_id: int,
        node_ids: List[int],
        self_id: int,
        election_timeout: int = 10,
        heartbeat_timeout: int = 1,
        rand_timeout: Optional[int] = None,
        check_quorum: bool = False,
        witnesses: Tuple[int, ...] = (),
        observers: Tuple[int, ...] = (),
    ) -> GroupInfo:
        if cluster_id in self.groups:
            raise ValueError(f"group {cluster_id} already registered")
        if not self._free:
            raise RuntimeError("quorum engine full")
        row = self._free.pop()
        all_ids = sorted(set(node_ids) | set(witnesses) | set(observers))
        if len(all_ids) > self.n_peers:
            raise ValueError("too many peers for tensor width")
        slots = {nid: i for i, nid in enumerate(all_ids)}
        gi = GroupInfo(cluster_id, row, slots, node_ids=all_ids)
        self.groups[cluster_id] = gi
        self.rows[row] = gi
        self._row_cid[row] = cluster_id
        self._row_base[row] = 0

        a = self.mirror.arrays
        a["live"][row] = True
        a["node_state"][row] = FOLLOWER
        a["term"][row] = 0
        a["committed"][row] = 0
        a["last_index"][row] = 0
        a["term_start"][row] = 0
        n_voting = len(set(node_ids) | set(witnesses))
        a["quorum"][row] = n_voting // 2 + 1
        a["self_slot"][row] = slots[self_id]
        a["election_tick"][row] = 0
        a["heartbeat_tick"][row] = 0
        a["election_timeout"][row] = election_timeout
        a["heartbeat_timeout"][row] = heartbeat_timeout
        a["rand_timeout"][row] = (
            rand_timeout if rand_timeout is not None else election_timeout * 2
        )
        is_voter = self_id in node_ids or self_id in witnesses
        a["electable"][row] = is_voter and self_id not in witnesses
        a["check_quorum_on"][row] = check_quorum
        a["match"][row, :] = 0
        a["next"][row, :] = 1
        a["voting"][row, :] = False
        a["present"][row, :] = False
        a["active"][row, :] = False
        a["votes"][row, :] = VOTE_NONE
        for nid, slot in slots.items():
            a["present"][row, slot] = True
            a["voting"][row, slot] = nid not in observers
        if self._read_plane_used:  # else provably already clear
            self.mirror.clear_reads(row)
            self._reset_read_rows([row])
        if self._devsm_used:  # fresh registration starts from an empty KV
            self.mirror.clear_kv(row)
            self._reset_kv_rows([row])
        if self._hier_used:  # else provably already clear
            a["near"][row, :] = False
            a["sub_quorum"][row] = 0
        self._dirty.add(row)
        return gi

    def _purge_row_events(self, row: int) -> None:
        """Invalidate queued acks/votes for a row.  Called on every state
        transition (and removal): events staged before the transition
        belong to the old term and must never reach the new term's tally
        (the scalar twin drops mismatched-term responses in
        ``handle_vote_resp`` / ``handle_replicate_resp``).  O(1): the row's
        staging epoch is bumped and stale-epoch events are filtered in one
        vectorized pass at dispatch.

        Pending READS die with the transition too (scalar twin: every
        ``become_*`` builds a fresh ``ReadIndex``) — slot bookkeeping and
        the mirror's read fields reset here; staged read/echo events fall
        to the same epoch filter as acks/votes.

        Devsm: BUFFERED entry ops die too (they sit strictly above the
        commit watermark — an uncertain log suffix the next leadership
        may rewrite), while the applied ``kv_value`` rows persist exactly
        like a scalar SM across terms.  Queued ops, staged slots and
        pending read captures drop with the host bookkeeping reset."""
        self._row_epoch[row] += 1
        self._reset_read_rows([row])
        if self._read_plane_used:  # else provably already clear
            self.mirror.clear_reads(row)
        self._reset_kv_rows([row])
        if self._devsm_used:  # else provably already clear
            self.mirror.clear_kv_ents(row)

    def _drop_churn_records(self, row: int, drop_events: bool = False) -> None:
        """Strip every undispatched recycle record for ``row`` — from the
        open round AND from closed blocks awaiting dispatch.  A stale
        record surviving into the program would revive a freed row (or
        clobber its next tenant) with the dead recycle's reset.

        ``drop_events=True`` additionally strips the row's ack/vote
        events from CLOSED blocks.  Required when the recycle collapses
        to pre-block ordering (a rare-path mutation, ``_sync_row``): the
        row's fresh state uploads before the block, so old-tenant events
        sealed into earlier rounds — whose epoch filters resolved at
        close time, immune to the recycle's epoch bump — would otherwise
        scatter into the NEW tenant.  This restores the single-round
        path's semantics, where a transition purges every staged event
        for its row."""
        if row in self._churn_rows:
            self._churn = [c for c in self._churn if c[0] != row]
            self._churn_rows.discard(row)
        if row in self._churn_pending:
            for b in self._round_blocks:
                if b.churn:
                    b.churn = [c for c in b.churn if c[0] != row]
            self._churn_pending.discard(row)
        if drop_events:
            for b in self._round_blocks:
                if b.rows.size:
                    keep = b.rows != row
                    if not keep.all():
                        b.rows = b.rows[keep]
                        b.slots = b.slots[keep]
                        b.rels = b.rels[keep]
                        if b.cells is not None:
                            b.cells = b.cells[keep]
                if b.votes:
                    b.votes = [v for v in b.votes if v[0] != row]
                self._purge_block_reads(b, row)
                self._purge_block_kv(b, row)

    @staticmethod
    def _purge_block_reads(b, row: int) -> None:
        """Drop ``row``'s staged read-stage/read-ack batches from one
        sealed round block (reads are droppable by contract; see
        ``recycle_leader``)."""
        if b.reads is not None and b.reads[0].size:
            keep = b.reads[0] != row
            if not keep.all():
                b.reads = tuple(a[keep] for a in b.reads)
        if b.racks is not None and b.racks[0].size:
            keep = b.racks[0] != row
            if not keep.all():
                b.racks = tuple(a[keep] for a in b.racks)

    def remove_group(self, cluster_id: int) -> None:
        gi = self.groups.pop(cluster_id)
        # any undispatched recycle of this row is now moot — it must not
        # revive the freed row when the block dispatches — and events
        # already sealed into closed blocks must die with the tenant (a
        # future add_group may hand this row to a new group before the
        # block dispatches)
        self._drop_churn_records(gi.row, drop_events=True)
        del self.rows[gi.row]
        self.mirror.arrays["live"][gi.row] = False
        self._dirty.add(gi.row)
        # purge queued events so a future tenant of this row never receives
        # the dead group's acks/votes
        self._purge_row_events(gi.row)
        self._row_cid[gi.row] = -1
        self._free.append(gi.row)

    # ------------------------------------------------------------------
    # rare-path row mutations (host scalar, mask-update tensors)
    # ------------------------------------------------------------------

    def _rel(self, gi: GroupInfo, index: int) -> int:
        rel = index - gi.base
        if rel < 0:
            raise ValueError(f"index {index} below base {gi.base}")
        if rel >= REBASE_THRESHOLD:
            raise ValueError("index needs rebase before ingest")
        return rel

    def set_leader(
        self, cluster_id: int, term: int, term_start: int, last_index: int
    ) -> None:
        """Promote to leader (twin: ``become_leader`` raft.go:1027-1045)."""
        gi = self.groups[cluster_id]
        a = self.mirror.arrays
        row = gi.row
        self._sync_row(row)
        a["node_state"][row] = LEADER
        a["term"][row] = term
        a["term_start"][row] = self._rel(gi, term_start)
        a["last_index"][row] = self._rel(gi, last_index)
        a["election_tick"][row] = 0
        a["heartbeat_tick"][row] = 0
        a["votes"][row, :] = VOTE_NONE
        # reset_remotes: fresh Remote structs — next = last+1 for all,
        # self match = last, activity cleared (raft.go:991-1010)
        a["match"][row, :] = 0
        a["next"][row, :] = self._rel(gi, last_index) + 1
        a["match"][row, a["self_slot"][row]] = self._rel(gi, last_index)
        a["active"][row, :] = False
        self._purge_row_events(row)
        self._dirty.add(row)

    def set_hier(
        self, cluster_id: int, near_ids, sub_quorum: int
    ) -> None:
        """Install a row's hier sub-quorum geometry (ISSUE 18): the
        leader-domain voter mask plus the domain-majority cardinality the
        fused commit reduction runs (kernels._finish_step has_hier
        branch).  ``sub_quorum=0`` disables the rule for the row — the
        coordinator pushes the real geometry at leader promotion and
        zeroes it on demotion.  A disable on a never-enabled engine is a
        no-op (the arrays are provably already clear), so hier-off hosts
        keep the latch down and their compiled program set unchanged."""
        if sub_quorum <= 0 and not self._hier_used:
            return
        gi = self.groups[cluster_id]
        a = self.mirror.arrays
        row = gi.row
        self._sync_row(row)
        a["near"][row, :] = False
        for nid in near_ids:
            slot = gi.slots.get(nid)
            if slot is not None:
                a["near"][row, slot] = True
        a["sub_quorum"][row] = max(int(sub_quorum), 0)
        if sub_quorum > 0:
            self._hier_used = True
        self._dirty.add(row)

    def set_candidate(self, cluster_id: int, term: int) -> None:
        """Start campaigning (twin: ``become_candidate``); the self-vote is
        ingested like any other vote event."""
        gi = self.groups[cluster_id]
        a = self.mirror.arrays
        row = gi.row
        self._sync_row(row)
        a["node_state"][row] = CANDIDATE
        a["term"][row] = term
        a["votes"][row, :] = VOTE_NONE
        a["election_tick"][row] = 0
        self._purge_row_events(row)
        self._dirty.add(row)

    def set_follower(self, cluster_id: int, term: int) -> None:
        gi = self.groups[cluster_id]
        a = self.mirror.arrays
        row = gi.row
        self._sync_row(row)
        a["node_state"][row] = FOLLOWER
        a["term"][row] = term
        a["votes"][row, :] = VOTE_NONE
        a["election_tick"][row] = 0
        self._purge_row_events(row)
        self._dirty.add(row)

    def set_randomized_timeout(self, cluster_id: int, timeout: int) -> None:
        """Host-seeded randomized election timeout (determinism: the PRNG
        stays host-side and seeded, see raft.py design notes)."""
        gi = self.groups[cluster_id]
        self._sync_row(gi.row)
        self.mirror.arrays["rand_timeout"][gi.row] = timeout
        self._dirty.add(gi.row)

    def restore_progress(
        self, cluster_id: int, committed: int, last_index: int
    ) -> None:
        """Snapshot-restore / log-truncation repair of the watermarks."""
        gi = self.groups[cluster_id]
        a = self.mirror.arrays
        row = gi.row
        self._sync_row(row)
        a["committed"][row] = self._rel(gi, committed)
        a["last_index"][row] = self._rel(gi, last_index)
        self._dirty.add(row)

    def rebase(self, cluster_id: int) -> None:
        """Shift a row's base up to its committed watermark so relative
        int32 indexes stay far from overflow (state.py design note)."""
        gi = self.groups[cluster_id]
        a = self.mirror.arrays
        row = gi.row
        self._sync_row(row)
        shift = int(a["committed"][row])
        if shift <= 0:
            return
        gi.base += shift
        self._row_base[row] = gi.base
        for f in ("committed", "last_index", "term_start"):
            a[f][row] = max(0, int(a[f][row]) - shift)
        a["match"][row, :] = np.maximum(a["match"][row, :] - shift, 0)
        a["next"][row, :] = np.maximum(a["next"][row, :] - shift, 1)
        # pending-read watermarks shift with the base; clamping to the new
        # floor only ever REWRITES a release index up (rel 0 = the old
        # committed), which ReadIndex semantics permit
        a["read_index"][row, :] = np.maximum(a["read_index"][row, :] - shift, 0)
        if self._devsm_used:
            # buffered devsm entries shift with the base (they sit above
            # the old committed == the shift, so the result stays >= 1);
            # host slot records whose tenants the shift proves applied
            # free outright
            ents = a["kv_ent_index"][row, :]
            a["kv_ent_index"][row, :] = np.where(ents >= 0, ents - shift, -1)
            kv = self._kv_ent_rel[row]
            self._kv_ent_rel[row] = np.where(
                (kv >= 0) & (kv - shift > 0), kv - shift, -1
            )
            q = self._kv_queue.get(row)
            if q:
                self._kv_queue[row] = deque(
                    (rel - shift, key, val) for rel, key, val in q
                )
        self._dirty.add(row)

    # ------------------------------------------------------------------
    # dense-path event ingest
    # ------------------------------------------------------------------

    def ack(self, cluster_id: int, node_id: int, index: int) -> None:
        """ReplicateResp success / local append (self ack).

        Acks below the rebased floor are legal raft traffic (delayed
        retransmits); they clamp to rel 0, a scatter-max no-op that still
        marks the peer active — same outcome as ``remote.try_update`` on a
        stale index.
        """
        gi = self.groups[cluster_id]
        rel = max(0, index - gi.base)
        if rel >= REBASE_THRESHOLD:
            raise ValueError(f"index {index} needs rebase (base {gi.base})")
        self._acks.append(
            (gi.row, gi.slots[node_id], rel, int(self._row_epoch[gi.row]))
        )

    def ack_block(self, rows, slots, rels) -> None:
        """Vectorized bulk ack ingest (numpy arrays in row/slot space).

        The per-event ``ack()`` path costs a Python call per event; a
        native or vectorized control plane staging thousands of acks per
        round uses this instead — arrays append as one block and are
        concatenated at dispatch.  Caller contract: rows are live group
        rows, slots valid for their rows, ``rels`` already rebased
        (0 <= rel < REBASE_THRESHOLD); the bounds are validated
        vectorized, membership is the caller's responsibility.
        """
        # validate on the ORIGINAL dtype (an int64 >= 2^32 must hit the
        # rebase guard, not wrap into range), then narrow
        rows = np.asarray(rows)
        slots = np.asarray(slots)
        rels = np.asarray(rels)
        if not (rows.shape == slots.shape == rels.shape):
            raise ValueError("ack_block arrays must share a shape")
        if rels.size and rels.max() >= REBASE_THRESHOLD:
            raise ValueError("ack_block rel out of range (rebase needed)")
        if rows.size and (rows.min() < 0 or rows.max() >= self.n_groups):
            raise ValueError("ack_block row out of range")
        if slots.size and (slots.min() < 0 or slots.max() >= self.n_peers):
            raise ValueError("ack_block slot out of range")
        # below-base acks are legal raft traffic (delayed retransmits) and
        # clamp to rel 0, matching ack()'s scalar semantics
        rels = np.maximum(rels, 0)
        rows32 = rows.astype(np.int32)
        self._ack_blocks.append(
            (rows32, slots.astype(np.int32), rels.astype(np.int32),
             self._row_epoch[rows32].copy())
        )

    def vote(self, cluster_id: int, node_id: int, granted: bool) -> None:
        """First vote per (group, peer) wins (twin: ``handle_vote_resp``).

        The kernel's first-wins guard reads pre-batch state, so within-batch
        duplicates must be deduped here — keep only the first event per cell.
        """
        gi = self.groups[cluster_id]
        cell = (gi.row, gi.slots[node_id])
        ep = int(self._row_epoch[gi.row])
        if self._voted_cells.get(cell) == ep:
            return
        self._voted_cells[cell] = ep
        self._votes.append(
            (cell[0], cell[1], VOTE_GRANT if granted else VOTE_REJECT, ep)
        )

    def heartbeat_resp(self, cluster_id: int, node_id: int) -> None:
        """Heartbeat response marks the peer active; an ack at index 0 is a
        no-op for match (scatter-max) but sets the activity bit."""
        gi = self.groups[cluster_id]
        self._acks.append(
            (gi.row, gi.slots[node_id], 0, int(self._row_epoch[gi.row]))
        )

    def leader_contact(self, cluster_id: int) -> None:
        """A follower heard from its leader: reset the row's election clock
        (twin: ``leader_is_available`` — the kernel resets election_tick on
        any event touching a non-leader row)."""
        gi = self.groups[cluster_id]
        self._acks.append(
            (gi.row, int(self.mirror.arrays["self_slot"][gi.row]), 0,
             int(self._row_epoch[gi.row]))
        )

    # ------------------------------------------------------------------
    # device read plane: ReadIndex staging (ISSUE 3 tentpole)
    # ------------------------------------------------------------------

    def _free_read_slot(self, rows: np.ndarray) -> np.ndarray:
        """Vectorized per-row free-slot pick (cursor + S-step scan);
        returns -1 where a row has no reusable slot.  A slot freed by
        predicted confirmation only becomes reusable in a LATER round
        (``_read_freed_round``): the device applies a round's stage
        BEFORE its echoes, so a same-round restage would overwrite the
        confirming batch ahead of its own release."""
        s = self.n_read_slots
        slot = np.full(rows.shape, -1, np.int32)
        cur = self._read_next_slot[rows]
        for k in range(s):
            cand = (cur + k) % s
            ok = (
                (slot < 0)
                & ~self._read_busy[rows, cand]
                & (self._read_freed_round[rows, cand] < self._round_seq)
            )
            slot = np.where(ok, cand, slot)
        return slot

    def _predict_read_confirm(self, rows: np.ndarray, rslots: np.ndarray) -> None:
        """Host-side confirmation prediction: the device only ever sees
        echoes THIS host staged, so once a batch's staged echoes reach
        quorum (self counted via the one-hot column, observers masked
        out — the exact ``kernels.read_confirm`` arithmetic on the
        mirror's host-authoritative membership), the batch provably
        confirms in its round and the slot can be freed for restaging
        without a device readback."""
        a = self.mirror.arrays
        echo = self._read_echo_host[rows, rslots]            # (n,P)
        selfc = (
            np.arange(self.n_peers, dtype=np.int32)[None, :]
            == a["self_slot"][rows][:, None]
        )
        cnt = ((echo | selfc) & a["voting"][rows]).sum(axis=1)
        conf = self._read_busy[rows, rslots] & (cnt >= a["quorum"][rows])
        if conf.any():
            self._read_busy[rows[conf], rslots[conf]] = False
            self._read_freed_round[rows[conf], rslots[conf]] = self._round_seq

    def _reset_read_rows(self, rows) -> None:
        """Drop the rows' pending-read bookkeeping (transition purge).
        Skipped outright until the read plane has been used: the arrays
        still hold their reset values then, and this runs on EVERY row
        transition — 265k numpy row-writes per rung-5 window, ~20% of
        its whole host budget (profiled), for a plane the ladder's write
        rungs never touch."""
        if not self._read_plane_used:
            return
        self._read_busy[rows] = False
        self._read_freed_round[rows] = -1
        self._read_echo_host[rows] = False

    def stage_read(
        self, cluster_id: int, count: int = 1, index: Optional[int] = None
    ) -> int:
        """Stage a batch of ``count`` ReadIndex requests for the group;
        returns the pending-read SLOT the batch rides (the caller keys
        its ctx bookkeeping on it — the confirmed-read egress names the
        slot back).  Scalar twin: ``ReadIndex.add_request``.

        ``index`` (absolute) pins the captured watermark explicitly (the
        live coordinator passes scalar raft's ``log.committed``); default
        is the engine's host view of the row's committed watermark.  The
        host view may trail an unharvested in-flight block, which is
        still linearizable: commits become client-observable only
        through harvest egress, so the host view is exactly the upper
        bound of what any client can have seen.

        Raises ``RuntimeError`` when all S slots hold unconfirmed
        batches — backpressure; the caller batches further reads into
        the next free slot (the scalar path bounds the same situation
        with request timeouts, ``requests.py``).
        """
        if count < 1:
            raise ValueError("stage_read count must be >= 1")
        gi = self.groups[cluster_id]
        row = gi.row
        rows1 = np.array([row], np.int64)
        slot = int(self._free_read_slot(rows1)[0])
        if slot < 0:
            raise RuntimeError(
                f"no free pending-read slot for group {cluster_id}"
            )
        if index is not None:
            rel = self._rel(gi, index)
        else:
            self._refresh_committed_cache()
            if row in self._dirty or row in self._churn_pending:
                rel = int(self.mirror.arrays["committed"][row])
            else:
                rel = int(self._committed_cache[row])
        self._read_plane_used = True
        self._read_busy[row, slot] = True
        self._read_next_slot[row] = (slot + 1) % self.n_read_slots
        self._read_echo_host[row, slot, :] = False
        self._read_stages.append(
            (row, slot, rel, count, int(self._row_epoch[row]))
        )
        return slot

    def stage_read_block(self, rows, rels, counts) -> np.ndarray:
        """Vectorized bulk read staging: one batch per row (rows must be
        unique), ``rels`` already rebased.  Returns the assigned slot per
        row.  Caller contract mirrors ``ack_block``: live rows, bounds
        validated vectorized here, membership the caller's business."""
        rows = np.asarray(rows)
        rels = np.asarray(rels)
        counts = np.asarray(counts)
        if not (rows.shape == rels.shape == counts.shape) or rows.ndim != 1:
            raise ValueError("stage_read_block arrays must share a 1-D shape")
        if rows.size == 0:
            return np.zeros((0,), np.int32)
        if rows.min() < 0 or rows.max() >= self.n_groups:
            raise ValueError("stage_read_block row out of range")
        if rels.min() < 0 or rels.max() >= REBASE_THRESHOLD:
            raise ValueError("stage_read_block rel out of range")
        if counts.min() < 1:
            raise ValueError("stage_read_block counts must be >= 1")
        if np.unique(rows).size != rows.size:
            raise ValueError("stage_read_block rows must be unique")
        rows64 = rows.astype(np.int64)
        slot = self._free_read_slot(rows64)
        if (slot < 0).any():
            raise RuntimeError(
                f"no free pending-read slot for {int((slot < 0).sum())} rows"
            )
        self._read_plane_used = True
        self._read_busy[rows64, slot] = True
        self._read_next_slot[rows64] = (slot + 1) % self.n_read_slots
        self._read_echo_host[rows64, slot, :] = False
        self._read_stage_blocks.append(
            (rows.astype(np.int32), slot.astype(np.int32),
             rels.astype(np.int32), counts.astype(np.int32),
             self._row_epoch[rows.astype(np.int32)].copy())
        )
        return slot

    def read_ack(self, cluster_id: int, node_id: int, slot: int) -> None:
        """Heartbeat-echo confirmation for the group's pending-read slot
        (scalar twin: the ``m.hint != 0`` branch of
        ``handle_leader_heartbeat_resp`` feeding ``ReadIndex.confirm``)."""
        gi = self.groups[cluster_id]
        row = gi.row
        if not (0 <= slot < self.n_read_slots):
            raise ValueError(f"read slot {slot} out of range")
        peer = gi.slots[node_id]
        self._read_plane_used = True
        self._read_echoes.append(
            (row, slot, peer, int(self._row_epoch[row]))
        )
        self._read_echo_host[row, slot, peer] = True
        self._predict_read_confirm(
            np.array([row], np.int64), np.array([slot], np.int64)
        )

    def read_ack_block(self, rows, rslots, peers) -> None:
        """Vectorized bulk echo ingest (row / pending-read-slot / peer-slot
        space); duplicates are harmless (echo sets are idempotent)."""
        rows = np.asarray(rows)
        rslots = np.asarray(rslots)
        peers = np.asarray(peers)
        if not (rows.shape == rslots.shape == peers.shape) or rows.ndim != 1:
            raise ValueError("read_ack_block arrays must share a 1-D shape")
        if rows.size == 0:
            return
        if rows.min() < 0 or rows.max() >= self.n_groups:
            raise ValueError("read_ack_block row out of range")
        if rslots.min() < 0 or rslots.max() >= self.n_read_slots:
            raise ValueError("read_ack_block read slot out of range")
        if peers.min() < 0 or peers.max() >= self.n_peers:
            raise ValueError("read_ack_block peer slot out of range")
        rows32 = rows.astype(np.int32)
        self._read_plane_used = True
        self._read_echo_blocks.append(
            (rows32, rslots.astype(np.int32), peers.astype(np.int32),
             self._row_epoch[rows32].copy())
        )
        rows64 = rows.astype(np.int64)
        rslots64 = rslots.astype(np.int64)
        self._read_echo_host[rows64, rslots64, peers.astype(np.int64)] = True
        self._predict_read_confirm(rows64, rslots64)

    def cancel_read(self, cluster_id: int, slot: int) -> None:
        """Withdraw a pending-read slot whose reads were released by
        another path (the scalar prefix release frees every ctx queued
        before a confirmed one — their device slots would otherwise leak
        until a transition purge).  The slot frees host-side now and
        device-side at its round: a zero-count stage overwrites the batch
        (``read_count == 0`` means free; ``read_confirm`` gates on it)."""
        gi = self.groups[cluster_id]
        row = gi.row
        if not (0 <= slot < self.n_read_slots):
            raise ValueError(f"read slot {slot} out of range")
        self._read_plane_used = True
        self._read_stages.append((row, slot, 0, 0, int(self._row_epoch[row])))
        self._read_busy[row, slot] = False
        self._read_freed_round[row, slot] = self._round_seq
        self._read_echo_host[row, slot, :] = False

    def read_slots_free(self, cluster_id: int) -> int:
        """Reusable pending-read slots for the group RIGHT NOW (counting
        the next-round availability rule) — backpressure introspection."""
        row = self.groups[cluster_id].row
        free = ~self._read_busy[row] & (
            self._read_freed_round[row] < self._round_seq
        )
        return int(free.sum())

    def _gather_reads(self):
        """Open-round read-plane buffers as flat arrays with stale-epoch
        events filtered; clears the buffers and advances the slot-reuse
        round seq (one call per round close).  Returns ``(reads, racks)``
        — each a tuple of int32 arrays or None."""
        self._round_seq += 1
        reads = racks = None
        parts = []
        if self._read_stages:
            cols = np.array(self._read_stages, dtype=np.int64)
            rows = cols[:, 0].astype(np.int32)
            keep = cols[:, 4].astype(np.int32) == self._row_epoch[rows]
            if keep.any():
                parts.append(tuple(
                    cols[keep, i].astype(np.int32) for i in range(4)
                ))
            self._read_stages = []
        if self._read_stage_blocks:
            for r, sl, v, c, ep in self._read_stage_blocks:
                keep = ep == self._row_epoch[r]
                if keep.all():
                    parts.append((r, sl, v, c))
                elif keep.any():
                    parts.append((r[keep], sl[keep], v[keep], c[keep]))
            self._read_stage_blocks = []
        if parts:
            reads = tuple(
                np.concatenate([p[i] for p in parts]) for i in range(4)
            )
        parts = []
        if self._read_echoes:
            cols = np.array(self._read_echoes, dtype=np.int64)
            rows = cols[:, 0].astype(np.int32)
            keep = cols[:, 3].astype(np.int32) == self._row_epoch[rows]
            if keep.any():
                parts.append(tuple(
                    cols[keep, i].astype(np.int32) for i in range(3)
                ))
            self._read_echoes = []
        if self._read_echo_blocks:
            for r, sl, p, ep in self._read_echo_blocks:
                keep = ep == self._row_epoch[r]
                if keep.all():
                    parts.append((r, sl, p))
                elif keep.any():
                    parts.append((r[keep], sl[keep], p[keep]))
            self._read_echo_blocks = []
        if parts:
            racks = tuple(
                np.concatenate([p[i] for p in parts]) for i in range(3)
            )
        return reads, racks

    def _reads_pending(self) -> bool:
        return bool(
            self._read_stages or self._read_stage_blocks
            or self._read_echoes or self._read_echo_blocks
        )

    # ------------------------------------------------------------------
    # device state machine: entry ops + KV reads (devsm, ISSUE 11)
    # ------------------------------------------------------------------

    def stage_kv_op(
        self, cluster_id: int, index: int, key: int, value: int
    ) -> None:
        """Stage one committed-entry ``SET key := value`` op for log
        ``index`` (absolute).  Scalar twin: the apply executor handing the
        entry to the user SM's ``update`` — here the write happens inside
        the fused program the moment the commit watermark passes the
        index, as a ``(G, slots)`` tensor update in HBM."""
        self.stage_kv_ops(cluster_id, [index], [key], [value])

    def stage_kv_ops(self, cluster_id: int, indexes, keys, values) -> bool:
        """Vectorized entry-op staging for one group.  ``indexes`` must be
        strictly increasing (log-append order); ops whose buffer slot
        (``rel % E``) still holds an unapplied tenant queue host-side and
        drain — order preserved — as harvested commit watermarks free
        slots.  A queued op therefore never errors; it just rides a later
        round (the scalar twin's apply queue depth, bounded by E on
        device and unbounded host-side).

        Returns True when EVERYTHING staged immediately (nothing queued
        for the row).  A False is the backpressure signal consumers that
        release reads at the commit watermark must honor: a QUEUED op may
        commit before it applies, so ``kv_value`` momentarily trails the
        watermark — the live plane unbinds and re-arms past the batch
        (``DevKVPlane.handle_ops``) instead of serving that window."""
        gi = self.groups[cluster_id]
        row = gi.row
        indexes = np.asarray(indexes, dtype=np.int64)
        keys = np.asarray(keys, dtype=np.int64)
        values = np.asarray(values, dtype=np.int64)
        if not (indexes.shape == keys.shape == values.shape) or (
            indexes.ndim != 1
        ):
            raise ValueError("stage_kv_ops arrays must share a 1-D shape")
        if indexes.size == 0:
            return True  # nothing to stage, nothing queued
        rels = indexes - gi.base
        if rels.min() < 1:
            raise ValueError("stage_kv_ops index at or below the group base")
        if rels.max() >= REBASE_THRESHOLD:
            raise ValueError("stage_kv_ops index needs rebase")
        if indexes.size > 1 and (np.diff(indexes) <= 0).any():
            raise ValueError("stage_kv_ops indexes must be strictly increasing")
        if keys.min() < 0 or keys.max() >= self.n_kv_slots:
            raise ValueError("stage_kv_ops key slot out of range")
        imin, imax = np.iinfo(np.int32).min, np.iinfo(np.int32).max
        if values.min() < imin or values.max() > imax:
            raise ValueError("stage_kv_ops value outside int32")
        self._devsm_used = True
        q = self._kv_queue.setdefault(row, deque())
        for rel, key, val in zip(
            rels.tolist(), keys.tolist(), values.tolist()
        ):
            q.append((rel, key, val))
        self._drain_kv_queue(row)
        return row not in self._kv_queue

    def _drain_kv_queue(self, row: int) -> None:
        """Move queued ops into the open round while their slots are
        free, in log order; stops at the first occupied slot (staging out
        of order would let a later op apply before an earlier same-key
        one)."""
        q = self._kv_queue.get(row)
        if not q:
            self._kv_queue.pop(row, None)
            return
        e = self.n_kv_ents
        ep = int(self._row_epoch[row])
        ent_rel = self._kv_ent_rel[row]
        while q:
            rel, key, val = q[0]
            slot = rel % e
            if ent_rel[slot] != -1:
                break
            ent_rel[slot] = rel
            self._kv_stage.append((row, slot, rel, key, val, ep))
            q.popleft()
        if not q:
            self._kv_queue.pop(row, None)

    def _kv_free_applied(self) -> None:
        """Free entry-buffer slots whose tenants the HARVESTED commit
        watermark has passed (the device freed them the round they
        applied), then drain any host-queued overflow into the open
        round.  Runs at every egress; devsm-free engines skip it via the
        latch."""
        mask = (self._kv_ent_rel >= 0) & (
            self._kv_ent_rel <= self._committed_cache[:, None]
        )
        if mask.any():
            self._kv_ent_rel[mask] = -1
        for row in list(self._kv_queue):
            self._drain_kv_queue(row)

    def stage_kv_read(self, cluster_id: int, key: int) -> int:
        """Stage a device KV read for the group; returns the read SLOT
        the capture will egress under (``StepResult.kv_reads``).  The
        value is captured in the read's own round, AFTER that round's
        apply fold, together with the commit watermark it reflects — the
        caller checks the watermark against its ReadIndex release index
        (on this plane apply == commit, so watermark >= release index
        means the value is linearizable for that release).

        Raises ``RuntimeError`` when all R slots hold un-harvested
        captures — backpressure, the ``stage_read`` precedent."""
        gi = self.groups[cluster_id]
        row = gi.row
        if not (0 <= key < self.n_kv_slots):
            raise ValueError(f"kv key slot {key} out of range")
        free = np.nonzero(~self._kv_read_busy[row])[0]
        if not free.size:
            raise RuntimeError(
                f"no free devsm read slot for group {cluster_id}"
            )
        slot = int(free[0])
        self._devsm_used = True
        self._kv_read_busy[row, slot] = True
        self._kv_read_stage.append(
            (row, slot, key, int(self._row_epoch[row]))
        )
        return slot

    def kv_reads_free(self, cluster_id: int) -> int:
        """Free devsm read slots for the group right now."""
        row = self.groups[cluster_id].row
        return int((~self._kv_read_busy[row]).sum())

    def kv_values(self, cluster_id: int) -> np.ndarray:
        """The group's device KV row (introspection / snapshot save):
        pending mirror edits win over the device, like every rare-path
        read."""
        gi = self.groups[cluster_id]
        return np.array(self._read("kv_value", gi.row), dtype=np.int64)

    def kv_restore(self, cluster_id: int, values) -> None:
        """Install a group's KV image (snapshot recover / the devsm
        plane's leadership rebind): mirror row write + dirty upload, with
        the pending-entry buffer cleared — the image IS the applied
        state, nothing buffered belongs with it."""
        gi = self.groups[cluster_id]
        row = gi.row
        values = np.asarray(values, dtype=np.int64)
        if values.shape != (self.n_kv_slots,):
            raise ValueError(
                f"kv_restore expects shape ({self.n_kv_slots},), "
                f"got {values.shape}"
            )
        self._devsm_used = True
        self._sync_row(row)
        a = self.mirror.arrays
        a["kv_value"][row, :] = values.astype(np.int32)
        self.mirror.clear_kv_ents(row)
        self._reset_kv_rows([row])
        self._dirty.add(row)

    def _reset_kv_rows(self, rows) -> None:
        """Drop the rows' devsm host bookkeeping (transition purge twin
        of ``_reset_read_rows``): queued ops die, staged slots free, read
        captures are abandoned.  Device-side entry buffers are cleared by
        the caller's mirror write (``clear_kv_ents``) or the in-program
        recycle reset."""
        if not self._devsm_used:
            return
        self._kv_ent_rel[rows] = -1
        self._kv_read_busy[rows] = False
        for r in np.atleast_1d(np.asarray(rows, dtype=np.int64)):
            self._kv_queue.pop(int(r), None)

    def _gather_kv(self):
        """Open-round devsm buffers as flat arrays with stale-epoch
        events filtered; clears the buffers.  Returns ``(kvents,
        kvreads)`` — tuples of int32 arrays or None.  Re-attempts the
        overflow drain first so ops unblocked by the latest harvest ride
        this round."""
        if self._kv_queue:
            for row in list(self._kv_queue):
                self._drain_kv_queue(row)
        kvents = kvreads = None
        if self._kv_stage:
            cols = np.array(self._kv_stage, dtype=np.int64)
            rows = cols[:, 0].astype(np.int32)
            keep = cols[:, 5].astype(np.int32) == self._row_epoch[rows]
            if keep.any():
                kvents = tuple(
                    cols[keep, i].astype(np.int32) for i in range(5)
                )
            self._kv_stage = []
        if self._kv_read_stage:
            cols = np.array(self._kv_read_stage, dtype=np.int64)
            rows = cols[:, 0].astype(np.int32)
            keep = cols[:, 3].astype(np.int32) == self._row_epoch[rows]
            if keep.any():
                kvreads = tuple(
                    cols[keep, i].astype(np.int32) for i in range(3)
                )
            self._kv_read_stage = []
        return kvents, kvreads

    def _kv_pending(self) -> bool:
        return bool(
            self._kv_stage or self._kv_read_stage or self._kv_queue
        )

    def _kv_ents_buffered(self) -> bool:
        """True while any entry-buffer slot holds an op the harvested
        watermark has not passed — the condition under which every
        dispatch must carry the apply fold (see ``_step_locked``)."""
        return self._devsm_used and bool((self._kv_ent_rel >= 0).any())

    @staticmethod
    def _purge_block_kv(b, row: int) -> None:
        """Drop ``row``'s staged devsm ops/reads from one sealed round
        block (recycle path: an old-tenant op applying before the
        in-program reset is wasted work, and a read capture there would
        egress misattributed to the new tenant — the ``_purge_block_reads``
        rationale exactly)."""
        if b.kvents is not None and b.kvents[0].size:
            keep = b.kvents[0] != row
            if not keep.all():
                b.kvents = tuple(a[keep] for a in b.kvents)
        if b.kvreads is not None and b.kvreads[0].size:
            keep = b.kvreads[0] != row
            if not keep.all():
                b.kvreads = tuple(a[keep] for a in b.kvreads)

    # ------------------------------------------------------------------
    # multi-round fused staging (ISSUE 1 tentpole)
    # ------------------------------------------------------------------

    def begin_round(self) -> None:
        """Close the current ingest round: everything staged so far forms
        one scanned round of the next fused dispatch; events staged after
        this call land in the NEXT round.  The round's stale-epoch filter
        resolves NOW — a transition staged later (including a
        ``stage_recycle`` in a later round) must not retroactively purge
        events that a per-round host dispatch would already have consumed.
        """
        if self._votes:
            votes = [
                (r, s, v)
                for r, s, v, ep in self._votes
                if ep == self._row_epoch[r]
            ]
            self._votes = []
            self._voted_cells.clear()
        else:
            votes = []
        rows, slots, rels = self._gather_acks()
        reads, racks = self._gather_reads()
        kvents, kvreads = self._gather_kv()
        self._round_blocks.append(
            _RoundBuf(
                rows, slots, rels, votes, self._churn,
                reads=reads, racks=racks, kvents=kvents, kvreads=kvreads,
            )
        )
        self._churn = []
        self._churn_rows = set()

    def pending_rounds(self) -> int:
        """Closed rounds awaiting the fused dispatch."""
        return len(self._round_blocks)

    def ack_block_rounds(self, rows, slots, rels_rounds) -> None:
        """K CLOSED rounds of bulk acks over ONE (row, slot) geometry —
        the steady-state shape of every ladder section (same cells every
        round, advancing rel indexes).  Validates the geometry once and
        snapshots the epoch filter once for the whole block instead of
        per round: at 64k groups × 3 acks × K=16 the per-round
        ``ack_block`` + ``begin_round`` path spent ~60ms/dispatch on
        validation min/max scans and defensive copies this API skips
        (the round buffers alias the caller's arrays — the caller must
        not mutate them until the block is dispatched).

        ``rels_rounds`` is (K, n): row ``r`` forms scanned round ``r``.
        Events/churn already staged are closed into one preceding round
        first (exactly ``begin_round`` semantics).
        """
        rows = np.asarray(rows)
        slots = np.asarray(slots)
        rels_rounds = np.asarray(rels_rounds)
        if rels_rounds.ndim != 2 or rows.shape != slots.shape or (
            rels_rounds.shape[1:] != rows.shape
        ):
            raise ValueError("ack_block_rounds: shape mismatch")
        if rels_rounds.size and rels_rounds.max() >= REBASE_THRESHOLD:
            raise ValueError("ack_block_rounds rel out of range")
        if rows.size and (rows.min() < 0 or rows.max() >= self.n_groups):
            raise ValueError("ack_block_rounds row out of range")
        if slots.size and (slots.min() < 0 or slots.max() >= self.n_peers):
            raise ValueError("ack_block_rounds slot out of range")
        if (
            self._acks or self._ack_blocks or self._votes or self._churn
            or self._reads_pending() or self._kv_pending()
        ):
            self.begin_round()
        rows32 = rows.astype(np.int32, copy=False)
        slots32 = slots.astype(np.int32, copy=False)
        cells = rows32.astype(np.int64) * self.n_peers + slots32
        # no epoch filter needed: every event is staged NOW under the
        # rows' current epochs — begin_round closing each round here
        # would resolve to the identity filter
        if rels_rounds.size and rels_rounds.min() < 0:
            # below-base retransmits clamp to rel 0 (ack() semantics)
            rels_rounds = np.maximum(rels_rounds, 0)
        for r in range(rels_rounds.shape[0]):
            self._round_blocks.append(
                _RoundBuf(
                    rows32, slots32,
                    rels_rounds[r].astype(np.int32, copy=False),
                    [], [], cells=cells,
                )
            )

    def stage_recycle(
        self,
        old_cluster_id: int,
        new_cluster_id: int,
        term: int,
        term_start: int,
        last_index: int,
        rand_timeout: Optional[int] = None,
    ) -> GroupInfo:
        """Replace a group with a fresh SAME-GEOMETRY leader tenant as a
        masked row update INSIDE the next dispatched program — the
        device-side twin of ``remove_group`` + ``add_group`` +
        ``set_leader`` (kernels._apply_recycle), with none of the
        host-side re-upload those pay (the dominant cost of churn-under-
        load at 100k groups: one dirty-row scatter per recycle).

        The reset applies at the START of the recycle's ingest round —
        before that round's events — exactly where the host path's
        ``_upload_dirty`` lands relative to its dispatch, so acks staged
        for the new tenant in the same round ingest correctly and events
        already staged for the old tenant this round are purged (epoch
        bump), while earlier CLOSED rounds still reach the old tenant.

        Geometry (peer slots, voting/present masks, quorum, self slot,
        timeouts) carries over unchanged; anything else — different
        membership, witnesses, a different randomized timeout — must take
        the host path.  ``rand_timeout`` may be passed to ASSERT the
        carried-over value.  Raises ValueError when the swap isn't a pure
        recycle.
        """
        gi = self.groups.get(old_cluster_id)
        if gi is None:
            raise ValueError(f"group {old_cluster_id} not registered")
        if new_cluster_id in self.groups:
            raise ValueError(f"group {new_cluster_id} already registered")
        row = gi.row
        if row in self._churn_rows:
            raise ValueError(
                f"row {row} already recycled this round (begin_round first)"
            )
        a = self.mirror.arrays
        if rand_timeout is not None and rand_timeout != int(a["rand_timeout"][row]):
            raise ValueError("rand_timeout differs: recycle must keep geometry")
        if term_start < 0 or last_index < 0 or term_start > last_index:
            raise ValueError("term_start/last_index out of range")
        if last_index >= REBASE_THRESHOLD:
            raise ValueError("index needs rebase before recycle")
        # host bookkeeping: the new tenant takes the SAME row at base 0
        del self.groups[old_cluster_id]
        ngi = GroupInfo(
            new_cluster_id, row, gi.slots, base=0, node_ids=gi.node_ids
        )
        self.groups[new_cluster_id] = ngi
        self.rows[row] = ngi
        self._row_cid[row] = new_cluster_id
        self._row_base[row] = 0
        # old-tenant events staged this round must not reach the new
        # tenant (closed rounds resolved their filter at close time)
        self._purge_row_events(row)
        # old-tenant READS die entirely — including batches sealed into
        # closed pre-recycle rounds.  Acks in those rounds still apply to
        # the old tenant (they run before the in-program reset), but a
        # read CONFIRMED there would egress after the recycle, when the
        # (G,S) accumulators can only attribute it to the row's final
        # tenant — a misdelivered read.  Reads are droppable by contract
        # (the scalar path drops on leader change/timeout and clients
        # retry), so dropping beats misattributing.  Devsm ops/reads of
        # the old tenant die the same way (_purge_block_kv rationale).
        for b in self._round_blocks:
            self._purge_block_reads(b, row)
            self._purge_block_kv(b, row)
        self._reset_kv_rows([row])
        # mirror coherence WITHOUT dirtying the row: the device applies
        # the identical reset in-program (state.HostMirror.recycle_row);
        # until the block dispatches, host reads of this row resolve to
        # the mirror (_read / committed caches), never the stale device
        self.mirror.recycle_row(
            row, term, term_start, last_index,
            clear_reads=self._read_plane_used,
            clear_kv=self._devsm_used,
            clear_telem=self._telem_used,
        )
        self._committed_cache[row] = 0
        self._synced.discard(row)
        self._churn.append((row, term, term_start, last_index))
        self._churn_rows.add(row)
        self._churn_pending.add(row)
        return ngi

    def step_rounds(
        self,
        do_tick: bool = False,
        pipelined: bool = False,
        pad_rounds_to: int = 0,
        tick_rounds: Optional[int] = None,
    ) -> Optional[MultiRoundResult]:
        """ONE fused dispatch over every staged round (``begin_round``
        boundaries; a non-empty open round is closed implicitly).

        ``pipelined=True`` double-buffers host staging against device
        execution: the call returns the PREVIOUS dispatch's egress (None
        on the first) and leaves this dispatch in flight, so the caller
        ingests/encodes block i+1 while block i executes.  Any host read
        of device state (``committed_view``, ``_read``, a rare-path
        transition, the next dispatch) harvests the in-flight block
        first, so the pipelining is invisible to correctness.  Host
        rare-path mutations (``set_leader`` …) staged between rounds
        apply BEFORE the whole block — mid-block transitions must use
        ``stage_recycle`` or split the block.

        ``pad_rounds_to`` pads the block with event-free, tick-masked-off
        rounds (provable no-ops) up to a fixed K, so a caller with a
        VARYING round count — the coordinator's missed-tick catch-up —
        reuses one compiled program instead of paying a multi-second
        XLA compile per distinct K (kernels.quorum_multiround tick_mask
        note).

        ``tick_rounds`` (with ``do_tick=True``) sets how many of the
        block's rounds tick — default: every REAL (unpadded) round, the
        historical behavior.  It may exceed the real round count up into
        the padding: the live coordinator replays a tick deficit of N
        with ONE staged event round plus N-1 event-free ticking padding
        rounds, fused into a single dispatch (the adaptive-K live path).
        """
        obs = self._obs
        if obs is None:
            with self._dispatch_mu:
                return self._step_rounds_locked(
                    do_tick, pipelined, pad_rounds_to, tick_rounds
                )
        # _dispatch_mu wait (EXACTLY zero on single-device engines, where
        # the "lock" is a nullcontext — don't record timer noise there):
        # attributed to the NEXT dispatch's span; a wait past the stall
        # threshold auto-dumps via the span's stall check.  ACCUMULATED,
        # not assigned — step()'s reroute into step_rounds() re-enters
        # here with the reentrant lock already held, and its ~0 wait must
        # not erase the contended outer acquire.
        timed = self._n_devices > 1
        t0 = time.perf_counter() if timed else 0.0
        with self._dispatch_mu:
            if timed:
                self._obs_mu_wait += (time.perf_counter() - t0) * 1e3
            return self._step_rounds_locked(
                do_tick, pipelined, pad_rounds_to, tick_rounds
            )

    def _step_rounds_locked(
        self, do_tick: bool, pipelined: bool, pad_rounds_to: int,
        tick_rounds: Optional[int] = None,
    ) -> Optional[MultiRoundResult]:
        if (
            self._acks or self._ack_blocks or self._votes or self._churn
            or self._reads_pending() or self._kv_pending()
        ):
            self.begin_round()
        if not self._round_blocks:
            # nothing staged: drain whatever is still in flight
            return self._harvest_inflight()
        blocks, self._round_blocks = self._round_blocks, []
        n_real = len(blocks)
        z = np.zeros((0,), np.int32)
        while len(blocks) < pad_rounds_to:
            blocks.append(_RoundBuf(z, z, z, [], []))
        if tick_rounds is None:
            tick_rounds = n_real
        tick_rounds = min(tick_rounds, len(blocks))
        tick_mask = np.zeros((len(blocks),), bool)
        tick_mask[:tick_rounds] = True
        prev = self._harvest_inflight()
        self._upload_dirty()
        self._refresh_committed_cache()
        out = self._dispatch_multiround(
            blocks, do_tick, tick_mask,
            k_rounds=max(n_real, tick_rounds if do_tick else 0),
        )
        self._synced.clear()
        # every staged recycle is now inside the dispatched program
        self._churn_pending.clear()
        self._inflight = (
            out,
            # snapshot, not alias: stage_recycle zeroes cache rows in
            # place while this dispatch is in flight, which must not
            # corrupt ITS commit-delta baseline
            self._committed_cache.copy(),
            self._row_cid.copy(),
            self._row_base.copy(),
            len(blocks),
        )
        if pipelined:
            return prev
        return self.harvest()

    def harvest(self) -> Optional[MultiRoundResult]:
        """Egress of the in-flight pipelined dispatch (None when idle)."""
        return self._harvest_inflight()

    def _harvest_inflight(self) -> Optional[MultiRoundResult]:
        if self._inflight is None:
            return None
        with self._dispatch_mu:
            return self._harvest_inflight_locked()

    def _harvest_inflight_locked(self) -> Optional[MultiRoundResult]:
        if self._inflight is None:
            return None
        out, prev_committed, row_cid, row_base, n_rounds = self._inflight
        self._inflight = None
        obs = self._obs
        span, self._obs_span = self._obs_span, None
        kv_span, self._obs_kv_span = self._obs_kv_span, None
        t_eg = time.perf_counter() if obs is not None else 0.0
        (
            committed, won, lost, elect, hb, demote, rdc, rdi,
            kvv, kvi, kva,
        ) = jax.device_get(
            (
                out.committed,
                out.won,
                out.lost,
                out.flags.elect_due,
                out.flags.hb_due,
                out.flags.checkq_demote,
                out.read_done_count,
                out.read_done_index,
                out.kv_read_val,
                out.kv_read_index,
                out.kv_applied,
            )
        )
        if out.telem is not None:
            # dispatch-time row_cid snapshot: a re-registration while the
            # block was in flight must not mislabel a drill-down row.
            # The device arrays stay resident until telem_snapshot pulls
            # them — the fold must not add a per-dispatch readback
            self._stage_telem(out.telem, row_cid, rounds=n_rounds)
        res = MultiRoundResult(n_rounds)
        if rdc is not None:
            self._translate_reads(res, rdc, rdi, row_cid, row_base)
        committed = np.asarray(committed)
        res.committed_rel = committed
        self._committed_cache = np.array(committed, dtype=np.int32)
        if self._churn_pending:
            # recycles staged while this block was in flight: their rows'
            # host watermark is the mirror's (new tenant) until THEIR
            # block lands — the harvested vector still shows the old one
            rows = np.fromiter(self._churn_pending, dtype=np.int64)
            self._committed_cache[rows] = (
                self.mirror.arrays["committed"][rows]
            )
        if kvi is not None:
            self._translate_kv(res, kvv, kvi, kva, row_cid, row_base)
            if self.kv_egress_hook is not None:
                self.kv_egress_hook(res)
        if self._devsm_used:
            self._kv_free_applied()
        res.commit_rows = self._translate_egress(
            res, committed, prev_committed, row_cid, row_base,
            (("won", won), ("lost", lost), ("elect", elect),
             ("heartbeat", hb), ("demote", demote)),
        )
        if obs is not None and span is not None:
            obs.egress(
                span,
                egress_ms=(time.perf_counter() - t_eg) * 1e3,
                egress_rows=int(res.commit_rows.size),
                reads_released=(
                    int(res.read_counts.sum())
                    if res.read_counts is not None else 0
                ),
            )
        if obs is not None and kv_span is not None:
            obs.devsm_egress(
                kv_span,
                applied=res.kv_applied_ops,
                reads_served=(
                    int(len(res.kv_cids)) if res.kv_cids is not None else 0
                ),
            )
        return res

    @staticmethod
    def _translate_egress(
        res, committed, prev_committed, row_cid, row_base, flags
    ) -> np.ndarray:
        """Vectorized row→cluster egress translation, shared by step()'s
        single-round path and the fused harvest: watermark deltas become
        (cid, abs) arrays (dead rows — cid -1 — dropped; the commit dict
        materializes lazily), flag vectors become cid lists.  Returns the
        changed-row index vector."""
        changed = np.nonzero(committed != prev_committed)[0]
        if changed.size:
            cids = row_cid[changed]
            live = cids >= 0
            res._commit_cids = cids[live]
            res._commit_abs = (row_base[changed] + committed[changed])[live]
        for name, arr in flags:
            idx = np.nonzero(np.asarray(arr))[0]
            if idx.size:
                cids = row_cid[idx]
                getattr(res, name).extend(cids[cids >= 0].tolist())
        return changed

    def _translate_kv(self, res, kvv, kvi, kva, row_cid, row_base) -> None:
        """Vectorized devsm egress translation: the device's (G,R)
        capture accumulators become flat (cid, slot, value, abs index)
        vectors (dead rows dropped; the tuple list materializes lazily
        via ``StepResult.kv_reads``), captured read slots free for
        restaging, and the block's applied-op total lands on the
        result."""
        kvi = np.asarray(kvi)
        res.kv_applied_ops = int(np.asarray(kva).sum())
        rows, slots = np.nonzero(kvi >= 0)
        if not rows.size:
            return
        self._kv_read_busy[rows, slots] = False
        cids = row_cid[rows]
        live = cids >= 0
        rows, slots = rows[live], slots[live]
        res.kv_cids = cids[live]
        res.kv_slots = slots.astype(np.int64)
        res.kv_vals = np.asarray(kvv)[rows, slots].astype(np.int64)
        res.kv_index_abs = row_base[rows] + kvi[rows, slots]

    @staticmethod
    def _translate_reads(res, done_cnt, done_idx, row_cid, row_base) -> None:
        """Vectorized confirmed-read egress translation: the device's
        (G,S) count/index accumulators become flat (cid, slot, abs index,
        count) vectors (dead rows dropped; the tuple list materializes
        lazily via ``StepResult.reads``)."""
        done_cnt = np.asarray(done_cnt)
        rows, slots = np.nonzero(done_cnt)
        if not rows.size:
            return
        cids = row_cid[rows]
        live = cids >= 0
        rows, slots = rows[live], slots[live]
        res.read_cids = cids[live]
        res.read_slots = slots.astype(np.int64)
        res.read_index_abs = (
            row_base[rows] + np.asarray(done_idx)[rows, slots]
        )
        res.read_counts = done_cnt[rows, slots].astype(np.int64)

    def _dispatch_multiround(
        self, blocks: List[_RoundBuf], do_tick: bool, tick_mask: np.ndarray,
        k_rounds: Optional[int] = None,
    ):
        """Stack K closed rounds into (K,G,P) tensors + (K,C) churn blocks
        and run ``kernels.quorum_multiround`` — one scan, one upload, one
        egress for the whole block."""
        from .kernels import quorum_multiround

        obs = self._obs
        t_disp = time.perf_counter() if obs is not None else 0.0
        k = len(blocks)
        g, p = self.n_groups, self.n_peers
        # -1 = untouched sentinel: one tensor instead of (max, touched) —
        # halves both the host staging stores and the upload bytes
        ack_max = np.full((k, g, p), -1, np.int32)
        flat = ack_max.reshape(-1)
        stride = g * p
        for r, b in enumerate(blocks):
            if b.rows.size:
                if b.cells is not None:  # shared-geometry fast path
                    cell = r * stride + b.cells
                else:
                    cell = (r * g + b.rows.astype(np.int64)) * p + b.slots
                np.maximum.at(flat, cell, b.rels)
        has_votes = any(b.votes for b in blocks)
        if has_votes:
            vote_new = np.full((k, g, p), VOTE_NONE, np.int8)
            for r, b in enumerate(blocks):
                if b.votes:
                    cols = np.array(b.votes, dtype=np.int64).T
                    vote_new[r, cols[0], cols[1]] = cols[2].astype(np.int8)
        else:
            vote_new = np.zeros((1, 1, 1), np.int8)  # unused dummy
        has_churn = any(b.churn for b in blocks)
        if has_churn:
            # pad the per-round churn width to a power of two so the jit
            # cache stays bounded at ~log2(G) entries per K (the same
            # shape-bucketing rationale as _pad_pow2_rows)
            cmax = max(len(b.churn) for b in blocks)
            cap = 1 << max(0, cmax - 1).bit_length()
            cap = max(cap, 1)
            churn_row = np.full((k, cap), g, np.int32)  # g = padding (drops)
            churn_term = np.zeros((k, cap), np.int32)
            churn_start = np.zeros((k, cap), np.int32)
            churn_last = np.zeros((k, cap), np.int32)
            for r, b in enumerate(blocks):
                if b.churn:
                    cols = np.array(b.churn, dtype=np.int64).T
                    n = cols.shape[1]
                    churn_row[r, :n] = cols[0]
                    churn_term[r, :n] = cols[1]
                    churn_start[r, :n] = cols[2]
                    churn_last[r, :n] = cols[3]
        else:
            z = np.zeros((1, 1), np.int32)
            churn_row = churn_term = churn_start = churn_last = z
        has_reads = any(
            b.reads is not None or b.racks is not None for b in blocks
        )
        if has_reads:
            s = self.n_read_slots
            stage_idx = np.full((k, g, s), -1, np.int32)
            stage_cnt = np.zeros((k, g, s), np.int32)
            echo = np.zeros((k, g, s, p), bool)
            for r, b in enumerate(blocks):
                if b.reads is not None and b.reads[0].size:
                    rr, sl, v, c = b.reads
                    stage_idx[r, rr, sl] = v
                    stage_cnt[r, rr, sl] = c
                if b.racks is not None and b.racks[0].size:
                    rr, sl, pe = b.racks
                    echo[r, rr, sl, pe] = True
            read_args = (
                jnp.asarray(stage_idx), jnp.asarray(stage_cnt),
                jnp.asarray(echo),
            )
        else:
            read_args = (None, None, None)
        has_kv = any(
            b.kvents is not None or b.kvreads is not None for b in blocks
        ) or self._kv_ents_buffered()  # fold runs while ops sit buffered
        if has_kv:
            e, rk = self.n_kv_ents, self.n_kv_reads
            kv_ei = np.full((k, g, e), -1, np.int32)
            kv_ek = np.zeros((k, g, e), np.int32)
            kv_ev = np.zeros((k, g, e), np.int32)
            kv_rk = np.full((k, g, rk), -1, np.int32)
            for r, b in enumerate(blocks):
                if b.kvents is not None and b.kvents[0].size:
                    rr, sl, rel, key, val = b.kvents
                    kv_ei[r, rr, sl] = rel
                    kv_ek[r, rr, sl] = key
                    kv_ev[r, rr, sl] = val
                if b.kvreads is not None and b.kvreads[0].size:
                    rr, sl, key = b.kvreads
                    kv_rk[r, rr, sl] = key
            kv_args = (
                jnp.asarray(kv_ei), jnp.asarray(kv_ek),
                jnp.asarray(kv_ev), jnp.asarray(kv_rk),
            )
        else:
            kv_args = (None, None, None, None)
        out = quorum_multiround(
            self._dev,
            jnp.asarray(ack_max),
            jnp.asarray(vote_new),
            jnp.asarray(churn_row),
            jnp.asarray(churn_term),
            jnp.asarray(churn_start),
            jnp.asarray(churn_last),
            jnp.asarray(tick_mask),
            *read_args,
            *kv_args,
            do_tick=do_tick,
            track_contact=self.device_ticks or do_tick,
            has_votes=has_votes,
            has_churn=has_churn,
            has_reads=has_reads,
            # a never-used read plane is all-zero: compile its recycle
            # purges out (measured ~40% of rung-5 churn throughput).
            # Normalized to False when the block carries no churn — the
            # flag is only consumed inside _apply_recycle, but as a
            # static it keys the jit cache, and letting it flip with
            # _read_plane_used would recompile the live coordinator's
            # fused program the moment the first read stages (exactly
            # the first-use stall the warmup pass exists to kill)
            purge_reads=self._read_plane_used and has_churn,
            has_kv=has_kv,
            # the devsm twin of purge_reads, same normalization rationale
            purge_kv=self._devsm_used and has_churn,
            has_hier=self._hier_used,
            has_telem=self._telem_used,
            # the telem twin of purge_reads, same normalization rationale
            purge_telem=self._telem_used and has_churn,
            telem_k=self.n_telem_topk,
        )
        self._dev = out.state
        if obs is not None:
            n_acks = int(sum(b.rows.size for b in blocks))
            n_votes = sum(len(b.votes) for b in blocks)
            n_rec = sum(len(b.churn) for b in blocks)
            n_reads = int(sum(
                b.reads[0].size for b in blocks if b.reads is not None
            ))
            n_echo = int(sum(
                b.racks[0].size for b in blocks if b.racks is not None
            ))
            # EXACTLY the argument tuple the kernel received (dummies of
            # compiled-out planes included — they are genuinely shipped):
            # the one accounting point shared with the devprof capacity
            # model (upload_nbytes docstring)
            up = upload_nbytes(
                ack_max, vote_new, churn_row, churn_term, churn_start,
                churn_last, tick_mask, *read_args, *kv_args,
            )
            if has_kv:
                n_kvops = int(sum(
                    b.kvents[0].size for b in blocks if b.kvents is not None
                ))
                n_kvreads = int(sum(
                    b.kvreads[0].size for b in blocks
                    if b.kvreads is not None
                ))
                self._obs_kv_span = obs.apply_kernel(
                    ops=n_kvops,
                    reads=n_kvreads,
                    rounds=k,
                    slot_occupancy=int((self._kv_ent_rel >= 0).sum()),
                )
            mu_wait, self._obs_mu_wait = self._obs_mu_wait, 0.0
            self._obs_span = obs.dispatch(
                "fused",
                rounds=k,
                k_rounds=k_rounds if k_rounds is not None else k,
                acks=n_acks,
                votes=n_votes,
                recycles=n_rec,
                reads=n_reads,
                echoes=n_echo,
                upload_bytes=int(up),
                dispatch_ms=(time.perf_counter() - t_disp) * 1e3,
                gate=self._obs_gate(
                    do_tick, n_acks, n_votes, n_rec, n_reads, n_echo
                ),
                mu_wait_ms=mu_wait,
                pending_rounds=len(self._round_blocks),
                read_slots_in_use=(
                    int(self._read_busy.sum())
                    if self._read_plane_used else None
                ),
            )
            self.last_span_seq = self._obs_span["seq"]
        dp = self._devprof
        if dp is not None:
            # device capacity & profiling plane (ISSUE 15): padding-waste
            # accounting (padded program K vs live rounds — the padding
            # rounds are provable no-ops, i.e. measurable wasted device
            # work) plus the sampled block_until_ready device-time
            # estimate; the sampled delta is stamped onto this
            # dispatch's flight-recorder span as `device_ms`
            dp.note_dispatch(
                "fused", out.committed, rounds=k,
                live_rounds=(
                    min(k, k_rounds) if k_rounds is not None else k
                ),
                # only a span THIS dispatch recorded: after disable_obs
                # the stale _obs_span still references an old ring
                # record, and stamping device_ms there would corrupt it
                span=self._obs_span if obs is not None else None,
            )
        return out

    def _refresh_committed_cache(self) -> None:
        """Re-read the host committed twin from the device when it was
        invalidated (external ``dev`` assignment).  Rows with a staged
        in-program recycle keep their MIRROR watermark (the device still
        holds the old tenant until the block dispatches)."""
        if not self._cache_stale:
            return
        self._committed_cache = np.array(
            np.asarray(self._dev.committed), dtype=np.int32
        )
        if self._churn_pending:
            rows = np.fromiter(self._churn_pending, dtype=np.int64)
            self._committed_cache[rows] = (
                self.mirror.arrays["committed"][rows]
            )
        self._cache_stale = False

    def committed_view(self) -> np.ndarray:
        """Absolute committed watermark per ROW as one (G,) int64 vector —
        the fully vectorized egress view (dead rows included; mask with
        ``row_cids() >= 0``).  Fresh after any step/harvest; reads the
        host twin, never the device."""
        self._harvest_inflight()
        self._refresh_committed_cache()
        view = self._row_base + self._committed_cache.astype(np.int64)
        if self._dirty:
            rows = np.fromiter(self._dirty, dtype=np.int64)
            view[rows] = (
                self._row_base[rows]
                + self.mirror.arrays["committed"][rows].astype(np.int64)
            )
        return view

    def row_cids(self) -> np.ndarray:
        """(G,) int64 cluster id per row (-1 = dead); pairs with
        ``committed_view`` for vectorized watermark asserts."""
        return self._row_cid.copy()

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------

    def _sync_row(self, row: int) -> None:
        """Pull one device row into the mirror before mutating it (the
        dense path may have advanced it since the last upload).

        A row with an undispatched in-program recycle is special: its
        MIRROR already holds the post-recycle state (recycle_row) and the
        device row is stale pre-recycle data — pulling it would resurrect
        the old tenant under the new cid.  The caller is about to mutate
        the row host-side, which supersedes the staged device reset, so
        the recycle collapses to pre-block ordering: drop the in-program
        record and dirty the (post-recycle) mirror for upload instead."""
        self._harvest_inflight()
        if row in self._churn_pending:
            self._drop_churn_records(row, drop_events=True)
            self._dirty.add(row)
            return
        if row in self._dirty or row in self._synced:
            return
        with self._dispatch_mu:  # the gathers are multi-device programs
            for k in self._sync_keys():
                self.mirror.arrays[k][row] = np.asarray(
                    getattr(self.dev, k)[row]
                )
        self._synced.add(row)

    _READ_KEYS = ("read_index", "read_count", "read_acks")
    _KV_KEYS = ("kv_value", "kv_ent_index", "kv_ent_key", "kv_ent_val")
    _HIER_KEYS = ("near", "sub_quorum")
    _TELEM_KEYS = ("telem_prev_committed",)

    def _sync_keys(self):
        """Mirror fields the rare-path row syncs move between host and
        device.  The read-plane arrays join only once the plane has been
        used (see the ``_read_plane_used`` latch in ``__init__``); before
        that both sides are all-zero by construction and the extra eager
        gather/scatter programs must not be dispatched at all.  The devsm,
        hier and telem arrays follow the same rule on their own latches."""
        skip = ()
        if not self._read_plane_used:
            skip += self._READ_KEYS
        if not self._devsm_used:
            skip += self._KV_KEYS
        if not self._hier_used:
            skip += self._HIER_KEYS
        if not self._telem_used:
            skip += self._TELEM_KEYS
        if not skip:
            return list(self.mirror.arrays)
        return [k for k in self.mirror.arrays if k not in skip]

    @staticmethod
    def _pad_pow2_rows(idx: np.ndarray) -> np.ndarray:
        """Pad a row-index vector to the next power-of-two length by
        repeating its first element.  Gather/scatter with a fresh index
        SHAPE recompiles the eager op (measured: an election burst's
        varying transition counts cost ~620ms/round in
        backend_compile_and_load); bucketing shapes to powers of two
        bounds the compile cache at ~log2(G) entries.  Duplicate indexes
        are harmless: gathers repeat a value, scatters rewrite the same
        value."""
        n = idx.size
        cap = 1 << max(0, n - 1).bit_length()
        if cap == n:
            return idx
        return np.concatenate([idx, np.full(cap - n, idx[0], idx.dtype)])

    def sync_rows(self, rows) -> None:
        """Bulk-pull many device rows into the mirror: one gather per
        field for the whole set instead of ~20 single-row device reads
        per transition (the per-row form measured ~0.5ms each on the CPU
        backend — an election burst syncing 1,024 rows one at a time was
        the bulk of a 680ms round)."""
        self._harvest_inflight()
        if self._churn_pending:
            # recycled-but-undispatched rows keep their mirror state and
            # collapse the recycle to pre-block ordering (see _sync_row)
            for r in rows:
                if r in self._churn_pending:
                    self._drop_churn_records(r, drop_events=True)
                    self._dirty.add(r)
        todo = [
            r for r in rows if r not in self._dirty and r not in self._synced
        ]
        if not todo:
            return
        idx = np.asarray(todo, np.int32)
        pidx = self._pad_pow2_rows(idx)
        with self._dispatch_mu:  # the gathers are multi-device programs
            for k in self._sync_keys():
                self.mirror.arrays[k][pidx] = np.asarray(
                    getattr(self.dev, k)[pidx]
                )
        self._synced.update(todo)

    def _upload_dirty(self) -> None:
        if not self._dirty:
            return
        self._harvest_inflight()
        rows = self._pad_pow2_rows(np.fromiter(self._dirty, dtype=np.int32))
        st = self.dev
        updates = dict(st._asdict())
        for k in self._sync_keys():
            host = self.mirror.arrays[k]
            dev_arr = getattr(st, k)
            updates[k] = dev_arr.at[rows].set(jnp.asarray(host[rows]))
        self._dev = QuorumState(**updates)
        # keep the host committed twin coherent with the rows just written
        self._committed_cache[rows] = self.mirror.arrays["committed"][rows]
        self._dirty.clear()

    def _pad(self, events, width):
        cap = self.event_cap
        n = len(events)
        g = np.zeros((cap,), np.int32)
        p = np.zeros((cap,), np.int32)
        v = np.zeros((cap,), np.int32 if width == 3 else np.int8)
        valid = np.zeros((cap,), bool)
        if n:
            cols = np.array(events, dtype=np.int64).T
            g[:n] = cols[0]
            p[:n] = cols[1]
            v[:n] = cols[2]
            valid[:n] = True
        return g, p, v, valid

    def step(self, do_tick: bool = True) -> StepResult:
        """Run one fused device dispatch over all pending events.

        Oversized event backlogs run extra (tickless) dispatches first so
        the jit program never recompiles for a new batch size.

        When rounds were staged (``begin_round`` / ``stage_recycle``),
        the whole backlog — closed rounds plus the open buffers as the
        final round — runs as ONE fused multi-round dispatch instead
        (``step_rounds``; the result satisfies the StepResult interface).
        """
        obs = self._obs
        if obs is None:
            with self._dispatch_mu:
                return self._step_locked(do_tick)
        timed = self._n_devices > 1
        t0 = time.perf_counter() if timed else 0.0
        with self._dispatch_mu:
            if timed:
                self._obs_mu_wait += (time.perf_counter() - t0) * 1e3
            return self._step_locked(do_tick)

    def _step_locked(self, do_tick: bool) -> StepResult:
        if self._round_blocks or self._churn:
            return self.step_rounds(do_tick=do_tick)
        self._harvest_inflight()
        # stale-epoch votes (staged before a row transition) drop here;
        # surviving entries shed the epoch column for the dispatch path
        if self._votes:
            self._votes = [
                (r, s, v)
                for r, s, v, ep in self._votes
                if ep == self._row_epoch[r]
            ]
        self._upload_dirty()
        # host twin, not a device readback (a full extra round trip per
        # step on a network-attached chip); _upload_dirty and the egress
        # below keep it coherent.  An external `eng.dev = ...` assignment
        # marks it stale and forces a one-time device re-read here.
        self._refresh_committed_cache()
        prev_committed = self._committed_cache

        obs = self._obs
        t_disp = time.perf_counter() if obs is not None else 0.0
        n_dispatches = 1
        ack_g, ack_p, ack_v = self._gather_acks()
        reads, racks = self._gather_reads()
        kvents, kvreads = self._gather_kv()
        n_votes = len(self._votes) if obs is not None else 0
        has_reads = reads is not None or racks is not None
        # the apply fold must ALSO run while any entry sits buffered on
        # device: its commit may land in this (otherwise kv-free)
        # dispatch, and a fold-free program would leave it unapplied —
        # stale for kv_values and unsafe for the host slot-free rule.
        # Empties back to event-driven the moment the buffers drain.
        has_kv = (
            kvents is not None or kvreads is not None
            or self._kv_ents_buffered()
        )
        # dense mode collapses ANY number of acks/votes into (G,P)
        # matrices — no cap, no chunk loop (votes are already first-wins
        # deduped per cell, so a dense matrix holds a whole round).
        # The read plane — and the devsm plane — exist only on the dense
        # kernel, so pending reads/kv ops force dense regardless of
        # occupancy or policy.
        if has_reads or has_kv or self.dense_ingest is True or (
            self.dense_ingest == "auto"
            and (
                ack_g.size >= self._dense_threshold
                or ack_g.size > self.event_cap
                or len(self._votes) > self.event_cap
            )
        ):
            out = self._dispatch_dense(
                ack_g, ack_p, ack_v, self._votes, do_tick, reads, racks,
                kvents, kvreads, has_kv=has_kv,
            )
        else:
            pos = 0
            n_chunks = 0
            while (ack_g.size - pos) > self.event_cap or len(self._votes) > self.event_cap:
                take = min(self.event_cap, ack_g.size - pos)
                self._dispatch(
                    (ack_g[pos : pos + take], ack_p[pos : pos + take],
                     ack_v[pos : pos + take]),
                    self._votes[: self.event_cap],
                    False,
                )
                pos += take
                n_chunks += 1
                del self._votes[: self.event_cap]
            out = self._dispatch(
                (ack_g[pos:], ack_p[pos:], ack_v[pos:]), self._votes, do_tick
            )
            n_dispatches += n_chunks
        self._votes.clear()
        self._voted_cells.clear()
        # the dispatch advanced every row on device; bulk-synced mirror
        # rows are stale now
        self._synced.clear()

        if obs is not None:
            n_reads = int(reads[0].size) if reads is not None else 0
            n_echo = int(racks[0].size) if racks is not None else 0
            if has_kv:
                self._obs_kv_span = obs.apply_kernel(
                    ops=int(kvents[0].size) if kvents is not None else 0,
                    reads=int(kvreads[0].size) if kvreads is not None else 0,
                    rounds=1,
                    slot_occupancy=int((self._kv_ent_rel >= 0).sum()),
                )
            mu_wait, self._obs_mu_wait = self._obs_mu_wait, 0.0
            upload, self._obs_upload = self._obs_upload, 0
            span = obs.dispatch(
                "dispatch",
                rounds=1,
                k_rounds=1,
                acks=int(ack_g.size),
                votes=n_votes,
                recycles=0,
                reads=n_reads,
                echoes=n_echo,
                upload_bytes=upload,
                n_dispatches=n_dispatches,
                dispatch_ms=(time.perf_counter() - t_disp) * 1e3,
                gate=self._obs_gate(
                    do_tick, ack_g.size, n_votes, 0, n_reads, n_echo
                ),
                mu_wait_ms=mu_wait,
                pending_rounds=0,
                read_slots_in_use=(
                    int(self._read_busy.sum())
                    if self._read_plane_used else None
                ),
            )
            self.last_span_seq = span["seq"]
            t_eg = time.perf_counter()

        res = StepResult()
        # one batched device→host transfer for the whole egress set (a
        # network-attached chip pays the full round trip per readback)
        (
            committed, won, lost, elect, hb, demote, rdc, rdi,
            kvv, kvi, kva,
        ) = jax.device_get(
            (
                out.committed,
                out.won,
                out.lost,
                out.flags.elect_due,
                out.flags.hb_due,
                out.flags.checkq_demote,
                out.read_done_count,
                out.read_done_index,
                out.kv_read_val,
                out.kv_read_index,
                out.kv_applied,
            )
        )
        if out.telem is not None:
            # deferred readback: stage the device aggregate, pull it at
            # snapshot (sampler) cadence, not dispatch cadence
            self._stage_telem(
                out.telem, self._row_cid.copy(), rounds=1
            )
        if rdc is not None:
            self._translate_reads(res, rdc, rdi, self._row_cid, self._row_base)
        # device_get arrays are read-only; the cache must stay writable
        # for _upload_dirty's row sync
        self._committed_cache = np.array(committed, dtype=np.int32)
        if kvi is not None:
            self._translate_kv(
                res, kvv, kvi, kva, self._row_cid, self._row_base
            )
            if self.kv_egress_hook is not None:
                self.kv_egress_hook(res)
        if self._devsm_used:
            self._kv_free_applied()
        changed = self._translate_egress(
            res, committed, prev_committed, self._row_cid, self._row_base,
            (("won", won), ("lost", lost), ("elect", elect),
             ("heartbeat", hb), ("demote", demote)),
        )
        if obs is not None:
            obs.egress(
                span,
                egress_ms=(time.perf_counter() - t_eg) * 1e3,
                egress_rows=int(changed.size),
                reads_released=(
                    int(res.read_counts.sum())
                    if res.read_counts is not None else 0
                ),
            )
            kv_span, self._obs_kv_span = self._obs_kv_span, None
            if kv_span is not None:
                obs.devsm_egress(
                    kv_span,
                    applied=res.kv_applied_ops,
                    reads_served=(
                        int(len(res.kv_cids))
                        if res.kv_cids is not None else 0
                    ),
                )
        return res

    def _gather_acks(self):
        """Tuple-staged + block-staged acks as three flat arrays, with
        stale-epoch events (staged before a row transition) filtered out
        in one vectorized pass; clears both buffers."""
        parts = []
        if self._acks:
            cols = np.array(self._acks, dtype=np.int64)
            rows = cols[:, 0].astype(np.int32)
            keep = cols[:, 3].astype(np.int32) == self._row_epoch[rows]
            parts.append(
                (rows[keep], cols[keep, 1].astype(np.int32),
                 cols[keep, 2].astype(np.int32))
            )
            self._acks = []
        if self._ack_blocks:
            for r, s, v, ep in self._ack_blocks:
                keep = ep == self._row_epoch[r]
                if keep.all():
                    parts.append((r, s, v))
                elif keep.any():
                    parts.append((r[keep], s[keep], v[keep]))
            self._ack_blocks = []
        if not parts:
            z = np.zeros((0,), np.int32)
            return z, z, z
        return (
            np.concatenate([p[0] for p in parts]),
            np.concatenate([p[1] for p in parts]),
            np.concatenate([p[2] for p in parts]),
        )

    def _pad_ack_arrays(self, g, p, v):
        cap = self.event_cap
        n = g.size
        og = np.zeros((cap,), np.int32)
        op = np.zeros((cap,), np.int32)
        ov = np.zeros((cap,), np.int32)
        valid = np.zeros((cap,), bool)
        if n:
            og[:n] = g
            op[:n] = p
            ov[:n] = v
            valid[:n] = True
        return og, op, ov, valid

    def _dispatch(self, acks, votes, do_tick: bool):
        if isinstance(acks, tuple):
            ag, ap, av, avalid = self._pad_ack_arrays(*acks)
        else:
            ag, ap, av, avalid = self._pad(acks, 3)
        if votes:
            vg, vp, vv, vvalid = self._pad(votes, 1)
        else:
            # vote-free round: the has_votes=False variant compiles the
            # vote scatter out entirely; the args are unused dummies
            vg = vp = np.zeros((1,), np.int32)
            vv = np.zeros((1,), np.int8)
            vvalid = np.zeros((1,), bool)
        if self._obs is not None:
            # accumulated: an oversized backlog runs several chunked
            # dispatches per step and the span must account them all
            self._obs_upload += upload_nbytes(
                ag, ap, av, avalid, vg, vp, vv, vvalid
            )
        out = quorum_step(
            self.dev,
            jnp.asarray(ag),
            jnp.asarray(ap),
            jnp.asarray(av),
            jnp.asarray(avalid),
            jnp.asarray(vg),
            jnp.asarray(vp),
            jnp.asarray(vv, dtype=jnp.int8),
            jnp.asarray(vvalid),
            do_tick=do_tick,
            # ticking rounds must track contact even on a device_ticks=False
            # engine (defensive: a stray do_tick=True call would otherwise
            # consume one-shot contact acks without the reset)
            track_contact=self.device_ticks or do_tick,
            has_votes=bool(votes),
            has_hier=self._hier_used,
            has_telem=self._telem_used,
            telem_k=self.n_telem_topk,
            # occupancy hints for the telem fold only — this path never
            # carries read/kv event planes
            has_reads=self._read_plane_used,
            has_kv=self._devsm_used,
        )
        self._dev = out.state
        dp = self._devprof
        if dp is not None:
            dp.note_dispatch("sparse", out.committed, rounds=1, live_rounds=1)
        return out

    def _dispatch_dense(
        self, ag, ap, av, votes, do_tick: bool, reads=None, racks=None,
        kvents=None, kvreads=None, has_kv=None,
    ):
        """Aggregate a round's events into (G,P) matrices and run the
        scatter-free dense kernel (kernels.quorum_step_dense_impl).
        ``reads``/``racks`` are the round's gathered read-plane buffers
        (``_gather_reads`` shape) and ``kvents``/``kvreads`` the devsm
        buffers (``_gather_kv`` shape); both planes live only on this
        kernel — step() forces dense whenever they are present."""
        from .kernels import quorum_step_dense

        g, p = self.n_groups, self.n_peers
        ack_max = np.zeros((g, p), np.int32)
        touched = np.zeros((g, p), bool)
        if ag.size:
            # max-aggregation == scatter-max: order-independent, exact.
            # Flat 1-D indexing keeps ufunc.at on numpy's contiguous fast
            # path (the 2-D tuple form is several× slower at the very
            # occupancies that select the dense path).
            cell = ag.astype(np.int64) * p + ap
            np.maximum.at(ack_max.reshape(-1), cell, av)
            touched.reshape(-1)[cell] = True
        if votes:
            vote_new = np.full((g, p), VOTE_NONE, np.int8)
            cols = np.array(votes, dtype=np.int64).T
            vote_new[cols[0], cols[1]] = cols[2].astype(np.int8)
        else:
            vote_new = np.zeros((1, 1), np.int8)  # unused dummy
        has_reads = reads is not None or racks is not None
        if has_reads:
            s = self.n_read_slots
            stage_idx = np.full((g, s), -1, np.int32)
            stage_cnt = np.zeros((g, s), np.int32)
            echo = np.zeros((g, s, p), bool)
            if reads is not None and reads[0].size:
                rr, sl, v, c = reads
                stage_idx[rr, sl] = v
                stage_cnt[rr, sl] = c
            if racks is not None and racks[0].size:
                rr, sl, pe = racks
                echo[rr, sl, pe] = True
            read_args = (
                jnp.asarray(stage_idx), jnp.asarray(stage_cnt),
                jnp.asarray(echo),
            )
        else:
            read_args = (None, None, None)
        if has_kv is None:
            has_kv = kvents is not None or kvreads is not None
        if has_kv:
            e, rk = self.n_kv_ents, self.n_kv_reads
            kv_ei = np.full((g, e), -1, np.int32)
            kv_ek = np.zeros((g, e), np.int32)
            kv_ev = np.zeros((g, e), np.int32)
            kv_rk = np.full((g, rk), -1, np.int32)
            if kvents is not None and kvents[0].size:
                rr, sl, rel, key, val = kvents
                kv_ei[rr, sl] = rel
                kv_ek[rr, sl] = key
                kv_ev[rr, sl] = val
            if kvreads is not None and kvreads[0].size:
                rr, sl, key = kvreads
                kv_rk[rr, sl] = key
            kv_args = (
                jnp.asarray(kv_ei), jnp.asarray(kv_ek),
                jnp.asarray(kv_ev), jnp.asarray(kv_rk),
            )
        else:
            kv_args = (None, None, None, None)
        if self._obs is not None:
            # the exact kernel argument tuple (upload_nbytes docstring)
            self._obs_upload += upload_nbytes(
                ack_max, touched, vote_new, *read_args, *kv_args
            )
        out = quorum_step_dense(
            self.dev,
            jnp.asarray(ack_max),
            jnp.asarray(touched),
            jnp.asarray(vote_new),
            *read_args,
            *kv_args,
            do_tick=do_tick,
            track_contact=self.device_ticks or do_tick,
            has_votes=bool(votes),
            has_reads=has_reads,
            has_kv=has_kv,
            has_hier=self._hier_used,
            has_telem=self._telem_used,
            telem_k=self.n_telem_topk,
        )
        self._dev = out.state
        dp = self._devprof
        if dp is not None:
            dp.note_dispatch("dense", out.committed, rounds=1, live_rounds=1)
        return out

    # ------------------------------------------------------------------
    # introspection (tests / debugging)
    # ------------------------------------------------------------------

    def _read(self, field_name: str, row: int):
        """Field value at a row: pending mirror edits win over device —
        including a staged in-program recycle, whose mirror row is the
        post-recycle truth while the device still holds the old tenant."""
        self._harvest_inflight()
        if row in self._dirty or row in self._churn_pending:
            return self.mirror.arrays[field_name][row]
        with self._dispatch_mu:  # the gather is a multi-device program
            return np.asarray(getattr(self.dev, field_name)[row])

    def committed_index(self, cluster_id: int) -> int:
        gi = self.groups[cluster_id]
        return int(gi.base) + int(self._read("committed", gi.row))

    def committed_snapshot(self, cids=None) -> Dict[int, int]:
        """Absolute committed indexes for ``cids`` (default: every
        registered group) from AT MOST one device→host transfer.
        ``committed_index`` costs a readback per call — prohibitive over
        a tunneled backend (~67ms RTT each); scale probes (bench rungs
        4/5) sample through this instead.  Right after ``step()`` the
        egress cache is fresh and the call is zero-transfer — it indexes
        the vector the device produced for that round's egress.  Pass
        ``cids`` when sampling: building the full dict for 100k groups
        costs ~100k boxed ints per call (vectorized twin:
        ``committed_view``)."""
        self._harvest_inflight()
        self._refresh_committed_cache()
        committed = self._committed_cache
        mirror = self.mirror.arrays["committed"]
        dirty = self._dirty
        pend = self._churn_pending
        items = (
            self.groups.items()
            if cids is None
            else ((cid, self.groups[cid]) for cid in cids)
        )
        return {
            cid: int(gi.base)
            + int(
                mirror[gi.row]
                if gi.row in dirty or gi.row in pend
                else committed[gi.row]
            )
            for cid, gi in items
        }

    def peer_match(self, cluster_id: int, node_id: int) -> int:
        gi = self.groups[cluster_id]
        return int(gi.base) + int(self._read("match", gi.row)[gi.slots[node_id]])
