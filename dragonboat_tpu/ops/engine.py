"""Host driver for the batched quorum engine.

Replaces the reference's 16-worker per-group iteration
(``execengine.go:860-949``) with: host ingest (queues → compact event
batches) → ONE ``quorum_step`` device dispatch per round → host egress
(commit advances, election/heartbeat/step-down flags).  Rare transitions
(membership change, becoming leader/candidate, snapshot restore, index
rebase) mutate a numpy mirror row and are scattered onto the device arrays
before the next dispatch.

The group axis is shardable over a ``jax.sharding.Mesh`` (see
``sharding.py``): every kernel op is row-wise over groups, so XLA partitions
the whole step with zero collectives — groups are embarrassingly parallel,
exactly like the reference's ``clusterID % workers`` partitioning but over
chips instead of goroutines.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .kernels import quorum_step
from .state import (
    CANDIDATE,
    FOLLOWER,
    LEADER,
    OBSERVER,
    VOTE_GRANT,
    VOTE_NONE,
    VOTE_REJECT,
    WITNESS,
    HostMirror,
    QuorumState,
)

# Event batches are padded to fixed sizes so jit compiles once.
DEFAULT_EVENT_CAP = 4096

# Rebase a row when relative indexes pass this (well clear of int32 max).
REBASE_THRESHOLD = 1 << 30


@dataclass
class GroupInfo:
    cluster_id: int
    row: int
    slots: Dict[int, int]            # node_id -> peer slot
    base: int = 0                    # uint64 absolute index of rel 0
    node_ids: List[int] = field(default_factory=list)


class StepResult:
    """Egress of one dispatch, in absolute-index / cluster-id terms.

    ``commit`` materializes lazily from the vectorized egress arrays: hot
    callers (the bench rungs, watermark probes) read the arrays or the
    engine's ``committed_view`` and never pay the per-row dict build."""

    __slots__ = (
        "won", "lost", "elect", "heartbeat", "demote",
        "_commit_cids", "_commit_abs", "_commit_dict",
    )

    def __init__(self):
        self._commit_cids = None   # np (n,) int64 cluster ids, or None
        self._commit_abs = None    # np (n,) int64 absolute committed
        self._commit_dict: Optional[Dict[int, int]] = None
        self.won: List[int] = []
        self.lost: List[int] = []
        self.elect: List[int] = []
        self.heartbeat: List[int] = []
        self.demote: List[int] = []

    @property
    def commit(self) -> Dict[int, int]:
        """cluster_id -> new committed (abs); built on first access."""
        if self._commit_dict is None:
            if self._commit_cids is None or not len(self._commit_cids):
                self._commit_dict = {}
            else:
                self._commit_dict = dict(
                    zip(self._commit_cids.tolist(), self._commit_abs.tolist())
                )
        return self._commit_dict


class MultiRoundResult(StepResult):
    """Egress of one K-round fused dispatch (``step_rounds``).

    Adds the raw vectorized views on top of the StepResult interface:
    ``committed_rel`` is the device's final (G,) relative watermark vector
    and ``commit_rows`` the rows that advanced vs the pre-block host twin —
    both numpy, zero per-row Python.  Flags are OR-accumulated across the
    block's rounds (see ``kernels.quorum_multiround_impl`` on recycled-row
    attribution)."""

    __slots__ = ("rounds", "committed_rel", "commit_rows")

    def __init__(self, rounds: int):
        super().__init__()
        self.rounds = rounds
        self.committed_rel: Optional[np.ndarray] = None  # (G,) i32
        self.commit_rows: Optional[np.ndarray] = None    # (n,) changed rows


class _RoundBuf:
    """One closed ingest round awaiting the fused multi-round dispatch:
    epoch-filtered ack arrays, first-wins-deduped votes, and the round's
    leader-recycle records (applied at round start, device-side).
    ``cells`` optionally carries the precomputed flat (row·P + slot)
    index vector when the staging path shares one geometry across rounds
    (``ack_block_rounds``), sparing a per-round int64 conversion."""

    __slots__ = ("rows", "slots", "rels", "votes", "churn", "cells")

    def __init__(self, rows, slots, rels, votes, churn, cells=None):
        self.rows = rows
        self.slots = slots
        self.rels = rels
        self.votes = votes   # list[(row, slot, grant)]
        self.churn = churn   # list[(row, term, term_start_rel, last_rel)]
        self.cells = cells   # np (n,) int64 row*P+slot, or None


class BatchedQuorumEngine:
    """Device-resident quorum state for up to ``n_groups`` Raft groups.

    Usage::

        eng = BatchedQuorumEngine(n_groups=1024, n_peers=5)
        eng.add_group(cid, node_ids=[1,2,3], self_id=1, election_timeout=10)
        eng.set_leader(cid, term=1, term_start=1, last_index=1)
        eng.ack(cid, node_id=2, index=5)      # ReplicateResp ingest
        out = eng.step()                       # one device dispatch
        out.commit[cid]                        # -> advanced commit index
    """

    def __init__(
        self,
        n_groups: int,
        n_peers: int,
        event_cap: int = DEFAULT_EVENT_CAP,
        sharding=None,
        device_ticks: bool = True,
        dense_ingest: str | bool = "auto",
    ):
        self.n_groups = n_groups
        self.n_peers = n_peers
        self.event_cap = event_cap
        #: dense-ingestion policy: collapse a round's acks into a (G,P)
        #: max matrix and dispatch the scatter-free dense kernel (see
        #: kernels.quorum_step_dense_impl — ~7× at full occupancy on TPU).
        #: "auto" picks per dispatch by byte volume: dense uploads
        #: 6·G·P bytes vs ~13 per sparse event, so dense wins once the
        #: staged acks outnumber ~G·P/2.  True forces dense, False never.
        # identity checks: `1 in (True, ...)` would pass by int equality
        if not (
            dense_ingest is True
            or dense_ingest is False
            or dense_ingest == "auto"
        ):
            raise ValueError(
                f"dense_ingest must be True, False, or 'auto', got {dense_ingest!r}"
            )
        self.dense_ingest = dense_ingest
        self._dense_threshold = (n_groups * n_peers) // 2
        #: whether this engine EVER runs tick_step on device.  Contact
        #: events (leader_contact zero-acks) are one-shot, so a ticking
        #: engine must apply the election-clock reset on every round —
        #: including do_tick=False rounds that drain staged acks between
        #: host ticks.  Engines that never tick (host-driven clocks) skip
        #: the reset scatter entirely (it is dead work there).
        self.device_ticks = device_ticks
        self.mirror = HostMirror(n_groups, n_peers)
        self.sharding = sharding
        self._dev: QuorumState = self.mirror.to_device(sharding)
        self._cache_stale = False
        self.groups: Dict[int, GroupInfo] = {}
        self.rows: Dict[int, GroupInfo] = {}
        # vectorized row→(cluster_id, base) translation for egress: at
        # full occupancy tens of thousands of rows change per round, and
        # a per-row Python dict walk dominates the host loop
        self._row_cid = np.full((n_groups,), -1, np.int64)
        self._row_base = np.zeros((n_groups,), np.int64)
        #: host twin of dev.committed — device state changes only through
        #: _dispatch (whose egress refreshes this) and _upload_dirty
        #: (which syncs the dirty rows), so step() never needs a device
        #: readback just to learn the PREVIOUS watermarks (that readback
        #: was a full extra round trip per step on a network-attached TPU)
        self._committed_cache = np.zeros((n_groups,), np.int32)
        self._free = list(range(n_groups - 1, -1, -1))
        self._dirty: set[int] = set()
        # rows bulk-pulled from the device since the last dispatch
        # (sync_rows); invalidated whenever device state advances
        self._synced: set[int] = set()
        # per-row staging epoch: a state transition bumps it, and events
        # staged under an older epoch are filtered at dispatch.  This is
        # the O(1) replacement for scanning the whole event buffer on
        # every transition (measured 0.66ms per transition at 4k groups —
        # an election burst of 1,024 transitions cost a 680ms round).
        self._row_epoch = np.zeros((n_groups,), np.int32)
        # pending event buffers (grow unbounded host-side; chunked at
        # dispatch); entries carry the staging epoch as a 4th column
        self._acks: List[Tuple[int, int, int, int]] = []  # row, slot, rel, ep
        self._votes: List[Tuple[int, int, int, int]] = []  # row, slot, g, ep
        self._voted_cells: dict = {}  # (row, slot) -> staging epoch
        # vectorized bulk-ingest blocks (ack_block): (rows, slots, rels, eps)
        self._ack_blocks: List[Tuple[np.ndarray, ...]] = []
        # --- multi-round fused staging (ISSUE 1 tentpole) ---------------
        # closed ingest rounds awaiting ONE fused dispatch (begin_round /
        # step_rounds); each round's epoch filter resolves at close time,
        # so a later transition only purges rounds still open
        self._round_blocks: List[_RoundBuf] = []
        # leader-recycle records of the CURRENT open round (stage_recycle)
        self._churn: List[Tuple[int, int, int, int]] = []
        self._churn_rows: set = set()  # one recycle per row per round
        # rows with an UNDISPATCHED recycle anywhere in the backlog (open
        # round or closed blocks): their mirror rows are authoritative
        # (recycle_row already applied) and host reads must not consult
        # the pre-recycle device row; a rare-path mutation on such a row
        # collapses the recycle to pre-block ordering (_sync_row)
        self._churn_pending: set = set()
        # in-flight pipelined dispatch: (StepOutputs, prev_committed,
        # row_cid snapshot, row_base snapshot, n_rounds) — the ingest of
        # block i+1 overlaps the device execution of block i, and every
        # host read of device state harvests first (_harvest_inflight)
        self._inflight = None

    @property
    def dev(self) -> QuorumState:
        return self._dev

    @dev.setter
    def dev(self, st: QuorumState) -> None:
        """External state assignment (hybrid direct-dispatch callers, e.g.
        the bench's staged multistep) — the host committed twin can no
        longer be trusted, so the next step() re-reads it from the device
        once instead of mis-reporting commit deltas."""
        self._harvest_inflight()
        self._dev = st
        self._cache_stale = True
        self._synced.clear()

    # ------------------------------------------------------------------
    # group lifecycle (rare path, host scalar)
    # ------------------------------------------------------------------

    def add_group(
        self,
        cluster_id: int,
        node_ids: List[int],
        self_id: int,
        election_timeout: int = 10,
        heartbeat_timeout: int = 1,
        rand_timeout: Optional[int] = None,
        check_quorum: bool = False,
        witnesses: Tuple[int, ...] = (),
        observers: Tuple[int, ...] = (),
    ) -> GroupInfo:
        if cluster_id in self.groups:
            raise ValueError(f"group {cluster_id} already registered")
        if not self._free:
            raise RuntimeError("quorum engine full")
        row = self._free.pop()
        all_ids = sorted(set(node_ids) | set(witnesses) | set(observers))
        if len(all_ids) > self.n_peers:
            raise ValueError("too many peers for tensor width")
        slots = {nid: i for i, nid in enumerate(all_ids)}
        gi = GroupInfo(cluster_id, row, slots, node_ids=all_ids)
        self.groups[cluster_id] = gi
        self.rows[row] = gi
        self._row_cid[row] = cluster_id
        self._row_base[row] = 0

        a = self.mirror.arrays
        a["live"][row] = True
        a["node_state"][row] = FOLLOWER
        a["term"][row] = 0
        a["committed"][row] = 0
        a["last_index"][row] = 0
        a["term_start"][row] = 0
        n_voting = len(set(node_ids) | set(witnesses))
        a["quorum"][row] = n_voting // 2 + 1
        a["self_slot"][row] = slots[self_id]
        a["election_tick"][row] = 0
        a["heartbeat_tick"][row] = 0
        a["election_timeout"][row] = election_timeout
        a["heartbeat_timeout"][row] = heartbeat_timeout
        a["rand_timeout"][row] = (
            rand_timeout if rand_timeout is not None else election_timeout * 2
        )
        is_voter = self_id in node_ids or self_id in witnesses
        a["electable"][row] = is_voter and self_id not in witnesses
        a["check_quorum_on"][row] = check_quorum
        a["match"][row, :] = 0
        a["next"][row, :] = 1
        a["voting"][row, :] = False
        a["present"][row, :] = False
        a["active"][row, :] = False
        a["votes"][row, :] = VOTE_NONE
        for nid, slot in slots.items():
            a["present"][row, slot] = True
            a["voting"][row, slot] = nid not in observers
        self._dirty.add(row)
        return gi

    def _purge_row_events(self, row: int) -> None:
        """Invalidate queued acks/votes for a row.  Called on every state
        transition (and removal): events staged before the transition
        belong to the old term and must never reach the new term's tally
        (the scalar twin drops mismatched-term responses in
        ``handle_vote_resp`` / ``handle_replicate_resp``).  O(1): the row's
        staging epoch is bumped and stale-epoch events are filtered in one
        vectorized pass at dispatch."""
        self._row_epoch[row] += 1

    def _drop_churn_records(self, row: int, drop_events: bool = False) -> None:
        """Strip every undispatched recycle record for ``row`` — from the
        open round AND from closed blocks awaiting dispatch.  A stale
        record surviving into the program would revive a freed row (or
        clobber its next tenant) with the dead recycle's reset.

        ``drop_events=True`` additionally strips the row's ack/vote
        events from CLOSED blocks.  Required when the recycle collapses
        to pre-block ordering (a rare-path mutation, ``_sync_row``): the
        row's fresh state uploads before the block, so old-tenant events
        sealed into earlier rounds — whose epoch filters resolved at
        close time, immune to the recycle's epoch bump — would otherwise
        scatter into the NEW tenant.  This restores the single-round
        path's semantics, where a transition purges every staged event
        for its row."""
        if row in self._churn_rows:
            self._churn = [c for c in self._churn if c[0] != row]
            self._churn_rows.discard(row)
        if row in self._churn_pending:
            for b in self._round_blocks:
                if b.churn:
                    b.churn = [c for c in b.churn if c[0] != row]
            self._churn_pending.discard(row)
        if drop_events:
            for b in self._round_blocks:
                if b.rows.size:
                    keep = b.rows != row
                    if not keep.all():
                        b.rows = b.rows[keep]
                        b.slots = b.slots[keep]
                        b.rels = b.rels[keep]
                        if b.cells is not None:
                            b.cells = b.cells[keep]
                if b.votes:
                    b.votes = [v for v in b.votes if v[0] != row]

    def remove_group(self, cluster_id: int) -> None:
        gi = self.groups.pop(cluster_id)
        # any undispatched recycle of this row is now moot — it must not
        # revive the freed row when the block dispatches — and events
        # already sealed into closed blocks must die with the tenant (a
        # future add_group may hand this row to a new group before the
        # block dispatches)
        self._drop_churn_records(gi.row, drop_events=True)
        del self.rows[gi.row]
        self.mirror.arrays["live"][gi.row] = False
        self._dirty.add(gi.row)
        # purge queued events so a future tenant of this row never receives
        # the dead group's acks/votes
        self._purge_row_events(gi.row)
        self._row_cid[gi.row] = -1
        self._free.append(gi.row)

    # ------------------------------------------------------------------
    # rare-path row mutations (host scalar, mask-update tensors)
    # ------------------------------------------------------------------

    def _rel(self, gi: GroupInfo, index: int) -> int:
        rel = index - gi.base
        if rel < 0:
            raise ValueError(f"index {index} below base {gi.base}")
        if rel >= REBASE_THRESHOLD:
            raise ValueError("index needs rebase before ingest")
        return rel

    def set_leader(
        self, cluster_id: int, term: int, term_start: int, last_index: int
    ) -> None:
        """Promote to leader (twin: ``become_leader`` raft.go:1027-1045)."""
        gi = self.groups[cluster_id]
        a = self.mirror.arrays
        row = gi.row
        self._sync_row(row)
        a["node_state"][row] = LEADER
        a["term"][row] = term
        a["term_start"][row] = self._rel(gi, term_start)
        a["last_index"][row] = self._rel(gi, last_index)
        a["election_tick"][row] = 0
        a["heartbeat_tick"][row] = 0
        a["votes"][row, :] = VOTE_NONE
        # reset_remotes: fresh Remote structs — next = last+1 for all,
        # self match = last, activity cleared (raft.go:991-1010)
        a["match"][row, :] = 0
        a["next"][row, :] = self._rel(gi, last_index) + 1
        a["match"][row, a["self_slot"][row]] = self._rel(gi, last_index)
        a["active"][row, :] = False
        self._purge_row_events(row)
        self._dirty.add(row)

    def set_candidate(self, cluster_id: int, term: int) -> None:
        """Start campaigning (twin: ``become_candidate``); the self-vote is
        ingested like any other vote event."""
        gi = self.groups[cluster_id]
        a = self.mirror.arrays
        row = gi.row
        self._sync_row(row)
        a["node_state"][row] = CANDIDATE
        a["term"][row] = term
        a["votes"][row, :] = VOTE_NONE
        a["election_tick"][row] = 0
        self._purge_row_events(row)
        self._dirty.add(row)

    def set_follower(self, cluster_id: int, term: int) -> None:
        gi = self.groups[cluster_id]
        a = self.mirror.arrays
        row = gi.row
        self._sync_row(row)
        a["node_state"][row] = FOLLOWER
        a["term"][row] = term
        a["votes"][row, :] = VOTE_NONE
        a["election_tick"][row] = 0
        self._purge_row_events(row)
        self._dirty.add(row)

    def set_randomized_timeout(self, cluster_id: int, timeout: int) -> None:
        """Host-seeded randomized election timeout (determinism: the PRNG
        stays host-side and seeded, see raft.py design notes)."""
        gi = self.groups[cluster_id]
        self._sync_row(gi.row)
        self.mirror.arrays["rand_timeout"][gi.row] = timeout
        self._dirty.add(gi.row)

    def restore_progress(
        self, cluster_id: int, committed: int, last_index: int
    ) -> None:
        """Snapshot-restore / log-truncation repair of the watermarks."""
        gi = self.groups[cluster_id]
        a = self.mirror.arrays
        row = gi.row
        self._sync_row(row)
        a["committed"][row] = self._rel(gi, committed)
        a["last_index"][row] = self._rel(gi, last_index)
        self._dirty.add(row)

    def rebase(self, cluster_id: int) -> None:
        """Shift a row's base up to its committed watermark so relative
        int32 indexes stay far from overflow (state.py design note)."""
        gi = self.groups[cluster_id]
        a = self.mirror.arrays
        row = gi.row
        self._sync_row(row)
        shift = int(a["committed"][row])
        if shift <= 0:
            return
        gi.base += shift
        self._row_base[row] = gi.base
        for f in ("committed", "last_index", "term_start"):
            a[f][row] = max(0, int(a[f][row]) - shift)
        a["match"][row, :] = np.maximum(a["match"][row, :] - shift, 0)
        a["next"][row, :] = np.maximum(a["next"][row, :] - shift, 1)
        self._dirty.add(row)

    # ------------------------------------------------------------------
    # dense-path event ingest
    # ------------------------------------------------------------------

    def ack(self, cluster_id: int, node_id: int, index: int) -> None:
        """ReplicateResp success / local append (self ack).

        Acks below the rebased floor are legal raft traffic (delayed
        retransmits); they clamp to rel 0, a scatter-max no-op that still
        marks the peer active — same outcome as ``remote.try_update`` on a
        stale index.
        """
        gi = self.groups[cluster_id]
        rel = max(0, index - gi.base)
        if rel >= REBASE_THRESHOLD:
            raise ValueError(f"index {index} needs rebase (base {gi.base})")
        self._acks.append(
            (gi.row, gi.slots[node_id], rel, int(self._row_epoch[gi.row]))
        )

    def ack_block(self, rows, slots, rels) -> None:
        """Vectorized bulk ack ingest (numpy arrays in row/slot space).

        The per-event ``ack()`` path costs a Python call per event; a
        native or vectorized control plane staging thousands of acks per
        round uses this instead — arrays append as one block and are
        concatenated at dispatch.  Caller contract: rows are live group
        rows, slots valid for their rows, ``rels`` already rebased
        (0 <= rel < REBASE_THRESHOLD); the bounds are validated
        vectorized, membership is the caller's responsibility.
        """
        # validate on the ORIGINAL dtype (an int64 >= 2^32 must hit the
        # rebase guard, not wrap into range), then narrow
        rows = np.asarray(rows)
        slots = np.asarray(slots)
        rels = np.asarray(rels)
        if not (rows.shape == slots.shape == rels.shape):
            raise ValueError("ack_block arrays must share a shape")
        if rels.size and rels.max() >= REBASE_THRESHOLD:
            raise ValueError("ack_block rel out of range (rebase needed)")
        if rows.size and (rows.min() < 0 or rows.max() >= self.n_groups):
            raise ValueError("ack_block row out of range")
        if slots.size and (slots.min() < 0 or slots.max() >= self.n_peers):
            raise ValueError("ack_block slot out of range")
        # below-base acks are legal raft traffic (delayed retransmits) and
        # clamp to rel 0, matching ack()'s scalar semantics
        rels = np.maximum(rels, 0)
        rows32 = rows.astype(np.int32)
        self._ack_blocks.append(
            (rows32, slots.astype(np.int32), rels.astype(np.int32),
             self._row_epoch[rows32].copy())
        )

    def vote(self, cluster_id: int, node_id: int, granted: bool) -> None:
        """First vote per (group, peer) wins (twin: ``handle_vote_resp``).

        The kernel's first-wins guard reads pre-batch state, so within-batch
        duplicates must be deduped here — keep only the first event per cell.
        """
        gi = self.groups[cluster_id]
        cell = (gi.row, gi.slots[node_id])
        ep = int(self._row_epoch[gi.row])
        if self._voted_cells.get(cell) == ep:
            return
        self._voted_cells[cell] = ep
        self._votes.append(
            (cell[0], cell[1], VOTE_GRANT if granted else VOTE_REJECT, ep)
        )

    def heartbeat_resp(self, cluster_id: int, node_id: int) -> None:
        """Heartbeat response marks the peer active; an ack at index 0 is a
        no-op for match (scatter-max) but sets the activity bit."""
        gi = self.groups[cluster_id]
        self._acks.append(
            (gi.row, gi.slots[node_id], 0, int(self._row_epoch[gi.row]))
        )

    def leader_contact(self, cluster_id: int) -> None:
        """A follower heard from its leader: reset the row's election clock
        (twin: ``leader_is_available`` — the kernel resets election_tick on
        any event touching a non-leader row)."""
        gi = self.groups[cluster_id]
        self._acks.append(
            (gi.row, int(self.mirror.arrays["self_slot"][gi.row]), 0,
             int(self._row_epoch[gi.row]))
        )

    # ------------------------------------------------------------------
    # multi-round fused staging (ISSUE 1 tentpole)
    # ------------------------------------------------------------------

    def begin_round(self) -> None:
        """Close the current ingest round: everything staged so far forms
        one scanned round of the next fused dispatch; events staged after
        this call land in the NEXT round.  The round's stale-epoch filter
        resolves NOW — a transition staged later (including a
        ``stage_recycle`` in a later round) must not retroactively purge
        events that a per-round host dispatch would already have consumed.
        """
        if self._votes:
            votes = [
                (r, s, v)
                for r, s, v, ep in self._votes
                if ep == self._row_epoch[r]
            ]
            self._votes = []
            self._voted_cells.clear()
        else:
            votes = []
        rows, slots, rels = self._gather_acks()
        self._round_blocks.append(
            _RoundBuf(rows, slots, rels, votes, self._churn)
        )
        self._churn = []
        self._churn_rows = set()

    def pending_rounds(self) -> int:
        """Closed rounds awaiting the fused dispatch."""
        return len(self._round_blocks)

    def ack_block_rounds(self, rows, slots, rels_rounds) -> None:
        """K CLOSED rounds of bulk acks over ONE (row, slot) geometry —
        the steady-state shape of every ladder section (same cells every
        round, advancing rel indexes).  Validates the geometry once and
        snapshots the epoch filter once for the whole block instead of
        per round: at 64k groups × 3 acks × K=16 the per-round
        ``ack_block`` + ``begin_round`` path spent ~60ms/dispatch on
        validation min/max scans and defensive copies this API skips
        (the round buffers alias the caller's arrays — the caller must
        not mutate them until the block is dispatched).

        ``rels_rounds`` is (K, n): row ``r`` forms scanned round ``r``.
        Events/churn already staged are closed into one preceding round
        first (exactly ``begin_round`` semantics).
        """
        rows = np.asarray(rows)
        slots = np.asarray(slots)
        rels_rounds = np.asarray(rels_rounds)
        if rels_rounds.ndim != 2 or rows.shape != slots.shape or (
            rels_rounds.shape[1:] != rows.shape
        ):
            raise ValueError("ack_block_rounds: shape mismatch")
        if rels_rounds.size and rels_rounds.max() >= REBASE_THRESHOLD:
            raise ValueError("ack_block_rounds rel out of range")
        if rows.size and (rows.min() < 0 or rows.max() >= self.n_groups):
            raise ValueError("ack_block_rounds row out of range")
        if slots.size and (slots.min() < 0 or slots.max() >= self.n_peers):
            raise ValueError("ack_block_rounds slot out of range")
        if self._acks or self._ack_blocks or self._votes or self._churn:
            self.begin_round()
        rows32 = rows.astype(np.int32, copy=False)
        slots32 = slots.astype(np.int32, copy=False)
        cells = rows32.astype(np.int64) * self.n_peers + slots32
        # no epoch filter needed: every event is staged NOW under the
        # rows' current epochs — begin_round closing each round here
        # would resolve to the identity filter
        if rels_rounds.size and rels_rounds.min() < 0:
            # below-base retransmits clamp to rel 0 (ack() semantics)
            rels_rounds = np.maximum(rels_rounds, 0)
        for r in range(rels_rounds.shape[0]):
            self._round_blocks.append(
                _RoundBuf(
                    rows32, slots32,
                    rels_rounds[r].astype(np.int32, copy=False),
                    [], [], cells=cells,
                )
            )

    def stage_recycle(
        self,
        old_cluster_id: int,
        new_cluster_id: int,
        term: int,
        term_start: int,
        last_index: int,
        rand_timeout: Optional[int] = None,
    ) -> GroupInfo:
        """Replace a group with a fresh SAME-GEOMETRY leader tenant as a
        masked row update INSIDE the next dispatched program — the
        device-side twin of ``remove_group`` + ``add_group`` +
        ``set_leader`` (kernels._apply_recycle), with none of the
        host-side re-upload those pay (the dominant cost of churn-under-
        load at 100k groups: one dirty-row scatter per recycle).

        The reset applies at the START of the recycle's ingest round —
        before that round's events — exactly where the host path's
        ``_upload_dirty`` lands relative to its dispatch, so acks staged
        for the new tenant in the same round ingest correctly and events
        already staged for the old tenant this round are purged (epoch
        bump), while earlier CLOSED rounds still reach the old tenant.

        Geometry (peer slots, voting/present masks, quorum, self slot,
        timeouts) carries over unchanged; anything else — different
        membership, witnesses, a different randomized timeout — must take
        the host path.  ``rand_timeout`` may be passed to ASSERT the
        carried-over value.  Raises ValueError when the swap isn't a pure
        recycle.
        """
        gi = self.groups.get(old_cluster_id)
        if gi is None:
            raise ValueError(f"group {old_cluster_id} not registered")
        if new_cluster_id in self.groups:
            raise ValueError(f"group {new_cluster_id} already registered")
        row = gi.row
        if row in self._churn_rows:
            raise ValueError(
                f"row {row} already recycled this round (begin_round first)"
            )
        a = self.mirror.arrays
        if rand_timeout is not None and rand_timeout != int(a["rand_timeout"][row]):
            raise ValueError("rand_timeout differs: recycle must keep geometry")
        if term_start < 0 or last_index < 0 or term_start > last_index:
            raise ValueError("term_start/last_index out of range")
        if last_index >= REBASE_THRESHOLD:
            raise ValueError("index needs rebase before recycle")
        # host bookkeeping: the new tenant takes the SAME row at base 0
        del self.groups[old_cluster_id]
        ngi = GroupInfo(
            new_cluster_id, row, gi.slots, base=0, node_ids=gi.node_ids
        )
        self.groups[new_cluster_id] = ngi
        self.rows[row] = ngi
        self._row_cid[row] = new_cluster_id
        self._row_base[row] = 0
        # old-tenant events staged this round must not reach the new
        # tenant (closed rounds resolved their filter at close time)
        self._purge_row_events(row)
        # mirror coherence WITHOUT dirtying the row: the device applies
        # the identical reset in-program (state.HostMirror.recycle_row);
        # until the block dispatches, host reads of this row resolve to
        # the mirror (_read / committed caches), never the stale device
        self.mirror.recycle_row(row, term, term_start, last_index)
        self._committed_cache[row] = 0
        self._synced.discard(row)
        self._churn.append((row, term, term_start, last_index))
        self._churn_rows.add(row)
        self._churn_pending.add(row)
        return ngi

    def step_rounds(
        self,
        do_tick: bool = False,
        pipelined: bool = False,
        pad_rounds_to: int = 0,
    ) -> Optional[MultiRoundResult]:
        """ONE fused dispatch over every staged round (``begin_round``
        boundaries; a non-empty open round is closed implicitly).

        ``pipelined=True`` double-buffers host staging against device
        execution: the call returns the PREVIOUS dispatch's egress (None
        on the first) and leaves this dispatch in flight, so the caller
        ingests/encodes block i+1 while block i executes.  Any host read
        of device state (``committed_view``, ``_read``, a rare-path
        transition, the next dispatch) harvests the in-flight block
        first, so the pipelining is invisible to correctness.  Host
        rare-path mutations (``set_leader`` …) staged between rounds
        apply BEFORE the whole block — mid-block transitions must use
        ``stage_recycle`` or split the block.

        ``pad_rounds_to`` pads the block with event-free, tick-masked-off
        rounds (provable no-ops) up to a fixed K, so a caller with a
        VARYING round count — the coordinator's 2..4 missed-tick catch-up
        — reuses one compiled program instead of paying a multi-second
        XLA compile per distinct K (kernels.quorum_multiround tick_mask
        note).
        """
        if self._acks or self._ack_blocks or self._votes or self._churn:
            self.begin_round()
        if not self._round_blocks:
            # nothing staged: drain whatever is still in flight
            return self._harvest_inflight()
        blocks, self._round_blocks = self._round_blocks, []
        n_real = len(blocks)
        z = np.zeros((0,), np.int32)
        while len(blocks) < pad_rounds_to:
            blocks.append(_RoundBuf(z, z, z, [], []))
        tick_mask = np.zeros((len(blocks),), bool)
        tick_mask[:n_real] = True
        prev = self._harvest_inflight()
        self._upload_dirty()
        self._refresh_committed_cache()
        out = self._dispatch_multiround(blocks, do_tick, tick_mask)
        self._synced.clear()
        # every staged recycle is now inside the dispatched program
        self._churn_pending.clear()
        self._inflight = (
            out,
            # snapshot, not alias: stage_recycle zeroes cache rows in
            # place while this dispatch is in flight, which must not
            # corrupt ITS commit-delta baseline
            self._committed_cache.copy(),
            self._row_cid.copy(),
            self._row_base.copy(),
            len(blocks),
        )
        if pipelined:
            return prev
        return self.harvest()

    def harvest(self) -> Optional[MultiRoundResult]:
        """Egress of the in-flight pipelined dispatch (None when idle)."""
        return self._harvest_inflight()

    def _harvest_inflight(self) -> Optional[MultiRoundResult]:
        if self._inflight is None:
            return None
        out, prev_committed, row_cid, row_base, n_rounds = self._inflight
        self._inflight = None
        committed, won, lost, elect, hb, demote = jax.device_get(
            (
                out.committed,
                out.won,
                out.lost,
                out.flags.elect_due,
                out.flags.hb_due,
                out.flags.checkq_demote,
            )
        )
        res = MultiRoundResult(n_rounds)
        committed = np.asarray(committed)
        res.committed_rel = committed
        self._committed_cache = np.array(committed, dtype=np.int32)
        if self._churn_pending:
            # recycles staged while this block was in flight: their rows'
            # host watermark is the mirror's (new tenant) until THEIR
            # block lands — the harvested vector still shows the old one
            rows = np.fromiter(self._churn_pending, dtype=np.int64)
            self._committed_cache[rows] = (
                self.mirror.arrays["committed"][rows]
            )
        res.commit_rows = self._translate_egress(
            res, committed, prev_committed, row_cid, row_base,
            (("won", won), ("lost", lost), ("elect", elect),
             ("heartbeat", hb), ("demote", demote)),
        )
        return res

    @staticmethod
    def _translate_egress(
        res, committed, prev_committed, row_cid, row_base, flags
    ) -> np.ndarray:
        """Vectorized row→cluster egress translation, shared by step()'s
        single-round path and the fused harvest: watermark deltas become
        (cid, abs) arrays (dead rows — cid -1 — dropped; the commit dict
        materializes lazily), flag vectors become cid lists.  Returns the
        changed-row index vector."""
        changed = np.nonzero(committed != prev_committed)[0]
        if changed.size:
            cids = row_cid[changed]
            live = cids >= 0
            res._commit_cids = cids[live]
            res._commit_abs = (row_base[changed] + committed[changed])[live]
        for name, arr in flags:
            idx = np.nonzero(np.asarray(arr))[0]
            if idx.size:
                cids = row_cid[idx]
                getattr(res, name).extend(cids[cids >= 0].tolist())
        return changed

    def _dispatch_multiround(
        self, blocks: List[_RoundBuf], do_tick: bool, tick_mask: np.ndarray
    ):
        """Stack K closed rounds into (K,G,P) tensors + (K,C) churn blocks
        and run ``kernels.quorum_multiround`` — one scan, one upload, one
        egress for the whole block."""
        from .kernels import quorum_multiround

        k = len(blocks)
        g, p = self.n_groups, self.n_peers
        # -1 = untouched sentinel: one tensor instead of (max, touched) —
        # halves both the host staging stores and the upload bytes
        ack_max = np.full((k, g, p), -1, np.int32)
        flat = ack_max.reshape(-1)
        stride = g * p
        for r, b in enumerate(blocks):
            if b.rows.size:
                if b.cells is not None:  # shared-geometry fast path
                    cell = r * stride + b.cells
                else:
                    cell = (r * g + b.rows.astype(np.int64)) * p + b.slots
                np.maximum.at(flat, cell, b.rels)
        has_votes = any(b.votes for b in blocks)
        if has_votes:
            vote_new = np.full((k, g, p), VOTE_NONE, np.int8)
            for r, b in enumerate(blocks):
                if b.votes:
                    cols = np.array(b.votes, dtype=np.int64).T
                    vote_new[r, cols[0], cols[1]] = cols[2].astype(np.int8)
        else:
            vote_new = np.zeros((1, 1, 1), np.int8)  # unused dummy
        has_churn = any(b.churn for b in blocks)
        if has_churn:
            # pad the per-round churn width to a power of two so the jit
            # cache stays bounded at ~log2(G) entries per K (the same
            # shape-bucketing rationale as _pad_pow2_rows)
            cmax = max(len(b.churn) for b in blocks)
            cap = 1 << max(0, cmax - 1).bit_length()
            cap = max(cap, 1)
            churn_row = np.full((k, cap), g, np.int32)  # g = padding (drops)
            churn_term = np.zeros((k, cap), np.int32)
            churn_start = np.zeros((k, cap), np.int32)
            churn_last = np.zeros((k, cap), np.int32)
            for r, b in enumerate(blocks):
                if b.churn:
                    cols = np.array(b.churn, dtype=np.int64).T
                    n = cols.shape[1]
                    churn_row[r, :n] = cols[0]
                    churn_term[r, :n] = cols[1]
                    churn_start[r, :n] = cols[2]
                    churn_last[r, :n] = cols[3]
        else:
            z = np.zeros((1, 1), np.int32)
            churn_row = churn_term = churn_start = churn_last = z
        out = quorum_multiround(
            self._dev,
            jnp.asarray(ack_max),
            jnp.asarray(vote_new),
            jnp.asarray(churn_row),
            jnp.asarray(churn_term),
            jnp.asarray(churn_start),
            jnp.asarray(churn_last),
            jnp.asarray(tick_mask),
            do_tick=do_tick,
            track_contact=self.device_ticks or do_tick,
            has_votes=has_votes,
            has_churn=has_churn,
        )
        self._dev = out.state
        return out

    def _refresh_committed_cache(self) -> None:
        """Re-read the host committed twin from the device when it was
        invalidated (external ``dev`` assignment).  Rows with a staged
        in-program recycle keep their MIRROR watermark (the device still
        holds the old tenant until the block dispatches)."""
        if not self._cache_stale:
            return
        self._committed_cache = np.array(
            np.asarray(self._dev.committed), dtype=np.int32
        )
        if self._churn_pending:
            rows = np.fromiter(self._churn_pending, dtype=np.int64)
            self._committed_cache[rows] = (
                self.mirror.arrays["committed"][rows]
            )
        self._cache_stale = False

    def committed_view(self) -> np.ndarray:
        """Absolute committed watermark per ROW as one (G,) int64 vector —
        the fully vectorized egress view (dead rows included; mask with
        ``row_cids() >= 0``).  Fresh after any step/harvest; reads the
        host twin, never the device."""
        self._harvest_inflight()
        self._refresh_committed_cache()
        view = self._row_base + self._committed_cache.astype(np.int64)
        if self._dirty:
            rows = np.fromiter(self._dirty, dtype=np.int64)
            view[rows] = (
                self._row_base[rows]
                + self.mirror.arrays["committed"][rows].astype(np.int64)
            )
        return view

    def row_cids(self) -> np.ndarray:
        """(G,) int64 cluster id per row (-1 = dead); pairs with
        ``committed_view`` for vectorized watermark asserts."""
        return self._row_cid.copy()

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------

    def _sync_row(self, row: int) -> None:
        """Pull one device row into the mirror before mutating it (the
        dense path may have advanced it since the last upload).

        A row with an undispatched in-program recycle is special: its
        MIRROR already holds the post-recycle state (recycle_row) and the
        device row is stale pre-recycle data — pulling it would resurrect
        the old tenant under the new cid.  The caller is about to mutate
        the row host-side, which supersedes the staged device reset, so
        the recycle collapses to pre-block ordering: drop the in-program
        record and dirty the (post-recycle) mirror for upload instead."""
        self._harvest_inflight()
        if row in self._churn_pending:
            self._drop_churn_records(row, drop_events=True)
            self._dirty.add(row)
            return
        if row in self._dirty or row in self._synced:
            return
        for k in self.mirror.arrays:
            self.mirror.arrays[k][row] = np.asarray(
                getattr(self.dev, k)[row]
            )
        self._synced.add(row)

    @staticmethod
    def _pad_pow2_rows(idx: np.ndarray) -> np.ndarray:
        """Pad a row-index vector to the next power-of-two length by
        repeating its first element.  Gather/scatter with a fresh index
        SHAPE recompiles the eager op (measured: an election burst's
        varying transition counts cost ~620ms/round in
        backend_compile_and_load); bucketing shapes to powers of two
        bounds the compile cache at ~log2(G) entries.  Duplicate indexes
        are harmless: gathers repeat a value, scatters rewrite the same
        value."""
        n = idx.size
        cap = 1 << max(0, n - 1).bit_length()
        if cap == n:
            return idx
        return np.concatenate([idx, np.full(cap - n, idx[0], idx.dtype)])

    def sync_rows(self, rows) -> None:
        """Bulk-pull many device rows into the mirror: one gather per
        field for the whole set instead of ~20 single-row device reads
        per transition (the per-row form measured ~0.5ms each on the CPU
        backend — an election burst syncing 1,024 rows one at a time was
        the bulk of a 680ms round)."""
        self._harvest_inflight()
        if self._churn_pending:
            # recycled-but-undispatched rows keep their mirror state and
            # collapse the recycle to pre-block ordering (see _sync_row)
            for r in rows:
                if r in self._churn_pending:
                    self._drop_churn_records(r, drop_events=True)
                    self._dirty.add(r)
        todo = [
            r for r in rows if r not in self._dirty and r not in self._synced
        ]
        if not todo:
            return
        idx = np.asarray(todo, np.int32)
        pidx = self._pad_pow2_rows(idx)
        for k in self.mirror.arrays:
            self.mirror.arrays[k][pidx] = np.asarray(
                getattr(self.dev, k)[pidx]
            )
        self._synced.update(todo)

    def _upload_dirty(self) -> None:
        if not self._dirty:
            return
        self._harvest_inflight()
        rows = self._pad_pow2_rows(np.fromiter(self._dirty, dtype=np.int32))
        st = self.dev
        updates = {}
        for k, host in self.mirror.arrays.items():
            dev_arr = getattr(st, k)
            updates[k] = dev_arr.at[rows].set(jnp.asarray(host[rows]))
        self._dev = QuorumState(**updates)
        # keep the host committed twin coherent with the rows just written
        self._committed_cache[rows] = self.mirror.arrays["committed"][rows]
        self._dirty.clear()

    def _pad(self, events, width):
        cap = self.event_cap
        n = len(events)
        g = np.zeros((cap,), np.int32)
        p = np.zeros((cap,), np.int32)
        v = np.zeros((cap,), np.int32 if width == 3 else np.int8)
        valid = np.zeros((cap,), bool)
        if n:
            cols = np.array(events, dtype=np.int64).T
            g[:n] = cols[0]
            p[:n] = cols[1]
            v[:n] = cols[2]
            valid[:n] = True
        return g, p, v, valid

    def step(self, do_tick: bool = True) -> StepResult:
        """Run one fused device dispatch over all pending events.

        Oversized event backlogs run extra (tickless) dispatches first so
        the jit program never recompiles for a new batch size.

        When rounds were staged (``begin_round`` / ``stage_recycle``),
        the whole backlog — closed rounds plus the open buffers as the
        final round — runs as ONE fused multi-round dispatch instead
        (``step_rounds``; the result satisfies the StepResult interface).
        """
        if self._round_blocks or self._churn:
            return self.step_rounds(do_tick=do_tick)
        self._harvest_inflight()
        # stale-epoch votes (staged before a row transition) drop here;
        # surviving entries shed the epoch column for the dispatch path
        if self._votes:
            self._votes = [
                (r, s, v)
                for r, s, v, ep in self._votes
                if ep == self._row_epoch[r]
            ]
        self._upload_dirty()
        # host twin, not a device readback (a full extra round trip per
        # step on a network-attached chip); _upload_dirty and the egress
        # below keep it coherent.  An external `eng.dev = ...` assignment
        # marks it stale and forces a one-time device re-read here.
        self._refresh_committed_cache()
        prev_committed = self._committed_cache

        ack_g, ack_p, ack_v = self._gather_acks()
        # dense mode collapses ANY number of acks/votes into (G,P)
        # matrices — no cap, no chunk loop (votes are already first-wins
        # deduped per cell, so a dense matrix holds a whole round)
        if self.dense_ingest is True or (
            self.dense_ingest == "auto"
            and (
                ack_g.size >= self._dense_threshold
                or ack_g.size > self.event_cap
                or len(self._votes) > self.event_cap
            )
        ):
            out = self._dispatch_dense(ack_g, ack_p, ack_v, self._votes, do_tick)
        else:
            pos = 0
            while (ack_g.size - pos) > self.event_cap or len(self._votes) > self.event_cap:
                take = min(self.event_cap, ack_g.size - pos)
                self._dispatch(
                    (ack_g[pos : pos + take], ack_p[pos : pos + take],
                     ack_v[pos : pos + take]),
                    self._votes[: self.event_cap],
                    False,
                )
                pos += take
                del self._votes[: self.event_cap]
            out = self._dispatch(
                (ack_g[pos:], ack_p[pos:], ack_v[pos:]), self._votes, do_tick
            )
        self._votes.clear()
        self._voted_cells.clear()
        # the dispatch advanced every row on device; bulk-synced mirror
        # rows are stale now
        self._synced.clear()

        res = StepResult()
        # one batched device→host transfer for the whole egress set (a
        # network-attached chip pays the full round trip per readback)
        committed, won, lost, elect, hb, demote = jax.device_get(
            (
                out.committed,
                out.won,
                out.lost,
                out.flags.elect_due,
                out.flags.hb_due,
                out.flags.checkq_demote,
            )
        )
        # device_get arrays are read-only; the cache must stay writable
        # for _upload_dirty's row sync
        self._committed_cache = np.array(committed, dtype=np.int32)
        self._translate_egress(
            res, committed, prev_committed, self._row_cid, self._row_base,
            (("won", won), ("lost", lost), ("elect", elect),
             ("heartbeat", hb), ("demote", demote)),
        )
        return res

    def _gather_acks(self):
        """Tuple-staged + block-staged acks as three flat arrays, with
        stale-epoch events (staged before a row transition) filtered out
        in one vectorized pass; clears both buffers."""
        parts = []
        if self._acks:
            cols = np.array(self._acks, dtype=np.int64)
            rows = cols[:, 0].astype(np.int32)
            keep = cols[:, 3].astype(np.int32) == self._row_epoch[rows]
            parts.append(
                (rows[keep], cols[keep, 1].astype(np.int32),
                 cols[keep, 2].astype(np.int32))
            )
            self._acks = []
        if self._ack_blocks:
            for r, s, v, ep in self._ack_blocks:
                keep = ep == self._row_epoch[r]
                if keep.all():
                    parts.append((r, s, v))
                elif keep.any():
                    parts.append((r[keep], s[keep], v[keep]))
            self._ack_blocks = []
        if not parts:
            z = np.zeros((0,), np.int32)
            return z, z, z
        return (
            np.concatenate([p[0] for p in parts]),
            np.concatenate([p[1] for p in parts]),
            np.concatenate([p[2] for p in parts]),
        )

    def _pad_ack_arrays(self, g, p, v):
        cap = self.event_cap
        n = g.size
        og = np.zeros((cap,), np.int32)
        op = np.zeros((cap,), np.int32)
        ov = np.zeros((cap,), np.int32)
        valid = np.zeros((cap,), bool)
        if n:
            og[:n] = g
            op[:n] = p
            ov[:n] = v
            valid[:n] = True
        return og, op, ov, valid

    def _dispatch(self, acks, votes, do_tick: bool):
        if isinstance(acks, tuple):
            ag, ap, av, avalid = self._pad_ack_arrays(*acks)
        else:
            ag, ap, av, avalid = self._pad(acks, 3)
        if votes:
            vg, vp, vv, vvalid = self._pad(votes, 1)
        else:
            # vote-free round: the has_votes=False variant compiles the
            # vote scatter out entirely; the args are unused dummies
            vg = vp = np.zeros((1,), np.int32)
            vv = np.zeros((1,), np.int8)
            vvalid = np.zeros((1,), bool)
        out = quorum_step(
            self.dev,
            jnp.asarray(ag),
            jnp.asarray(ap),
            jnp.asarray(av),
            jnp.asarray(avalid),
            jnp.asarray(vg),
            jnp.asarray(vp),
            jnp.asarray(vv, dtype=jnp.int8),
            jnp.asarray(vvalid),
            do_tick=do_tick,
            # ticking rounds must track contact even on a device_ticks=False
            # engine (defensive: a stray do_tick=True call would otherwise
            # consume one-shot contact acks without the reset)
            track_contact=self.device_ticks or do_tick,
            has_votes=bool(votes),
        )
        self._dev = out.state
        return out

    def _dispatch_dense(self, ag, ap, av, votes, do_tick: bool):
        """Aggregate a round's events into (G,P) matrices and run the
        scatter-free dense kernel (kernels.quorum_step_dense_impl)."""
        from .kernels import quorum_step_dense

        g, p = self.n_groups, self.n_peers
        ack_max = np.zeros((g, p), np.int32)
        touched = np.zeros((g, p), bool)
        if ag.size:
            # max-aggregation == scatter-max: order-independent, exact.
            # Flat 1-D indexing keeps ufunc.at on numpy's contiguous fast
            # path (the 2-D tuple form is several× slower at the very
            # occupancies that select the dense path).
            cell = ag.astype(np.int64) * p + ap
            np.maximum.at(ack_max.reshape(-1), cell, av)
            touched.reshape(-1)[cell] = True
        if votes:
            vote_new = np.full((g, p), VOTE_NONE, np.int8)
            cols = np.array(votes, dtype=np.int64).T
            vote_new[cols[0], cols[1]] = cols[2].astype(np.int8)
        else:
            vote_new = np.zeros((1, 1), np.int8)  # unused dummy
        out = quorum_step_dense(
            self.dev,
            jnp.asarray(ack_max),
            jnp.asarray(touched),
            jnp.asarray(vote_new),
            do_tick=do_tick,
            track_contact=self.device_ticks or do_tick,
            has_votes=bool(votes),
        )
        self._dev = out.state
        return out

    # ------------------------------------------------------------------
    # introspection (tests / debugging)
    # ------------------------------------------------------------------

    def _read(self, field_name: str, row: int):
        """Field value at a row: pending mirror edits win over device —
        including a staged in-program recycle, whose mirror row is the
        post-recycle truth while the device still holds the old tenant."""
        self._harvest_inflight()
        if row in self._dirty or row in self._churn_pending:
            return self.mirror.arrays[field_name][row]
        return np.asarray(getattr(self.dev, field_name)[row])

    def committed_index(self, cluster_id: int) -> int:
        gi = self.groups[cluster_id]
        return int(gi.base) + int(self._read("committed", gi.row))

    def committed_snapshot(self, cids=None) -> Dict[int, int]:
        """Absolute committed indexes for ``cids`` (default: every
        registered group) from AT MOST one device→host transfer.
        ``committed_index`` costs a readback per call — prohibitive over
        a tunneled backend (~67ms RTT each); scale probes (bench rungs
        4/5) sample through this instead.  Right after ``step()`` the
        egress cache is fresh and the call is zero-transfer — it indexes
        the vector the device produced for that round's egress.  Pass
        ``cids`` when sampling: building the full dict for 100k groups
        costs ~100k boxed ints per call (vectorized twin:
        ``committed_view``)."""
        self._harvest_inflight()
        self._refresh_committed_cache()
        committed = self._committed_cache
        mirror = self.mirror.arrays["committed"]
        dirty = self._dirty
        pend = self._churn_pending
        items = (
            self.groups.items()
            if cids is None
            else ((cid, self.groups[cid]) for cid in cids)
        )
        return {
            cid: int(gi.base)
            + int(
                mirror[gi.row]
                if gi.row in dirty or gi.row in pend
                else committed[gi.row]
            )
            for cid, gi in items
        }

    def peer_match(self, cluster_id: int, node_id: int) -> int:
        gi = self.groups[cluster_id]
        return int(gi.base) + int(self._read("match", gi.row)[gi.slots[node_id]])
