"""Host driver for the batched quorum engine.

Replaces the reference's 16-worker per-group iteration
(``execengine.go:860-949``) with: host ingest (queues → compact event
batches) → ONE ``quorum_step`` device dispatch per round → host egress
(commit advances, election/heartbeat/step-down flags).  Rare transitions
(membership change, becoming leader/candidate, snapshot restore, index
rebase) mutate a numpy mirror row and are scattered onto the device arrays
before the next dispatch.

The group axis is shardable over a ``jax.sharding.Mesh`` (see
``sharding.py``): every kernel op is row-wise over groups, so XLA partitions
the whole step with zero collectives — groups are embarrassingly parallel,
exactly like the reference's ``clusterID % workers`` partitioning but over
chips instead of goroutines.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .kernels import quorum_step
from .state import (
    CANDIDATE,
    FOLLOWER,
    LEADER,
    OBSERVER,
    VOTE_GRANT,
    VOTE_NONE,
    VOTE_REJECT,
    WITNESS,
    HostMirror,
    QuorumState,
)

# Event batches are padded to fixed sizes so jit compiles once.
DEFAULT_EVENT_CAP = 4096

# Rebase a row when relative indexes pass this (well clear of int32 max).
REBASE_THRESHOLD = 1 << 30


@dataclass
class GroupInfo:
    cluster_id: int
    row: int
    slots: Dict[int, int]            # node_id -> peer slot
    base: int = 0                    # uint64 absolute index of rel 0
    node_ids: List[int] = field(default_factory=list)


class StepResult:
    """Egress of one dispatch, in absolute-index / cluster-id terms."""

    __slots__ = ("commit", "won", "lost", "elect", "heartbeat", "demote")

    def __init__(self):
        self.commit: Dict[int, int] = {}   # cluster_id -> new committed (abs)
        self.won: List[int] = []
        self.lost: List[int] = []
        self.elect: List[int] = []
        self.heartbeat: List[int] = []
        self.demote: List[int] = []


class BatchedQuorumEngine:
    """Device-resident quorum state for up to ``n_groups`` Raft groups.

    Usage::

        eng = BatchedQuorumEngine(n_groups=1024, n_peers=5)
        eng.add_group(cid, node_ids=[1,2,3], self_id=1, election_timeout=10)
        eng.set_leader(cid, term=1, term_start=1, last_index=1)
        eng.ack(cid, node_id=2, index=5)      # ReplicateResp ingest
        out = eng.step()                       # one device dispatch
        out.commit[cid]                        # -> advanced commit index
    """

    def __init__(
        self,
        n_groups: int,
        n_peers: int,
        event_cap: int = DEFAULT_EVENT_CAP,
        sharding=None,
        device_ticks: bool = True,
        dense_ingest: str | bool = "auto",
    ):
        self.n_groups = n_groups
        self.n_peers = n_peers
        self.event_cap = event_cap
        #: dense-ingestion policy: collapse a round's acks into a (G,P)
        #: max matrix and dispatch the scatter-free dense kernel (see
        #: kernels.quorum_step_dense_impl — ~7× at full occupancy on TPU).
        #: "auto" picks per dispatch by byte volume: dense uploads
        #: 6·G·P bytes vs ~13 per sparse event, so dense wins once the
        #: staged acks outnumber ~G·P/2.  True forces dense, False never.
        # identity checks: `1 in (True, ...)` would pass by int equality
        if not (
            dense_ingest is True
            or dense_ingest is False
            or dense_ingest == "auto"
        ):
            raise ValueError(
                f"dense_ingest must be True, False, or 'auto', got {dense_ingest!r}"
            )
        self.dense_ingest = dense_ingest
        self._dense_threshold = (n_groups * n_peers) // 2
        #: whether this engine EVER runs tick_step on device.  Contact
        #: events (leader_contact zero-acks) are one-shot, so a ticking
        #: engine must apply the election-clock reset on every round —
        #: including do_tick=False rounds that drain staged acks between
        #: host ticks.  Engines that never tick (host-driven clocks) skip
        #: the reset scatter entirely (it is dead work there).
        self.device_ticks = device_ticks
        self.mirror = HostMirror(n_groups, n_peers)
        self.sharding = sharding
        self._dev: QuorumState = self.mirror.to_device(sharding)
        self._cache_stale = False
        self.groups: Dict[int, GroupInfo] = {}
        self.rows: Dict[int, GroupInfo] = {}
        # vectorized row→(cluster_id, base) translation for egress: at
        # full occupancy tens of thousands of rows change per round, and
        # a per-row Python dict walk dominates the host loop
        self._row_cid = np.full((n_groups,), -1, np.int64)
        self._row_base = np.zeros((n_groups,), np.int64)
        #: host twin of dev.committed — device state changes only through
        #: _dispatch (whose egress refreshes this) and _upload_dirty
        #: (which syncs the dirty rows), so step() never needs a device
        #: readback just to learn the PREVIOUS watermarks (that readback
        #: was a full extra round trip per step on a network-attached TPU)
        self._committed_cache = np.zeros((n_groups,), np.int32)
        self._free = list(range(n_groups - 1, -1, -1))
        self._dirty: set[int] = set()
        # rows bulk-pulled from the device since the last dispatch
        # (sync_rows); invalidated whenever device state advances
        self._synced: set[int] = set()
        # per-row staging epoch: a state transition bumps it, and events
        # staged under an older epoch are filtered at dispatch.  This is
        # the O(1) replacement for scanning the whole event buffer on
        # every transition (measured 0.66ms per transition at 4k groups —
        # an election burst of 1,024 transitions cost a 680ms round).
        self._row_epoch = np.zeros((n_groups,), np.int32)
        # pending event buffers (grow unbounded host-side; chunked at
        # dispatch); entries carry the staging epoch as a 4th column
        self._acks: List[Tuple[int, int, int, int]] = []  # row, slot, rel, ep
        self._votes: List[Tuple[int, int, int, int]] = []  # row, slot, g, ep
        self._voted_cells: dict = {}  # (row, slot) -> staging epoch
        # vectorized bulk-ingest blocks (ack_block): (rows, slots, rels, eps)
        self._ack_blocks: List[Tuple[np.ndarray, ...]] = []

    @property
    def dev(self) -> QuorumState:
        return self._dev

    @dev.setter
    def dev(self, st: QuorumState) -> None:
        """External state assignment (hybrid direct-dispatch callers, e.g.
        the bench's staged multistep) — the host committed twin can no
        longer be trusted, so the next step() re-reads it from the device
        once instead of mis-reporting commit deltas."""
        self._dev = st
        self._cache_stale = True
        self._synced.clear()

    # ------------------------------------------------------------------
    # group lifecycle (rare path, host scalar)
    # ------------------------------------------------------------------

    def add_group(
        self,
        cluster_id: int,
        node_ids: List[int],
        self_id: int,
        election_timeout: int = 10,
        heartbeat_timeout: int = 1,
        rand_timeout: Optional[int] = None,
        check_quorum: bool = False,
        witnesses: Tuple[int, ...] = (),
        observers: Tuple[int, ...] = (),
    ) -> GroupInfo:
        if cluster_id in self.groups:
            raise ValueError(f"group {cluster_id} already registered")
        if not self._free:
            raise RuntimeError("quorum engine full")
        row = self._free.pop()
        all_ids = sorted(set(node_ids) | set(witnesses) | set(observers))
        if len(all_ids) > self.n_peers:
            raise ValueError("too many peers for tensor width")
        slots = {nid: i for i, nid in enumerate(all_ids)}
        gi = GroupInfo(cluster_id, row, slots, node_ids=all_ids)
        self.groups[cluster_id] = gi
        self.rows[row] = gi
        self._row_cid[row] = cluster_id
        self._row_base[row] = 0

        a = self.mirror.arrays
        a["live"][row] = True
        a["node_state"][row] = FOLLOWER
        a["term"][row] = 0
        a["committed"][row] = 0
        a["last_index"][row] = 0
        a["term_start"][row] = 0
        n_voting = len(set(node_ids) | set(witnesses))
        a["quorum"][row] = n_voting // 2 + 1
        a["self_slot"][row] = slots[self_id]
        a["election_tick"][row] = 0
        a["heartbeat_tick"][row] = 0
        a["election_timeout"][row] = election_timeout
        a["heartbeat_timeout"][row] = heartbeat_timeout
        a["rand_timeout"][row] = (
            rand_timeout if rand_timeout is not None else election_timeout * 2
        )
        is_voter = self_id in node_ids or self_id in witnesses
        a["electable"][row] = is_voter and self_id not in witnesses
        a["check_quorum_on"][row] = check_quorum
        a["match"][row, :] = 0
        a["next"][row, :] = 1
        a["voting"][row, :] = False
        a["present"][row, :] = False
        a["active"][row, :] = False
        a["votes"][row, :] = VOTE_NONE
        for nid, slot in slots.items():
            a["present"][row, slot] = True
            a["voting"][row, slot] = nid not in observers
        self._dirty.add(row)
        return gi

    def _purge_row_events(self, row: int) -> None:
        """Invalidate queued acks/votes for a row.  Called on every state
        transition (and removal): events staged before the transition
        belong to the old term and must never reach the new term's tally
        (the scalar twin drops mismatched-term responses in
        ``handle_vote_resp`` / ``handle_replicate_resp``).  O(1): the row's
        staging epoch is bumped and stale-epoch events are filtered in one
        vectorized pass at dispatch."""
        self._row_epoch[row] += 1

    def remove_group(self, cluster_id: int) -> None:
        gi = self.groups.pop(cluster_id)
        del self.rows[gi.row]
        self.mirror.arrays["live"][gi.row] = False
        self._dirty.add(gi.row)
        # purge queued events so a future tenant of this row never receives
        # the dead group's acks/votes
        self._purge_row_events(gi.row)
        self._row_cid[gi.row] = -1
        self._free.append(gi.row)

    # ------------------------------------------------------------------
    # rare-path row mutations (host scalar, mask-update tensors)
    # ------------------------------------------------------------------

    def _rel(self, gi: GroupInfo, index: int) -> int:
        rel = index - gi.base
        if rel < 0:
            raise ValueError(f"index {index} below base {gi.base}")
        if rel >= REBASE_THRESHOLD:
            raise ValueError("index needs rebase before ingest")
        return rel

    def set_leader(
        self, cluster_id: int, term: int, term_start: int, last_index: int
    ) -> None:
        """Promote to leader (twin: ``become_leader`` raft.go:1027-1045)."""
        gi = self.groups[cluster_id]
        a = self.mirror.arrays
        row = gi.row
        self._sync_row(row)
        a["node_state"][row] = LEADER
        a["term"][row] = term
        a["term_start"][row] = self._rel(gi, term_start)
        a["last_index"][row] = self._rel(gi, last_index)
        a["election_tick"][row] = 0
        a["heartbeat_tick"][row] = 0
        a["votes"][row, :] = VOTE_NONE
        # reset_remotes: fresh Remote structs — next = last+1 for all,
        # self match = last, activity cleared (raft.go:991-1010)
        a["match"][row, :] = 0
        a["next"][row, :] = self._rel(gi, last_index) + 1
        a["match"][row, a["self_slot"][row]] = self._rel(gi, last_index)
        a["active"][row, :] = False
        self._purge_row_events(row)
        self._dirty.add(row)

    def set_candidate(self, cluster_id: int, term: int) -> None:
        """Start campaigning (twin: ``become_candidate``); the self-vote is
        ingested like any other vote event."""
        gi = self.groups[cluster_id]
        a = self.mirror.arrays
        row = gi.row
        self._sync_row(row)
        a["node_state"][row] = CANDIDATE
        a["term"][row] = term
        a["votes"][row, :] = VOTE_NONE
        a["election_tick"][row] = 0
        self._purge_row_events(row)
        self._dirty.add(row)

    def set_follower(self, cluster_id: int, term: int) -> None:
        gi = self.groups[cluster_id]
        a = self.mirror.arrays
        row = gi.row
        self._sync_row(row)
        a["node_state"][row] = FOLLOWER
        a["term"][row] = term
        a["votes"][row, :] = VOTE_NONE
        a["election_tick"][row] = 0
        self._purge_row_events(row)
        self._dirty.add(row)

    def set_randomized_timeout(self, cluster_id: int, timeout: int) -> None:
        """Host-seeded randomized election timeout (determinism: the PRNG
        stays host-side and seeded, see raft.py design notes)."""
        gi = self.groups[cluster_id]
        self._sync_row(gi.row)
        self.mirror.arrays["rand_timeout"][gi.row] = timeout
        self._dirty.add(gi.row)

    def restore_progress(
        self, cluster_id: int, committed: int, last_index: int
    ) -> None:
        """Snapshot-restore / log-truncation repair of the watermarks."""
        gi = self.groups[cluster_id]
        a = self.mirror.arrays
        row = gi.row
        self._sync_row(row)
        a["committed"][row] = self._rel(gi, committed)
        a["last_index"][row] = self._rel(gi, last_index)
        self._dirty.add(row)

    def rebase(self, cluster_id: int) -> None:
        """Shift a row's base up to its committed watermark so relative
        int32 indexes stay far from overflow (state.py design note)."""
        gi = self.groups[cluster_id]
        a = self.mirror.arrays
        row = gi.row
        self._sync_row(row)
        shift = int(a["committed"][row])
        if shift <= 0:
            return
        gi.base += shift
        self._row_base[row] = gi.base
        for f in ("committed", "last_index", "term_start"):
            a[f][row] = max(0, int(a[f][row]) - shift)
        a["match"][row, :] = np.maximum(a["match"][row, :] - shift, 0)
        a["next"][row, :] = np.maximum(a["next"][row, :] - shift, 1)
        self._dirty.add(row)

    # ------------------------------------------------------------------
    # dense-path event ingest
    # ------------------------------------------------------------------

    def ack(self, cluster_id: int, node_id: int, index: int) -> None:
        """ReplicateResp success / local append (self ack).

        Acks below the rebased floor are legal raft traffic (delayed
        retransmits); they clamp to rel 0, a scatter-max no-op that still
        marks the peer active — same outcome as ``remote.try_update`` on a
        stale index.
        """
        gi = self.groups[cluster_id]
        rel = max(0, index - gi.base)
        if rel >= REBASE_THRESHOLD:
            raise ValueError(f"index {index} needs rebase (base {gi.base})")
        self._acks.append(
            (gi.row, gi.slots[node_id], rel, int(self._row_epoch[gi.row]))
        )

    def ack_block(self, rows, slots, rels) -> None:
        """Vectorized bulk ack ingest (numpy arrays in row/slot space).

        The per-event ``ack()`` path costs a Python call per event; a
        native or vectorized control plane staging thousands of acks per
        round uses this instead — arrays append as one block and are
        concatenated at dispatch.  Caller contract: rows are live group
        rows, slots valid for their rows, ``rels`` already rebased
        (0 <= rel < REBASE_THRESHOLD); the bounds are validated
        vectorized, membership is the caller's responsibility.
        """
        # validate on the ORIGINAL dtype (an int64 >= 2^32 must hit the
        # rebase guard, not wrap into range), then narrow
        rows = np.asarray(rows)
        slots = np.asarray(slots)
        rels = np.asarray(rels)
        if not (rows.shape == slots.shape == rels.shape):
            raise ValueError("ack_block arrays must share a shape")
        if rels.size and rels.max() >= REBASE_THRESHOLD:
            raise ValueError("ack_block rel out of range (rebase needed)")
        if rows.size and (rows.min() < 0 or rows.max() >= self.n_groups):
            raise ValueError("ack_block row out of range")
        if slots.size and (slots.min() < 0 or slots.max() >= self.n_peers):
            raise ValueError("ack_block slot out of range")
        # below-base acks are legal raft traffic (delayed retransmits) and
        # clamp to rel 0, matching ack()'s scalar semantics
        rels = np.maximum(rels, 0)
        rows32 = rows.astype(np.int32)
        self._ack_blocks.append(
            (rows32, slots.astype(np.int32), rels.astype(np.int32),
             self._row_epoch[rows32].copy())
        )

    def vote(self, cluster_id: int, node_id: int, granted: bool) -> None:
        """First vote per (group, peer) wins (twin: ``handle_vote_resp``).

        The kernel's first-wins guard reads pre-batch state, so within-batch
        duplicates must be deduped here — keep only the first event per cell.
        """
        gi = self.groups[cluster_id]
        cell = (gi.row, gi.slots[node_id])
        ep = int(self._row_epoch[gi.row])
        if self._voted_cells.get(cell) == ep:
            return
        self._voted_cells[cell] = ep
        self._votes.append(
            (cell[0], cell[1], VOTE_GRANT if granted else VOTE_REJECT, ep)
        )

    def heartbeat_resp(self, cluster_id: int, node_id: int) -> None:
        """Heartbeat response marks the peer active; an ack at index 0 is a
        no-op for match (scatter-max) but sets the activity bit."""
        gi = self.groups[cluster_id]
        self._acks.append(
            (gi.row, gi.slots[node_id], 0, int(self._row_epoch[gi.row]))
        )

    def leader_contact(self, cluster_id: int) -> None:
        """A follower heard from its leader: reset the row's election clock
        (twin: ``leader_is_available`` — the kernel resets election_tick on
        any event touching a non-leader row)."""
        gi = self.groups[cluster_id]
        self._acks.append(
            (gi.row, int(self.mirror.arrays["self_slot"][gi.row]), 0,
             int(self._row_epoch[gi.row]))
        )

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------

    def _sync_row(self, row: int) -> None:
        """Pull one device row into the mirror before mutating it (the
        dense path may have advanced it since the last upload)."""
        if row in self._dirty or row in self._synced:
            return
        for k in self.mirror.arrays:
            self.mirror.arrays[k][row] = np.asarray(
                getattr(self.dev, k)[row]
            )
        self._synced.add(row)

    @staticmethod
    def _pad_pow2_rows(idx: np.ndarray) -> np.ndarray:
        """Pad a row-index vector to the next power-of-two length by
        repeating its first element.  Gather/scatter with a fresh index
        SHAPE recompiles the eager op (measured: an election burst's
        varying transition counts cost ~620ms/round in
        backend_compile_and_load); bucketing shapes to powers of two
        bounds the compile cache at ~log2(G) entries.  Duplicate indexes
        are harmless: gathers repeat a value, scatters rewrite the same
        value."""
        n = idx.size
        cap = 1 << max(0, n - 1).bit_length()
        if cap == n:
            return idx
        return np.concatenate([idx, np.full(cap - n, idx[0], idx.dtype)])

    def sync_rows(self, rows) -> None:
        """Bulk-pull many device rows into the mirror: one gather per
        field for the whole set instead of ~20 single-row device reads
        per transition (the per-row form measured ~0.5ms each on the CPU
        backend — an election burst syncing 1,024 rows one at a time was
        the bulk of a 680ms round)."""
        todo = [
            r for r in rows if r not in self._dirty and r not in self._synced
        ]
        if not todo:
            return
        idx = np.asarray(todo, np.int32)
        pidx = self._pad_pow2_rows(idx)
        for k in self.mirror.arrays:
            self.mirror.arrays[k][pidx] = np.asarray(
                getattr(self.dev, k)[pidx]
            )
        self._synced.update(todo)

    def _upload_dirty(self) -> None:
        if not self._dirty:
            return
        rows = self._pad_pow2_rows(np.fromiter(self._dirty, dtype=np.int32))
        st = self.dev
        updates = {}
        for k, host in self.mirror.arrays.items():
            dev_arr = getattr(st, k)
            updates[k] = dev_arr.at[rows].set(jnp.asarray(host[rows]))
        self._dev = QuorumState(**updates)
        # keep the host committed twin coherent with the rows just written
        self._committed_cache[rows] = self.mirror.arrays["committed"][rows]
        self._dirty.clear()

    def _pad(self, events, width):
        cap = self.event_cap
        n = len(events)
        g = np.zeros((cap,), np.int32)
        p = np.zeros((cap,), np.int32)
        v = np.zeros((cap,), np.int32 if width == 3 else np.int8)
        valid = np.zeros((cap,), bool)
        if n:
            cols = np.array(events, dtype=np.int64).T
            g[:n] = cols[0]
            p[:n] = cols[1]
            v[:n] = cols[2]
            valid[:n] = True
        return g, p, v, valid

    def step(self, do_tick: bool = True) -> StepResult:
        """Run one fused device dispatch over all pending events.

        Oversized event backlogs run extra (tickless) dispatches first so
        the jit program never recompiles for a new batch size.
        """
        # stale-epoch votes (staged before a row transition) drop here;
        # surviving entries shed the epoch column for the dispatch path
        if self._votes:
            self._votes = [
                (r, s, v)
                for r, s, v, ep in self._votes
                if ep == self._row_epoch[r]
            ]
        self._upload_dirty()
        # host twin, not a device readback (a full extra round trip per
        # step on a network-attached chip); _upload_dirty and the egress
        # below keep it coherent.  An external `eng.dev = ...` assignment
        # marks it stale and forces a one-time device re-read here.
        if self._cache_stale:
            self._committed_cache = np.array(
                np.asarray(self._dev.committed), dtype=np.int32
            )
            self._cache_stale = False
        prev_committed = self._committed_cache

        ack_g, ack_p, ack_v = self._gather_acks()
        # dense mode collapses ANY number of acks/votes into (G,P)
        # matrices — no cap, no chunk loop (votes are already first-wins
        # deduped per cell, so a dense matrix holds a whole round)
        if self.dense_ingest is True or (
            self.dense_ingest == "auto"
            and (
                ack_g.size >= self._dense_threshold
                or ack_g.size > self.event_cap
                or len(self._votes) > self.event_cap
            )
        ):
            out = self._dispatch_dense(ack_g, ack_p, ack_v, self._votes, do_tick)
        else:
            pos = 0
            while (ack_g.size - pos) > self.event_cap or len(self._votes) > self.event_cap:
                take = min(self.event_cap, ack_g.size - pos)
                self._dispatch(
                    (ack_g[pos : pos + take], ack_p[pos : pos + take],
                     ack_v[pos : pos + take]),
                    self._votes[: self.event_cap],
                    False,
                )
                pos += take
                del self._votes[: self.event_cap]
            out = self._dispatch(
                (ack_g[pos:], ack_p[pos:], ack_v[pos:]), self._votes, do_tick
            )
        self._votes.clear()
        self._voted_cells.clear()
        # the dispatch advanced every row on device; bulk-synced mirror
        # rows are stale now
        self._synced.clear()

        res = StepResult()
        # one batched device→host transfer for the whole egress set (a
        # network-attached chip pays the full round trip per readback)
        committed, won, lost, elect, hb, demote = jax.device_get(
            (
                out.committed,
                out.won,
                out.lost,
                out.flags.elect_due,
                out.flags.hb_due,
                out.flags.checkq_demote,
            )
        )
        changed = np.nonzero(committed != prev_committed)[0]
        # device_get arrays are read-only; the cache must stay writable
        # for _upload_dirty's row sync
        self._committed_cache = np.array(committed, dtype=np.int32)
        if changed.size:
            # vectorized row→(cid, abs index) translation: dead rows carry
            # cid -1 and are dropped (their committed can flip when a row
            # is reused mid-buffer)
            cids = self._row_cid[changed]
            live_mask = cids >= 0
            abs_commit = self._row_base[changed] + committed[changed]
            res.commit = dict(
                zip(cids[live_mask].tolist(), abs_commit[live_mask].tolist())
            )
        for name, arr in (
            ("won", won),
            ("lost", lost),
            ("elect", elect),
            ("heartbeat", hb),
            ("demote", demote),
        ):
            idx = np.nonzero(np.asarray(arr))[0]
            if idx.size:
                cids = self._row_cid[idx]
                getattr(res, name).extend(cids[cids >= 0].tolist())
        return res

    def _gather_acks(self):
        """Tuple-staged + block-staged acks as three flat arrays, with
        stale-epoch events (staged before a row transition) filtered out
        in one vectorized pass; clears both buffers."""
        parts = []
        if self._acks:
            cols = np.array(self._acks, dtype=np.int64)
            rows = cols[:, 0].astype(np.int32)
            keep = cols[:, 3].astype(np.int32) == self._row_epoch[rows]
            parts.append(
                (rows[keep], cols[keep, 1].astype(np.int32),
                 cols[keep, 2].astype(np.int32))
            )
            self._acks = []
        if self._ack_blocks:
            for r, s, v, ep in self._ack_blocks:
                keep = ep == self._row_epoch[r]
                if keep.all():
                    parts.append((r, s, v))
                elif keep.any():
                    parts.append((r[keep], s[keep], v[keep]))
            self._ack_blocks = []
        if not parts:
            z = np.zeros((0,), np.int32)
            return z, z, z
        return (
            np.concatenate([p[0] for p in parts]),
            np.concatenate([p[1] for p in parts]),
            np.concatenate([p[2] for p in parts]),
        )

    def _pad_ack_arrays(self, g, p, v):
        cap = self.event_cap
        n = g.size
        og = np.zeros((cap,), np.int32)
        op = np.zeros((cap,), np.int32)
        ov = np.zeros((cap,), np.int32)
        valid = np.zeros((cap,), bool)
        if n:
            og[:n] = g
            op[:n] = p
            ov[:n] = v
            valid[:n] = True
        return og, op, ov, valid

    def _dispatch(self, acks, votes, do_tick: bool):
        if isinstance(acks, tuple):
            ag, ap, av, avalid = self._pad_ack_arrays(*acks)
        else:
            ag, ap, av, avalid = self._pad(acks, 3)
        if votes:
            vg, vp, vv, vvalid = self._pad(votes, 1)
        else:
            # vote-free round: the has_votes=False variant compiles the
            # vote scatter out entirely; the args are unused dummies
            vg = vp = np.zeros((1,), np.int32)
            vv = np.zeros((1,), np.int8)
            vvalid = np.zeros((1,), bool)
        out = quorum_step(
            self.dev,
            jnp.asarray(ag),
            jnp.asarray(ap),
            jnp.asarray(av),
            jnp.asarray(avalid),
            jnp.asarray(vg),
            jnp.asarray(vp),
            jnp.asarray(vv, dtype=jnp.int8),
            jnp.asarray(vvalid),
            do_tick=do_tick,
            # ticking rounds must track contact even on a device_ticks=False
            # engine (defensive: a stray do_tick=True call would otherwise
            # consume one-shot contact acks without the reset)
            track_contact=self.device_ticks or do_tick,
            has_votes=bool(votes),
        )
        self._dev = out.state
        return out

    def _dispatch_dense(self, ag, ap, av, votes, do_tick: bool):
        """Aggregate a round's events into (G,P) matrices and run the
        scatter-free dense kernel (kernels.quorum_step_dense_impl)."""
        from .kernels import quorum_step_dense

        g, p = self.n_groups, self.n_peers
        ack_max = np.zeros((g, p), np.int32)
        touched = np.zeros((g, p), bool)
        if ag.size:
            # max-aggregation == scatter-max: order-independent, exact.
            # Flat 1-D indexing keeps ufunc.at on numpy's contiguous fast
            # path (the 2-D tuple form is several× slower at the very
            # occupancies that select the dense path).
            cell = ag.astype(np.int64) * p + ap
            np.maximum.at(ack_max.reshape(-1), cell, av)
            touched.reshape(-1)[cell] = True
        if votes:
            vote_new = np.full((g, p), VOTE_NONE, np.int8)
            cols = np.array(votes, dtype=np.int64).T
            vote_new[cols[0], cols[1]] = cols[2].astype(np.int8)
        else:
            vote_new = np.zeros((1, 1), np.int8)  # unused dummy
        out = quorum_step_dense(
            self.dev,
            jnp.asarray(ack_max),
            jnp.asarray(touched),
            jnp.asarray(vote_new),
            do_tick=do_tick,
            track_contact=self.device_ticks or do_tick,
            has_votes=bool(votes),
        )
        self._dev = out.state
        return out

    # ------------------------------------------------------------------
    # introspection (tests / debugging)
    # ------------------------------------------------------------------

    def _read(self, field_name: str, row: int):
        """Field value at a row: pending mirror edits win over device."""
        if row in self._dirty:
            return self.mirror.arrays[field_name][row]
        return np.asarray(getattr(self.dev, field_name)[row])

    def committed_index(self, cluster_id: int) -> int:
        gi = self.groups[cluster_id]
        return int(gi.base) + int(self._read("committed", gi.row))

    def committed_snapshot(self, cids=None) -> Dict[int, int]:
        """Absolute committed indexes for ``cids`` (default: every
        registered group) from AT MOST one device→host transfer.
        ``committed_index`` costs a readback per call — prohibitive over
        a tunneled backend (~67ms RTT each); scale probes (bench rungs
        4/5) sample through this instead.  Right after ``step()`` the
        egress cache is fresh and the call is zero-transfer — it indexes
        the vector the device produced for that round's egress.  Pass
        ``cids`` when sampling: building the full dict for 100k groups
        costs ~100k boxed ints per call."""
        if self._cache_stale:
            self._committed_cache = np.array(
                np.asarray(self.dev.committed), dtype=np.int32
            )
            self._cache_stale = False
        committed = self._committed_cache
        mirror = self.mirror.arrays["committed"]
        dirty = self._dirty
        items = (
            self.groups.items()
            if cids is None
            else ((cid, self.groups[cid]) for cid in cids)
        )
        return {
            cid: int(gi.base)
            + int(mirror[gi.row] if gi.row in dirty else committed[gi.row])
            for cid, gi in items
        }

    def peer_match(self, cluster_id: int, node_id: int) -> int:
        gi = self.groups[cluster_id]
        return int(gi.base) + int(self._read("match", gi.row)[gi.slots[node_id]])
