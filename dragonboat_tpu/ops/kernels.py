"""Pure jit kernels for the batched quorum engine.

Each kernel is the tensorized twin of a scalar hot loop in
:mod:`dragonboat_tpu.raft.raft`; the differential tests in
``tests/test_ops_quorum.py`` (and the live-path suites
``tests/test_tpuquorum.py``, ``tests/test_raft_etcd_tpu.py``,
``tests/test_device_ticks.py``) assert bit-identical outputs against it.

Scalar twin map:

===================  ==================================================
kernel               scalar twin (reference location)
===================  ==================================================
``commit_quorum``    ``Raft.try_commit`` (``raft.go:861-909``)
``vote_tally``       ``Raft.handle_vote_resp`` (``raft.go:1062-1080``)
``check_quorum``     ``Raft.leader_has_quorum`` (``raft.go:380-390``)
``tick_step``        ``Raft.tick`` (``raft.go:553-623``)
``quorum_step``      one whole ``processSteps`` round (``execengine.go:923``)
===================  ==================================================

All shapes are static: ``G`` groups × ``P`` peer slots, event batches
padded to a fixed ``K`` with a validity mask (invalid rows scatter out of
bounds with ``mode='drop'``).  Everything fuses into one XLA program; on
TPU the sort/scatter work sits in VMEM with no host round-trips.

Every kernel is also PLACEMENT-AGNOSTIC by construction: no collective
primitive appears anywhere in this module, because no per-group update
ever reads another group's row — the group axis is embarrassingly
parallel.  That property is what the mesh dispatch plane
(``ops/mesh.py``, ISSUE 16) builds on: instead of one GSPMD-partitioned
program whose compiled collectives forced a global dispatch mutex, each
mesh shard launches these SAME kernels as ordinary single-device
programs over its group partition, from its own stream, with no
cross-shard rendezvous to deadlock and therefore no lock to serialize
behind.  A kernel change here is automatically a change on every shard;
keep the no-collectives invariant or the mesh plane's concurrency story
breaks.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .state import (
    CANDIDATE,
    INDEX_MIN,
    LEADER,
    QuorumState,
    VOTE_NONE,
    I32,
)


# Optimal compare-exchange networks (Knuth TAOCP v3 §5.3.4) per width;
# each pair (i, j) with i < j exchanges so the LARGER value lands at i —
# after the full network the columns are sorted descending.  A comparator
# network sorts under either orientation as long as every comparator uses
# the same one.
_SORT_NETWORKS = {
    1: [],
    2: [(0, 1)],
    3: [(0, 1), (1, 2), (0, 1)],
    4: [(0, 1), (2, 3), (0, 2), (1, 3), (1, 2)],
    5: [(0, 1), (3, 4), (2, 4), (2, 3), (1, 4), (0, 3), (0, 2), (1, 3),
        (1, 2)],
    6: [(1, 2), (4, 5), (0, 2), (3, 5), (0, 1), (3, 4), (2, 5), (0, 3),
        (1, 4), (2, 4), (1, 3), (2, 3)],
    7: [(1, 2), (3, 4), (5, 6), (0, 2), (3, 5), (4, 6), (0, 1), (4, 5),
        (2, 6), (0, 4), (1, 5), (0, 3), (2, 5), (1, 3), (2, 4), (2, 3)],
    8: [(0, 1), (2, 3), (4, 5), (6, 7), (0, 2), (1, 3), (4, 6), (5, 7),
        (1, 2), (5, 6), (0, 4), (3, 7), (1, 5), (2, 6), (1, 4), (3, 6),
        (2, 4), (3, 5), (3, 4)],
}


def _kth_largest(values: jax.Array, mask: jax.Array, k: jax.Array) -> jax.Array:
    """Row-wise k-th largest of masked values; k is 1-based, (G,).

    For the practical peer widths (P ≤ 8) this unrolls an optimal
    compare-exchange sorting network over the P columns — pure
    elementwise ``maximum``/``minimum`` on (G,) vectors that the VPU
    streams, with no sort HLO and no (G,P,P) intermediate.  At the
    131k-group × P=3 headline shape this measured ~3× cheaper than the
    previous (G,P,P) rank-select, which itself was ~5× cheaper than
    ``jnp.sort``'s padded bitonic lowering.  Wider P falls back to the
    rank form: each element's descending rank is the count of elements
    that beat it (value, then slot index as the tie-break); ranks are a
    permutation of 0..P-1, so exactly one element has rank k-1 and a
    masked sum selects it.  Both forms return the identical *value*
    (ties share the value); only selection strategy differs.

    Precondition: ``1 <= k <= P`` per row (the only caller,
    ``commit_quorum``, passes ``quorum = voters//2 + 1`` which the
    engine keeps in range — ``engine.py`` add_group/membership paths).
    Out-of-range k is unspecified and the two forms disagree on it.
    """
    masked = jnp.where(mask, values, INDEX_MIN)
    p = masked.shape[1]
    ksel = k - 1
    if p in _SORT_NETWORKS:
        cols = [masked[:, i] for i in range(p)]
        for i, j in _SORT_NETWORKS[p]:
            hi = jnp.maximum(cols[i], cols[j])
            cols[j] = jnp.minimum(cols[i], cols[j])
            cols[i] = hi
        out = cols[0]
        for i in range(1, p):  # cols sorted descending; pick column k-1
            out = jnp.where(ksel == i, cols[i], out)
        return out
    v_i = masked[:, :, None]  # candidate
    v_j = masked[:, None, :]  # competitor
    slot = jnp.arange(p, dtype=I32)
    beats = (v_j > v_i) | (
        (v_j == v_i) & (slot[None, None, :] < slot[None, :, None])
    )
    rank = jnp.sum(beats, axis=2).astype(I32)  # 0-based, descending, unique
    sel = rank == ksel[:, None]
    return jnp.sum(jnp.where(sel, masked, 0), axis=1)


def _self_column(match: jax.Array, self_slot: jax.Array) -> jax.Array:
    """``match[g, self_slot[g]]`` for every group, as an elementwise
    one-hot masked sum.  The obvious ``take_along_axis`` compiles to a
    TPU gather that measured 1.42 ms/round at the 131k-group headline
    shape — 5× the cost of everything else in the round combined; this
    form is free (fuses into the surrounding elementwise ops).  Rows
    whose ``self_slot`` is out of range (dead rows) contribute 0, which
    the ``max`` against ``last_index`` ignores — same net effect as the
    gather's clamp.  match values are rel indexes ≥ 0, so 0 is the
    identity."""
    p = match.shape[1]
    sel = jax.nn.one_hot(self_slot, p, dtype=jnp.bool_)
    return jnp.sum(jnp.where(sel, match, 0), axis=1)


def commit_quorum(
    match: jax.Array, voting: jax.Array, quorum: jax.Array
) -> jax.Array:
    """Quorum match index per group (scalar twin: ``Raft.try_commit``).

    The reference sorts each group's match array and picks
    ``matched[n - quorum]`` (``raft.go:888-909``); that is exactly the
    quorum-th largest, computed here for all groups at once.
    """
    return _kth_largest(match, voting, quorum)


def vote_tally(
    votes: jax.Array, voting: jax.Array, quorum: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """(granted, rejected) counts per group (twin: ``handle_vote_resp``)."""
    granted = jnp.sum((votes == 1) & voting, axis=1).astype(I32)
    rejected = jnp.sum((votes == 0) & voting, axis=1).astype(I32)
    return granted, rejected


def check_quorum(
    active: jax.Array,
    voting: jax.Array,
    self_slot: jax.Array,
    quorum: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """(has_quorum, cleared_active) per group (twin: ``leader_has_quorum``).

    Counts self plus recently-active voters, clearing activity flags as the
    reference does (``raft.go:380-390``).
    """
    p = active.shape[1]
    self_onehot = jax.nn.one_hot(self_slot, p, dtype=jnp.bool_)
    count = jnp.sum((active | self_onehot) & voting, axis=1).astype(I32)
    cleared = active & ~voting  # voting members' activity is consumed
    return count >= quorum, cleared


class TickFlags(NamedTuple):
    elect_due: jax.Array    # (G,) bool — non-leader election timeout fired
    hb_due: jax.Array       # (G,) bool — leader heartbeat due
    checkq_demote: jax.Array  # (G,) bool — CheckQuorum failed, leader must step down


# Device telemetry fold (ISSUE 20).  Every aggregate shape is STATIC, so
# the telemetry egress per dispatch is fixed-size no matter how many
# groups the shard holds — the property that lets the health plane watch
# a million groups at O(shards) host cost instead of an O(G) Python walk.
TELEM_LAG_BUCKETS = 16
TELEM_STATES = 5   # FOLLOWER..WITNESS (state.py raft states)
TELEM_TOPK = 8


class TelemAggregate(NamedTuple):
    """Fixed-size per-shard health aggregate (:func:`telem_fold`).

    ``lag`` throughout is the DEVICE-visible commit lag
    ``last_index - committed`` — entries appended but not yet quorum-
    committed.  The host-side committed−applied apply lag remains a
    per-group host signal: the aggregate sampler reads it only for the
    drill-down set this aggregate names (top-K worst rows plus
    non-device groups), which is the point of the fold.
    """

    lag_hist: jax.Array      # (B,) i32 — live groups per log2 lag bucket
    state_counts: jax.Array  # (TELEM_STATES,) i32 — live groups per raft state
    stalled: jax.Array       # () i32 — live, lag > 0, committed flat since last fold
    read_slots: jax.Array    # () i32 — occupied ReadIndex slots (read_count > 0)
    kv_ents: jax.Array       # () i32 — occupied devsm entry slots (index >= 0)
    topk_row: jax.Array      # (K,) i32 — worst rows by lag; -1 = fewer than K live
    topk_lag: jax.Array      # (K,) i32 — their lag values


def telem_fold(
    st: QuorumState, k: int = TELEM_TOPK,
    count_reads: bool = True, count_kv: bool = True,
) -> tuple[QuorumState, TelemAggregate]:
    """Reduce per-group health signals into one :class:`TelemAggregate`.

    Pure masked reductions over the group axis — no collectives (the
    module invariant), no new input tensors, so the fold rides any
    dispatch for a handful of VPU passes over state already in HBM.
    Also advances ``telem_prev_committed`` to this fold's commit
    watermark: the stalled predicate compares against the PREVIOUS
    fold, giving "commitIndex flat across a whole dispatch window with
    pending work" rather than a noisy within-round flatline.
    """
    live = st.live
    lag = jnp.where(live, jnp.maximum(st.last_index - st.committed, 0), 0)
    # Exact integer log2 bucketing: bucket = #{i < B-1 : lag >= 2^i}
    # (0→0, 1→1, 2..3→2, …, ≥2^(B-2)→B-1).  Float log2 would disagree
    # with the integer host oracle near power-of-two boundaries
    # (float32 rounds 2^25 − 1 up across the bucket edge).
    # searchsorted(side="right") counts thresholds <= lag — identical to
    # summing (lag >= 2^i) but a binary search per element instead of a
    # (G, B-1) compare matrix.
    thresholds = jnp.asarray(
        [1 << i for i in range(TELEM_LAG_BUCKETS - 1)], I32
    )
    bucket = jnp.searchsorted(thresholds, lag, side="right").astype(I32)
    # Counting via (G, buckets) compare-matrix column sums — NOT
    # scatter-add and NOT one-hot matmul.  Scatter lowers to a
    # serialized per-update loop on the cpu backend (~0.1 ms per
    # scatter at G=1024, dominating the fold) and one-hot matmuls
    # materialize float intermediates; a bool compare plus integer
    # column reduction is a handful of fully-vectorized passes over
    # G×16 / G×5 elements.
    bucket_ids = jnp.arange(TELEM_LAG_BUCKETS, dtype=I32)
    lag_hist = jnp.sum(
        (bucket[:, None] == bucket_ids[None, :]) & live[:, None],
        axis=0, dtype=I32,
    )
    state_ids = jnp.arange(TELEM_STATES, dtype=I32)
    state_counts = jnp.sum(
        (st.node_state.astype(I32)[:, None] == state_ids[None, :])
        & live[:, None],
        axis=0, dtype=I32,
    )
    stalled = jnp.sum(
        live & (st.committed == st.telem_prev_committed) & (lag > 0)
    ).astype(I32)
    # Slot-occupancy reductions gate on the caller's plane latches: when
    # a plane has never been used its arrays are provably all-idle, so
    # the count is the constant 0 and the (G, S)/(G, E) sweeps vanish
    # from the program entirely.
    zero = jnp.asarray(0, I32)
    read_slots = (
        jnp.sum(st.read_count > 0).astype(I32) if count_reads else zero
    )
    kv_ents = (
        jnp.sum(st.kv_ent_index >= 0).astype(I32) if count_kv else zero
    )
    # Top-K worst rows by lag; dead rows mask to -1, sorting below any
    # live lag (≥ 0).  K sequential argmax passes, not lax.top_k: the
    # full sort top_k lowers to costs ~0.2ms at G=1024 on the cpu
    # backend (most of the fold's dispatch overhead), while K masked
    # argmax sweeps are linear in G.  argmax returns the FIRST maximal
    # index, so ties break toward the LOWER row — the host oracle sorts
    # by (-lag, row) to match bit-for-bit.
    masked = jnp.where(live, lag, -1).astype(I32)
    # an engine smaller than K egresses its whole group axis
    k = min(int(k), masked.shape[0])
    rows, lags = [], []
    for _ in range(k):  # unrolled — k is static; no while-loop overhead
        i = jnp.argmax(masked).astype(I32)
        rows.append(i)
        lags.append(masked[i])
        masked = masked.at[i].set(jnp.iinfo(jnp.int32).min)
    topk_row = jnp.stack(rows)
    topk_lag = jnp.stack(lags)
    topk_row = jnp.where(topk_lag >= 0, topk_row, -1).astype(I32)
    st = st._replace(telem_prev_committed=st.committed)
    return st, TelemAggregate(
        lag_hist, state_counts, stalled, read_slots, kv_ents,
        topk_row, topk_lag,
    )


class StepOutputs(NamedTuple):
    state: QuorumState
    committed: jax.Array    # (G,) i32 rel — post-step commit watermark
    won: jax.Array          # (G,) bool — candidate reached vote quorum
    lost: jax.Array         # (G,) bool — candidate rejected by quorum
    flags: TickFlags
    # device read plane egress (None unless has_reads): per pending-read
    # slot, the client reads confirmed this dispatch and the rel index
    # each batch was released at.  Multi-round dispatches ACCUMULATE
    # (count-sum / index-max) across their scanned rounds — safe because
    # a ReadIndex release index may only be rewritten UP (serving at a
    # higher watermark is strictly more conservative; the scalar twin's
    # prefix release does the same rewrite, readindex.py:70-74).
    read_done_count: jax.Array | None = None  # (G,S) i32
    read_done_index: jax.Array | None = None  # (G,S) i32 rel, -1 = none
    # devsm egress (None unless has_kv): per staged KV read slot, the
    # captured value and the commit watermark it was captured at (-1 =
    # slot not staged this dispatch).  The engine never restages a read
    # slot within one block, so a multi-round scan's per-round captures
    # merge by simple overwrite-where-staged.  ``kv_applied`` counts ops
    # the apply fold consumed (per group; summed across a block).
    kv_read_val: jax.Array | None = None      # (G,R) i32
    kv_read_index: jax.Array | None = None    # (G,R) i32 rel, -1 = none
    kv_applied: jax.Array | None = None       # (G,) i32
    # device telemetry egress (None unless has_telem, ISSUE 20): the
    # fixed-size aggregate telem_fold computed over the POST-step state.
    # A multi-round dispatch folds ONCE on the final scanned state — the
    # aggregate is a snapshot of where the block left the shard, not a
    # per-round accumulation (commit watermarks are monotone, so the
    # final fold is exactly the aggregate a fresh dispatch would see).
    telem: TelemAggregate | None = None


def read_confirm(
    read_acks: jax.Array,   # (G,S,P) bool — heartbeat-echo acks per slot
    read_count: jax.Array,  # (G,S) i32 — reads batched per slot (0 = free)
    voting: jax.Array,      # (G,P) bool
    self_slot: jax.Array,   # (G,) i32
    quorum: jax.Array,      # (G,) i32
    node_state: jax.Array,  # (G,) i8
    live: jax.Array,        # (G,) bool
) -> jax.Array:
    """(G,S) bool — pending-read slots whose echo quorum is reached.

    Scalar twin: ``ReadIndex.confirm`` (``raft/readindex.py:51``,
    reference ``readindex.go:77-90``): ``len(p.confirmed) + 1 >= quorum``
    — the ``+1`` is the leader counting itself, expressed here as the
    same elementwise one-hot self-column trick as :func:`_self_column`
    (a gather-free OR into the ack matrix).  The row-sum is masked by
    ``voting`` exactly like :func:`vote_tally`/:func:`check_quorum`, so
    observer echoes never count toward the quorum.  Only live LEADER
    rows confirm: a row that lost leadership keeps its (about-to-be-
    purged) slots unconfirmed, matching the scalar path dropping pending
    reads on every state transition (``raft.py become_*`` builds a fresh
    ``ReadIndex``).
    """
    p = voting.shape[1]
    self_onehot = jax.nn.one_hot(self_slot, p, dtype=jnp.bool_)  # (G,P)
    acked = (read_acks | self_onehot[:, None, :]) & voting[:, None, :]
    count = jnp.sum(acked, axis=2).astype(I32)  # (G,S)
    is_leader = (node_state == LEADER) & live
    return (count >= quorum[:, None]) & (read_count > 0) & is_leader[:, None]


def _read_plane(
    st: QuorumState,
    stage_idx: jax.Array,  # (G,S) i32 — new batch index per slot; -1 = no stage
    stage_cnt: jax.Array,  # (G,S) i32 — reads in the new batch
    ack: jax.Array,        # (G,S,P) bool — this round's heartbeat echoes
) -> tuple[QuorumState, jax.Array, jax.Array]:
    """One round of the device read plane: stage → echo ingest → confirm
    → release.  Returns ``(state, done_count, done_index)`` where the
    done arrays describe the batches released THIS round ((G,S) i32;
    index -1 where nothing confirmed).

    Staging a slot overwrites it and RESETS its acks: an echo proves
    leadership only at a time >= its own ctx's capture, so echoes of an
    older tenant of the slot must never count toward a newer batch (the
    engine's host-side slot bookkeeping avoids overwriting unconfirmed
    batches; the reset makes a violation conservative, not unsafe).
    Echoes staged in the same round as the batch DO count — the host
    sequences them after the stage, mirroring a heartbeat response
    arriving after ``add_request`` in the scalar path.
    """
    staged = stage_idx >= 0                                   # (G,S)
    read_index = jnp.where(staged, stage_idx, st.read_index)
    read_count = jnp.where(staged, stage_cnt, st.read_count)
    read_acks = jnp.where(staged[:, :, None], ack, st.read_acks | ack)
    confirmed = read_confirm(
        read_acks, read_count, st.voting, st.self_slot, st.quorum,
        st.node_state, st.live,
    )
    done_count = jnp.where(confirmed, read_count, 0)
    done_index = jnp.where(confirmed, read_index, -1)
    # release: confirmed slots free (count 0) with acks cleared; the
    # captured index is left in place (harmless — count gates everything)
    read_count = jnp.where(confirmed, 0, read_count)
    read_acks = read_acks & ~confirmed[:, :, None]
    st = st._replace(
        read_index=read_index, read_count=read_count, read_acks=read_acks
    )
    return st, done_count, done_index


def _kv_plane(
    st: QuorumState,
    ent_idx: jax.Array,   # (G,E) i32 — staged op log index per buffer slot; -1 = no stage
    ent_key: jax.Array,   # (G,E) i32 — staged op key slot
    ent_val: jax.Array,   # (G,E) i32 — staged op value
    read_key: jax.Array,  # (G,R) i32 — staged KV read keys; -1 = no read
) -> tuple[QuorumState, jax.Array, jax.Array, jax.Array]:
    """One round of the device state machine (devsm, ISSUE 11): stage →
    apply → read.  Returns ``(state, read_val, read_idx, applied)``.

    Stage: a non-``-1`` ``ent_idx`` cell overwrites its buffer slot (the
    engine's host bookkeeping only restages a slot whose previous tenant
    provably applied — the slot-occupancy rule in
    ``BatchedQuorumEngine.stage_kv_ops``).

    Apply — the fold this subsystem exists for: every buffered entry
    whose index the commit watermark has passed writes its value into
    ``kv_value[key]`` and frees its slot, in ONE ``(G,V)`` tensor update.
    Commit-order correctness without a sequential walk: ops are pure SETs,
    so the post-batch value of a key is exactly the value of its
    highest-index ready entry — selected per key by an index-max over the
    ``(G,E,V)`` key one-hot (indexes are unique per group, so exactly one
    winner exists; the selection is bit-identical to applying the batch
    sequentially in log order, which ``tests/test_devsm.py`` pins against
    the scalar oracle).  Entries above the watermark stay buffered for a
    later round — the buffer is always a suffix strictly above
    ``committed``.

    Read: staged keys gather their post-apply value plus the commit
    watermark it reflects.  Captured AFTER the fold, so a read staged in
    the round an entry commits sees it — on this plane apply == commit by
    construction, the property that lets lease/ReadIndex reads serve
    straight from device state with zero host apply.
    """
    staged = ent_idx >= 0                                     # (G,E)
    b_idx = jnp.where(staged, ent_idx, st.kv_ent_index)
    b_key = jnp.where(staged, ent_key, st.kv_ent_key)
    b_val = jnp.where(staged, ent_val, st.kv_ent_val)

    v = st.kv_value.shape[1]
    ready = (b_idx >= 0) & (b_idx <= st.committed[:, None])   # (G,E)
    key_oh = jax.nn.one_hot(b_key, v, dtype=jnp.bool_)        # (G,E,V)
    sel = ready[:, :, None] & key_oh
    masked_idx = jnp.where(sel, b_idx[:, :, None], -1)        # (G,E,V)
    win_idx = jnp.max(masked_idx, axis=1)                     # (G,V)
    is_win = sel & (masked_idx == win_idx[:, None, :]) & (
        win_idx[:, None, :] >= 0
    )
    new_val = jnp.sum(jnp.where(is_win, b_val[:, :, None], 0), axis=1)
    kv_value = jnp.where(win_idx >= 0, new_val, st.kv_value)  # (G,V)

    applied = jnp.sum(ready, axis=1).astype(I32)              # (G,)
    b_idx = jnp.where(ready, -1, b_idx)                       # free applied slots

    st = st._replace(
        kv_value=kv_value,
        kv_ent_index=b_idx,
        kv_ent_key=b_key,
        kv_ent_val=b_val,
    )
    has_read = read_key >= 0                                  # (G,R)
    read_oh = jax.nn.one_hot(read_key, v, dtype=jnp.bool_)    # (G,R,V)
    read_val = jnp.sum(
        jnp.where(read_oh, kv_value[:, None, :], 0), axis=2
    )                                                         # (G,R)
    read_val = jnp.where(has_read, read_val, 0)
    read_idx = jnp.where(has_read, st.committed[:, None], -1)
    return st, read_val, read_idx, applied


def tick_step(st: QuorumState) -> tuple[QuorumState, TickFlags]:
    """Advance per-group clocks one tick (twin: ``Raft.tick``).

    Emits *flags* for the rare follow-ups (campaign, heartbeat broadcast,
    leader step-down) which the host executes scalar-side; the dense
    counter arithmetic and CheckQuorum activity scan stay on device.
    """
    live = st.live
    is_leader = (st.node_state == LEADER) & live

    election_tick = jnp.where(live, st.election_tick + 1, st.election_tick)

    # non-leader: election timeout (raft.go:568-592)
    elect_due = (
        live
        & ~is_leader
        & st.electable
        & (election_tick >= st.rand_timeout)
    )
    # leader: CheckQuorum window (raft.go:594-623)
    checkq_due = is_leader & (election_tick >= st.election_timeout)
    election_tick = jnp.where(elect_due | checkq_due, 0, election_tick)

    has_q, cleared_active = check_quorum(
        st.active, st.voting, st.self_slot, st.quorum
    )
    run_checkq = checkq_due & st.check_quorum_on
    # fire on EVERY window expiry (not only when the device tally lacks a
    # quorum): the scalar CHECK_QUORUM handler is the authority and must
    # consume its per-peer activity bits once per window exactly like the
    # reference's leader_tick cadence — otherwise stale scalar bits would
    # make the first real demotion refuse (doubling stale-leader exposure)
    checkq_demote = run_checkq
    del has_q  # advisory only; the scalar re-check decides
    active = jnp.where(run_checkq[:, None], cleared_active, st.active)

    heartbeat_tick = jnp.where(is_leader, st.heartbeat_tick + 1, st.heartbeat_tick)
    hb_due = is_leader & (heartbeat_tick >= st.heartbeat_timeout)
    heartbeat_tick = jnp.where(hb_due, 0, heartbeat_tick)

    st = st._replace(
        election_tick=election_tick,
        heartbeat_tick=heartbeat_tick,
        active=active,
    )
    return st, TickFlags(elect_due, hb_due, checkq_demote)


def quorum_step_impl(
    st: QuorumState,
    ack_g: jax.Array,      # (K,) i32 group row of each ack event
    ack_p: jax.Array,      # (K,) i32 peer slot
    ack_val: jax.Array,    # (K,) i32 rel match index acknowledged
    ack_valid: jax.Array,  # (K,) bool
    vote_g: jax.Array,     # (K,) i32
    vote_p: jax.Array,     # (K,) i32
    vote_grant: jax.Array,  # (K,) i8 — 1 grant / 0 reject
    vote_valid: jax.Array,  # (K,) bool
    do_tick: bool = True,
    track_contact: bool = True,
    has_votes: bool = True,
    has_hier: bool = False,
    has_telem: bool = False,
    telem_k: int = TELEM_TOPK,
    has_reads: bool = False,
    has_kv: bool = False,
) -> StepOutputs:
    """ONE fused dispatch for a whole engine round (SURVEY.md §7).

    Scalar order of operations matches ``processSteps``: ingest acks and
    votes, tally elections, advance commits, then tick clocks.  Ack
    ingestion uses scatter-max (``remote.try_update`` keeps only forward
    progress, so max is exact and order-independent → deterministic).

    ``has_votes=False`` (static) compiles out the vote-event scatter and
    gather for the common vote-free round; the tally over the standing
    ``st.votes`` still runs (flags stay idempotent across rounds exactly
    as with an empty vote batch).  The vote_* args may then be dummies.
    """
    g_total = st.term.shape[0]
    # route invalid events out of bounds; XLA drops them
    ag = jnp.where(ack_valid, ack_g, g_total)

    # --- ack ingestion (twin: handleLeaderReplicateResp raft.go:1671) ---
    match = st.match.at[ag, ack_p].max(ack_val, mode="drop")
    # remote.next >= remote.match + 1 is a raft invariant every writer
    # preserves (make_state, set_leader's reset_remotes, rebase, and this
    # kernel), so the scatter-max of ack_val+1 into ``next`` equals a
    # dense max against the freshly scattered match — one scatter fewer
    # (~1ms/round at 131k groups)
    next_ = jnp.maximum(st.next, match + 1)
    active = st.active.at[ag, ack_p].set(True, mode="drop")
    # leader contact: any event touching a NON-leader row resets its
    # election clock (twin: leader_is_available / raft.go follower
    # heartbeat handling) — the host stages a zero-value ack when a
    # follower hears from its leader, so device-tick followers don't
    # campaign against a healthy leader.  Contact events are ONE-SHOT
    # (consumed by whichever round drains them), so the reset must run on
    # every round of a ticking engine — including its do_tick=False
    # rounds — or an idle follower's clock would climb to elect_due and
    # spam spurious (scalar-rejected) election flags; the ENGINE therefore
    # passes track_contact = device_ticks OR do_tick.  Compiling the
    # scatter out (~8% of the multistep round at 131k groups) is legal
    # only when the engine never ticks on device (host-driven clocks:
    # drive_ticks=False coordinators, the bench host-loop/rung sections)
    # OR no benched row is a non-leader (the reset writes are masked by
    # `contacted & nonleader` — the headline bench's explicit False).
    if track_contact:
        contacted = (
            jnp.zeros((g_total + 1,), bool).at[ag].set(True)[:g_total]
        )
        nonleader = (st.node_state != LEADER) & st.live
        election_tick = jnp.where(
            contacted & nonleader, 0, st.election_tick
        )
    else:
        election_tick = st.election_tick
    # self-acks raise last_index (leader append); followers never exceed it
    self_match = _self_column(match, st.self_slot)
    last_index = jnp.maximum(st.last_index, self_match)

    # --- vote ingestion (first vote per peer per term wins) -------------
    if has_votes:
        vg = jnp.where(vote_valid, vote_g, g_total)
        cur = st.votes[vg.clip(0, g_total - 1), vote_p]
        newv = jnp.where(cur == VOTE_NONE, vote_grant, cur)
        votes = st.votes.at[vg, vote_p].set(newv, mode="drop")
    else:
        votes = st.votes

    out = _finish_step(
        st, match, next_, active, votes, election_tick, last_index, do_tick,
        has_hier=has_hier,
    )
    if has_telem:
        # has_reads/has_kv carry no event planes on this path — they are
        # pure occupancy hints so the fold only sweeps read/kv slot
        # arrays that could actually be non-idle (the engine passes its
        # plane latches).
        tst, agg = telem_fold(
            out.state, telem_k,
            count_reads=has_reads, count_kv=has_kv,
        )
        out = out._replace(state=tst, telem=agg)
    return out


def _finish_step(
    st: QuorumState,
    match: jax.Array,
    next_: jax.Array,
    active: jax.Array,
    votes: jax.Array,
    election_tick: jax.Array,
    last_index: jax.Array,
    do_tick: bool,
    has_hier: bool = False,
) -> StepOutputs:
    """Tally/commit/tick tail shared by the sparse and dense steps — the
    ingestion front-ends differ, the raft semantics must not."""
    # --- election tally (twin: handleVoteResp / campaign) ---------------
    granted, rejected = vote_tally(votes, st.voting, st.quorum)
    is_cand = (st.node_state == CANDIDATE) & st.live
    won = is_cand & (granted >= st.quorum)
    lost = is_cand & (rejected >= st.quorum)

    # --- commit advancement (twin: try_commit raft.go:888-909) ----------
    q = commit_quorum(match, st.voting, st.quorum)
    if has_hier:
        # hier sub-quorum rule (twin: Raft._hier_try_commit, ISSUE 18):
        # the near-domain kth-largest can close ahead of the far acks;
        # the classic quorum stays the floor.  sub_quorum == 0 rows
        # (hier off / ineligible domain / non-leader) keep the classic
        # value bit-for-bit — the clamp only satisfies _kth_largest's
        # 1 <= k precondition and its result is discarded by the where.
        q_near = _kth_largest(
            match, st.voting & st.near, jnp.maximum(st.sub_quorum, 1)
        )
        q = jnp.where(st.sub_quorum > 0, jnp.maximum(q, q_near), q)
    is_leader = (st.node_state == LEADER) & st.live
    # raft paper p8: only current-term entries commit by counting; on the
    # leader q >= term_start ⟺ log.match_term(q, term) (see state.py)
    can_commit = is_leader & (q > st.committed) & (q >= st.term_start)
    committed = jnp.where(can_commit, q, st.committed)

    st = st._replace(
        match=match,
        next=next_,
        active=active,
        votes=votes,
        committed=committed,
        last_index=last_index,
        election_tick=election_tick,
    )

    if do_tick:
        st, flags = tick_step(st)
    else:
        zeros = jnp.zeros_like(won)
        flags = TickFlags(zeros, zeros, zeros)

    return StepOutputs(st, committed, won, lost, flags)


quorum_step = jax.jit(
    quorum_step_impl,
    static_argnames=(
        "do_tick", "track_contact", "has_votes", "has_hier", "has_telem",
        "telem_k", "has_reads", "has_kv",
    ),
    donate_argnums=(0,),
)


def quorum_step_dense_impl(
    st: QuorumState,
    ack_max: jax.Array,      # (G,P) i32 — max acked rel index, 0 where untouched
    ack_touched: jax.Array,  # (G,P) bool — slot received ≥1 event this round
    vote_new: jax.Array,     # (G,P) i8 — VOTE_NONE where no vote event
    read_stage_idx: jax.Array | None = None,  # (G,S) i32, -1 = no stage
    read_stage_cnt: jax.Array | None = None,  # (G,S) i32
    read_ack: jax.Array | None = None,        # (G,S,P) bool echo events
    kv_ent_idx: jax.Array | None = None,      # (G,E) i32, -1 = no stage
    kv_ent_key: jax.Array | None = None,      # (G,E) i32
    kv_ent_val: jax.Array | None = None,      # (G,E) i32
    kv_read_key: jax.Array | None = None,     # (G,R) i32, -1 = no read
    do_tick: bool = True,
    track_contact: bool = True,
    has_votes: bool = True,
    has_reads: bool = False,
    has_kv: bool = False,
    has_hier: bool = False,
    has_telem: bool = False,
    telem_k: int = TELEM_TOPK,
) -> StepOutputs:
    """Dense-ingestion twin of :func:`quorum_step_impl` — zero scatters.

    Scatter-max aggregation is order-independent, so a round's sparse ack
    events collapse exactly into a per-(group, peer) **max matrix** plus a
    touched mask; ingestion becomes pure elementwise ``maximum``/``or`` on
    ``(G, P)`` arrays, which the VPU streams at HBM speed.  Measured on the
    131k-group headline shape: 14.0 → 2.0 ms/round vs the scatter form —
    TPU scatters serialize per update window while this form is shape-
    oblivious.  The engine picks dense vs sparse per dispatch by event
    occupancy (`BatchedQuorumEngine.step`); both produce bit-identical
    states (differential: ``tests/test_ops_quorum.py``).

    Caller contract: ``ack_max`` holds 0 in untouched cells (rel indexes
    are non-negative, so 0 is a max no-op — `ack()` clamps below-base
    retransmits the same way); ``vote_new`` holds first-wins-deduped vote
    events (engine.vote dedups within a batch, the kernel guards against
    standing votes).
    """
    # --- ack ingestion ---------------------------------------------------
    match = jnp.maximum(st.match, jnp.where(ack_touched, ack_max, 0))
    # next >= match + 1 invariant (see quorum_step_impl)
    next_ = jnp.maximum(st.next, match + 1)
    active = st.active | ack_touched
    if track_contact:
        contacted = jnp.any(ack_touched, axis=1)
        nonleader = (st.node_state != LEADER) & st.live
        election_tick = jnp.where(contacted & nonleader, 0, st.election_tick)
    else:
        election_tick = st.election_tick
    self_match = _self_column(match, st.self_slot)
    last_index = jnp.maximum(st.last_index, self_match)

    # --- vote ingestion (first vote per peer per term wins) --------------
    if has_votes:
        votes = jnp.where(
            (st.votes == VOTE_NONE) & (vote_new != VOTE_NONE),
            vote_new,
            st.votes,
        )
    else:
        votes = st.votes

    out = _finish_step(
        st, match, next_, active, votes, election_tick, last_index, do_tick,
        has_hier=has_hier,
    )
    if has_reads:
        # read plane LAST: stage / echo ingest / confirm / release
        # (ReadIndex confirmation is independent of this round's commit
        # advancement — the release index is the CAPTURED watermark, not
        # the current one — so ordering vs _finish_step is free; last
        # keeps the write path byte-identical when reads are quiet)
        rst, done_cnt, done_idx = _read_plane(
            out.state, read_stage_idx, read_stage_cnt, read_ack
        )
        out = out._replace(
            state=rst, read_done_count=done_cnt, read_done_index=done_idx
        )
    if has_kv:
        # devsm plane after commit advancement (an entry committing this
        # round applies this round — apply == commit is the plane's whole
        # contract) and after the read plane (a ReadIndex slot confirming
        # this round can pair with a KV read capture at >= its release
        # watermark in the SAME dispatch)
        kst, kv_rv, kv_ri, kv_ap = _kv_plane(
            out.state, kv_ent_idx, kv_ent_key, kv_ent_val, kv_read_key
        )
        out = out._replace(
            state=kst, kv_read_val=kv_rv, kv_read_index=kv_ri,
            kv_applied=kv_ap,
        )
    if has_telem:
        # telemetry fold LAST: the aggregate must describe the state this
        # dispatch leaves behind — including reads released and entries
        # applied above — and the fold writes no field any plane reads,
        # so ordering after them is free and keeps the telem-off program
        # byte-identical.
        tst, agg = telem_fold(
            out.state, telem_k,
            count_reads=has_reads, count_kv=has_kv,
        )
        out = out._replace(state=tst, telem=agg)
    return out


quorum_step_dense = jax.jit(
    quorum_step_dense_impl,
    static_argnames=(
        "do_tick", "track_contact", "has_votes", "has_reads", "has_kv",
        "has_hier", "has_telem", "telem_k",
    ),
    donate_argnums=(0,),
)


def quorum_multistep_impl(
    st: QuorumState,
    ack_g: jax.Array,      # (R,K) — R staged rounds of event batches
    ack_p: jax.Array,
    ack_val: jax.Array,
    ack_valid: jax.Array,
    vote_g: jax.Array,
    vote_p: jax.Array,
    vote_grant: jax.Array,
    vote_valid: jax.Array,
    do_tick: bool = True,
    track_contact: bool = True,
    has_votes: bool = True,
    has_hier: bool = False,
) -> StepOutputs:
    """R engine rounds in ONE dispatch via ``lax.scan``.

    Host↔device round trips are the latency floor (SURVEY.md §7 hard-part
    3) — especially over a network-attached TPU.  The host therefore stages
    R rounds of ingested events and scans them on device, mirroring the
    reference's pipelining (proposals accepted while prior ones are in
    flight, ``execengine.go:954-966``).  Outputs carry the final state plus
    OR-accumulated flags and the final commit watermark; commit
    notifications are monotone, so the final watermark is sufficient for
    host egress.
    """

    def body(carry, ev):
        if has_votes:
            args = ev
        else:
            # vote args are NOT scanned when has_votes=False; the step
            # accepts dummies of any shape there
            z32 = jnp.zeros((1,), I32)
            args = ev + (z32, z32, jnp.zeros((1,), jnp.int8),
                         jnp.zeros((1,), jnp.bool_))
        out = quorum_step_impl(
            carry,
            *args,
            do_tick=do_tick,
            track_contact=track_contact,
            has_votes=has_votes,
            has_hier=has_hier,
        )
        acc = (out.won, out.lost, out.flags)
        return out.state, acc

    xs = (
        (ack_g, ack_p, ack_val, ack_valid, vote_g, vote_p, vote_grant, vote_valid)
        if has_votes
        else (ack_g, ack_p, ack_val, ack_valid)
    )
    st, (won, lost, flags) = jax.lax.scan(body, st, xs)
    any_ = lambda x: jnp.any(x, axis=0)  # noqa: E731
    return StepOutputs(
        st,
        st.committed,
        any_(won),
        any_(lost),
        TickFlags(*(any_(f) for f in flags)),
    )


quorum_multistep = jax.jit(
    quorum_multistep_impl,
    static_argnames=("do_tick", "track_contact", "has_votes", "has_hier"),
    donate_argnums=(0,),
)


def quorum_multistep_dense_impl(
    st: QuorumState,
    ack_max: jax.Array,      # (R,G,P)
    ack_touched: jax.Array,  # (R,G,P)
    vote_new: jax.Array,     # (R,G,P) i8
    do_tick: bool = True,
    track_contact: bool = True,
    has_votes: bool = True,
    has_hier: bool = False,
) -> StepOutputs:
    """R dense rounds in ONE dispatch (see :func:`quorum_multistep_impl`).

    Stacked ``(R, G, P)`` inputs are only practical when R·G·P stays small
    or the rounds are derived on device (the headline bench synthesizes
    them inside its own jit and calls :func:`quorum_step_dense_impl` in a
    scan directly); this wrapper serves host-staged short pipelines and
    the differential tests.
    """

    def body(carry, ev):
        if has_votes:
            am, at_, vn = ev
        else:
            # vote_new is NOT scanned when has_votes=False (the caller may
            # pass a dummy of any shape, per the step contract)
            am, at_ = ev
            vn = jnp.zeros((1, 1), jnp.int8)
        out = quorum_step_dense_impl(
            carry,
            am,
            at_,
            vn,
            do_tick=do_tick,
            track_contact=track_contact,
            has_votes=has_votes,
            has_hier=has_hier,
        )
        acc = (out.won, out.lost, out.flags)
        return out.state, acc

    xs = (ack_max, ack_touched, vote_new) if has_votes else (ack_max, ack_touched)
    st, (won, lost, flags) = jax.lax.scan(body, st, xs)
    any_ = lambda x: jnp.any(x, axis=0)  # noqa: E731
    return StepOutputs(
        st,
        st.committed,
        any_(won),
        any_(lost),
        TickFlags(*(any_(f) for f in flags)),
    )


quorum_multistep_dense = jax.jit(
    quorum_multistep_dense_impl,
    static_argnames=("do_tick", "track_contact", "has_votes", "has_hier"),
    donate_argnums=(0,),
)


def _apply_recycle(
    st: QuorumState,
    row: jax.Array,    # (C,) i32 — target rows; G (out of range) = padding
    term: jax.Array,   # (C,) i32
    start: jax.Array,  # (C,) i32 rel — term_start of the fresh leader
    last: jax.Array,   # (C,) i32 rel — last_index of the fresh leader
    reset_reads: bool = True,
    reset_kv: bool = True,
    reset_telem: bool = True,
) -> QuorumState:
    """Masked leader-recycle row reset (twin: the host's ``remove_group``
    + ``add_group`` + ``set_leader`` sequence for a SAME-GEOMETRY tenant
    swap, ``engine.py``).  Membership geometry (quorum, self_slot, voting,
    present, electable, timeouts) is untouched — the engine's
    ``stage_recycle`` validates that invariant host-side — so the reset is
    a handful of row scatters instead of a full host re-upload: the
    VERDICT §7 design pivot (churn as masked updates inside the dispatched
    program).  Padding rows carry ``row == G`` and drop out of bounds.
    """
    g, p = st.match.shape
    s = st.read_index.shape[1]
    c = row.shape[0]
    sel = st.self_slot[row.clip(0, g - 1)]  # (C,) — self slot per target row
    cols = jnp.arange(p, dtype=I32)[None, :]
    # reset_remotes: match 0 everywhere except self = last; next = last + 1
    match_rows = jnp.where(cols == sel[:, None], last[:, None], 0)
    next_rows = jnp.broadcast_to(last[:, None] + 1, match_rows.shape)
    zc = jnp.zeros_like(term)
    if reset_reads:
        # pending reads die with the tenant (HostMirror.clear_reads twin).
        # Compiled OUT (reset_reads=False, a static flag) when the engine's
        # read plane has never been used: the read arrays are provably
        # all-zero then, the resets are no-ops, and the three extra row
        # scatters per scanned round cost ~40% of rung-5 throughput at
        # 100k groups under churn (measured 2.83M -> 1.60M w/s).
        zread = jnp.zeros((c, s), I32)
        st = st._replace(
            read_index=st.read_index.at[row].set(zread, mode="drop"),
            read_count=st.read_count.at[row].set(zread, mode="drop"),
            read_acks=st.read_acks.at[row].set(
                jnp.zeros((c, s, p), jnp.bool_), mode="drop"
            ),
        )
    if reset_kv:
        # the fresh tenant starts from an EMPTY device state machine
        # (HostMirror.clear_kv twin).  Compiled OUT (static) while the
        # engine's devsm plane has never been used — the kv arrays are
        # provably at their reset values then, exactly the reset_reads
        # rationale above.
        v = st.kv_value.shape[1]
        e = st.kv_ent_index.shape[1]
        zke = jnp.zeros((c, e), I32)
        st = st._replace(
            kv_value=st.kv_value.at[row].set(
                jnp.zeros((c, v), I32), mode="drop"
            ),
            kv_ent_index=st.kv_ent_index.at[row].set(
                jnp.full((c, e), -1, I32), mode="drop"
            ),
            kv_ent_key=st.kv_ent_key.at[row].set(zke, mode="drop"),
            kv_ent_val=st.kv_ent_val.at[row].set(zke, mode="drop"),
        )
    if reset_telem:
        # the fresh tenant's stall horizon starts at zero (HostMirror.
        # clear_telem twin).  Compiled OUT (static) while the engine's
        # telem plane has never been used — the array is provably zero
        # then, exactly the reset_reads rationale above.
        st = st._replace(
            telem_prev_committed=st.telem_prev_committed.at[row].set(
                zc, mode="drop"
            ),
        )
    return st._replace(
        node_state=st.node_state.at[row].set(LEADER, mode="drop"),
        live=st.live.at[row].set(True, mode="drop"),
        term=st.term.at[row].set(term, mode="drop"),
        term_start=st.term_start.at[row].set(start, mode="drop"),
        last_index=st.last_index.at[row].set(last, mode="drop"),
        committed=st.committed.at[row].set(zc, mode="drop"),
        election_tick=st.election_tick.at[row].set(zc, mode="drop"),
        heartbeat_tick=st.heartbeat_tick.at[row].set(zc, mode="drop"),
        match=st.match.at[row].set(match_rows, mode="drop"),
        next=st.next.at[row].set(next_rows, mode="drop"),
        active=st.active.at[row].set(False, mode="drop"),
        votes=st.votes.at[row].set(
            jnp.full(match_rows.shape, VOTE_NONE, jnp.int8), mode="drop"
        ),
    )


def quorum_multiround_impl(
    st: QuorumState,
    ack_max: jax.Array,     # (K,G,P) i32 — per-round ack maxima; -1 = untouched
    vote_new: jax.Array,    # (K,G,P) i8, or (1,1,1) dummy when not has_votes
    churn_row: jax.Array,   # (K,C) i32 — rows recycled at round start; G = pad
    churn_term: jax.Array,  # (K,C) i32
    churn_start: jax.Array,  # (K,C) i32 rel
    churn_last: jax.Array,  # (K,C) i32 rel
    tick_mask: jax.Array,   # (K,) bool — which rounds tick; dummy when !do_tick
    read_stage_idx: jax.Array | None = None,  # (K,G,S) i32, -1 = no stage
    read_stage_cnt: jax.Array | None = None,  # (K,G,S) i32
    read_ack: jax.Array | None = None,        # (K,G,S,P) bool echoes
    kv_ent_idx: jax.Array | None = None,      # (K,G,E) i32, -1 = no stage
    kv_ent_key: jax.Array | None = None,      # (K,G,E) i32
    kv_ent_val: jax.Array | None = None,      # (K,G,E) i32
    kv_read_key: jax.Array | None = None,     # (K,G,R) i32, -1 = no read
    do_tick: bool = False,
    track_contact: bool = True,
    has_votes: bool = False,
    has_churn: bool = False,
    has_reads: bool = False,
    purge_reads: bool = True,
    has_kv: bool = False,
    purge_kv: bool = True,
    has_hier: bool = False,
    has_telem: bool = False,
    purge_telem: bool = True,
    telem_k: int = TELEM_TOPK,
) -> StepOutputs:
    """K engine rounds — INCLUDING membership churn — in ONE dispatch.

    This is the ladder's workhorse (ISSUE 1 tentpole): the host stages K
    rounds of dense event blocks plus per-round leader-recycle records and
    the device scans them, paying one dispatch + one egress transfer for
    the whole block instead of per round.  Round structure mirrors the
    host sequence exactly: (1) apply that round's row recycles (the twin
    of ``_upload_dirty`` scattering a re-registered row before the
    dispatch), (2) ingest the round's dense ack/vote block, (3) tally /
    commit / tick.  The single ``-1``-sentinel ack tensor replaces the
    separate ``(ack_max, ack_touched)`` pair — ``touched == ack_max >= 0``
    is computed on device, halving host staging stores and upload bytes.

    ``tick_mask`` makes the per-round tick decision DYNAMIC under a
    static ``do_tick=True``: the live coordinator catches up a varying
    tick deficit (2..4) by padding every block to a FIXED K with
    event-free masked-off rounds, so one compiled program serves every
    deficit — per-K recompiles measured 0.5-4s each on a loaded 2-vCPU
    host, long enough to stall proposals behind the compile.  A padding
    round (no events, tick masked off) is a provable no-op: ingestion of
    an all-sentinel block changes nothing and the standing-state
    tally/commit flags are idempotent across rounds.

    Ingestion delegates to :func:`quorum_step_dense_impl`, so each scanned
    round is bit-identical to a standalone dense dispatch of the same
    block (differential: ``tests/test_multiround.py``).  Egress carries
    the final state, final commit watermarks (monotone ⇒ sufficient), and
    OR-accumulated flags.  Flag OR-accumulation is per ROW: a row recycled
    mid-block attributes surviving flags to its final tenant — recycling
    callers (bench rungs, tickless coordinators) run flag-free rounds.

    ``has_reads`` rides the device read plane on the same scan: per round,
    staged ReadIndex ctx batches land in their slots, heartbeat echoes OR
    in, and :func:`read_confirm` releases quorum-confirmed slots — read
    contexts confirm in the SAME dispatch that advances commits.  The
    confirmed-read egress accumulates in the scan carry (count-sum /
    index-max per slot; see :class:`StepOutputs`), so one transfer serves
    the whole block.  A slot confirming twice in one block (the engine
    restages only deterministically-confirmed slots) reports the summed
    count at the max index — an UP-only index rewrite, which ReadIndex
    semantics permit (``tests/test_read_confirm.py`` pins all of this
    against the scalar oracle, including a recycle and a leader change
    with pending ctxs mid-block).

    ``has_kv`` folds the device state machine into the same scan (devsm,
    ISSUE 11): per round, staged ``(key_slot, value)`` entry ops land in
    their groups' pending-entry buffers, the apply fold writes every op
    the round's commit advancement covered into the HBM-resident
    ``kv_value`` rows, and staged KV reads capture post-apply values plus
    the watermark they reflect.  Read captures and applied-op counts
    accumulate in the scan carry (overwrite-where-staged / sum; see
    :class:`StepOutputs`), so the whole block's state-machine work rides
    the one dispatch that advances its commits — the apply stage has no
    host component at all (differential: ``tests/test_devsm.py``).
    """

    def body(carry, ev):
        c = 0
        stc = carry[c]; c += 1
        if has_reads:
            rcnt_acc, ridx_acc = carry[c], carry[c + 1]
            c += 2
        if has_kv:
            kval_acc, kidx_acc, kap_acc = carry[c], carry[c + 1], carry[c + 2]
            c += 3
        i = 0
        am = ev[i]; i += 1
        if has_votes:
            vn = ev[i]; i += 1
        else:
            vn = jnp.zeros((1, 1), jnp.int8)
        if has_churn:
            crow, cterm, cstart, clast = (
                ev[i], ev[i + 1], ev[i + 2], ev[i + 3]
            )
            i += 4
            # reset_reads compiles the read-slot purges out of the recycle
            # when the engine's read plane has never been used (all-zero
            # arrays; see _apply_recycle) — the engine passes purge_reads=
            # _read_plane_used; has_reads keeps the purge for blocks that
            # stage reads themselves.  reset_kv is the devsm twin of the
            # same rule (_devsm_used / has_kv).
            stc = _apply_recycle(
                stc, crow, cterm, cstart, clast,
                reset_reads=has_reads or purge_reads,
                reset_kv=has_kv or purge_kv,
                reset_telem=has_telem or purge_telem,
            )
        if has_reads:
            rsi, rsc, rak = ev[i], ev[i + 1], ev[i + 2]
            i += 3
        else:
            rsi = rsc = rak = None
        if has_kv:
            kei, kek, kev, krk = ev[i], ev[i + 1], ev[i + 2], ev[i + 3]
            i += 4
        else:
            kei = kek = kev = krk = None
        out = quorum_step_dense_impl(
            stc,
            jnp.maximum(am, 0),  # -1 sentinel → 0 (a scatter-max no-op)
            am >= 0,
            vn,
            rsi,
            rsc,
            rak,
            kei,
            kek,
            kev,
            krk,
            do_tick=False,  # ticking handled below, per-round masked
            track_contact=track_contact,
            has_votes=has_votes,
            has_reads=has_reads,
            has_kv=has_kv,
            has_hier=has_hier,
        )
        stc = out.state
        if do_tick:
            tm = ev[i]  # () bool — this round's tick decision
            ticked, tflags = tick_step(stc)
            stc = QuorumState(
                *(jnp.where(tm, t, o) for t, o in zip(ticked, stc))
            )
            flags = TickFlags(*(f & tm for f in tflags))
        else:
            zeros = jnp.zeros_like(out.won)
            flags = TickFlags(zeros, zeros, zeros)
        carry = (stc,)
        if has_reads:
            carry = carry + (
                rcnt_acc + out.read_done_count,
                jnp.maximum(ridx_acc, out.read_done_index),
            )
        if has_kv:
            # a KV read slot captures in exactly one round of the block
            # (the engine never restages a slot before its harvest), so
            # overwrite-where-staged is exact, not a merge heuristic
            kcap = out.kv_read_index >= 0
            carry = carry + (
                jnp.where(kcap, out.kv_read_val, kval_acc),
                jnp.where(kcap, out.kv_read_index, kidx_acc),
                kap_acc + out.kv_applied,
            )
        return carry, (out.won, out.lost, flags)

    xs = (ack_max,)
    if has_votes:
        xs = xs + (vote_new,)
    if has_churn:
        xs = xs + (churn_row, churn_term, churn_start, churn_last)
    if has_reads:
        xs = xs + (read_stage_idx, read_stage_cnt, read_ack)
    if has_kv:
        xs = xs + (kv_ent_idx, kv_ent_key, kv_ent_val, kv_read_key)
    if do_tick:
        xs = xs + (tick_mask,)
    carry0 = (st,)
    if has_reads:
        g, s = st.read_index.shape
        carry0 = carry0 + (
            jnp.zeros((g, s), I32), jnp.full((g, s), -1, I32)
        )
    if has_kv:
        g = st.kv_value.shape[0]
        r = kv_read_key.shape[2]
        carry0 = carry0 + (
            jnp.zeros((g, r), I32), jnp.full((g, r), -1, I32),
            jnp.zeros((g,), I32),
        )
    carry, (won, lost, flags) = jax.lax.scan(body, carry0, xs)
    c = 0
    st = carry[c]; c += 1
    read_done_count = read_done_index = None
    if has_reads:
        read_done_count, read_done_index = carry[c], carry[c + 1]
        c += 2
    kv_read_val = kv_read_index = kv_applied = None
    if has_kv:
        kv_read_val, kv_read_index, kv_applied = (
            carry[c], carry[c + 1], carry[c + 2]
        )
        c += 3
    telem = None
    if has_telem:
        # fold ONCE on the block's final state (see StepOutputs.telem):
        # one set of reductions per dispatch, not per scanned round
        st, telem = telem_fold(
            st, telem_k, count_reads=has_reads, count_kv=has_kv,
        )
    any_ = lambda x: jnp.any(x, axis=0)  # noqa: E731
    return StepOutputs(
        st,
        st.committed,
        any_(won),
        any_(lost),
        TickFlags(*(any_(f) for f in flags)),
        read_done_count,
        read_done_index,
        kv_read_val,
        kv_read_index,
        kv_applied,
        telem,
    )


quorum_multiround = jax.jit(
    quorum_multiround_impl,
    static_argnames=(
        "do_tick", "track_contact", "has_votes", "has_churn", "has_reads",
        "purge_reads", "has_kv", "purge_kv", "has_hier", "has_telem",
        "purge_telem", "telem_k",
    ),
    donate_argnums=(0,),
)
