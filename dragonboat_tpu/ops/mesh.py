"""Mesh-sharded dispatch plane: per-shard engines, concurrent streams.

The GSPMD path (``BatchedQuorumEngine(sharding=NamedSharding(...))``)
partitions ONE program over the mesh — correct, but every dispatch is a
multi-device program: on the XLA CPU client each one is an
all-participant rendezvous on a shared per-device thread pool, which is
why multi-device dispatches used to serialize process-wide on the old
``_MULTIDEV_MU`` class lock.  One engine, one dispatch at a time, zero
dispatch concurrency from mesh hardware.

:class:`MeshQuorumEngine` takes the other branch the quorum math allows:
no data ever flows BETWEEN groups, so a mesh of N devices can run N
completely independent single-device programs — one
:class:`~.engine.BatchedQuorumEngine` per shard, each owning a
contiguous group partition, each with its own dispatch stream (a
dedicated launcher thread) and its own per-shard dispatch lock (a
single-device engine's lock is ``nullcontext`` — nothing to
rendezvous).  ``begin_round`` / ``step_rounds`` / ``harvest`` fan out to
every stream and join, so the pipelined double-buffer ingress/egress
runs per shard and the blocking egress transfers overlap instead of
queueing behind a global mutex.

The facade presents the single-engine API the coordinator speaks
(staging, round plane, warmup latches, obs/devprof attachment) plus a
group-sharded global ``dev`` view assembled zero-copy from the shard
states via ``jax.make_array_from_single_device_arrays`` — callers that
introspect sharding (``tests/test_sharding.py``,
``testing.run_sharded_stack_check``) see exactly the
``P(GROUP_AXIS)``-sharded state the GSPMD path produced.

Placement is live: groups land on the least-loaded shard at
registration, and :meth:`maybe_rebalance` migrates hot groups between
shards — stage-out on the source (sync + mirror-row capture), stage-in
on the target (fresh row + captured image + base restore), commit
watermarks preserved.  This is the cross-shard generalization of the
in-program membership-recycle path: same same-geometry tenant-swap
contract, but the row changes device, so the swap goes through the
mirror instead of the recycle kernel.
"""
from __future__ import annotations

import os
import threading
import time
from queue import Queue
from typing import Dict, List, Optional, Tuple

import numpy as np

from .engine import (
    DEFAULT_EVENT_CAP,
    BatchedQuorumEngine,
    MultiRoundResult,
    StepResult,
    WARM_K_BUCKETS,
)
from .state import QuorumState
from ..logger import get_logger

mlog = get_logger("mesh")

#: mirror fields excluded from the migration image: the read plane is
#: required quiescent at stage-out (pending reads die with transitions
#: anyway — scalar twin builds a fresh ReadIndex) and the devsm KV image
#: migrates through ``kv_restore`` (the applied-state restore path), so
#: copying the raw device-plane rows would only risk resurrecting stale
#: slot bookkeeping on the target.
_MIGRATE_SKIP = (
    "read_index", "read_count", "read_acks",
    "kv_value", "kv_ent_index", "kv_ent_key", "kv_ent_val",
)


class _ShardStream(threading.Thread):
    """One shard's dispatch stream: a dedicated launcher thread so every
    dispatch of shard *i* issues from the same thread, in program order,
    concurrently with every other shard's stream.  The facade submits
    one closure per shard per round and joins — the engines themselves
    are only ever touched by their stream while a fan-out is in flight,
    and only by the (coordinator-serialized) caller between fan-outs."""

    def __init__(self, idx: int):
        super().__init__(name=f"mesh-shard-{idx}", daemon=True)
        self.idx = idx
        self._jobs: Queue = Queue()
        self.start()

    def run(self) -> None:
        while True:
            job = self._jobs.get()
            if job is None:
                return
            fn, out, done = job
            try:
                out["result"] = fn()
            except BaseException as e:  # joined and re-raised by caller
                out["error"] = e
            finally:
                done.set()

    def submit(self, fn):
        out: dict = {}
        done = threading.Event()
        self._jobs.put((fn, out, done))
        return out, done

    def stop(self) -> None:
        self._jobs.put(None)


class _MeshGroupInfo:
    """Facade view of a shard's ``GroupInfo`` in GLOBAL row space.

    Delegates to the owning shard's live record (mutations — rebase,
    membership — show through) and survives migration: the facade
    repoints ``_gi``/``_off`` when the group changes shard, so a held
    reference never goes stale."""

    __slots__ = ("_gi", "_off")

    def __init__(self, gi, off: int):
        self._gi = gi
        self._off = off

    @property
    def row(self) -> int:
        return self._off + self._gi.row

    @property
    def cluster_id(self) -> int:
        return self._gi.cluster_id

    @property
    def base(self) -> int:
        return self._gi.base

    @property
    def slots(self):
        return self._gi.slots

    @property
    def node_ids(self):
        return self._gi.node_ids


class MeshQuorumEngine:
    """N per-shard single-device engines behind the batched-engine API.

    ``n_groups`` must divide evenly over the shards (the coordinator
    rounds capacity up to a device multiple before constructing this).
    Global row numbering is ``shard * groups_per_shard + local_row``;
    cluster-id-keyed calls route through the live assignment table.
    """

    def __init__(
        self,
        n_groups: int,
        n_peers: int,
        event_cap: int = DEFAULT_EVENT_CAP,
        devices=None,
        device_ticks: bool = True,
        rebalance_ratio: float = 1.5,
        **engine_kwargs,
    ):
        import jax
        from jax.sharding import SingleDeviceSharding

        from .sharding import make_mesh

        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        if len(devices) < 2:
            raise ValueError("mesh engine needs >= 2 devices")
        if n_groups % len(devices):
            raise ValueError(
                f"{n_groups} groups do not shard evenly over "
                f"{len(devices)} devices"
            )
        self.devices = devices
        self.n_shards = len(devices)
        self.n_groups = n_groups
        self.n_peers = n_peers
        self.event_cap = event_cap
        self.device_ticks = device_ticks
        self.shard_groups = n_groups // self.n_shards
        #: cost-driven placement knob: migrate only when the hottest
        #: shard's dispatch-cost EMA exceeds the coolest's by this factor
        self.rebalance_ratio = float(rebalance_ratio)
        self.mesh = make_mesh(np.array(devices))
        per_cap = max(event_cap // self.n_shards, 512)
        self.shards: List[BatchedQuorumEngine] = [
            BatchedQuorumEngine(
                self.shard_groups, n_peers, event_cap=per_cap,
                device_ticks=device_ticks,
                sharding=SingleDeviceSharding(d),
                **engine_kwargs,
            )
            for d in devices
        ]
        s0 = self.shards[0]
        self.n_read_slots = s0.n_read_slots
        self.n_kv_slots = s0.n_kv_slots
        self.n_kv_ents = s0.n_kv_ents
        self.n_kv_reads = s0.n_kv_reads
        self.groups: Dict[int, _MeshGroupInfo] = {}
        self._assign: Dict[int, int] = {}
        #: add_group kwargs per cid, replayed verbatim at stage-in (the
        #: voting/observer/witness split is not recoverable from the
        #: mirror masks alone)
        self._reg: Dict[int, dict] = {}
        self._streams = [_ShardStream(i) for i in range(self.n_shards)]
        #: per-shard dispatch-cost EMA (ms) — the facade's own cost
        #: attribution; devprof's sampled device_ms rides the same spans
        self._load_ms = np.zeros(self.n_shards, np.float64)
        self._migrations = 0
        self._fanout_mu = threading.Lock()
        self._inflight_n = 0
        self._inflight_peak = 0
        self._kv_hook = None
        self._kv_hook_mu = threading.Lock()
        for s in self.shards:
            s.kv_egress_hook = self._relay_kv_egress
        self._obs = None
        self._devprof = None
        self._warmup_mu = threading.Lock()
        self._warmup_thread: Optional[threading.Thread] = None
        self._warmup_cancel = threading.Event()
        # commit-rate snapshot for hot-group selection (maybe_rebalance)
        self._rate_base: Optional[np.ndarray] = None

    # ------------------------------------------------------------------
    # routing
    # ------------------------------------------------------------------

    def _shard_of(self, cluster_id: int) -> BatchedQuorumEngine:
        return self.shards[self._assign[cluster_id]]

    def _shard_of_row(self, row: int) -> Tuple[BatchedQuorumEngine, int]:
        return self.shards[row // self.shard_groups], row % self.shard_groups

    def shard_index(self, cluster_id: int) -> int:
        """Which shard currently owns the group (the assignment table)."""
        return self._assign[cluster_id]

    @property
    def free_rows(self) -> int:
        return sum(len(s._free) for s in self.shards)

    def assign_shard(self, cluster_id: int) -> int:
        """Placement decision for a NEW group: the least-loaded shard
        with a free row — load is the dispatch-cost EMA, group count the
        tie-break (both zero at startup → round-robin by count)."""
        best, best_key = -1, None
        for i, s in enumerate(self.shards):
            if not s._free:
                continue
            key = (len(s.groups), self._load_ms[i])
            if best_key is None or key < best_key:
                best, best_key = i, key
        if best < 0:
            raise RuntimeError("quorum engine full")
        return best

    # ------------------------------------------------------------------
    # group lifecycle
    # ------------------------------------------------------------------

    def add_group(
        self,
        cluster_id: int,
        node_ids: List[int],
        self_id: int,
        election_timeout: int = 10,
        heartbeat_timeout: int = 1,
        rand_timeout: Optional[int] = None,
        check_quorum: bool = False,
        witnesses: Tuple[int, ...] = (),
        observers: Tuple[int, ...] = (),
    ) -> _MeshGroupInfo:
        if cluster_id in self.groups:
            raise ValueError(f"group {cluster_id} already registered")
        idx = self.assign_shard(cluster_id)
        gi = self.shards[idx].add_group(
            cluster_id, node_ids, self_id,
            election_timeout=election_timeout,
            heartbeat_timeout=heartbeat_timeout,
            rand_timeout=rand_timeout,
            check_quorum=check_quorum,
            witnesses=witnesses,
            observers=observers,
        )
        self._assign[cluster_id] = idx
        self._reg[cluster_id] = dict(
            node_ids=list(node_ids), self_id=self_id,
            election_timeout=election_timeout,
            heartbeat_timeout=heartbeat_timeout,
            check_quorum=check_quorum,
            witnesses=tuple(witnesses), observers=tuple(observers),
        )
        mgi = _MeshGroupInfo(gi, idx * self.shard_groups)
        self.groups[cluster_id] = mgi
        if self._obs is not None:
            self._obs.placement(self.shard_counts())
        return mgi

    def remove_group(self, cluster_id: int) -> None:
        idx = self._assign.pop(cluster_id)
        self.groups.pop(cluster_id)
        self._reg.pop(cluster_id, None)
        self.shards[idx].remove_group(cluster_id)
        if self._obs is not None:
            self._obs.placement(self.shard_counts())

    # ------------------------------------------------------------------
    # migration (cost-driven placement)
    # ------------------------------------------------------------------

    def _quiescent(self, s: BatchedQuorumEngine, gi) -> bool:
        """Stage-out precondition: no pending device-plane work for the
        row.  Staged-but-undispatched acks/votes are droppable raft
        traffic (retransmits re-stage them) and die with the stage-out's
        ``remove_group`` purge; pending READS and buffered devsm entry
        ops are not droppable mid-flight, so a group carrying either
        stays put until they drain."""
        if s._read_plane_used and (
            s.read_slots_free(gi.cluster_id) < s.n_read_slots
        ):
            return False
        if s._devsm_used:
            if s._kv_queue.get(gi.row):
                return False
            if (s._kv_ent_rel[gi.row] >= 0).any():
                return False
        if gi.row in s._churn_pending or gi.row in s._churn_rows:
            return False
        return True

    def migrate_group(self, cluster_id: int, target: int) -> bool:
        """Move a group to ``target`` shard: stage-out on the source
        (harvest + row sync + mirror-image capture + remove), stage-in
        on the target (fresh row, captured image, base restore) —
        commit watermarks preserved to the index.  Returns False (and
        moves nothing) when the move is not currently safe."""
        if not (0 <= target < self.n_shards):
            raise ValueError(f"no shard {target}")
        src_idx = self._assign[cluster_id]
        if target == src_idx:
            return False
        src, tgt = self.shards[src_idx], self.shards[target]
        if not tgt._free:
            return False
        gi = src.groups[cluster_id]
        if not self._quiescent(src, gi):
            return False
        t0 = time.perf_counter()
        # stage-out: device row -> mirror, capture the image + base
        src.sync_rows([gi.row])
        img = src.mirror.row_image(gi.row, skip=_MIGRATE_SKIP)
        kv_img = src.kv_values(cluster_id) if src._devsm_used else None
        base = gi.base
        reg = self._reg[cluster_id]
        src.remove_group(cluster_id)
        # stage-in: fresh target row, then the captured image verbatim
        # (same geometry — the cross-shard twin of recycle_row), then
        # the base so relative indexes keep their absolute meaning
        ngi = tgt.add_group(
            cluster_id, rand_timeout=int(img["rand_timeout"]), **reg
        )
        tgt.mirror.restore_row(ngi.row, img)
        ngi.base = base
        tgt._row_base[ngi.row] = base
        tgt._dirty.add(ngi.row)
        if kv_img is not None:
            tgt.kv_restore(cluster_id, kv_img)
        mgi = self.groups[cluster_id]
        mgi._gi = ngi
        mgi._off = target * self.shard_groups
        self._assign[cluster_id] = target
        self._migrations += 1
        if self._obs is not None:
            self._obs.migration(
                cluster_id, src_idx, target,
                (time.perf_counter() - t0) * 1e3,
                self.shard_counts(),
            )
        mlog.debug(
            "migrated group %d: shard %d -> %d", cluster_id, src_idx, target
        )
        return True

    @property
    def migrations(self) -> int:
        return self._migrations

    def shard_counts(self) -> List[int]:
        return [len(s.groups) for s in self.shards]

    def shard_stats(self) -> List[dict]:
        """Per-shard placement/cost snapshot (health sampler food)."""
        return [
            {
                "groups": len(s.groups),
                "load_ms": round(float(self._load_ms[i]), 4),
                "fused_ready": bool(s.fused_ready),
            }
            for i, s in enumerate(self.shards)
        ]

    def maybe_rebalance(self, max_moves: int = 1) -> int:
        """Cost-driven placement pass: when the hottest shard's
        dispatch-cost EMA exceeds the coolest's by ``rebalance_ratio``
        (or its group count leads by more than one), migrate its hottest
        group — highest commit advance since the last pass — to the
        coolest shard.  Returns migrations performed."""
        moved = 0
        view = None
        for _ in range(max_moves):
            counts = np.array(self.shard_counts())
            hot = int(np.argmax(self._load_ms))
            cool = int(np.argmin(self._load_ms))
            cost_skew = (
                hot != cool
                and counts[hot] > 0
                and self._load_ms[hot]
                > self.rebalance_ratio * max(self._load_ms[cool], 1e-6)
            )
            count_skew = counts.max() - counts.min() > 1
            if count_skew and not cost_skew:
                hot = int(np.argmax(counts))
                cool = int(np.argmin(counts))
            elif not cost_skew:
                break
            cid = self._hottest_group(hot, view)
            if cid is None or not self.migrate_group(cid, cool):
                break
            moved += 1
        # re-baseline the commit-rate window every pass
        self._rate_base = np.concatenate(
            [s.committed_view() for s in self.shards]
        )
        return moved

    def _hottest_group(self, shard_idx: int, _view=None) -> Optional[int]:
        """The source shard's group with the largest commit advance since
        the last rebalance pass (ties -> first); None when the shard is
        empty."""
        s = self.shards[shard_idx]
        if not s.groups:
            return None
        view = s.committed_view()  # absolute (base included)
        off = shard_idx * self.shard_groups
        if self._rate_base is not None:
            base = (
                self._rate_base[off:off + self.shard_groups]
            )
            delta = view - base
        else:
            delta = view
        cids = s.row_cids()
        live = cids >= 0
        if not live.any():
            return None
        delta = np.where(live, delta, -1)
        return int(cids[int(np.argmax(delta))])

    # ------------------------------------------------------------------
    # staging (cid-routed pass-through)
    # ------------------------------------------------------------------

    def set_leader(self, cluster_id, term, term_start, last_index) -> None:
        self._shard_of(cluster_id).set_leader(
            cluster_id, term, term_start, last_index
        )

    def set_candidate(self, cluster_id, term) -> None:
        self._shard_of(cluster_id).set_candidate(cluster_id, term)

    def set_follower(self, cluster_id, term) -> None:
        self._shard_of(cluster_id).set_follower(cluster_id, term)

    def set_randomized_timeout(self, cluster_id, timeout) -> None:
        self._shard_of(cluster_id).set_randomized_timeout(
            cluster_id, timeout
        )

    def restore_progress(self, cluster_id, committed, last_index) -> None:
        self._shard_of(cluster_id).restore_progress(
            cluster_id, committed, last_index
        )

    def rebase(self, cluster_id) -> None:
        self._shard_of(cluster_id).rebase(cluster_id)

    def ack(self, cluster_id, node_id, index) -> None:
        self._shard_of(cluster_id).ack(cluster_id, node_id, index)

    def vote(self, cluster_id, node_id, granted) -> None:
        self._shard_of(cluster_id).vote(cluster_id, node_id, granted)

    def heartbeat_resp(self, cluster_id, node_id) -> None:
        self._shard_of(cluster_id).heartbeat_resp(cluster_id, node_id)

    def leader_contact(self, cluster_id) -> None:
        self._shard_of(cluster_id).leader_contact(cluster_id)

    def stage_read(self, cluster_id, count: int = 1, index=None) -> int:
        return self._shard_of(cluster_id).stage_read(
            cluster_id, count=count, index=index
        )

    def read_ack(self, cluster_id, node_id, slot) -> None:
        self._shard_of(cluster_id).read_ack(cluster_id, node_id, slot)

    def cancel_read(self, cluster_id, slot) -> None:
        self._shard_of(cluster_id).cancel_read(cluster_id, slot)

    def read_slots_free(self, cluster_id) -> int:
        return self._shard_of(cluster_id).read_slots_free(cluster_id)

    def stage_recycle(self, old_cid, new_cid, *args, **kwargs):
        """Same-shard in-program tenant swap (the recycle kernel path);
        the new tenant inherits the old one's shard — cross-shard moves
        go through :meth:`migrate_group`."""
        idx = self._assign[old_cid]
        gi = self.shards[idx].stage_recycle(old_cid, new_cid, *args, **kwargs)
        reg = self._reg.pop(old_cid, None)
        self._assign.pop(old_cid)
        self.groups.pop(old_cid)
        self._assign[new_cid] = idx
        if reg is not None:
            self._reg[new_cid] = reg
        self.groups[new_cid] = _MeshGroupInfo(gi, idx * self.shard_groups)
        return gi

    # devsm KV plane
    def stage_kv_op(self, cluster_id, *args, **kwargs):
        return self._shard_of(cluster_id).stage_kv_op(
            cluster_id, *args, **kwargs
        )

    def stage_kv_ops(self, cluster_id, indexes, keys, values) -> bool:
        return self._shard_of(cluster_id).stage_kv_ops(
            cluster_id, indexes, keys, values
        )

    def stage_kv_read(self, cluster_id, key) -> int:
        return self._shard_of(cluster_id).stage_kv_read(cluster_id, key)

    def kv_reads_free(self, cluster_id) -> int:
        return self._shard_of(cluster_id).kv_reads_free(cluster_id)

    def kv_values(self, cluster_id) -> np.ndarray:
        return self._shard_of(cluster_id).kv_values(cluster_id)

    def kv_restore(self, cluster_id, values) -> None:
        self._shard_of(cluster_id).kv_restore(cluster_id, values)

    def _relay_kv_egress(self, res) -> None:
        # shard harvests run on their streams; the caller-facing hook
        # fires serialized so a scalar-side consumer never re-enters
        hook = self._kv_hook
        if hook is not None:
            with self._kv_hook_mu:
                hook(res)

    @property
    def kv_egress_hook(self):
        return self._kv_hook

    @kv_egress_hook.setter
    def kv_egress_hook(self, fn) -> None:
        self._kv_hook = fn

    # ------------------------------------------------------------------
    # reads / views (global row space)
    # ------------------------------------------------------------------

    def _read(self, field_name: str, row: int):
        s, local = self._shard_of_row(row)
        return s._read(field_name, local)

    def sync_rows(self, rows) -> None:
        by_shard: Dict[int, list] = {}
        for r in rows:
            by_shard.setdefault(r // self.shard_groups, []).append(
                r % self.shard_groups
            )
        for i, local in by_shard.items():
            self.shards[i].sync_rows(local)

    def committed_index(self, cluster_id) -> int:
        return self._shard_of(cluster_id).committed_index(cluster_id)

    def peer_match(self, cluster_id, node_id) -> int:
        return self._shard_of(cluster_id).peer_match(cluster_id, node_id)

    def committed_snapshot(self, cids=None) -> Dict[int, int]:
        if cids is not None:
            by_shard: Dict[int, list] = {}
            for cid in cids:
                by_shard.setdefault(self._assign[cid], []).append(cid)
            out: Dict[int, int] = {}
            for i, part in by_shard.items():
                out.update(self.shards[i].committed_snapshot(part))
            return out
        out = {}
        for s in self.shards:
            out.update(s.committed_snapshot())
        return out

    def committed_view(self) -> np.ndarray:
        return np.concatenate([s.committed_view() for s in self.shards])

    def row_cids(self) -> np.ndarray:
        return np.concatenate([s.row_cids() for s in self.shards])

    def _upload_dirty(self) -> None:
        for s in self.shards:
            s._upload_dirty()

    @property
    def dev(self) -> QuorumState:
        """Global group-sharded view of the shard states, assembled
        zero-copy: per field, the N single-device arrays become ONE
        ``P(GROUP_AXIS)``-sharded global array over the facade's mesh.
        Point-in-time — the next dispatch donates the underlying
        buffers, so hold it only across a quiescent window (exactly the
        GSPMD engine's contract for externally-held state)."""
        import jax

        from .sharding import state_sharding

        shardings = state_sharding(self.mesh)
        fields = {}
        for name in QuorumState._fields:
            pieces = [getattr(s._dev, name) for s in self.shards]
            global_shape = (self.n_groups,) + tuple(pieces[0].shape[1:])
            fields[name] = jax.make_array_from_single_device_arrays(
                global_shape, getattr(shardings, name), pieces
            )
        return QuorumState(**fields)

    # ------------------------------------------------------------------
    # round plane (fan-out / join over the shard streams)
    # ------------------------------------------------------------------

    def _fanout(self, jobs):
        """Run ``(shard_index, closure)`` jobs on their dispatch streams;
        join; track the concurrency high-water mark for the mesh
        histogram."""
        pending = []
        for i, fn in jobs:
            def wrapped(fn=fn):
                with self._fanout_mu:
                    self._inflight_n += 1
                    self._inflight_peak = max(
                        self._inflight_peak, self._inflight_n
                    )
                try:
                    return fn()
                finally:
                    with self._fanout_mu:
                        self._inflight_n -= 1
            pending.append(self._streams[i].submit(wrapped))
        results = []
        for out, done in pending:
            done.wait()
            if "error" in out:
                raise out["error"]
            results.append(out.get("result"))
        with self._fanout_mu:
            peak, self._inflight_peak = self._inflight_peak, 0
        if self._obs is not None:
            self._obs.concurrency(peak)
        return results

    def begin_round(self) -> None:
        for s in self.shards:
            s.begin_round()

    def pending_rounds(self) -> int:
        return max(s.pending_rounds() for s in self.shards)

    @staticmethod
    def _buf_empty(rb) -> bool:
        return (
            len(rb.rows) == 0 and not rb.votes and not rb.churn
            and rb.reads is None and rb.racks is None
            and rb.kvents is None and rb.kvreads is None
        )

    def _shard_idle(self, s) -> bool:
        """True when a tickless dispatch on this shard would be a pure
        no-op: nothing staged, nothing dirty, nothing in flight, and
        every closed round is empty (``begin_round`` fans out
        unconditionally, so quiet shards accumulate empty bufs)."""
        if (
            s._acks or s._ack_blocks or s._votes or s._churn or s._dirty
            or s._reads_pending() or s._kv_pending()
            or s._kv_ents_buffered() or s._inflight is not None
        ):
            return False
        return all(self._buf_empty(rb) for rb in s._round_blocks)

    def _live_shards(self, do_tick: bool) -> List[int]:
        """Shards a dispatch must reach.  Tick rounds reach every shard
        that owns groups (its clocks must advance); event rounds skip
        idle shards entirely — their all-empty staged rounds are
        discarded, the event-free dispatch they'd pad into never
        launches.  This is where mesh fan-out beats the single GSPMD
        program on cost: a one-group hot spot costs ONE shard dispatch,
        not a whole-mesh rendezvous."""
        live = []
        for i, s in enumerate(self.shards):
            if do_tick:
                if s.groups:
                    live.append(i)
                continue
            if self._shard_idle(s):
                s._round_blocks.clear()
            else:
                live.append(i)
        return live

    def step_rounds(
        self,
        do_tick: bool = False,
        pipelined: bool = False,
        pad_rounds_to: int = 0,
        tick_rounds: Optional[int] = None,
    ) -> Optional[MultiRoundResult]:
        live = self._live_shards(do_tick)
        if not live:
            return None
        t0 = [0.0] * self.n_shards

        def job(i):
            def run():
                t = time.perf_counter()
                r = self.shards[i].step_rounds(
                    do_tick=do_tick, pipelined=pipelined,
                    pad_rounds_to=pad_rounds_to, tick_rounds=tick_rounds,
                )
                t0[i] = (time.perf_counter() - t) * 1e3
                return r
            return run

        results = self._fanout([(i, job(i)) for i in live])
        self._note_load(t0)
        return self._merge(results)

    def step(self, do_tick: bool = True) -> StepResult:
        live = self._live_shards(do_tick)
        if not live:
            return StepResult()
        t0 = [0.0] * self.n_shards

        def job(i):
            def run():
                t = time.perf_counter()
                r = self.shards[i].step(do_tick)
                t0[i] = (time.perf_counter() - t) * 1e3
                return r
            return run

        results = self._fanout([(i, job(i)) for i in live])
        self._note_load(t0)
        merged = self._merge(results)
        return merged if merged is not None else StepResult()

    def harvest(self) -> Optional[MultiRoundResult]:
        live = [
            i for i, s in enumerate(self.shards) if s._inflight is not None
        ]
        if not live:
            return None
        results = self._fanout(
            [(i, (lambda s=self.shards[i]: s.harvest())) for i in live]
        )
        return self._merge(results)

    def _note_load(self, walls_ms) -> None:
        # EMA with a short horizon: placement should chase the current
        # hot set, not the boot transient
        self._load_ms = 0.9 * self._load_ms + 0.1 * np.asarray(walls_ms)

    def _merge(self, results):
        """Merge per-shard egress into one result.  Cluster-id-keyed
        egress concatenates verbatim (every shard already reports in
        absolute cid/index terms); row-keyed views offset into global
        row space."""
        live = [r for r in results if r is not None]
        if not live:
            return None
        multi = [r for r in live if isinstance(r, MultiRoundResult)]
        if multi:
            out = MultiRoundResult(max(r.rounds for r in multi))
        else:
            out = StepResult()
        for r in live:
            out.won.extend(r.won)
            out.lost.extend(r.lost)
            out.elect.extend(r.elect)
            out.heartbeat.extend(r.heartbeat)
            out.demote.extend(r.demote)
            out.kv_applied_ops += r.kv_applied_ops
        for field in ("_commit_cids", "_commit_abs"):
            parts = [
                getattr(r, field) for r in live
                if getattr(r, field) is not None
            ]
            if parts:
                setattr(out, field, np.concatenate(parts))
        for field in (
            "read_cids", "read_slots", "read_index_abs", "read_counts",
            "kv_cids", "kv_slots", "kv_vals", "kv_index_abs",
        ):
            parts = [
                getattr(r, field) for r in live
                if getattr(r, field) is not None
            ]
            if parts:
                setattr(out, field, np.concatenate(parts))
        if multi and len(multi) == len(results) and all(
            r.committed_rel is not None for r in multi
        ):
            out.committed_rel = np.concatenate(
                [r.committed_rel for r in multi]
            )
        if multi:
            rows_parts = [
                r.commit_rows + i * self.shard_groups
                for i, r in enumerate(results)
                if isinstance(r, MultiRoundResult)
                and r.commit_rows is not None
            ]
            if rows_parts:
                out.commit_rows = np.concatenate(rows_parts)
        return out

    # ------------------------------------------------------------------
    # staging-state gates (coordinator round policy)
    # ------------------------------------------------------------------

    @property
    def _acks(self) -> bool:
        return any(len(s._acks) for s in self.shards)

    @property
    def _ack_blocks(self) -> bool:
        return any(len(s._ack_blocks) for s in self.shards)

    @property
    def _votes(self) -> bool:
        return any(len(s._votes) for s in self.shards)

    @property
    def _churn(self) -> bool:
        return any(len(s._churn) for s in self.shards)

    @property
    def _round_blocks(self) -> bool:
        return any(len(s._round_blocks) for s in self.shards)

    @property
    def _dirty(self) -> bool:
        return any(s._dirty for s in self.shards)

    @property
    def _read_plane_used(self) -> bool:
        return any(s._read_plane_used for s in self.shards)

    @property
    def _devsm_used(self) -> bool:
        return any(s._devsm_used for s in self.shards)

    def _reads_pending(self) -> bool:
        return any(s._reads_pending() for s in self.shards)

    def _kv_pending(self) -> bool:
        return any(s._kv_pending() for s in self.shards)

    def _kv_ents_buffered(self) -> bool:
        return any(s._kv_ents_buffered() for s in self.shards)

    @property
    def last_span_seq(self) -> int:
        return max(s.last_span_seq for s in self.shards)

    # ------------------------------------------------------------------
    # warmup (per-shard program sets, one niced background walker)
    # ------------------------------------------------------------------

    @property
    def fused_ready(self) -> bool:
        return all(s.fused_ready for s in self.shards)

    @property
    def kv_fused_ready(self) -> bool:
        return all(s.kv_fused_ready for s in self.shards)

    def warmup_fused(
        self,
        k_buckets=WARM_K_BUCKETS,
        include_reads: bool = True,
        include_single: bool = True,
        background: bool = True,
        include_kv: bool = False,
    ):
        """Warm every shard's program set.  One background walker warms
        the shards sequentially (each shard's programs compile for ITS
        device) — N concurrent XLA compile storms would starve the round
        thread on a small host, and the single-device programs carry no
        collectives, so there is no rendezvous to order (the historical
        ``test_full_stack_sharded_engine`` wedge cannot recur here)."""
        args = (
            tuple(k_buckets), include_reads, include_single, include_kv
        )
        if not background:
            self._warmup_walk(*args)
            return self.warmup_stats
        with self._warmup_mu:
            if self._warmup_thread is not None and (
                self._warmup_thread.is_alive()
            ):
                return self._warmup_thread
            if self.fused_ready:
                return None
            self._warmup_cancel.clear()
            self._warmup_thread = threading.Thread(
                target=self._warmup_walk, args=args,
                name="mesh-warmup", daemon=True,
            )
            self._warmup_thread.start()
            return self._warmup_thread

    def _warmup_walk(
        self, k_buckets, include_reads, include_single, include_kv
    ) -> None:
        try:  # same deprioritization as the engine's warm thread
            if threading.current_thread() is self._warmup_thread:
                os.nice(10)
        except (OSError, AttributeError):
            pass
        for s in self.shards:
            if self._warmup_cancel.is_set():
                return
            s.warmup_fused(
                k_buckets=k_buckets, include_reads=include_reads,
                include_single=include_single, background=False,
                include_kv=include_kv,
            )

    def warmup_devsm(self, k_buckets=WARM_K_BUCKETS, background: bool = True):
        if not background:
            for s in self.shards:
                s.warmup_devsm(k_buckets=k_buckets, background=False)
            return self.warmup_stats
        t = threading.Thread(
            target=lambda: [
                s.warmup_devsm(k_buckets=k_buckets, background=False)
                for s in self.shards
            ],
            name="mesh-warmup-devsm", daemon=True,
        )
        t.start()
        return t

    def cancel_warmup(self) -> None:
        self._warmup_cancel.set()
        for s in self.shards:
            s.cancel_warmup()

    @property
    def warmup_stats(self) -> dict:
        """Aggregate warm-compile record across shards (per-shard stats
        stay on each shard engine)."""
        agg = {
            "seconds": 0.0, "programs": 0,
            "cache_hits": 0, "cache_misses": 0, "error": None,
        }
        for s in self.shards:
            st = s.warmup_stats
            agg["seconds"] += st["seconds"]
            agg["programs"] += st["programs"]
            agg["cache_hits"] += st["cache_hits"]
            agg["cache_misses"] += st["cache_misses"]
            if agg["error"] is None and st["error"] is not None:
                agg["error"] = st["error"]
        agg["shards_ready"] = sum(
            1 for s in self.shards if s.fused_ready
        )
        return agg

    # devprof program-registry hooks (walked on shard 0: the program
    # set is identical per shard, only the target device differs)
    def warm_plan(self, *args, **kwargs):
        return self.shards[0].warm_plan(*args, **kwargs)

    def lower_variant(self, *args, **kwargs):
        return self.shards[0].lower_variant(*args, **kwargs)

    def _variant_args(self, *args, **kwargs):
        return self.shards[0]._variant_args(*args, **kwargs)

    @staticmethod
    def variant_label(kind, arg, has_reads, has_kv):
        return BatchedQuorumEngine.variant_label(kind, arg, has_reads, has_kv)

    # ------------------------------------------------------------------
    # observability / profiling attachment
    # ------------------------------------------------------------------

    def enable_obs(self, recorder=None, registry=None):
        """Attach per-shard ``EngineObs`` (one shared recorder so all
        shards' dispatch spans interleave in one ring — the overlap
        evidence) plus the facade's ``dragonboat_mesh_*`` instruments.
        Same repeat-call contract as the engine: no-args is a no-op,
        explicit arguments rebind."""
        if self._obs is not None and recorder is None and registry is None:
            return self._obs
        from ..obs.instruments import MeshObs

        if recorder is None:
            if self._obs is not None:
                recorder = self._obs.recorder
            else:
                from .. import obs as _obs_mod

                recorder = _obs_mod.default_recorder()
        for i, s in enumerate(self.shards):
            s.enable_obs(recorder, registry, shard=i)
        self._obs = MeshObs(
            recorder, registry=registry, n_shards=self.n_shards
        )
        self._obs.placement(self.shard_counts())
        return self._obs

    def disable_obs(self) -> None:
        self._obs = None
        for s in self.shards:
            s.disable_obs()

    def enable_devprof(self, devprof) -> None:
        self._devprof = devprof
        for s in self.shards:
            s.enable_devprof(devprof)

    def disable_devprof(self) -> None:
        self._devprof = None
        for s in self.shards:
            s.disable_devprof()

    def enable_telem(self, topk: int | None = None) -> None:
        """Flip every shard's telemetry latch (ISSUE 20): each shard's
        dispatches fold ITS partition's aggregate with no cross-shard
        rendezvous (the kernels' no-collectives invariant), and
        :meth:`telem_snapshot` merges the per-shard aggregates host-side
        — O(shards) work, independent of the group count."""
        for s in self.shards:
            s.enable_telem(topk)

    @property
    def telem_enabled(self) -> bool:
        return any(s.telem_enabled for s in self.shards)

    @property
    def n_telem_topk(self) -> int:
        return self.shards[0].n_telem_topk

    def telem_snapshot(self) -> dict | None:
        """Mesh-wide rollup of the shard aggregates: histograms, state
        counts and occupancy totals SUM (disjoint group partitions); the
        top-K merges by taking the K worst of the concatenated per-shard
        top-Ks — exact, because each shard's list already holds its K
        worst and K is the same everywhere.  None until every telem-on
        shard has harvested at least one fold (a partial merge would
        under-report fleet totals)."""
        snaps = [s.telem_snapshot() for s in self.shards]
        snaps = [t for t in snaps if t is not None]
        if not snaps or len(snaps) != sum(
            1 for s in self.shards if s.telem_enabled
        ):
            return None
        k = self.n_telem_topk
        merged = {
            "seq": min(t["seq"] for t in snaps),
            "mono": min(t["mono"] for t in snaps),
            "rounds": max(t["rounds"] for t in snaps),
            "groups": sum(t["groups"] for t in snaps),
            "lag_hist": [
                sum(t["lag_hist"][i] for t in snaps)
                for i in range(len(snaps[0]["lag_hist"]))
            ],
            "state_counts": [
                sum(t["state_counts"][i] for t in snaps)
                for i in range(len(snaps[0]["state_counts"]))
            ],
            "stalled": sum(t["stalled"] for t in snaps),
            "read_slots": sum(t["read_slots"] for t in snaps),
            "kv_ents": sum(t["kv_ents"] for t in snaps),
            "topk": sorted(
                (pair for t in snaps for pair in t["topk"]),
                key=lambda p: (-p[1], p[0]),
            )[:k],
            "shards": len(snaps),
        }
        return merged

    @property
    def _obs_instance(self):
        return self._obs

    def stop(self) -> None:
        """Tear down the dispatch streams (idempotent)."""
        self.cancel_warmup()
        for stream in self._streams:
            stream.stop()
