"""Device-mesh sharding of the batched quorum state.

The reference scales by partitioning groups over 16 worker goroutines with
``clusterID % workers`` (``execengine.go:654-706``, ``server/partition.go:38``).
The TPU-native analog partitions the *group axis of the state tensors* over a
``jax.sharding.Mesh``: every kernel op in :mod:`.kernels` is row-wise over
groups, so GSPMD partitions the entire ``quorum_step`` program with **zero
collectives** — each chip steps its slice of groups independently, the same
embarrassing parallelism the reference exploits, but across chips over ICI
instead of goroutines.

Event batches are replicated (they are tiny: ``(K,)`` int32); each chip
applies only the scatter rows that land in its group slice — XLA handles
this natively for scatter-into-sharded-operand.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .state import QuorumState

GROUP_AXIS = "groups"


def make_mesh(devices=None, axis: str = GROUP_AXIS) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(devices, (axis,))


def state_sharding(mesh: Mesh, axis: str = GROUP_AXIS) -> QuorumState:
    """A ``QuorumState`` of shardings: group axis split, peer axis local.

    Peer columns stay on-chip with their group row (quorum math reduces
    across peers — splitting peers would force cross-chip reductions for a
    7-wide axis; splitting groups costs nothing).
    """
    row = NamedSharding(mesh, P(axis))
    mat = NamedSharding(mesh, P(axis, None))
    cube = NamedSharding(mesh, P(axis, None, None))  # (G,S,P) read acks
    mats = (
        "match", "next", "voting", "present", "active", "votes",
        "read_index", "read_count",
        "kv_value", "kv_ent_index", "kv_ent_key", "kv_ent_val",
    )
    fields = {
        k: (cube if k == "read_acks" else mat if k in mats else row)
        for k in QuorumState._fields
    }
    return QuorumState(**fields)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_state(st: QuorumState, mesh: Mesh, axis: str = GROUP_AXIS) -> QuorumState:
    sh = state_sharding(mesh, axis)
    return QuorumState(
        *(jax.device_put(v, s) for v, s in zip(st, sh))
    )
