"""Tensor state layout for the batched quorum engine.

All per-group Raft bookkeeping that the reference keeps in per-node structs
(``internal/raft/raft.go:198`` ``raft`` struct, ``internal/raft/remote.go:62``
``remote`` struct) is held here as a struct-of-arrays pytree of
``(nGroups,)`` and ``(nGroups, nPeers)`` device arrays.

TPU-first design decisions (deltas from the reference):

* **int32 indexes over a host uint64 base.**  The reference uses uint64 log
  indexes everywhere.  TPUs emulate int64, so device tensors store indexes
  *relative to a per-group host-side base* (the group's compacted floor).
  Quorum math (k-th largest, comparisons, maxima) is translation-invariant,
  so the kernels are exact; the host rebases a group's row when its relative
  indexes approach 2^31 (see ``BatchedQuorumEngine.rebase``).

* **Term guard without a log probe.**  ``tryCommit`` (reference
  ``raft.go:888-909``) must check ``log.match_term(q, term)`` before
  committing.  A Raft leader appends a noop entry at the start of its term
  (reference ``raft.go:1044`` / thesis p72) and only ever appends entries at
  its own term, so on the leader ``match_term(q, current_term)`` is exactly
  ``q >= term_start_index``.  One ``(G,)`` tensor replaces the log lookup.

* **Masks, not ragged shapes.**  Variable membership (3/5 voters, observers,
  witnesses, mid-change) is expressed by ``voting`` / ``present`` boolean
  masks over a fixed ``nPeers`` axis (SURVEY.md §7 hard-part 4).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# Device-side dtypes.  Indexes are int32 *relative to the group base*;
# terms are int32 (terms advance only on elections — 2^31 is unreachable).
I32 = jnp.int32
I8 = jnp.int8
BOOL = jnp.bool_

INDEX_MIN = np.iinfo(np.int32).min

# Raft node states — must match raft.RaftState (reference raft.go:64-71).
FOLLOWER, CANDIDATE, LEADER, OBSERVER, WITNESS = 0, 1, 2, 3, 4

# Vote cell encoding: -1 = no response, 0 = rejected, 1 = granted.
VOTE_NONE, VOTE_REJECT, VOTE_GRANT = -1, 0, 1

# Pending ReadIndex ctx slots per group (the ``S`` axis).  Each slot holds
# ONE staged read batch: its captured commit watermark, the number of
# client reads riding it, and the per-peer heartbeat-echo acks.  Four
# slots cover a full K-round pipeline depth: a batch staged in round r
# confirms in round >= r, and the engine's host-side slot bookkeeping
# only reuses a slot once its batch deterministically confirmed
# (``BatchedQuorumEngine.stage_read``).
READ_SLOTS = 4

# Device state machine (devsm, ISSUE 11): value slots per group (the
# ``V`` axis of ``kv_value``) and pending-entry buffer depth (the ``E``
# axis).  A committed entry is a fixed-width ``(key_slot, value)`` SET op;
# the apply fold (``kernels._kv_plane``) writes it into its group's
# ``kv_value`` row the moment the commit watermark passes its index.  An
# entry staged at APPEND time rides buffer slot ``rel_index % E`` until it
# commits; the engine's host bookkeeping queues ops whose slot is still
# occupied (``BatchedQuorumEngine.stage_kv_ops``).
KV_SLOTS = 16
KV_ENT_SLOTS = 16

# Per-round device KV read slots (the ``R`` axis): a staged read is
# transient — it captures its value (and the committed watermark at
# capture) in exactly its round, so unlike the ReadIndex plane there is
# no device-resident read state, only the per-round stage tensor.
KV_READ_SLOTS = 4


# HBM-ledger plane classification (obs/devprof.py, ISSUE 15): which
# subsystem owns each resident device field.  Everything not listed in
# an optional plane belongs to the core quorum plane; the optional
# planes are exactly the field sets the engine's `_read_plane_used` /
# `_devsm_used` latches gate (``BatchedQuorumEngine._READ_KEYS`` /
# ``_KV_KEYS`` must stay in lockstep — asserted in tests/test_devprof.py).
READ_PLANE_FIELDS = ("read_index", "read_count", "read_acks")
DEVSM_PLANE_FIELDS = ("kv_value", "kv_ent_index", "kv_ent_key", "kv_ent_val")
HIER_PLANE_FIELDS = ("near", "sub_quorum")
TELEM_PLANE_FIELDS = ("telem_prev_committed",)


def field_plane(name: str) -> str:
    """The HBM-ledger plane a :class:`QuorumState` field belongs to."""
    if name in READ_PLANE_FIELDS:
        return "read"
    if name in DEVSM_PLANE_FIELDS:
        return "devsm"
    if name in HIER_PLANE_FIELDS:
        return "hier"
    if name in TELEM_PLANE_FIELDS:
        return "telem"
    return "quorum"


def state_layout(
    n_groups: int,
    n_peers: int,
    n_read_slots: int = None,
    n_kv_slots: int = None,
    n_kv_ents: int = None,
) -> dict:
    """Shape/dtype/byte layout of the resident device state WITHOUT
    allocating it (``jax.eval_shape`` over :func:`make_state`): the
    capacity model's source of truth.  Every field scales linearly with
    the group axis, so ``sum(nbytes) / n_groups`` is the exact
    bytes-per-group figure ``predict_bytes`` extrapolates from — and
    because this walks the same constructor the engine allocates
    through, a new state field can never silently escape the ledger."""
    kw = {}
    if n_read_slots is not None:
        kw["n_read_slots"] = n_read_slots
    if n_kv_slots is not None:
        kw["n_kv_slots"] = n_kv_slots
    if n_kv_ents is not None:
        kw["n_kv_ents"] = n_kv_ents
    sds = jax.eval_shape(lambda: make_state(n_groups, n_peers, **kw))
    return {
        name: {
            "shape": tuple(int(d) for d in leaf.shape),
            "dtype": str(np.dtype(leaf.dtype)),
            "nbytes": int(np.prod(leaf.shape, dtype=np.int64))
            * np.dtype(leaf.dtype).itemsize,
            "plane": field_plane(name),
        }
        for name, leaf in sds._asdict().items()
    }


class QuorumState(NamedTuple):
    """Struct-of-arrays state for G groups × P peer slots.

    Group-axis ``(G,)`` arrays mirror the per-``raft`` scalars; peer-axis
    ``(G, P)`` arrays mirror the per-``remote`` progress tracker columns.
    """

    # --- per-group scalars ---------------------------------------------
    node_state: jax.Array      # (G,) i8: FOLLOWER..WITNESS
    term: jax.Array            # (G,) i32
    committed: jax.Array       # (G,) i32 rel: log.committed
    last_index: jax.Array      # (G,) i32 rel: log.last_index()
    term_start: jax.Array      # (G,) i32 rel: first index of current leader term
    quorum: jax.Array          # (G,) i32: num_voting//2 + 1
    self_slot: jax.Array       # (G,) i32: peer-slot of this replica
    election_tick: jax.Array   # (G,) i32
    heartbeat_tick: jax.Array  # (G,) i32
    rand_timeout: jax.Array    # (G,) i32: randomized election timeout (host-seeded)
    election_timeout: jax.Array   # (G,) i32
    heartbeat_timeout: jax.Array  # (G,) i32
    electable: jax.Array       # (G,) bool: voter, not self-removed, not observer/witness
    check_quorum_on: jax.Array  # (G,) bool: config.check_quorum
    live: jax.Array            # (G,) bool: row holds a real group

    # --- per-peer columns ----------------------------------------------
    match: jax.Array           # (G,P) i32 rel: remote.match
    next: jax.Array            # (G,P) i32 rel: remote.next
    voting: jax.Array          # (G,P) bool: full member or witness (counts for quorum)
    present: jax.Array         # (G,P) bool: slot occupied (incl. observers)
    active: jax.Array          # (G,P) bool: remote.active (CheckQuorum recency)
    votes: jax.Array           # (G,P) i8: VOTE_NONE / VOTE_REJECT / VOTE_GRANT

    # --- pending ReadIndex ctx slots (device read plane) ---------------
    # Scalar twin: ``raft/readindex.py`` ReadStatus (index + confirmed
    # set), batched per group into S fixed slots.  ``read_count == 0``
    # means the slot is free; confirmation is a masked row-sum of
    # ``read_acks`` vs quorum (kernels.read_confirm).
    read_index: jax.Array      # (G,S) i32 rel: commit watermark captured at stage
    read_count: jax.Array      # (G,S) i32: client reads batched in the slot (0 = free)
    read_acks: jax.Array       # (G,S,P) bool: heartbeat-echo acks per slot

    # --- device state machine (devsm, ISSUE 11) ------------------------
    # Scalar twin: a user KV state machine's value array plus the apply
    # queue between commit and apply.  ``kv_value`` IS the replicated
    # state (HBM-resident, mutated in-program by the apply fold);
    # ``kv_ent_*`` is the pending-entry buffer — a committed entry leaves
    # it the round its index passes the commit watermark, so buffered
    # entries are always a suffix strictly above ``committed``.
    kv_value: jax.Array        # (G,V) i32: the replicated KV state
    kv_ent_index: jax.Array    # (G,E) i32 rel: staged op's log index; -1 = free
    kv_ent_key: jax.Array      # (G,E) i32: key slot of the staged op
    kv_ent_val: jax.Array      # (G,E) i32: value of the staged op

    # --- hierarchical commit plane (ISSUE 18) --------------------------
    # Scalar twin: ``raft/hier.py`` HierPlane's near-voter set and
    # sub-quorum cardinality for a LEADER row (host-authoritative, pushed
    # at promotion like the membership columns).  ``sub_quorum == 0``
    # disables the rule for the row — the commit reduction then matches
    # the classic kth-largest bit-for-bit.
    near: jax.Array            # (G,P) bool: leader-domain voter slots
    sub_quorum: jax.Array      # (G,) i32: domain majority; 0 = hier off

    # --- device telemetry plane (ISSUE 20) -----------------------------
    # Commit watermark at the end of the previous telemetry fold: the
    # cross-dispatch horizon the stalled-group predicate compares against
    # (``committed`` flat since the last fold while ``last_index`` shows
    # pending work).  Written in-program by ``kernels.telem_fold``; reset
    # with the row on recycle so a fresh tenant never inherits the old
    # tenant's watermark.
    telem_prev_committed: jax.Array  # (G,) i32 rel


def make_state(
    n_groups: int,
    n_peers: int,
    n_read_slots: int = READ_SLOTS,
    n_kv_slots: int = KV_SLOTS,
    n_kv_ents: int = KV_ENT_SLOTS,
) -> QuorumState:
    """All-dead state: rows are claimed by the host as groups start."""
    g, p, s = n_groups, n_peers, n_read_slots
    v, e = n_kv_slots, n_kv_ents
    zi = jnp.zeros((g,), I32)
    return QuorumState(
        node_state=jnp.zeros((g,), I8),
        term=zi,
        committed=zi,
        last_index=zi,
        term_start=zi,
        quorum=jnp.ones((g,), I32),
        self_slot=zi,
        election_tick=zi,
        heartbeat_tick=zi,
        rand_timeout=jnp.full((g,), 10, I32),
        election_timeout=jnp.full((g,), 10, I32),
        heartbeat_timeout=jnp.ones((g,), I32),
        electable=jnp.zeros((g,), BOOL),
        check_quorum_on=jnp.zeros((g,), BOOL),
        live=jnp.zeros((g,), BOOL),
        match=jnp.zeros((g, p), I32),
        next=jnp.ones((g, p), I32),
        voting=jnp.zeros((g, p), BOOL),
        present=jnp.zeros((g, p), BOOL),
        active=jnp.zeros((g, p), BOOL),
        votes=jnp.full((g, p), VOTE_NONE, I8),
        read_index=jnp.zeros((g, s), I32),
        read_count=jnp.zeros((g, s), I32),
        read_acks=jnp.zeros((g, s, p), BOOL),
        kv_value=jnp.zeros((g, v), I32),
        kv_ent_index=jnp.full((g, e), -1, I32),
        kv_ent_key=jnp.zeros((g, e), I32),
        kv_ent_val=jnp.zeros((g, e), I32),
        near=jnp.zeros((g, p), BOOL),
        sub_quorum=zi,
        telem_prev_committed=zi,
    )


class HostMirror:
    """Numpy twin of :class:`QuorumState` for cheap host-side mutation.

    The host mutates rows scalar-style for rare transitions (membership
    change, becoming leader, snapshot restore) and uploads only between
    ticks; dense per-tick updates travel as compact event batches instead
    (see ``kernels.quorum_step``).
    """

    def __init__(
        self,
        n_groups: int,
        n_peers: int,
        n_read_slots: int = READ_SLOTS,
        n_kv_slots: int = KV_SLOTS,
        n_kv_ents: int = KV_ENT_SLOTS,
    ):
        self.n_groups = n_groups
        self.n_peers = n_peers
        self.n_read_slots = n_read_slots
        self.n_kv_slots = n_kv_slots
        self.n_kv_ents = n_kv_ents
        dev = make_state(n_groups, n_peers, n_read_slots, n_kv_slots, n_kv_ents)
        self.arrays = {k: np.asarray(v).copy() for k, v in dev._asdict().items()}

    def to_device(self, sharding=None) -> QuorumState:
        put = (
            (lambda a: jax.device_put(a, sharding))
            if sharding is not None
            else jax.device_put
        )
        return QuorumState(**{k: put(v) for k, v in self.arrays.items()})

    def pull(self, st: QuorumState) -> None:
        for k, v in st._asdict().items():
            np.copyto(self.arrays[k], np.asarray(v))

    def recycle_row(
        self,
        row: int,
        term: int,
        term_start: int,
        last_index: int,
        clear_reads: bool = True,
        clear_kv: bool = True,
        clear_telem: bool = True,
    ) -> None:
        """Numpy twin of ``kernels._apply_recycle``: reset a row to a
        fresh same-geometry leader tenant WITHOUT touching membership
        columns.  The engine applies this when it stages a device-side
        recycle (``BatchedQuorumEngine.stage_recycle``) so the mirror's
        host-authoritative columns (term, watermarks) match what the
        dispatched program will compute — the row is deliberately NOT
        marked dirty; the device applies the same reset in-program."""
        a = self.arrays
        a["live"][row] = True
        a["node_state"][row] = LEADER
        a["term"][row] = term
        a["term_start"][row] = term_start
        a["last_index"][row] = last_index
        a["committed"][row] = 0
        a["election_tick"][row] = 0
        a["heartbeat_tick"][row] = 0
        a["match"][row, :] = 0
        a["match"][row, a["self_slot"][row]] = last_index
        a["next"][row, :] = last_index + 1
        a["active"][row, :] = False
        a["votes"][row, :] = VOTE_NONE
        if clear_reads:  # engine skips while its read plane is untouched
            self.clear_reads(row)
        if clear_kv:  # engine skips while its devsm plane is untouched
            self.clear_kv(row)
        if clear_telem:  # engine skips while its telem plane is untouched
            self.clear_telem(row)

    def row_image(self, row: int, skip=frozenset()) -> dict:
        """Per-field dense copy of one row — the stage-out half of a
        cross-shard group migration (``ops/mesh.py``).  ``skip`` names
        fields the caller deliberately leaves behind: the mesh plane
        skips its read/kv-plane columns because the migration quiescence
        gate has already drained them, so the target's fresh-registration
        defaults are the correct values."""
        return {
            k: np.copy(a[row]) for k, a in self.arrays.items()
            if k not in skip
        }

    def restore_row(self, row: int, image: dict) -> None:
        """Paste a captured ``row_image`` onto ``row`` verbatim — the
        stage-in half of a migration (same geometry on both shards; the
        cross-shard twin of ``recycle_row``).  The caller owns dirty
        tracking: unlike ``recycle_row`` there is no in-program twin
        applying the same write, so the row MUST be re-uploaded."""
        a = self.arrays
        for k, v in image.items():
            a[k][row] = v

    def clear_kv(self, row: int) -> None:
        """Reset a row's device state machine: value slots to zero AND the
        pending-entry buffer freed.  A recycle's fresh tenant starts from
        an empty KV exactly like a fresh ``add_group`` registration."""
        a = self.arrays
        a["kv_value"][row, :] = 0
        self.clear_kv_ents(row)

    def clear_kv_ents(self, row: int) -> None:
        """Free a row's pending-entry buffer WITHOUT touching the value
        slots (leadership-transition twin: buffered entries sit strictly
        above the commit watermark, an uncertain log suffix the next
        leader may rewrite — they die with the transition; applied state
        persists exactly like the scalar SM across terms)."""
        a = self.arrays
        a["kv_ent_index"][row, :] = -1
        a["kv_ent_key"][row, :] = 0
        a["kv_ent_val"][row, :] = 0

    def clear_telem(self, row: int) -> None:
        """Reset a row's telemetry watermark: the stalled-group predicate
        compares ``committed`` against the previous fold's value, and a
        recycled row restarts its relative indexes at zero — the old
        tenant's watermark would read as forward progress (or a phantom
        stall) for the new one."""
        self.arrays["telem_prev_committed"][row] = 0

    def clear_reads(self, row: int) -> None:
        """Drop a row's pending ReadIndex slots (twin of the scalar path's
        fresh ``ReadIndex()`` on every state transition, ``raft.py``
        ``become_*``): reads staged under the old leadership must never
        confirm under the new one."""
        a = self.arrays
        a["read_index"][row, :] = 0
        a["read_count"][row, :] = 0
        a["read_acks"][row, :, :] = False
