"""Input queues between the API layer and the step engine.

Reference: ``queue.go`` — double-buffered ``entryQueue`` for proposals,
``readIndexQueue`` for reads, and the ``readyCluster`` map pair used by the
engine's wakeup paths.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set

from .requests import RequestState
from .wire import Entry


class EntryQueue:
    """Reference ``queue.go:24`` — bounded, double-buffered."""

    def __init__(self, size: int):
        self.size = size
        self._mu = threading.Lock()
        self._left: List[Entry] = []
        self._right: List[Entry] = []
        self._use_left = True
        self._stopped = False
        self._paused = False

    def _active(self) -> List[Entry]:
        return self._left if self._use_left else self._right

    def add(self, e: Entry) -> bool:
        with self._mu:
            if self._stopped or self._paused:
                return False
            q = self._active()
            if len(q) >= self.size:
                return False
            q.append(e)
            return True

    def add_batch(self, entries: List[Entry]) -> int:
        """Append a burst under ONE lock acquisition (hostplane ingress
        batcher).  Returns how many were accepted — a full queue truncates
        the tail exactly like per-entry ``add`` calls would."""
        with self._mu:
            if self._stopped or self._paused:
                return 0
            q = self._active()
            room = self.size - len(q)
            if room <= 0:
                return 0
            take = entries[:room]
            q.extend(take)
            return len(take)

    def get(self, paused: bool = False) -> List[Entry]:
        # lock-free empty fast path (hot: every step round polls this);
        # only valid when the pause flag isn't changing
        if paused == self._paused and not self._left and not self._right:
            return []
        with self._mu:
            self._paused = paused
            q = self._active()
            self._use_left = not self._use_left
            out = list(q)
            q.clear()
            return out

    def close(self) -> None:
        with self._mu:
            self._stopped = True


class ReadIndexQueue:
    """Reference ``queue.go:110``."""

    def __init__(self, size: int):
        self.size = size
        self._mu = threading.Lock()
        self._reqs: List[RequestState] = []
        self._stopped = False

    def add(self, rs: RequestState) -> bool:
        with self._mu:
            if self._stopped or len(self._reqs) >= self.size:
                return False
            self._reqs.append(rs)
            return True

    def get(self) -> List[RequestState]:
        with self._mu:
            out, self._reqs = self._reqs, []
            return out

    def peep(self) -> bool:
        # GIL-atomic read; hot-path poll (node._handle_read_index)
        return bool(self._reqs)

    def close(self) -> None:
        with self._mu:
            self._stopped = True


class ReadyCluster:
    """Set of clusters with pending work, swapped atomically
    (reference ``queue.go:178`` ``readyCluster``)."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._ready: Set[int] = set()

    def set_ready(self, cluster_id: int) -> None:
        with self._mu:
            self._ready.add(cluster_id)

    def get_ready(self) -> Set[int]:
        with self._mu:
            out, self._ready = self._ready, set()
            return out


class LeaderInfoQueue:
    """Reference ``queue.go:213`` — leader change notifications."""

    def __init__(self, size: int = 2048):
        self.size = size
        self._mu = threading.Lock()
        self._q: List = []

    def add(self, info) -> bool:
        with self._mu:
            if len(self._q) >= self.size:
                return False
            self._q.append(info)
            return True

    def get(self) -> List:
        with self._mu:
            out, self._q = self._q, []
            return out
