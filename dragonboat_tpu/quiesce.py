"""Per-group idle detection.

Reference: ``quiesce.go`` — a group with no message activity for
10× election ticks enters quiesce: ticks stop generating heartbeats and
replicas exchange ``Quiesce`` messages; any new activity (or an incoming
election-class message) exits quiesce and fast-forwards the election tick.
"""
from __future__ import annotations

from .settings import Soft
from .wire import Message, MessageType

MT = MessageType


class QuiesceManager:
    """Reference ``quiesce.go:23`` ``quiesceManager``."""

    def __init__(self, cluster_id: int, node_id: int, election_tick: int,
                 enabled: bool):
        self.cluster_id = cluster_id
        self.node_id = node_id
        self.enabled = enabled
        self.election_tick = election_tick
        self.threshold = election_tick * Soft.quiesce_threshold_factor
        self.current_tick = 0
        self.idle_since = 0
        self.quiesced_since = 0
        self._quiesced = False
        self.new_quiesce_trigger = False

    def quiesced(self) -> bool:
        return self.enabled and self._quiesced

    def increase_quiesce_tick(self) -> int:
        if not self.enabled:
            return 0
        self.current_tick += 1
        if not self._quiesced:
            if self.current_tick - self.idle_since > self.threshold:
                self._quiesced = True
                self.quiesced_since = self.current_tick
                self.new_quiesce_trigger = False
        return self.current_tick

    def record_activity(self, msg_type: MessageType) -> None:
        if not self.enabled:
            return
        if msg_type == MT.HEARTBEAT or msg_type == MT.HEARTBEAT_RESP:
            if not self._quiesced:
                return
        self.idle_since = self.current_tick
        if self._quiesced:
            self._exit_quiesce()

    def just_entered_quiesce(self) -> bool:
        """True exactly once after entering quiesce — the trigger for
        broadcasting Quiesce messages (reference ``quiesce.go:107``).  Ticks
        arrive in batches, so any tick past the entry point fires it."""
        if not self.enabled or not self._quiesced:
            return False
        if not self.new_quiesce_trigger and self.current_tick > self.quiesced_since:
            self.new_quiesce_trigger = True
            return True
        return False

    def try_enter_quiesce(self) -> None:
        """A peer told us it quiesced (reference exchange of Quiesce msgs)."""
        if self.enabled and not self._quiesced:
            self._quiesced = True
            self.quiesced_since = self.current_tick
            self.idle_since = self.current_tick

    def _exit_quiesce(self) -> None:
        self._quiesced = False

    def should_handle(self, m: Message) -> bool:
        """Filter messages while quiesced; activity-bearing ones wake us."""
        if not self.quiesced():
            return True
        if m.type == MT.QUIESCE:
            self.try_enter_quiesce()
            return False
        self.record_activity(m.type)
        return True
