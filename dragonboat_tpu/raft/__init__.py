from .log import (  # noqa: F401
    CompactedError,
    EntryLog,
    ILogDB,
    SnapshotOutOfDateError,
    UnavailableError,
)
from .inmemory import InMemory  # noqa: F401
from .memlogdb import InMemLogDB, TestLogDB  # noqa: F401
from .peer import Peer, PeerAddress  # noqa: F401
from .raft import Raft, RaftState  # noqa: F401
from .rate import InMemRateLimiter, RateLimiter  # noqa: F401
from .readindex import ReadIndex  # noqa: F401
from .remote import Remote, RemoteState  # noqa: F401
