"""Hierarchical commit plane: domain-local sub-quorums (ISSUE 18).

BENCH_r14's replication-path attribution showed wire_out + wire_back are
~94% of every cross-domain quorum close — commits were priced at the far
RTT even when a near-domain majority acked long ago.  CD-Raft
(arxiv 2603.10555) and "Fast Raft for Hierarchical Consensus"
(arxiv 2506.17793) give the fix its shape, and this module holds the
host-side pieces:

:class:`HierPlane`
    The domain model plus the two coupled rules.

    **Commit rule** — the leader's own domain ``D_L``, when *eligible*
    (>= :data:`MIN_DOMAIN_VOTERS` voters), closes a commit once
    ``|D_L| // 2 + 1`` of its voters (a majority of the domain — the
    sub-quorum) have matched the index; far-domain voters catch up
    asynchronously through the ordinary replicate/resend machinery.
    Classic full-quorum closes remain valid throughout: the effective
    rule is ``max(classic kth-largest, near-domain kth-largest)``.

    **Vote rule** — a candidate may only take office once, *in addition
    to* the classic quorum, it holds at least ``(|D| + 1) // 2`` grants
    inside **every** eligible domain ``D``.  Why that bound: a
    sub-quorum in ``D`` has ``|D| // 2 + 1`` members, and
    ``(|D| + 1) // 2 + (|D| // 2 + 1) = |D| + 1 > |D|`` — the two sets
    must intersect, so the new leader's log carries every
    sub-quorum-committed entry (the same pigeonhole that makes classic
    Raft safe, applied per domain).  The bound is minimal: one grant
    fewer admits a disjoint counterexample.

    Liveness tradeoff (accepted, documented in docs/overview.md): while
    an eligible domain is *entirely* partitioned away, no candidate can
    satisfy its intersection bound and elections stall until the domain
    heals or membership drops it below eligibility.  Commits under an
    established leader are unaffected — the classic quorum still closes
    them.

:class:`FarReadBatcher`
    Far-follower read locality.  A follower whose domain differs from
    the leader's coalesces forwarded ReadIndex round trips: at most one
    cross-domain fetch is in flight; reads arriving meanwhile queue for
    the *next* fetch (never the current one — a read may only ride a
    fetch initiated after it arrived, otherwise the leader could answer
    with a commit point predating the read) and the whole batch
    releases at the single returned index.

:class:`HierObs` / :func:`describe_families`
    ``dragonboat_hier_*`` registry families (the LeaseObs pattern).

Everything here is constructed only when ``Config.hier_commit`` is on;
``raft.hier is None`` is the structural latch keeping the off path
bit-identical (the lease/_obs precedent).
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

#: a domain forms sub-quorums only at this many voters or more; singleton
#: domains (and the unassigned "" class) always defer to the full quorum
MIN_DOMAIN_VOTERS = 2

_H = "dragonboat_hier_"

_HELP = {
    _H + "subquorum_commit_total":
        "commit advances closed by the near-domain sub-quorum",
    _H + "fallback_commit_total":
        "commit advances closed by the classic full quorum",
    _H + "far_lag_entries":
        "entries the slowest far-domain voter trails the commit point",
    _H + "read_batches_total":
        "far-follower ReadIndex fetches sent to the leader",
    _H + "reads_coalesced_total":
        "far-follower reads that joined a pending fetch batch",
    _H + "election_holds_total":
        "vote tallies held at quorum awaiting domain intersection",
}


def describe_families(registry) -> None:
    """Register the ``# HELP`` texts for every ``dragonboat_hier_*``
    family (test_events round-trip contract: one HELP per TYPE)."""
    for name, text in _HELP.items():
        registry.describe(name, text)


class HierObs:
    """Registry-backed hier instruments, shared by every hier-enabled
    group on one NodeHost; attached only when ``enable_metrics`` is on
    and gated on ``is not None`` at every call site."""

    __slots__ = ("registry",)

    def __init__(self, registry):
        self.registry = registry
        describe_families(registry)
        for name in ("subquorum_commit_total", "fallback_commit_total",
                     "read_batches_total", "reads_coalesced_total",
                     "election_holds_total"):
            registry.counter_add(_H + name, 0)
        registry.gauge_set(_H + "far_lag_entries", 0)

    def commit_close(self, via_sub: bool) -> None:
        self.registry.counter_add(
            _H + ("subquorum_commit_total" if via_sub
                  else "fallback_commit_total")
        )

    def far_lag(self, entries: int) -> None:
        self.registry.gauge_set(_H + "far_lag_entries", int(entries))

    def read_batch(self) -> None:
        self.registry.counter_add(_H + "read_batches_total")

    def read_coalesced(self) -> None:
        self.registry.counter_add(_H + "reads_coalesced_total")

    def election_hold(self) -> None:
        self.registry.counter_add(_H + "election_holds_total")


def sub_quorum_size(n: int) -> int:
    """Majority of an ``n``-voter domain — the sub-quorum cardinality."""
    return n // 2 + 1


def intersect_threshold(n: int) -> int:
    """Minimal grants inside an ``n``-voter domain that guarantee
    intersection with any ``sub_quorum_size(n)``-member sub-quorum:
    ``n - sub_quorum_size(n) + 1 == (n + 1) // 2``."""
    return (n + 1) // 2


class HierPlane:
    """One replica's view of the domain model (all methods run under the
    owning node's raftMu — no internal locking).  Membership is passed in
    per call (the voter set is the raft object's live truth and changes
    under config change), so there is nothing here to invalidate on
    add/remove — stale domain *assignments* for departed peers are
    simply never consulted."""

    __slots__ = (
        "domains", "node_id", "obs",
        "subquorum_closes", "fallback_closes", "election_holds",
    )

    def __init__(self, domains: Dict[int, str], node_id: int):
        self.domains = dict(domains)
        self.node_id = node_id
        self.obs: Optional[HierObs] = None
        # plain counters always maintained (tests/bench read them
        # without the metrics plumbing; HierObs mirrors when attached)
        self.subquorum_closes = 0
        self.fallback_closes = 0
        self.election_holds = 0

    def domain_of(self, node_id: int) -> str:
        return self.domains.get(node_id, "")

    def eligible_domains(
        self, voter_ids: Iterable[int]
    ) -> Dict[str, List[int]]:
        """Domain label -> member voter ids, for every domain holding at
        least :data:`MIN_DOMAIN_VOTERS` of the given voters.  The
        unassigned class ("") is never eligible."""
        by_dom: Dict[str, List[int]] = {}
        for nid in voter_ids:
            dom = self.domains.get(nid, "")
            if dom:
                by_dom.setdefault(dom, []).append(nid)
        return {
            d: m for d, m in by_dom.items() if len(m) >= MIN_DOMAIN_VOTERS
        }

    def near_voters(self, voter_ids: Iterable[int]) -> List[int]:
        """This replica's domain members among ``voter_ids`` — the
        sub-quorum candidate set — or ``[]`` when the domain is
        ineligible (unassigned, or fewer than MIN_DOMAIN_VOTERS
        voters)."""
        mine = self.domains.get(self.node_id, "")
        if not mine:
            return []
        members = [
            nid for nid in voter_ids if self.domains.get(nid, "") == mine
        ]
        return members if len(members) >= MIN_DOMAIN_VOTERS else []

    def commit_quorum(
        self, match_of: Dict[int, int], voter_ids: Iterable[int]
    ) -> int:
        """The sub-quorum commit candidate: the kth-largest matchIndex
        over the leader's domain members, k = the domain majority.
        Returns 0 (never advances anything) when the leader's domain is
        ineligible."""
        near = self.near_voters(voter_ids)
        if not near:
            return 0
        matched = sorted(match_of.get(nid, 0) for nid in near)
        return matched[len(near) - sub_quorum_size(len(near))]

    def election_ok(
        self, votes: Dict[int, bool], voter_ids: Iterable[int]
    ) -> bool:
        """The vote-side safety rule: True iff the granted set holds at
        least ``intersect_threshold(|D|)`` members of every eligible
        domain D (guaranteeing intersection with any sub-quorum that may
        have closed a commit there).  The classic quorum test is the
        caller's — this is the *additional* constraint."""
        granted = {nid for nid, ok in votes.items() if ok}
        for members in self.eligible_domains(voter_ids).values():
            need = intersect_threshold(len(members))
            if sum(1 for nid in members if nid in granted) < need:
                return False
        return True

    def note_close(self, via_sub: bool) -> None:
        if via_sub:
            self.subquorum_closes += 1
        else:
            self.fallback_closes += 1
        if self.obs is not None:
            self.obs.commit_close(via_sub)

    def note_election_hold(self) -> None:
        self.election_holds += 1
        if self.obs is not None:
            self.obs.election_hold()

    def note_far_lag(
        self, match_of: Dict[int, int], voter_ids: Iterable[int],
        committed: int,
    ) -> int:
        """Entries the slowest far-domain voter trails the commit point
        (0 when no far voters exist); mirrored to the gauge."""
        mine = self.domains.get(self.node_id, "")
        far = [
            nid for nid in voter_ids
            if self.domains.get(nid, "") != mine or not mine
        ] if mine else []
        if not far:
            lag = 0
        else:
            lag = max(0, committed - min(match_of.get(n, 0) for n in far))
        if self.obs is not None:
            self.obs.far_lag(lag)
        return lag

    def is_far_follower(self, leader_id: int) -> bool:
        """True when this replica and the leader sit in different
        *assigned* domains — the gate for far-read batching.  Unassigned
        on either side stays conservative (no batching)."""
        mine = self.domains.get(self.node_id, "")
        theirs = self.domains.get(leader_id, "")
        return bool(mine) and bool(theirs) and mine != theirs

    def snapshot(self) -> Dict[str, object]:
        return {
            "domains": dict(self.domains),
            "node_domain": self.domains.get(self.node_id, ""),
            "subquorum_closes": self.subquorum_closes,
            "fallback_closes": self.fallback_closes,
            "election_holds": self.election_holds,
        }


class FarReadBatcher:
    """Coalesces a far follower's forwarded ReadIndex round trips.

    At most one cross-domain fetch is in flight.  ``admit`` answers
    whether the caller should forward this ctx to the leader (it becomes
    the in-flight batch's representative) or hold it for the next fetch.
    ``on_resp`` hands back every ctx releasable at the returned index
    plus the representative of the next fetch to forward, if any.
    ``invalidate`` (leader/term change — raft.reset) drains everything
    for the dropped_read_indexes path.

    Safety: a read may only ride a fetch initiated AFTER the read
    arrived.  A fetch the leader is already answering may reflect a
    commit point older than a just-arrived read's linearization point,
    so mid-flight arrivals always queue for the next fetch.
    """

    __slots__ = ("_inflight", "_next", "batches", "coalesced")

    def __init__(self):
        self._inflight: List[object] = []  # [0] is the representative
        self._next: List[object] = []
        self.batches = 0
        self.coalesced = 0

    def admit(self, ctx) -> bool:
        """True -> forward ``ctx`` now (new fetch, ctx is the
        representative); False -> held for the next fetch."""
        if self._inflight:
            self._next.append(ctx)
            self.coalesced += 1
            return False
        self._inflight = [ctx]
        self.batches += 1
        return True

    def on_resp(self, ctx) -> Tuple[List[object], Optional[object]]:
        """Leader answered the fetch whose representative is ``ctx``:
        returns ``(members_to_release, next_representative)``.  A ctx
        that is not the current representative (stale resp after an
        invalidate) releases only itself."""
        if not self._inflight or self._inflight[0] != ctx:
            return [ctx], None
        released = self._inflight
        if self._next:
            self._inflight, self._next = self._next, []
            self.batches += 1
            return released, self._inflight[0]
        self._inflight = []
        return released, None

    def invalidate(self) -> List[object]:
        """Drop every held ctx (leader/term change); the caller routes
        them to ``dropped_read_indexes``."""
        dropped = self._inflight + self._next
        self._inflight = []
        self._next = []
        return dropped

    @property
    def pending(self) -> int:
        return len(self._inflight) + len(self._next)


def seed_domains_from_latency(
    injector, addresses: Dict[int, str]
) -> Dict[int, str]:
    """Build a ``hier_domains`` map from a
    :class:`~dragonboat_tpu.transport.latency.LatencyInjector`'s static
    domain assignment: ``addresses`` maps node_id -> raft address."""
    return {
        nid: injector.domain_of(addr) or ""
        for nid, addr in addresses.items()
    }


def seed_domains_from_rtt(
    self_id: int,
    rtt_s: Dict[int, float],
    near_ratio: float = 4.0,
) -> Dict[int, str]:
    """RTT-classifier fallback when no injector topology exists: peers
    within ``near_ratio`` x the fastest measured RTT classify into this
    replica's domain ("near"), the rest into "far".  ``rtt_s`` maps
    peer node_id -> RTT seconds (e.g. the per-peer EWMAs
    ``obs/replattr.py`` maintains); the caller ships the result through
    ``Config.hier_domains`` so every replica agrees on one map."""
    out = {self_id: "near"}
    finite = [r for r in rtt_s.values() if r > 0]
    if not finite:
        return out
    floor = min(finite)
    for nid, r in rtt_s.items():
        out[nid] = "near" if (r > 0 and r <= floor * near_ratio) else "far"
    return out
