"""In-memory log cache holding not-yet-saved / not-yet-applied entries.

Reference: ``internal/raft/inmemory.go`` — a two-stage in-memory store with a
``marker_index`` separating the LogDB-backed body from the in-memory tail,
``saved_to`` tracking persistence progress, GC on apply, and snapshot staging.
Python lists make the slice bookkeeping simpler than Go's capacity management;
the resize/shrunk machinery of the reference exists to fight Go allocator
behavior and is intentionally not replicated.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..wire import Entry, Snapshot, UpdateCommit
from .rate import InMemRateLimiter


def check_entries_to_append(ents: List[Entry], to_append: List[Entry]) -> None:
    if len(ents) == 0 or len(to_append) == 0:
        return
    last = ents[-1]
    first = to_append[0]
    if last.index + 1 != first.index:
        raise RuntimeError(
            f"found a hole in entries, last {last.index}, first new {first.index}"
        )
    if last.term > first.term:
        raise RuntimeError(
            f"term regression, last {last.term}, first new {first.term}"
        )


def entries_mem_size(entries: List[Entry]) -> int:
    return sum(e.size() for e in entries)


class InMemory:
    """Reference ``inmemory.go:30-47``."""

    __slots__ = (
        "snapshot",
        "entries",
        "marker_index",
        "applied_to_index",
        "applied_to_term",
        "saved_to",
        "rl",
    )

    def __init__(self, last_index: int, rl: Optional[InMemRateLimiter] = None):
        self.snapshot: Optional[Snapshot] = None
        self.entries: List[Entry] = []
        self.marker_index = last_index + 1
        self.applied_to_index = 0
        self.applied_to_term = 0
        self.saved_to = last_index
        self.rl = rl

    def _check_marker(self) -> None:
        if self.entries and self.entries[0].index != self.marker_index:
            raise RuntimeError(
                f"marker index {self.marker_index}, "
                f"first index {self.entries[0].index}"
            )

    def get_entries(self, low: int, high: int) -> List[Entry]:
        upper = self.marker_index + len(self.entries)
        if low > high or low < self.marker_index:
            raise RuntimeError(
                f"invalid low {low}, high {high}, marker {self.marker_index}"
            )
        if high > upper:
            raise RuntimeError(f"invalid high {high}, upperBound {upper}")
        return self.entries[low - self.marker_index : high - self.marker_index]

    def get_snapshot_index(self) -> Tuple[int, bool]:
        if self.snapshot is not None:
            return self.snapshot.index, True
        return 0, False

    def get_last_index(self) -> Tuple[int, bool]:
        if self.entries:
            return self.entries[-1].index, True
        return self.get_snapshot_index()

    def get_term(self, index: int) -> Tuple[int, bool]:
        # reference inmemory.go:86-105
        if index > 0 and index == self.applied_to_index:
            if self.applied_to_term == 0:
                raise RuntimeError(f"applied_to_term == 0, index {index}")
            return self.applied_to_term, True
        if index < self.marker_index:
            idx, ok = self.get_snapshot_index()
            if ok and idx == index:
                return self.snapshot.term, True
            return 0, False
        last, ok = self.get_last_index()
        if ok and index <= last:
            return self.entries[index - self.marker_index].term, True
        return 0, False

    def commit_update(self, cu: UpdateCommit) -> None:
        if cu.stable_log_to > 0:
            self.saved_log_to(cu.stable_log_to, cu.stable_log_term)
        if cu.stable_snapshot_to > 0:
            self.saved_snapshot_to(cu.stable_snapshot_to)

    def entries_to_save(self) -> List[Entry]:
        idx = self.saved_to + 1
        if idx - self.marker_index > len(self.entries):
            return []
        return self.entries[idx - self.marker_index :]

    def saved_log_to(self, index: int, term: int) -> None:
        # reference inmemory.go:125-138
        if index < self.marker_index:
            return
        if not self.entries:
            return
        if (
            index > self.entries[-1].index
            or term != self.entries[index - self.marker_index].term
        ):
            return
        self.saved_to = index

    def applied_log_to(self, index: int) -> None:
        # reference inmemory.go:140-166: GC applied prefix
        if index < self.marker_index:
            return
        if not self.entries:
            return
        if index > self.entries[-1].index:
            return
        last_applied = self.entries[index - self.marker_index]
        if last_applied.index != index:
            raise RuntimeError("last_applied.index != index")
        self.applied_to_index = last_applied.index
        self.applied_to_term = last_applied.term
        new_marker = index + 1
        applied = self.entries[: new_marker - self.marker_index]
        self.entries = self.entries[new_marker - self.marker_index :]
        self.marker_index = new_marker
        self._check_marker()
        if self._rate_limited():
            self.rl.decrease(entries_mem_size(applied))

    def saved_snapshot_to(self, index: int) -> None:
        idx, ok = self.get_snapshot_index()
        if ok and idx == index:
            self.snapshot = None

    def merge(self, ents: List[Entry]) -> None:
        # reference inmemory.go:197-227
        if not ents:
            return
        first_new = ents[0].index
        tail_index = self.marker_index + len(self.entries)
        if first_new == tail_index:
            check_entries_to_append(self.entries, ents)
            self.entries.extend(ents)
            if self._rate_limited():
                self.rl.increase(entries_mem_size(ents))
        elif first_new <= self.marker_index:
            self.marker_index = first_new
            self.entries = list(ents)
            self.saved_to = first_new - 1
            if self._rate_limited():
                self.rl.set(entries_mem_size(ents))
        else:
            existing = self.get_entries(self.marker_index, first_new)
            check_entries_to_append(existing, ents)
            self.entries = list(existing) + list(ents)
            self.saved_to = min(self.saved_to, first_new - 1)
            if self._rate_limited():
                self.rl.set(
                    entries_mem_size(ents) + entries_mem_size(existing)
                )
        self._check_marker()

    def restore(self, ss: Snapshot) -> None:
        self.snapshot = ss
        self.marker_index = ss.index + 1
        self.applied_to_index = ss.index
        self.applied_to_term = ss.term
        self.entries = []
        self.saved_to = ss.index
        if self._rate_limited():
            self.rl.set(0)

    def _rate_limited(self) -> bool:
        return self.rl is not None and self.rl.enabled()
