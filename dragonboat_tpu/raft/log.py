"""Two-tier raft entry log: in-memory tail over a persistent body.

Reference: ``internal/raft/logentry.go`` — ``entryLog`` with ``committed`` /
``processed`` watermarks, conflict detection, the ``upToDate`` election check
and the term-guarded ``tryCommit``.
"""
from __future__ import annotations

from typing import List, Optional, Protocol, Tuple

from ..settings import Soft
from ..wire import Entry, Membership, Snapshot, State, UpdateCommit
from .inmemory import InMemory, check_entries_to_append
from .rate import InMemRateLimiter


class CompactedError(Exception):
    """Requested entries no longer in the LogDB due to compaction
    (reference ``logentry.go`` ``ErrCompacted``)."""


class UnavailableError(Exception):
    """Requested entries not available in LogDB
    (reference ``logentry.go`` ``ErrUnavailable``)."""


class SnapshotOutOfDateError(Exception):
    """Reference ``ErrSnapshotOutOfDate``."""


class ILogDB(Protocol):
    """Read view of persistent storage used by the raft core
    (reference ``logentry.go:45-75``)."""

    def get_range(self) -> Tuple[int, int]: ...

    def set_range(self, index: int, length: int) -> None: ...

    def node_state(self) -> Tuple[State, Membership]: ...

    def set_state(self, ps: State) -> None: ...

    def create_snapshot(self, ss: Snapshot) -> None: ...

    def apply_snapshot(self, ss: Snapshot) -> None: ...

    def term(self, index: int) -> int: ...  # raises Compacted/Unavailable

    def entries(self, low: int, high: int, max_size: int) -> List[Entry]: ...

    def snapshot(self) -> Snapshot: ...

    def compact(self, index: int) -> None: ...

    def append(self, entries: List[Entry]) -> None: ...


def limit_entry_size(entries: List[Entry], max_size: int) -> List[Entry]:
    if not entries:
        return entries
    size = entries[0].size()
    limit = 1
    while limit < len(entries):
        size += entries[limit].size()
        if size > max_size:
            break
        limit += 1
    return entries[:limit]


class EntryLog:
    """Reference ``logentry.go:78-399``."""

    __slots__ = ("logdb", "inmem", "committed", "processed")

    def __init__(self, logdb: ILogDB, rl: Optional[InMemRateLimiter] = None):
        first_index, last_index = logdb.get_range()
        self.logdb = logdb
        self.inmem = InMemory(last_index, rl)
        self.committed = first_index - 1
        self.processed = first_index - 1

    def first_index(self) -> int:
        index, ok = self.inmem.get_snapshot_index()
        if ok:
            return index + 1
        index, _ = self.logdb.get_range()
        return index

    def last_index(self) -> int:
        index, ok = self.inmem.get_last_index()
        if ok:
            return index
        _, index = self.logdb.get_range()
        return index

    def _term_entry_range(self) -> Tuple[int, int]:
        return self.first_index() - 1, self.last_index()

    def _entry_range(self) -> Tuple[int, int, bool]:
        if self.inmem.snapshot is not None and not self.inmem.entries:
            return 0, 0, False
        return self.first_index(), self.last_index(), True

    def last_term(self) -> int:
        return self.term(self.last_index())

    def term(self, index: int) -> int:
        """Raises CompactedError/UnavailableError like the reference's
        ``(uint64, error)`` return."""
        first, last = self._term_entry_range()
        if index < first or index > last:
            return 0
        t, ok = self.inmem.get_term(index)
        if ok:
            return t
        return self.logdb.term(index)

    def _check_bound(self, low: int, high: int) -> None:
        if low > high:
            raise RuntimeError(f"input low {low} > high {high}")
        first, last, ok = self._entry_range()
        if not ok or low < first:
            raise CompactedError()
        if high > last + 1:
            raise RuntimeError(
                f"range [{low},{high}) out of bound [{first},{last}]"
            )

    def get_uncommitted_entries(self) -> List[Entry]:
        last = self.inmem.marker_index + len(self.inmem.entries)
        return self._get_entries_from_inmem([], self.committed + 1, last)

    def _get_entries_from_logdb(
        self, low: int, high: int, max_size: int
    ) -> Tuple[List[Entry], bool]:
        if low >= self.inmem.marker_index:
            return [], True
        upper = min(high, self.inmem.marker_index)
        ents = self.logdb.entries(low, upper, max_size)
        if len(ents) > upper - low:
            raise RuntimeError("len(ents) > upper-low")
        return ents, len(ents) == upper - low

    def _get_entries_from_inmem(
        self, ents: List[Entry], low: int, high: int
    ) -> List[Entry]:
        if high <= self.inmem.marker_index:
            return ents
        lower = max(low, self.inmem.marker_index)
        inmem = self.inmem.get_entries(lower, high)
        if inmem:
            if ents:
                check_entries_to_append(ents, inmem)
                return list(ents) + list(inmem)
            return list(inmem)
        return ents

    def get_entries(self, low: int, high: int, max_size: int) -> List[Entry]:
        self._check_bound(low, high)
        if low == high:
            return []
        ents, check_inmem = self._get_entries_from_logdb(low, high, max_size)
        if not check_inmem:
            return ents
        return limit_entry_size(
            self._get_entries_from_inmem(ents, low, high), max_size
        )

    def entries(self, start: int, max_size: int) -> List[Entry]:
        if start > self.last_index():
            return []
        return self.get_entries(start, self.last_index() + 1, max_size)

    def snapshot(self) -> Snapshot:
        if self.inmem.snapshot is not None:
            return self.inmem.snapshot
        return self.logdb.snapshot()

    def first_not_applied_index(self) -> int:
        return max(self.processed + 1, self.first_index())

    def to_apply_index_limit(self) -> int:
        return self.committed + 1

    def has_entries_to_apply(self) -> bool:
        return self.to_apply_index_limit() > self.first_not_applied_index()

    def has_more_entries_to_apply(self, applied_to: int) -> bool:
        return self.committed > applied_to

    def entries_to_apply(self) -> List[Entry]:
        return self.get_entries_to_apply(Soft.max_entry_size)

    def get_entries_to_apply(self, limit: int) -> List[Entry]:
        if self.has_entries_to_apply():
            return self.get_entries(
                self.first_not_applied_index(), self.to_apply_index_limit(), limit
            )
        return []

    def entries_to_save(self) -> List[Entry]:
        return self.inmem.entries_to_save()

    def try_append(self, index: int, ents: List[Entry]) -> bool:
        # reference logentry.go:290-302
        conflict = self.get_conflict_index(ents)
        if conflict != 0:
            if conflict <= self.committed:
                raise RuntimeError(
                    f"entry {conflict} conflicts with committed entry "
                    f"{self.committed}"
                )
            self.append(ents[conflict - index - 1 :])
            return True
        return False

    def append(self, entries: List[Entry]) -> None:
        if not entries:
            return
        if entries[0].index <= self.committed:
            raise RuntimeError(
                f"committed entries being changed, committed {self.committed}, "
                f"first {entries[0].index}"
            )
        self.inmem.merge(entries)

    def get_conflict_index(self, entries: List[Entry]) -> int:
        for e in entries:
            if not self.match_term(e.index, e.term):
                return e.index
        return 0

    def commit_to(self, index: int) -> None:
        if index <= self.committed:
            return
        if index > self.last_index():
            raise RuntimeError(
                f"invalid commit_to index {index}, last_index {self.last_index()}"
            )
        self.committed = index

    def commit_update(self, cu: UpdateCommit) -> None:
        # reference logentry.go:334-360
        self.inmem.commit_update(cu)
        if cu.processed > 0:
            if cu.processed < self.processed or cu.processed > self.committed:
                raise RuntimeError(
                    f"invalid processed {cu.processed}, "
                    f"current {self.processed}, committed {self.committed}"
                )
            self.processed = cu.processed
        if cu.last_applied > 0:
            if cu.last_applied > self.committed or cu.last_applied > self.processed:
                raise RuntimeError(
                    f"invalid last_applied {cu.last_applied}, "
                    f"committed {self.committed}, processed {self.processed}"
                )
            self.inmem.applied_log_to(cu.last_applied)

    def match_term(self, index: int, term: int) -> bool:
        try:
            lt = self.term(index)
        except (CompactedError, UnavailableError):
            return False
        return lt == term

    def up_to_date(self, index: int, term: int) -> bool:
        # reference logentry.go:364-376 (raft paper §5.4.1)
        last_term = self.term(self.last_index())
        if term >= last_term:
            if term > last_term:
                return True
            return index >= self.last_index()
        return False

    def try_commit(self, index: int, term: int) -> bool:
        # reference logentry.go:378-392
        if index <= self.committed:
            return False
        try:
            lterm = self.term(index)
        except CompactedError:
            lterm = 0
        if index > self.committed and lterm == term:
            self.commit_to(index)
            return True
        return False

    def restore(self, ss: Snapshot) -> None:
        self.inmem.restore(ss)
        self.committed = ss.index
        self.processed = ss.index
