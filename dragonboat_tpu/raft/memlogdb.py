"""In-memory ILogDB used by conformance tests and single-process benches.

Plays the role of the reference's etcd-test ``TestLogDB`` helper
(``internal/raft/logdb_etcd_test.go``): a plain list-backed log with state,
snapshot and compaction — the minimal persistent-view contract the raft core
needs (reference ``internal/raft/logentry.go:45-75``).  The backing layout
mirrors etcd's MemoryStorage: ``_ents[0]`` is a dummy marker entry carrying
the term of the compacted prefix boundary.
"""
from __future__ import annotations

from typing import List, Tuple

from ..wire import Entry, Membership, Snapshot, State
from .log import CompactedError, UnavailableError


class InMemLogDB:
    """Array-backed ILogDB implementation."""

    __slots__ = (
        "_ents",
        "marker",
        "state",
        "membership",
        "snapshot_record",
        "max_index",
    )

    def __init__(self) -> None:
        self._ents: List[Entry] = [Entry(index=0, term=0)]
        self.marker = 0
        self.state = State()
        self.membership = Membership()
        self.snapshot_record = Snapshot()
        self.max_index = 0

    def get_range(self) -> Tuple[int, int]:
        return self.marker + 1, self.max_index

    def set_range(self, index: int, length: int) -> None:
        if length == 0:
            return
        end = index + length - 1
        if end > self.max_index:
            self.max_index = end

    def node_state(self) -> Tuple[State, Membership]:
        return self.state, self.membership

    def set_state(self, ps: State) -> None:
        self.state = ps

    def create_snapshot(self, ss: Snapshot) -> None:
        self.snapshot_record = ss

    def apply_snapshot(self, ss: Snapshot) -> None:
        self.snapshot_record = ss
        self.membership = ss.membership
        self.marker = ss.index
        self._ents = [Entry(index=ss.index, term=ss.term)]
        self.max_index = ss.index

    def term(self, index: int) -> int:
        if index < self.marker:
            raise CompactedError()
        if index > self.max_index:
            raise UnavailableError()
        return self._ents[index - self.marker].term

    def entries(self, low: int, high: int, max_size: int) -> List[Entry]:
        if low <= self.marker:
            raise CompactedError()
        if high > self.max_index + 1:
            raise UnavailableError()
        ents = self._ents[low - self.marker : high - self.marker]
        out: List[Entry] = []
        size = 0
        for e in ents:
            size += e.size()
            if out and size > max_size:
                break
            out.append(e)
        return out

    def snapshot(self) -> Snapshot:
        return self.snapshot_record

    def compact(self, index: int) -> None:
        if index <= self.marker:
            raise CompactedError()
        if index > self.max_index:
            raise UnavailableError()
        self._ents = self._ents[index - self.marker :]
        self.marker = index

    def append(self, entries: List[Entry]) -> None:
        if not entries:
            return
        ents = [e for e in entries if e.index > self.marker]
        if not ents:
            return
        first = ents[0].index
        if first > self.marker + len(self._ents):
            raise RuntimeError(
                f"hole in log: marker {self.marker}, have {len(self._ents)}, "
                f"appending {first}"
            )
        self._ents = self._ents[: first - self.marker] + list(ents)
        self.max_index = max(self.max_index, self._ents[-1].index)


TestLogDB = InMemLogDB
