"""Peer — the iterative etcd-style API shim over the raft core.

Reference: ``internal/raft/peer.go`` — inputs become messages, output is an
``Update`` (entries to save, committed entries to apply, messages to send,
snapshot, ready-to-reads); ``commit(ud)`` acknowledges processing.  The node
runtime and the batched quorum engine both drive replicas exclusively through
this interface.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from ..config import Config
from ..wire import (
    NO_LEADER,
    ConfigChange,
    Entry,
    EntryType,
    Message,
    MessageType,
    Snapshot,
    State,
    SystemCtx,
    Update,
    UpdateCommit,
    is_empty_snapshot,
    is_empty_state,
    is_state_equal,
)
from ..wire.codec import encode_config_change
from .log import ILogDB
from .raft import Raft, is_local_message

MT = MessageType


@dataclass(slots=True)
class PeerAddress:
    node_id: int
    address: str


def is_response_message_type(t: MessageType) -> bool:
    return t in (
        MT.REPLICATE_RESP,
        MT.REQUEST_VOTE_RESP,
        MT.HEARTBEAT_RESP,
        MT.READ_INDEX_RESP,
        MT.UNREACHABLE,
        MT.SNAPSHOT_STATUS,
        MT.LEADER_TRANSFER,
        MT.RATE_LIMIT,
    )


def check_launch_request(
    config: Config, addresses: List[PeerAddress], initial: bool, new_node: bool
) -> None:
    if config.node_id == 0:
        raise ValueError("config.node_id must not be zero")
    if initial and new_node and len(addresses) == 0:
        raise ValueError("addresses must be specified")
    unique = {a.address for a in addresses}
    if len(unique) != len(addresses):
        raise ValueError(f"duplicated address found {addresses}")


def _bootstrap(r: Raft, addresses: List[PeerAddress]) -> None:
    # reference peer.go:378-408: synthesize term-1 AddNode entries
    addresses = sorted(addresses, key=lambda a: a.node_id)
    ents = []
    for i, peer in enumerate(addresses):
        cc = ConfigChange(
            type=cc_add_node_type(), node_id=peer.node_id,
            initialize=True, address=peer.address,
        )
        ents.append(
            Entry(
                type=EntryType.CONFIG_CHANGE,
                term=1,
                index=i + 1,
                cmd=encode_config_change(cc),
            )
        )
    r.log.append(ents)
    r.log.committed = len(ents)
    for peer in addresses:
        r.add_node(peer.node_id)


def cc_add_node_type():
    from ..wire import ConfigChangeType

    return ConfigChangeType.ADD_NODE


def validate_update(ud: Update) -> None:
    if ud.state.commit > 0 and ud.committed_entries:
        last_index = ud.committed_entries[-1].index
        if last_index > ud.state.commit:
            raise RuntimeError(
                f"applying not committed entry: {ud.state.commit}, {last_index}"
            )
    if ud.committed_entries and ud.entries_to_save:
        last_apply = ud.committed_entries[-1].index
        last_save = ud.entries_to_save[-1].index
        if last_apply > last_save:
            raise RuntimeError(
                f"applying not saved entry: {last_apply}, {last_save}"
            )


def set_fast_apply(ud: Update) -> Update:
    # reference peer.go setFastApply: apply can overlap save unless the
    # committed entries include entries not yet persisted
    ud.fast_apply = True
    if not is_empty_snapshot(ud.snapshot):
        ud.fast_apply = False
    if ud.fast_apply:
        if ud.committed_entries and ud.entries_to_save:
            last_apply = ud.committed_entries[-1].index
            last_save = ud.entries_to_save[-1].index
            first_save = ud.entries_to_save[0].index
            if first_save <= last_apply <= last_save:
                ud.fast_apply = False
    return ud


def get_update_commit(ud: Update) -> UpdateCommit:
    uc = UpdateCommit(
        ready_to_read=len(ud.ready_to_reads), last_applied=ud.last_applied
    )
    if ud.committed_entries:
        uc.processed = ud.committed_entries[-1].index
    if ud.entries_to_save:
        last = ud.entries_to_save[-1]
        uc.stable_log_to, uc.stable_log_term = last.index, last.term
    if not is_empty_snapshot(ud.snapshot):
        uc.stable_snapshot_to = ud.snapshot.index
        uc.processed = max(uc.processed, uc.stable_snapshot_to)
    return uc


class Peer:
    """Reference ``peer.go:55-60``."""

    __slots__ = ("raft", "prev_state")

    def __init__(self, raft: Raft):
        self.raft = raft
        self.prev_state = State()

    @staticmethod
    def launch(
        config: Config,
        logdb: ILogDB,
        events,
        addresses: List[PeerAddress],
        initial: bool,
        new_node: bool,
        seed: Optional[int] = None,
    ) -> "Peer":
        # reference peer.go:62-85
        check_launch_request(config, addresses, initial, new_node)
        r = Raft(config, logdb, seed=seed)
        p = Peer(r)
        r.events = events
        _, last_index = logdb.get_range()
        if new_node and not config.is_observer and not config.is_witness:
            r.become_follower(1, NO_LEADER)
        if initial and new_node:
            _bootstrap(r, addresses)
        if last_index == 0:
            p.prev_state = State()
        else:
            p.prev_state = r.raft_state()
        return p

    def tick(self) -> None:
        self.raft.handle(Message(type=MT.LOCAL_TICK, reject=False))

    def campaign(self) -> None:
        """Start an election immediately (etcd ``raft.Campaign`` — the
        same local ELECTION message ``raft.go:395`` injects when the
        randomized election timeout fires)."""
        self.raft.handle(Message(type=MT.ELECTION, from_=self.raft.node_id))

    def quiesced_tick(self) -> None:
        self.raft.handle(Message(type=MT.LOCAL_TICK, reject=True))

    def request_leader_transfer(self, target: int) -> None:
        self.raft.handle(
            Message(
                type=MT.LEADER_TRANSFER,
                to=self.raft.node_id,
                from_=target,
                hint=target,
            )
        )

    def propose_entries(self, ents: List[Entry]) -> None:
        self.raft.handle(
            Message(type=MT.PROPOSE, from_=self.raft.node_id, entries=ents)
        )

    def propose_config_change(self, cc: ConfigChange, key: int) -> None:
        data = encode_config_change(cc)
        self.raft.handle(
            Message(
                type=MT.PROPOSE,
                entries=[Entry(type=EntryType.CONFIG_CHANGE, cmd=data, key=key)],
            )
        )

    def apply_config_change(self, cc: ConfigChange) -> None:
        if cc.node_id == NO_LEADER:
            self.raft.clear_pending_config_change()
            return
        self.raft.handle(
            Message(
                type=MT.CONFIG_CHANGE_EVENT,
                reject=False,
                hint=cc.node_id,
                hint_high=int(cc.type),
            )
        )

    def reject_config_change(self) -> None:
        self.raft.handle(Message(type=MT.CONFIG_CHANGE_EVENT, reject=True))

    def restore_remotes(self, ss: Snapshot) -> None:
        self.raft.handle(Message(type=MT.SNAPSHOT_RECEIVED, snapshot=ss))

    def report_unreachable_node(self, node_id: int) -> None:
        self.raft.handle(Message(type=MT.UNREACHABLE, from_=node_id))

    def report_snapshot_status(self, node_id: int, reject: bool) -> None:
        self.raft.handle(
            Message(type=MT.SNAPSHOT_STATUS, from_=node_id, reject=reject)
        )

    def handle(self, m: Message) -> None:
        # reference peer.go:186-199: drop responses from unknown nodes
        if is_local_message(m.type):
            raise RuntimeError("local message sent to Step")
        known = (
            m.from_ in self.raft.remotes
            or m.from_ in self.raft.observers
            or m.from_ in self.raft.witnesses
        )
        if known or not is_response_message_type(m.type):
            self.raft.handle(m)

    def read_index(self, ctx: SystemCtx) -> None:
        self.raft.handle(
            Message(type=MT.READ_INDEX, hint=ctx.low, hint_high=ctx.high)
        )

    def notify_raft_last_applied(self, last_applied: int) -> None:
        self.raft.set_applied(last_applied)

    def has_entry_to_apply(self) -> bool:
        return self.raft.log.has_entries_to_apply()

    def rate_limited(self) -> bool:
        return self.raft.rl.rate_limited()

    def has_update(self, more_entries_to_apply: bool) -> bool:
        # reference peer.go:253-280
        r = self.raft
        pst = r.raft_state()
        if not is_empty_state(pst) and not is_state_equal(pst, self.prev_state):
            return True
        if r.log.inmem.snapshot is not None and not r.log.inmem.snapshot.is_empty():
            return True
        if r.msgs:
            return True
        if r.log.entries_to_save():
            return True
        if more_entries_to_apply and r.log.has_entries_to_apply():
            return True
        if r.ready_to_read:
            return True
        if r.dropped_entries or r.dropped_read_indexes:
            return True
        return False

    def get_update(self, more_to_apply: bool, last_applied: int) -> Update:
        ud = self._get_update(more_to_apply, last_applied)
        validate_update(ud)
        ud = set_fast_apply(ud)
        ud.update_commit = get_update_commit(ud)
        return ud

    def _get_update(self, more_entries_to_apply: bool, last_applied: int) -> Update:
        r = self.raft
        ud = Update(
            cluster_id=r.cluster_id,
            node_id=r.node_id,
            entries_to_save=r.log.entries_to_save(),
            messages=r.msgs,
            last_applied=last_applied,
            fast_apply=True,
        )
        if more_entries_to_apply:
            ud.committed_entries = r.log.entries_to_apply()
        if ud.committed_entries:
            last_index = ud.committed_entries[-1].index
            ud.more_committed_entries = r.log.has_more_entries_to_apply(last_index)
        pst = r.raft_state()
        if not is_state_equal(pst, self.prev_state):
            ud.state = pst
        if r.log.inmem.snapshot is not None:
            ud.snapshot = r.log.inmem.snapshot
        if r.ready_to_read:
            ud.ready_to_reads = r.ready_to_read
        if r.dropped_entries:
            ud.dropped_entries = r.dropped_entries
        if r.dropped_read_indexes:
            ud.dropped_read_indexes = r.dropped_read_indexes
        return ud

    def commit(self, ud: Update) -> None:
        # reference peer.go:282-295
        r = self.raft
        r.msgs = []
        r.dropped_entries = []
        r.dropped_read_indexes = []
        if not is_empty_state(ud.state):
            self.prev_state = ud.state
        if ud.update_commit.ready_to_read > 0:
            r.clear_ready_to_read()
        r.log.commit_update(ud.update_commit)

    def local_status(self):
        from dataclasses import dataclass as _dc

        r = self.raft
        return {
            "cluster_id": r.cluster_id,
            "node_id": r.node_id,
            "leader_id": r.leader_id,
            "state": r.state,
            "is_leader": r.is_leader(),
        }
