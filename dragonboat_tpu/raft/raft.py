"""The scalar raft protocol state machine — the correctness oracle.

Reference: ``internal/raft/raft.go`` — full Raft with leader election,
replication flow control, membership change, snapshot install, ReadIndex,
leader transfer, observers, witnesses, quiesce and in-memory-log rate
limiting, driven through one message-typed ``handle`` entry point dispatching
via a ``[state][message_type]`` handler table (reference ``raft.go:2034-2102``).

Design deltas from the reference (TPU-first build):

* **Determinism.** The reference draws randomized election timeouts from a
  global locked PRNG (``raft.go:633-636``) and iterates Go maps in random
  order inside ``tryCommit``/broadcasts.  Here every node owns a seeded
  ``random.Random`` and all peer iteration is in sorted-id order, so a run is
  a pure function of (seed, message sequence).  This is what makes the
  scalar-vs-batched differential tests (bit-identical commitIndex) meaningful.

* **Batched-engine contract.**  The dense per-tick work — vote tallying
  (``handleVoteResp`` reference :1062-1080), commit advancement over sorted
  match indexes (``tryCommit`` reference :861-909), CheckQuorum scans
  (``leaderHasQuorum`` :380-390) and tick counters — is factored so the
  :mod:`dragonboat_tpu.ops` kernels can compute the same outputs for
  ``(nGroups, nPeers)`` tensors; see ``ops/state.py`` for the mapping.
"""
from __future__ import annotations

import collections as _collections
import enum
import random as _random
import time as _time
from typing import Callable, Dict, List, Optional, Tuple

from .. import logger
from ..config import Config
from ..settings import Soft
from ..wire import (
    NO_LEADER,
    NO_NODE,
    ConfigChangeType,
    Entry,
    EntryType,
    Message,
    MessageType,
    ReadyToRead,
    Snapshot,
    State,
    SystemCtx,
    entries_size,
)
from ..lease import LeaderLease
from .hier import FarReadBatcher, HierPlane, sub_quorum_size
from .log import CompactedError, EntryLog, ILogDB, UnavailableError
from .rate import InMemRateLimiter
from .readindex import ReadIndex
from .remote import Remote

plog = logger.get_logger("raft")

MT = MessageType


class RaftState(enum.IntEnum):
    # reference raft.go:64-71
    FOLLOWER = 0
    CANDIDATE = 1
    LEADER = 2
    OBSERVER = 3
    WITNESS = 4


NUM_STATES = 5

# an Election message with reject=True requests a quiesced tick
# (see node runtime); LocalTick reject=True likewise (reference node.go:933)


def is_request_message(t: MessageType) -> bool:
    return t in (MT.PROPOSE, MT.READ_INDEX)


def is_leader_message(t: MessageType) -> bool:
    return t in (
        MT.REPLICATE,
        MT.INSTALL_SNAPSHOT,
        MT.HEARTBEAT,
        MT.TIMEOUT_NOW,
        MT.READ_INDEX_RESP,
    )


def is_local_message(t: MessageType) -> bool:
    return t in (
        MT.LOCAL_TICK,
        MT.ELECTION,
        MT.LEADER_HEARTBEAT,
        MT.CHECK_QUORUM,
        MT.SNAPSHOT_STATUS,
        MT.UNREACHABLE,
        MT.RATE_LIMIT,
        MT.BATCHED_READ_INDEX,
    )


def count_config_change(entries: List[Entry]) -> int:
    return sum(1 for e in entries if e.type == EntryType.CONFIG_CHANGE)


def make_metadata_entries(entries: List[Entry]) -> List[Entry]:
    # witnesses replicate metadata-only entries (reference raft.go:744-758)
    out = []
    for ent in entries:
        if ent.type != EntryType.CONFIG_CHANGE:
            out.append(Entry(type=EntryType.METADATA, index=ent.index, term=ent.term))
        else:
            out.append(ent)
    return out


def make_witness_snapshot(ss: Snapshot) -> Snapshot:
    # reference raft.go:700-708
    from dataclasses import replace

    return replace(ss, filepath="", file_size=0, files=[], witness=True, dummy=False)


class Raft:
    """One raft replica's protocol state (reference ``raft.go:198-234``)."""

    def __init__(self, c: Config, logdb: ILogDB, seed: Optional[int] = None):
        c.validate()
        if logdb is None:
            raise ValueError("logdb is nil")
        self.cluster_id = c.cluster_id
        self.node_id = c.node_id
        self.leader_id = NO_LEADER
        self.term = 0
        self.vote = NO_NODE
        self.applied = 0
        self.rl = InMemRateLimiter(c.max_in_mem_log_size)
        self.log = EntryLog(logdb, self.rl)
        self.remotes: Dict[int, Remote] = {}
        self.observers: Dict[int, Remote] = {}
        self.witnesses: Dict[int, Remote] = {}
        self.state = RaftState.FOLLOWER
        self.votes: Dict[int, bool] = {}
        self.msgs: List[Message] = []
        self.leader_transfer_target = NO_NODE
        self.is_leader_transfer_target = False
        self.pending_config_change = False
        self.read_index = ReadIndex()
        # leader-lease read plane (ISSUE 10, Config.read_lease): None is
        # the structural latch — every hook below gates on `is not None`,
        # so lease-off request paths are bit-identical to the pre-lease
        # build (the _read_plane_used precedent).  Constructed before the
        # become_* calls at the bottom of __init__ (reset() touches it).
        self.lease = (
            LeaderLease(c.election_rtt) if c.read_lease else None
        )
        # replication attribution plane (obs/replattr.py, ISSUE 14): set
        # by the node when request tracing is on; None is the structural
        # latch — every hook below gates on `is not None`, so trace-off
        # request paths stay bit-identical (the lease/offload precedent)
        self.replattr = None
        # hierarchical commit plane (raft/hier.py, ISSUE 18,
        # Config.hier_commit): None is the structural latch — every hook
        # below gates on `is not None`, so hier-off request paths stay
        # bit-identical (the lease/replattr precedent).  The plane holds
        # the domain map plus the coupled sub-quorum commit / vote
        # intersection rules; the far-read batcher rides beside it and
        # activates only on followers whose domain differs from the
        # leader's.
        self.hier = (
            HierPlane(c.hier_domains, c.node_id) if c.hier_commit else None
        )
        self.far_reads = FarReadBatcher() if c.hier_commit else None
        # whether the most recent commit advancement closed via the
        # near-domain sub-quorum rather than the classic quorum — read
        # by _note_commit so replication attribution counts the closer
        # against the rule that actually closed the commit
        self._commit_via_sub = False
        self.ready_to_read: List[ReadyToRead] = []
        self.dropped_entries: List[Entry] = []
        self.dropped_read_indexes: List[SystemCtx] = []
        self.quiesce = False
        self.check_quorum = c.check_quorum
        self.tick_count = 0
        self.election_tick = 0
        self.heartbeat_tick = 0
        self.election_timeout = c.election_rtt
        self.heartbeat_timeout = c.heartbeat_rtt
        self.randomized_election_timeout = 0
        self.matched: List[int] = []
        self.events = None  # IRaftEventListener
        # TPU quorum plugin (tpuquorum.TpuQuorumCoordinator); None = pure
        # scalar path.  When set, ack/vote tallying and commit advancement
        # are staged to the batched device engine instead of computed here
        self.offload = None
        # True when the device quorum engine owns the per-tick FIRING
        # decisions (election due / heartbeat due / check-quorum window);
        # scalar clocks still advance (vote-lease checks, transfer abort)
        # but the local fire sites below are suppressed — the coordinator
        # applies the device flags through the same handlers instead
        self.device_ticks = False
        # True when the device engine's read plane batches ReadIndex
        # confirmations (kernels.read_confirm): pending-read bookkeeping
        # (queue, hint rebroadcast) stays HERE — the scalar path remains
        # the fallback and the releaser — but heartbeat-echo quorum
        # counting moves to the per-round fused dispatch; the coordinator
        # routes confirmed ctxs back through ``read_index.release`` with
        # leader/term guards intact (node._apply_offload_effects)
        self.device_reads = False
        # True when the group's state machine is device-resident (devsm,
        # ISSUE 11): the leader offloads every appended application
        # entry's (index, payload) to the coordinator's DevKVPlane at
        # append time, so the in-program apply fold has the op buffered
        # before its commit can land.  Set by NodeHost registration
        # (Config.device_kv on the tpu engine); False keeps append_entries
        # bit-identical.
        self.device_kv = False
        # first index of the current leadership term (set at promotion)
        self.term_start_index = 0
        # ring buffer of recent election-related events (campaigns, vote
        # grants/rejections, state transitions) — near-free and invaluable
        # when diagnosing wedged elections at 4k+ group scale
        self.vote_trace: _collections.deque = _collections.deque(maxlen=24)
        # elapsed election clock stashed across a REQUEST_VOTE step-down
        # (consumed by handle_node_request_vote's log-behind restore)
        self._stepdown_etick: Optional[int] = None
        self.has_not_applied_config_change: Optional[Callable[[], bool]] = None
        # deterministic, seedable randomness (design delta; see module docstring)
        self.prng = _random.Random(
            seed if seed is not None else (c.cluster_id << 32) ^ c.node_id
        )

        st, members = logdb.node_state()
        for p in members.addresses:
            self.remotes[p] = Remote(next=1)
        for p in members.observers:
            self.observers[p] = Remote(next=1)
        for p in members.witnesses:
            self.witnesses[p] = Remote(next=1)
        self.reset_match_value_array()
        if not st.is_empty():
            self.load_state(st)
        if c.is_observer:
            self.state = RaftState.OBSERVER
            self.become_observer(self.term, NO_LEADER)
        elif c.is_witness:
            self.state = RaftState.WITNESS
            self.become_witness(self.term, NO_LEADER)
        else:
            self.become_follower(self.term, NO_LEADER)

    # ------------------------------------------------------------------
    # introspection helpers
    # ------------------------------------------------------------------

    def describe(self) -> str:
        li = self.log.last_index()
        try:
            t = self.log.term(li)
        except CompactedError:
            t = 0
        return (
            f"[f:{self.log.first_index()},l:{li},t:{t},"
            f"c:{self.log.committed},a:{self.log.processed}] "
            f"[{self.cluster_id}:{self.node_id}] t{self.term}"
        )

    def is_leader(self) -> bool:
        return self.state == RaftState.LEADER

    def is_candidate(self) -> bool:
        return self.state == RaftState.CANDIDATE

    def is_follower(self) -> bool:
        return self.state == RaftState.FOLLOWER

    def is_observer(self) -> bool:
        return self.state == RaftState.OBSERVER

    def is_witness(self) -> bool:
        return self.state == RaftState.WITNESS

    def must_be_leader(self) -> None:
        if not self.is_leader():
            raise RuntimeError(f"{self.describe()} is not a leader")

    def set_leader_id(self, leader_id: int) -> None:
        self.leader_id = leader_id
        if self.events is not None:
            self.events.leader_updated(
                self.cluster_id, self.node_id, leader_id, self.term
            )

    def set_applied(self, applied: int) -> None:
        self.applied = applied

    def get_applied(self) -> int:
        return self.applied

    def leader_transfering(self) -> bool:
        return self.leader_transfer_target != NO_NODE and self.is_leader()

    def abort_leader_transfer(self) -> None:
        self.leader_transfer_target = NO_NODE

    def num_voting_members(self) -> int:
        return len(self.remotes) + len(self.witnesses)

    def quorum(self) -> int:
        return self.num_voting_members() // 2 + 1

    def is_single_node_quorum(self) -> bool:
        return self.quorum() == 1

    def leader_has_quorum(self) -> bool:
        # reference raft.go:380-390
        c = 0
        for nid, member in self.voting_members().items():
            if nid == self.node_id or member.is_active():
                c += 1
                member.set_not_active()
        return c >= self.quorum()

    def nodes(self) -> List[int]:
        return sorted(
            list(self.remotes) + list(self.observers) + list(self.witnesses)
        )

    def nodes_sorted(self) -> List[int]:
        return self.nodes()

    def voting_members(self) -> Dict[int, Remote]:
        out = dict(self.remotes)
        out.update(self.witnesses)
        return out

    def raft_state(self) -> State:
        return State(term=self.term, vote=self.vote, commit=self.log.committed)

    def load_state(self, st: State) -> None:
        if st.commit < self.log.committed or st.commit > self.log.last_index():
            raise RuntimeError(
                f"{self.describe()} out of range state, commit {st.commit}, "
                f"range [{self.log.committed},{self.log.last_index()}]"
            )
        self.log.committed = st.commit
        self.term = st.term
        self.vote = st.vote

    def reset_match_value_array(self) -> None:
        self.matched = [0] * self.num_voting_members()

    # ------------------------------------------------------------------
    # snapshot restore
    # ------------------------------------------------------------------

    def restore(self, ss: Snapshot) -> bool:
        # reference raft.go:441-480
        if ss.index <= self.log.committed:
            return False
        if not self.is_observer():
            for nid in ss.membership.observers:
                if nid == self.node_id:
                    raise RuntimeError(
                        f"{self.describe()} converting to observer, {ss.index}"
                    )
        if not self.is_witness():
            for nid in ss.membership.witnesses:
                if nid == self.node_id:
                    raise RuntimeError(
                        f"{self.describe()} converting to witness, {ss.index}"
                    )
        # p52 of the raft thesis
        if self.log.match_term(ss.index, ss.term):
            # a snapshot at index X implies X has been committed
            self.log.commit_to(ss.index)
            return False
        self.log.restore(ss)
        return True

    def restore_remotes(self, ss: Snapshot) -> None:
        # reference raft.go:482-530
        self.remotes = {}
        for nid in sorted(ss.membership.addresses):
            if nid == self.node_id and self.is_observer():
                self.become_follower(self.term, self.leader_id)
            if nid in self.witnesses:
                raise RuntimeError("witness could not promote to full member")
            match = 0
            next_ = self.log.last_index() + 1
            if nid == self.node_id:
                match = next_ - 1
            self.set_remote(nid, match, next_)
        if self.self_removed() and self.is_leader():
            self.become_follower(self.term, NO_LEADER)
        self.observers = {}
        for nid in sorted(ss.membership.observers):
            match = 0
            next_ = self.log.last_index() + 1
            if nid == self.node_id:
                match = next_ - 1
            self.set_observer(nid, match, next_)
        self.witnesses = {}
        for nid in sorted(ss.membership.witnesses):
            match = 0
            next_ = self.log.last_index() + 1
            if nid == self.node_id:
                match = next_ - 1
            self.set_witness(nid, match, next_)
        self.reset_match_value_array()
        self.lease_membership_changed()
        if self.offload is not None:
            self.offload.membership_changed(self.cluster_id)

    def lease_membership_changed(self) -> None:
        """Invalidation matrix: membership changed — the quorum the lease
        bases were tallied against no longer exists.  Re-arm from fresh
        acks against the new membership.  A PARTIAL reset: same-term
        acks still in flight must keep consuming the sends that elicited
        them (see ``LeaderLease.membership_changed``)."""
        if self.lease is not None:
            self.lease.membership_changed()

    # ------------------------------------------------------------------
    # tick
    # ------------------------------------------------------------------

    def time_for_election(self) -> bool:
        return self.election_tick >= self.randomized_election_timeout

    def time_for_heartbeat(self) -> bool:
        return self.heartbeat_tick >= self.heartbeat_timeout

    def time_for_check_quorum(self) -> bool:
        return self.election_tick >= self.election_timeout

    def time_to_abort_leader_transfer(self) -> bool:
        return self.leader_transfering() and self.election_tick >= self.election_timeout

    def time_for_rate_limit_check(self) -> bool:
        return self.tick_count % self.election_timeout == 0

    def tick(self) -> None:
        # reference raft.go:553-566
        self.quiesce = False
        self.tick_count += 1
        if self.is_leader():
            self.leader_tick()
        else:
            self.non_leader_tick()

    def non_leader_tick(self) -> None:
        # reference raft.go:568-592
        if self.is_leader():
            raise RuntimeError("non_leader_tick called on leader")
        self.election_tick += 1
        if self.time_for_rate_limit_check():
            if self.rl.enabled():
                self.rl.tick()
                self.send_rate_limit_message()
        # section 4.2.1 of the raft thesis: non-voting members and witnesses
        # do not participate in elections
        if self.is_observer() or self.is_witness():
            return
        # 6th paragraph section 5.2 of the raft paper
        if (
            not self.device_ticks
            and not self.self_removed()
            and self.time_for_election()
        ):
            self.election_tick = 0
            self.handle(Message(from_=self.node_id, type=MT.ELECTION))

    def leader_tick(self) -> None:
        # reference raft.go:594-623
        self.must_be_leader()
        self.election_tick += 1
        if self.time_for_rate_limit_check():
            if self.rl.enabled():
                self.rl.tick()
        time_to_abort = self.time_to_abort_leader_transfer()
        if self.time_for_check_quorum():
            self.election_tick = 0
            if self.check_quorum and not self.device_ticks:
                self.handle(Message(from_=self.node_id, type=MT.CHECK_QUORUM))
        if time_to_abort:
            self.abort_leader_transfer()
        self.heartbeat_tick += 1
        if not self.device_ticks and self.time_for_heartbeat():
            self.heartbeat_tick = 0
            self.handle(Message(from_=self.node_id, type=MT.LEADER_HEARTBEAT))

    def quiesced_tick(self) -> None:
        if not self.quiesce:
            self.quiesce = True
        self.election_tick += 1

    def set_randomized_election_timeout(self) -> None:
        # deterministic seeded PRNG (design delta; reference raft.go:633-636)
        self.randomized_election_timeout = (
            self.election_timeout + self.prng.randrange(self.election_timeout)
        )
        if self.offload is not None and self.device_ticks:
            # keep the device row's election period in step so split votes
            # get the randomized backoff the raft paper relies on
            self.offload.set_randomized_timeout(
                self.cluster_id, self.randomized_election_timeout
            )

    # ------------------------------------------------------------------
    # send and broadcast
    # ------------------------------------------------------------------

    def finalize_message_term(self, m: Message) -> Message:
        # reference raft.go:641-652
        if m.term == 0 and m.type == MT.REQUEST_VOTE:
            raise RuntimeError("sending RequestVote with 0 term")
        if m.term > 0 and m.type != MT.REQUEST_VOTE:
            raise RuntimeError(f"term unexpectedly set for message type {m.type}")
        if not is_request_message(m.type):
            m.term = self.term
        return m

    def send(self, m: Message) -> None:
        m.from_ = self.node_id
        # stamp the group id so the runtime can route between hosts
        # (reference raft.go send path sets ClusterId on every message)
        m.cluster_id = self.cluster_id
        m = self.finalize_message_term(m)
        self.msgs.append(m)

    def send_rate_limit_message(self) -> None:
        # reference raft.go:663-686
        if self.is_leader():
            raise RuntimeError("leader called send_rate_limit_message")
        if self.leader_id == NO_LEADER:
            return
        if not self.rl.enabled():
            return
        mv = 0
        if self.rl.rate_limited():
            inmem_sz = self.rl.get()
            not_committed = entries_size(self.log.get_uncommitted_entries())
            mv = max(inmem_sz - not_committed, 0)
        self.send(Message(type=MT.RATE_LIMIT, to=self.leader_id, hint=mv))

    def make_install_snapshot_message(self, to: int, m: Message) -> int:
        # reference raft.go:688-698
        m.to = to
        m.type = MT.INSTALL_SNAPSHOT
        snapshot = self.log.snapshot()
        if snapshot.is_empty():
            raise RuntimeError(f"{self.describe()} got an empty snapshot")
        if to in self.witnesses:
            snapshot = make_witness_snapshot(snapshot)
        m.snapshot = snapshot
        return snapshot.index

    def make_replicate_message(
        self, to: int, next_: int, max_size: int
    ) -> Message:
        # raises CompactedError when log is unavailable (then send snapshot)
        term = self.log.term(next_ - 1)
        entries = self.log.entries(next_, max_size)
        if entries:
            last_index = entries[-1].index
            expected = next_ - 1 + len(entries)
            if last_index != expected:
                raise RuntimeError(
                    f"expected last index {expected}, got {last_index}"
                )
        if to in self.witnesses:
            entries = make_metadata_entries(entries)
        return Message(
            to=to,
            type=MT.REPLICATE,
            log_index=next_ - 1,
            log_term=term,
            entries=entries,
            commit=self.log.committed,
        )

    def send_replicate_message(self, to: int) -> None:
        # reference raft.go:760-794
        rp = self.remotes.get(to) or self.observers.get(to) or self.witnesses.get(to)
        if rp is None:
            raise RuntimeError(f"{self.describe()} failed to get remote {to}")
        if rp.is_paused():
            return
        try:
            m = self.make_replicate_message(to, rp.next, Soft.max_entry_size)
        except (CompactedError, UnavailableError):
            # log not available due to compaction, send snapshot
            if not rp.is_active():
                return
            m = Message()
            self.make_install_snapshot_message(to, m)
            rp.become_snapshot(m.snapshot.index)
        else:
            if m.entries:
                rp.progress(m.entries[-1].index)
        self.send(m)

    def broadcast_replicate_message(self) -> None:
        if not self.is_leader():
            raise RuntimeError("non-leader broadcasting replication msg")
        for nid in self.nodes():
            if nid != self.node_id:
                self.send_replicate_message(nid)

    def send_heartbeat_message(self, to: int, hint: SystemCtx, match: int) -> None:
        commit = min(match, self.log.committed)
        self.send(
            Message(
                to=to,
                type=MT.HEARTBEAT,
                commit=commit,
                hint=hint.low,
                hint_high=hint.high,
            )
        )

    def broadcast_heartbeat_message(self) -> None:
        # p72 of the raft thesis: heartbeats carry ReadIndex confirmation hints
        self.must_be_leader()
        if self.read_index.has_pending_request():
            self.broadcast_heartbeat_message_with_hint(self.read_index.peep_ctx())
        else:
            self.broadcast_heartbeat_message_with_hint(SystemCtx())

    def broadcast_heartbeat_message_with_hint(self, ctx: SystemCtx) -> None:
        # sorted iteration for determinism (reference iterates Go maps)
        vm = self.voting_members()
        for nid in sorted(vm):
            if nid != self.node_id:
                self.send_heartbeat_message(nid, ctx, vm[nid].match)
        if self.lease is not None:
            # lease bookkeeping: a quorum of acks to heartbeats SENT at
            # this tick extends the lease to tick + duration (lease.py
            # validity rule; the send tick, not the ack tick, is the
            # conservative basis)
            self.lease.record_send(
                self.tick_count,
                (nid for nid in vm if nid != self.node_id),
            )
        if ctx.is_empty():
            for nid in sorted(self.observers):
                self.send_heartbeat_message(nid, SystemCtx(), self.observers[nid].match)

    def send_timeout_now_message(self, node_id: int) -> None:
        self.send(Message(type=MT.TIMEOUT_NOW, to=node_id))

    # ------------------------------------------------------------------
    # log append and commit — THE NORTH-STAR HOT PATH
    # ------------------------------------------------------------------

    def try_commit(self) -> bool:
        """Commit advancement by quorum match index (reference
        ``raft.go:888-909``).  The batched engine computes the identical
        ``q = kth_largest(match, quorum)`` reduction for all groups at once
        (see ``ops/kernels.py:commit_quorum``)."""
        self.must_be_leader()
        if self.num_voting_members() != len(self.matched):
            self.reset_match_value_array()
        idx = 0
        for nid in sorted(self.remotes):
            self.matched[idx] = self.remotes[nid].match
            idx += 1
        for nid in sorted(self.witnesses):
            self.matched[idx] = self.witnesses[nid].match
            idx += 1
        self.matched.sort()
        q = self.matched[self.num_voting_members() - self.quorum()]
        if self.hier is not None:
            return self._hier_try_commit(q)
        # raft paper p8: only entries from the leader's current term are
        # committed by counting replicas
        return self.log.try_commit(q, self.term)

    def _hier_try_commit(self, q_classic: int) -> bool:
        """Sub-quorum commit rule (hier.py module docstring): the
        effective commit candidate is ``max(classic, near-domain
        kth-largest)`` — the near-domain majority can close ahead of the
        far acks, and the classic quorum remains the fallback.  The
        current-term guard stays inside ``log.try_commit`` exactly as on
        the classic path."""
        hier = self.hier
        voters = self.voting_members()
        match_of = {nid: r.match for nid, r in voters.items()}
        q_near = hier.commit_quorum(match_of, voters.keys())
        advanced = self.log.try_commit(max(q_classic, q_near), self.term)
        if advanced:
            self._commit_via_sub = q_near > q_classic
            hier.note_close(via_sub=q_near > q_classic)
            hier.note_far_lag(match_of, voters.keys(), self.log.committed)
        return advanced

    def _note_commit(self) -> None:
        """Commit watermark advanced (replication attribution hook,
        ISSUE 14): close every covered record against the EXACT voter
        set and quorum the advancing ``try_commit`` counted.  Callers
        invoke this right after a successful commit advancement; the
        device path's twin lives in ``node._apply_offload_effects``.

        Hier (ISSUE 18): when the advancement closed via the near-domain
        sub-quorum, the attributed quorum position is the sub-quorum
        size — ``times[q-1]`` then lands on the near ack that actually
        closed the commit, so the closer table flips far→near while the
        far peers still fold in as laggards against the full voter set.
        The device path keeps classic attribution (the kernel does not
        report which rule advanced)."""
        ra = self.replattr
        if ra is not None:
            q = self.quorum()
            if self.hier is not None and self._commit_via_sub:
                near = self.hier.near_voters(self.voting_members().keys())
                if near:
                    q = sub_quorum_size(len(near))
            ra.on_commit(
                self.cluster_id, self.log.committed, self.term,
                self.voting_members(), q, self.node_id,
            )

    def append_entries(self, entries: List[Entry]) -> None:
        # reference raft.go:911-922
        last_index = self.log.last_index()
        for i, e in enumerate(entries):
            e.term = self.term
            e.index = last_index + 1 + i
            e._enc = None  # invalidate cached encoding (codec.py)
        self.log.append(entries)
        self.remotes[self.node_id].try_update(self.log.last_index())
        if self.offload is not None:
            self.offload.ack(
                self.cluster_id, self.node_id, self.log.last_index()
            )
            if self.device_kv and self.is_leader():
                # devsm (ISSUE 11): hand application entries to the
                # device apply plane at append — non-ops are filtered by
                # the plane's codec, encoded payloads are unwrapped here
                # so the plane sees what the SM would
                from ..rsm.encoded import get_entry_payload

                ops = []
                for e in entries:
                    if e.type in (
                        EntryType.APPLICATION, EntryType.ENCODED
                    ) and e.cmd:
                        try:
                            ops.append((e.index, get_entry_payload(e)))
                        except ValueError:
                            continue
                if ops:
                    self.offload.stage_sm_ops(self.cluster_id, ops)
        elif self.is_single_node_quorum():
            self.try_commit()

    # ------------------------------------------------------------------
    # state transitions
    # ------------------------------------------------------------------

    def become_observer(self, term: int, leader_id: int) -> None:
        if not self.is_observer():
            raise RuntimeError("transitioning to observer from non-observer")
        self.reset(term)
        self.set_leader_id(leader_id)
        if self.offload is not None:
            self.offload.set_follower(self.cluster_id, term)

    def become_witness(self, term: int, leader_id: int) -> None:
        if not self.is_witness():
            raise RuntimeError("transitioning to witness from non-witness")
        self.reset(term)
        self.set_leader_id(leader_id)
        if self.offload is not None:
            self.offload.set_follower(self.cluster_id, term)

    def become_follower(self, term: int, leader_id: int) -> None:
        if self.is_witness():
            raise RuntimeError("transitioning to follower from witness state")
        self.state = RaftState.FOLLOWER
        self.vote_trace.append(("fol", term, leader_id))
        self.reset(term)
        self.set_leader_id(leader_id)
        if self.offload is not None:
            self.offload.set_follower(self.cluster_id, term)

    def become_candidate(self) -> None:
        if self.is_leader():
            raise RuntimeError("transitioning to candidate from leader")
        if self.is_observer():
            raise RuntimeError("observer is becoming candidate")
        if self.is_witness():
            raise RuntimeError("witness is becoming candidate")
        self.state = RaftState.CANDIDATE
        # 2nd paragraph section 5.2 of the raft paper
        self.reset(self.term + 1)
        self.set_leader_id(NO_LEADER)
        self.vote = self.node_id
        if self.offload is not None:
            self.offload.set_candidate(self.cluster_id, self.term)

    def become_leader(self) -> None:
        if not self.is_leader() and not self.is_candidate():
            raise RuntimeError(f"transitioning to leader from {self.state}")
        self.state = RaftState.LEADER
        self.reset(self.term)
        self.set_leader_id(self.node_id)
        self.pre_leader_promotion_handle_config_change()
        # p72 of the raft thesis: commit a noop entry at the start of the term
        self.append_entries([Entry(type=EntryType.APPLICATION, cmd=b"")])
        # O(1) record of the noop's index — the floor below which
        # counting-based commit is forbidden (raft paper p8); consumed by
        # the device-engine row sync instead of a log scan
        self.term_start_index = self.log.last_index()
        if self.offload is not None:
            # term_start = the noop's index: the floor for counting commits
            self.offload.set_leader(
                self.cluster_id,
                self.term,
                self.log.last_index(),
                self.log.last_index(),
            )

    def reset(self, term: int) -> None:
        # reference raft.go:991-1010
        if self.term != term:
            self.term = term
            self.vote = NO_LEADER
        if self.rl.enabled():
            self.rl.reset()
        self.votes = {}
        self.election_tick = 0
        self.heartbeat_tick = 0
        self.set_randomized_election_timeout()
        self.read_index = ReadIndex()
        if self.lease is not None:
            # invalidation matrix: any state transition (term change,
            # promotion, demotion) drops the lease; it re-arms only from
            # post-transition heartbeat acks
            self.lease.reset()
        if self.replattr is not None:
            # same matrix for replication attribution: a transition
            # invalidates the quorum the open commit records were
            # tallied against — drop them, never cross-term attribute
            self.replattr.on_reset(self.cluster_id)
        if self.far_reads is not None:
            # same matrix for the far-read batcher: the leader the
            # in-flight fetch targeted (or the term it was valid in) is
            # gone — every held ctx reports dropped so clients retry
            self.dropped_read_indexes.extend(self.far_reads.invalidate())
        self.clear_pending_config_change()
        self.abort_leader_transfer()
        self.reset_remotes()
        self.reset_observers()
        self.reset_witnesses()
        self.reset_match_value_array()

    def pre_leader_promotion_handle_config_change(self) -> None:
        n = self.get_pending_config_change_count()
        if n > 1:
            raise RuntimeError("multiple uncommitted config change entries")
        elif n == 1:
            self.set_pending_config_change()

    def reset_remotes(self) -> None:
        # raft paper §5.3: leader initializes nextIndex to last+1
        for nid in self.remotes:
            self.remotes[nid] = Remote(next=self.log.last_index() + 1)
            if nid == self.node_id:
                self.remotes[nid].match = self.log.last_index()

    def reset_observers(self) -> None:
        for nid in self.observers:
            self.observers[nid] = Remote(next=self.log.last_index() + 1)
            if nid == self.node_id:
                self.observers[nid].match = self.log.last_index()

    def reset_witnesses(self) -> None:
        for nid in self.witnesses:
            self.witnesses[nid] = Remote(next=self.log.last_index() + 1)
            if nid == self.node_id:
                self.witnesses[nid].match = self.log.last_index()

    # ------------------------------------------------------------------
    # election
    # ------------------------------------------------------------------

    def handle_vote_resp(self, from_: int, rejected: bool) -> int:
        """Vote tally (reference ``raft.go:1062-1080``).  Batched twin:
        ``ops/kernels.py:vote_quorum``."""
        if from_ not in self.votes:
            self.votes[from_] = not rejected
        return sum(1 for v in self.votes.values() if v)

    def campaign(self) -> None:
        # reference raft.go:1082-1117
        self.become_candidate()
        self.vote_trace.append(("camp", self.term))
        term = self.term
        if self.events is not None:
            self.events.campaign_launched(self.cluster_id, self.node_id, term)
        self.handle_vote_resp(self.node_id, False)
        if self.is_single_node_quorum():
            self.become_leader()
            return
        if self.offload is not None:
            self.offload.vote(self.cluster_id, self.node_id, True)
        hint = 0
        if self.is_leader_transfer_target:
            hint = self.node_id
            self.is_leader_transfer_target = False
        for k in sorted(self.voting_members()):
            if k == self.node_id:
                continue
            self.send(
                Message(
                    term=term,
                    to=k,
                    type=MT.REQUEST_VOTE,
                    log_index=self.log.last_index(),
                    log_term=self.log.last_term(),
                    hint=hint,
                )
            )

    # ------------------------------------------------------------------
    # membership
    # ------------------------------------------------------------------

    def self_removed(self) -> bool:
        if self.is_observer():
            return self.node_id not in self.observers
        if self.is_witness():
            return self.node_id not in self.witnesses
        return self.node_id not in self.remotes

    def add_node(self, node_id: int) -> None:
        # reference raft.go:1131-1153
        self.clear_pending_config_change()
        if node_id == self.node_id and self.is_witness():
            raise RuntimeError(f"{self.describe()} is a witness")
        if node_id in self.remotes:
            return
        if node_id in self.observers:
            # promoting to full member with inherited progress
            rp = self.observers.pop(node_id)
            self.remotes[node_id] = rp
            if node_id == self.node_id:
                self.become_follower(self.term, self.leader_id)
        elif node_id in self.witnesses:
            raise RuntimeError("could not promote witness to full member")
        else:
            self.set_remote(node_id, 0, self.log.last_index() + 1)
        self.lease_membership_changed()
        if self.offload is not None:
            self.offload.membership_changed(self.cluster_id)

    def add_observer(self, node_id: int) -> None:
        self.clear_pending_config_change()
        if node_id == self.node_id and not self.is_observer():
            raise RuntimeError(f"{self.describe()} is not an observer")
        if node_id in self.observers:
            return
        self.set_observer(node_id, 0, self.log.last_index() + 1)
        self.lease_membership_changed()
        if self.offload is not None:
            self.offload.membership_changed(self.cluster_id)

    def add_witness(self, node_id: int) -> None:
        self.clear_pending_config_change()
        if node_id == self.node_id and not self.is_witness():
            raise RuntimeError(f"{self.describe()} is not a witness")
        if node_id in self.witnesses:
            return
        self.set_witness(node_id, 0, self.log.last_index() + 1)
        self.lease_membership_changed()
        if self.offload is not None:
            self.offload.membership_changed(self.cluster_id)

    def remove_node(self, node_id: int) -> None:
        # reference raft.go:1189-1208
        self.remotes.pop(node_id, None)
        self.observers.pop(node_id, None)
        self.witnesses.pop(node_id, None)
        self.clear_pending_config_change()
        if self.node_id == node_id and self.is_leader():
            self.become_follower(self.term, NO_LEADER)
        if self.leader_transfering() and self.leader_transfer_target == node_id:
            self.abort_leader_transfer()
        self.lease_membership_changed()
        if self.offload is not None:
            # quorum may have shrunk: resync the row; the next round
            # recomputes the commit watermark over the new membership
            self.offload.membership_changed(self.cluster_id)
        elif self.is_leader() and self.num_voting_members() > 0:
            if self.try_commit():
                self._note_commit()
                self.broadcast_replicate_message()

    def set_remote(self, node_id: int, match: int, next_: int) -> None:
        self.remotes[node_id] = Remote(next=next_, match=match)

    def set_observer(self, node_id: int, match: int, next_: int) -> None:
        self.observers[node_id] = Remote(next=next_, match=match)

    def set_witness(self, node_id: int, match: int, next_: int) -> None:
        self.witnesses[node_id] = Remote(next=next_, match=match)

    def set_pending_config_change(self) -> None:
        self.pending_config_change = True

    def has_pending_config_change(self) -> bool:
        return self.pending_config_change

    def clear_pending_config_change(self) -> None:
        self.pending_config_change = False

    def get_pending_config_change_count(self) -> int:
        # reference raft.go:1373-1387
        idx = self.log.committed + 1
        count = 0
        while True:
            ents = self.log.entries(idx, Soft.max_entry_size)
            if not ents:
                return count
            count += count_config_change(ents)
            idx = ents[-1].index + 1

    def has_config_change_to_apply(self) -> bool:
        # test-only hook eases conformance test porting (reference :1463-1469)
        if self.has_not_applied_config_change is not None:
            return self.has_not_applied_config_change()
        return self.log.committed > self.get_applied()

    # ------------------------------------------------------------------
    # shared message handlers
    # ------------------------------------------------------------------

    def can_grant_vote(self, m: Message) -> bool:
        return self.vote in (NO_NODE, m.from_) or m.term > self.term

    def handle_heartbeat_message(self, m: Message) -> None:
        self.log.commit_to(m.commit)
        self.send(
            Message(
                to=m.from_,
                type=MT.HEARTBEAT_RESP,
                hint=m.hint,
                hint_high=m.hint_high,
            )
        )

    def handle_install_snapshot_message(self, m: Message) -> None:
        # reference raft.go:1396-1424
        resp = Message(to=m.from_, type=MT.REPLICATE_RESP)
        if self.restore(m.snapshot):
            resp.log_index = self.log.last_index()
        else:
            resp.log_index = self.log.committed
            if self.events is not None:
                self.events.snapshot_rejected(
                    self.cluster_id,
                    self.node_id,
                    m.snapshot.index,
                    m.snapshot.term,
                    m.from_,
                )
        self.send(resp)

    def handle_replicate_message(self, m: Message) -> None:
        # reference raft.go:1426-1450
        resp = Message(to=m.from_, type=MT.REPLICATE_RESP)
        # replication tracing (ISSUE 14): a sampled REPLICATE's context
        # flows onto the ack so the leader sees the follower's stamps;
        # the fsync/ack-send stamps land later on the runtime's
        # post-persist send path (node.process_raft_update)
        ctx = m.trace
        if ctx is not None:
            resp.trace = ctx
        if m.log_index < self.log.committed:
            resp.log_index = self.log.committed
            self.send(resp)
            return
        if self.log.match_term(m.log_index, m.log_term):
            self.log.try_append(m.log_index, m.entries)
            if ctx is not None:
                ctx.t_append = _time.time()
            last_idx = m.log_index + len(m.entries)
            self.log.commit_to(min(last_idx, m.commit))
            resp.log_index = last_idx
        else:
            resp.reject = True
            resp.log_index = m.log_index
            resp.hint = self.log.last_index()
            if self.events is not None:
                self.events.replication_rejected(
                    self.cluster_id, self.node_id, m.log_index, m.log_term, m.from_
                )
        self.send(resp)

    # ------------------------------------------------------------------
    # term filtering + dispatch
    # ------------------------------------------------------------------

    def drop_request_vote_from_high_term_node(self, m: Message) -> bool:
        # reference raft.go:1273-1295
        if m.type != MT.REQUEST_VOTE or not self.check_quorum or m.term <= self.term:
            return False
        # p42 of the raft thesis: leader-transfer RequestVote must not be dropped
        if m.hint == m.from_:
            return False
        if (
            self.is_leader()
            and not self.quiesce
            and self.election_tick >= self.election_timeout
        ):
            raise RuntimeError("election_tick >= election_timeout on leader")
        # last paragraph of section 6 of the raft paper: drop RequestVote from
        # partitioned nodes when we recently heard from a quorum-backed leader
        if self.leader_id != NO_LEADER and self.election_tick < self.election_timeout:
            return True
        return False

    def on_message_term_not_matched(self, m: Message) -> bool:
        # reference raft.go:1300-1339
        if m.term == 0 or m.term == self.term:
            return False
        if self.drop_request_vote_from_high_term_node(m):
            return True
        if m.term > self.term:
            leader_id = NO_LEADER
            if is_leader_message(m.type):
                leader_id = m.from_
            # Stash the elapsed election clock across the step-down: if
            # this REQUEST_VOTE turns out to come from a log-behind
            # candidate, handle_node_request_vote restores the clock (see
            # there).  Everything else keeps etcd's full reset+resample.
            self._stepdown_etick = (
                self.election_tick if m.type == MT.REQUEST_VOTE else None
            )
            if self.is_observer():
                self.become_observer(m.term, leader_id)
            elif self.is_witness():
                self.become_witness(m.term, leader_id)
            else:
                self.become_follower(m.term, leader_id)
        elif m.term < self.term:
            if is_leader_message(m.type) and self.check_quorum:
                # etcd TestFreeStuckCandidateWithCheckQuorum corner case
                self.send(Message(to=m.from_, type=MT.NOOP))
            return True
        return False

    def double_check_term_matched(self, msg_term: int) -> None:
        if msg_term != 0 and self.term != msg_term:
            raise RuntimeError(f"{self.describe()} mismatched term found")

    def handle(self, m: Message) -> None:
        """Main entry: term-filter then dispatch (reference ``Handle``
        ``raft.go:1454-1461``)."""
        self._stepdown_etick = None
        if not self.on_message_term_not_matched(m):
            self.double_check_term_matched(m.term)
            handler = _HANDLERS[self.state].get(m.type)
            if handler is not None:
                handler(self, m)

    Handle = handle  # reference-style alias

    # ------------------------------------------------------------------
    # handlers for nodes in any state
    # ------------------------------------------------------------------

    def handle_node_election(self, m: Message) -> None:
        # reference raft.go:1485-1515
        if not self.is_leader():
            # ignore Election when a config change is committed but not applied:
            # campaigning then could form a quorum that does not overlap with
            # the committed-config quorum (see reference comment)
            if self.has_config_change_to_apply():
                if self.events is not None:
                    self.events.campaign_skipped(
                        self.cluster_id, self.node_id, self.term
                    )
                return
            self.campaign()

    def handle_node_request_vote(self, m: Message) -> None:
        # reference raft.go:1517-1539
        resp = Message(to=m.from_, type=MT.REQUEST_VOTE_RESP)
        can_grant = self.can_grant_vote(m)
        is_up_to_date = self.log.up_to_date(m.log_index, m.log_term)
        self.vote_trace.append(
            ("rv", m.from_, m.term, can_grant, is_up_to_date)
        )
        if can_grant and is_up_to_date:
            self.election_tick = 0
            self.vote = m.from_
        else:
            resp.reject = True
            if not is_up_to_date and self._stepdown_etick is not None:
                # Liveness at scale: a log-behind candidate can never win
                # (§5.4.1) yet re-campaigns every timeout, and if each
                # doomed campaign zeroed its healthy peers' clocks (term
                # bump → become_follower → reset) the replica that COULD
                # win fires first only with p≈1/n per cycle — measured as
                # 11/4,096 groups wedged 200s+.  Restore the elapsed
                # clock for exactly this case; healthy collisions (vote
                # already spent) keep the full reset+resample, which is
                # what desynchronizes colliding candidates.  Safety never
                # depends on clock resets — this is PreVote's protection
                # folded into the clock instead of a new RPC round.
                self.election_tick = min(
                    self._stepdown_etick, self.randomized_election_timeout
                )
        self._stepdown_etick = None
        self.send(resp)

    def handle_node_config_change(self, m: Message) -> None:
        # reference raft.go:1541-1560
        if m.reject:
            self.clear_pending_config_change()
        else:
            cctype = ConfigChangeType(m.hint_high)
            node_id = m.hint
            if cctype == ConfigChangeType.ADD_NODE:
                self.add_node(node_id)
            elif cctype == ConfigChangeType.REMOVE_NODE:
                self.remove_node(node_id)
            elif cctype == ConfigChangeType.ADD_OBSERVER:
                self.add_observer(node_id)
            elif cctype == ConfigChangeType.ADD_WITNESS:
                self.add_witness(node_id)
            else:
                raise RuntimeError("unexpected config change type")

    def handle_local_tick(self, m: Message) -> None:
        if m.reject:
            self.quiesced_tick()
        else:
            self.tick()

    def handle_restore_remote(self, m: Message) -> None:
        self.restore_remotes(m.snapshot)

    # ------------------------------------------------------------------
    # leader handlers
    # ------------------------------------------------------------------

    def handle_leader_heartbeat(self, m: Message) -> None:
        self.broadcast_heartbeat_message()

    def handle_leader_check_quorum(self, m: Message) -> None:
        # p69 of the raft thesis
        self.must_be_leader()
        if not self.leader_has_quorum():
            self.become_follower(self.term, NO_LEADER)

    def handle_leader_propose(self, m: Message) -> None:
        # reference raft.go:1590-1611
        self.must_be_leader()
        if self.leader_transfering():
            self.report_dropped_proposal(m)
            return
        for i, e in enumerate(m.entries):
            if e.type == EntryType.CONFIG_CHANGE:
                if self.has_pending_config_change():
                    self.report_dropped_config_change(m.entries[i])
                    m.entries[i] = Entry(type=EntryType.APPLICATION)
                self.set_pending_config_change()
        self.append_entries(m.entries)
        self.broadcast_replicate_message()

    def has_committed_entry_at_current_term(self) -> bool:
        # p72 of the raft thesis
        if self.term == 0:
            raise RuntimeError("not supposed to reach here")
        try:
            last_committed_term = self.log.term(self.log.committed)
        except CompactedError:
            return False
        return last_committed_term == self.term

    def clear_ready_to_read(self) -> None:
        self.ready_to_read = []

    def add_ready_to_read(
        self, index: int, ctx: SystemCtx, lease: bool = False
    ) -> None:
        self.ready_to_read.append(
            ReadyToRead(index=index, system_ctx=ctx, lease=lease)
        )

    def try_lease_read(self, m: Message, ctx: SystemCtx) -> bool:
        """Serve a linearizable read locally under a valid leader lease
        (ISSUE 10 tentpole; thesis §6.4.1) — ZERO confirmation rounds.

        Preconditions already held by the caller: leader, multi-node
        quorum, committed entry at the current term.  A valid lease means
        a quorum acked heartbeats within the last ``duration`` ticks, so
        no other leader can have been elected (CheckQuorum's §6 vote
        lease protects the bound even against forced campaigns; leader
        transfer — which bypasses it via TIMEOUT_NOW — ceded the lease
        first).  Serving at ``log.committed`` and routing exactly like a
        confirmed release keeps released indices identical to the
        ReadIndex path (differential: tests/test_lease.py)."""
        lease = self.lease
        remaining = lease.check(
            self.tick_count, self.quorum(),
            self.voting_members(), self.node_id,
        )
        if remaining <= 0:
            lease.note_read_fallback()
            return False
        lease.note_read_local(remaining)
        # same routing as apply_read_releases on a confirmed ctx
        if m.from_ == NO_NODE or m.from_ == self.node_id:
            self.add_ready_to_read(self.log.committed, ctx, lease=True)
        else:
            self.send(
                Message(
                    to=m.from_,
                    type=MT.READ_INDEX_RESP,
                    log_index=self.log.committed,
                    hint=ctx.low,
                    hint_high=ctx.high,
                )
            )
        return True

    def handle_leader_read_index(self, m: Message) -> None:
        # section 6.4 of the raft thesis (reference raft.go:1636-1669)
        self.must_be_leader()
        ctx = SystemCtx(low=m.hint, high=m.hint_high)
        if m.from_ in self.witnesses:
            pass  # witness cannot read
        elif not self.is_single_node_quorum():
            if not self.has_committed_entry_at_current_term():
                # thesis §6.4 step 1: leader must have committed in this term
                self.report_dropped_read_index(m)
                return
            if self.lease is not None and self.try_lease_read(m, ctx):
                # lease-served: no pending entry, no hint broadcast, no
                # device read-plane staging — the short path ends here
                return
            self.read_index.add_request(self.log.committed, ctx, m.from_)
            if self.offload is not None and self.device_reads:
                # device read plane: the echo-quorum counting for this ctx
                # runs in the engine's per-round fused dispatch; the local
                # pending entry above still drives hint rebroadcast and
                # the prefix release when the coordinator confirms
                self.offload.read_stage(
                    self.cluster_id, self.log.committed, ctx.low, ctx.high,
                    self.term,
                )
            self.broadcast_heartbeat_message_with_hint(ctx)
        else:
            self.add_ready_to_read(self.log.committed, ctx)
            if m.from_ != self.node_id and m.from_ in self.observers:
                self.send(
                    Message(
                        to=m.from_,
                        type=MT.READ_INDEX_RESP,
                        log_index=self.log.committed,
                        hint=m.hint,
                        hint_high=m.hint_high,
                        commit=m.commit,
                    )
                )

    def handle_leader_replicate_resp(self, m: Message, rp: Remote) -> None:
        # reference raft.go:1671-1700
        self.must_be_leader()
        rp.set_active()
        if not m.reject:
            paused = rp.is_paused()
            if rp.try_update(m.log_index):
                rp.responded_to()
                if self.replattr is not None:
                    # fold the ack (and its follower stage stamps) into
                    # the open commit records BEFORE the commit
                    # advancement below may close them
                    self.replattr.on_ack(
                        self.cluster_id, m.from_, rp.match, self.term,
                        m.trace,
                    )
                if self.offload is not None:
                    # north-star hot path: the quorum reduction runs on
                    # device over all groups; commit lands via
                    # node.offload_commit with the term guard re-applied
                    self.offload.ack(self.cluster_id, m.from_, rp.match)
                    if paused:
                        self.send_replicate_message(m.from_)
                elif self.try_commit():
                    self._note_commit()
                    self.broadcast_replicate_message()
                elif paused:
                    self.send_replicate_message(m.from_)
                # leadership transfer protocol, p29 of the raft thesis
                if (
                    self.leader_transfering()
                    and m.from_ == self.leader_transfer_target
                    and self.log.last_index() == rp.match
                ):
                    self.send_timeout_now_message(self.leader_transfer_target)
        else:
            # etcd-style conservative flow control: reset next to match+1
            if rp.decrease_to(m.log_index, m.hint):
                self.enter_retry_state(rp)
                self.send_replicate_message(m.from_)

    def handle_leader_heartbeat_resp(self, m: Message, rp: Remote) -> None:
        # reference raft.go:1702-1714
        self.must_be_leader()
        rp.set_active()
        if self.lease is not None and (
            m.from_ in self.remotes or m.from_ in self.witnesses
        ):
            # voting members only: an observer ack extends no quorum
            self.lease.record_ack(m.from_, self.tick_count)
        if self.offload is not None and self.device_ticks:
            # device check-quorum tallies activity bits per row (its only
            # consumer is the device-tick demote flag, so scalar-tick
            # groups must not pay a dispatch per heartbeat for it)
            self.offload.heartbeat_resp(self.cluster_id, m.from_)
        rp.wait_to_retry()
        if rp.match < self.log.last_index():
            self.send_replicate_message(m.from_)
        if m.hint != 0:
            if self.offload is not None and self.device_reads:
                # batched per coordinator round: the echo joins the
                # group's pending-read slot and the device's masked
                # row-sum decides the quorum (ctxs the coordinator is
                # not tracking — slot overflow, stale echoes — fall
                # back to the scalar tally below via the node)
                self.offload.read_ack_hint(
                    self.cluster_id, m.from_, m.hint, m.hint_high
                )
            else:
                self.handle_read_index_leader_confirmation(m)

    def handle_leader_transfer(self, m: Message, rp: Remote) -> None:
        # reference raft.go:1716-1738
        self.must_be_leader()
        target = m.hint
        if target == NO_NODE:
            raise RuntimeError("leader transfer target not set")
        if self.leader_transfering():
            return
        if self.node_id == target:
            return
        self.leader_transfer_target = target
        self.election_tick = 0
        if self.lease is not None:
            # the lease must be explicitly ceded BEFORE the transfer can
            # complete: TIMEOUT_NOW lets the target campaign without
            # waiting out the election timeout, voiding the clock bound.
            # Ceding here (at target-set time) strictly precedes every
            # send_timeout_now_message path.  Sticky until the next term:
            # even an aborted transfer may have delivered TIMEOUT_NOW.
            self.lease.cede()
        # fast path if the target is already caught up (p29, raft thesis)
        if rp.match == self.log.last_index():
            self.send_timeout_now_message(target)

    def handle_read_index_leader_confirmation(self, m: Message) -> None:
        # reference raft.go:1740-1760
        ctx = SystemCtx(low=m.hint, high=m.hint_high)
        ris = self.read_index.confirm(ctx, m.from_, self.quorum())
        self.apply_read_releases(ris, ctx)

    def apply_read_releases(self, ris, ctx: SystemCtx) -> None:
        """Route released ReadStatuses: local requesters land in
        ``ready_to_read``, remote ones get a READ_INDEX_RESP carrying the
        CONFIRMED ctx (reference raft.go:1740-1760 echoes ``m.Hint``, not
        the released request's own ctx).  Shared by the scalar confirm
        above and the device read plane's confirmed egress
        (``node._apply_offload_effects``) — both release through
        ``read_index``, so routing and indices are identical."""
        for s in ris:
            if s.from_ == NO_NODE or s.from_ == self.node_id:
                self.add_ready_to_read(s.index, s.ctx)
            else:
                self.send(
                    Message(
                        to=s.from_,
                        type=MT.READ_INDEX_RESP,
                        log_index=s.index,
                        hint=ctx.low,
                        hint_high=ctx.high,
                    )
                )

    def handle_leader_snapshot_status(self, m: Message, rp: Remote) -> None:
        # reference raft.go:1762-1775
        if rp.state != rp.state.SNAPSHOT:
            return
        if m.reject:
            rp.clear_pending_snapshot()
        rp.become_wait()

    def handle_leader_unreachable(self, m: Message, rp: Remote) -> None:
        self.enter_retry_state(rp)

    def handle_leader_rate_limit(self, m: Message) -> None:
        if self.rl.enabled():
            self.rl.set_follower_state(m.from_, m.hint)

    def enter_retry_state(self, rp: Remote) -> None:
        if rp.state == rp.state.REPLICATE:
            rp.become_retry()

    def _get_remote_for_leader_message(self, m: Message) -> Optional[Remote]:
        return (
            self.remotes.get(m.from_)
            or self.observers.get(m.from_)
            or self.witnesses.get(m.from_)
        )

    # ------------------------------------------------------------------
    # follower/observer/witness handlers
    # ------------------------------------------------------------------

    def handle_follower_propose(self, m: Message) -> None:
        if self.leader_id == NO_LEADER:
            self.report_dropped_proposal(m)
            return
        m.to = self.leader_id
        m.entries = [e.clone() for e in m.entries]
        self.send(m)

    def leader_is_available(self) -> None:
        self.election_tick = 0
        if self.offload is not None and self.device_ticks:
            # reset the device row's election clock too, or the tick
            # kernel would campaign against a healthy leader
            self.offload.leader_contact(self.cluster_id)

    def handle_follower_replicate(self, m: Message) -> None:
        self.leader_is_available()
        self.set_leader_id(m.from_)
        self.handle_replicate_message(m)

    def handle_follower_heartbeat(self, m: Message) -> None:
        self.leader_is_available()
        self.set_leader_id(m.from_)
        self.handle_heartbeat_message(m)

    def handle_follower_read_index(self, m: Message) -> None:
        if self.leader_id == NO_LEADER:
            self.report_dropped_read_index(m)
            return
        if (
            self.far_reads is not None
            and self.hier is not None
            and self.hier.is_far_follower(self.leader_id)
        ):
            # far-read batching (hier.py FarReadBatcher): at most one
            # cross-domain fetch in flight; a read arriving mid-flight
            # holds for the NEXT fetch (it may only ride a fetch
            # initiated after it arrived) and the whole batch releases
            # at that fetch's returned index
            ctx = SystemCtx(low=m.hint, high=m.hint_high)
            if not self.far_reads.admit(ctx):
                if self.hier.obs is not None:
                    self.hier.obs.read_coalesced()
                return
            if self.hier.obs is not None:
                self.hier.obs.read_batch()
        m.to = self.leader_id
        self.send(m)

    def handle_follower_leader_transfer(self, m: Message) -> None:
        if self.leader_id == NO_LEADER:
            return
        m.to = self.leader_id
        self.send(m)

    def handle_follower_read_index_resp(self, m: Message) -> None:
        ctx = SystemCtx(low=m.hint, high=m.hint_high)
        self.leader_is_available()
        self.set_leader_id(m.from_)
        if self.far_reads is not None and self.far_reads.pending:
            # release the whole fetch batch at the returned index (every
            # member arrived before the fetch was initiated, so the
            # leader's commit point at fetch time linearizes them all),
            # then forward the next batch's representative
            released, nxt = self.far_reads.on_resp(ctx)
            for c in released:
                self.add_ready_to_read(m.log_index, c)
            if nxt is not None:
                self.send(
                    Message(
                        type=MT.READ_INDEX,
                        to=self.leader_id,
                        hint=nxt.low,
                        hint_high=nxt.high,
                    )
                )
            return
        self.add_ready_to_read(m.log_index, ctx)

    def handle_follower_install_snapshot(self, m: Message) -> None:
        self.leader_is_available()
        self.set_leader_id(m.from_)
        self.handle_install_snapshot_message(m)

    def handle_follower_timeout_now(self, m: Message) -> None:
        # p29 of the raft thesis: equivalent to the clock jumping forward
        self.election_tick = self.randomized_election_timeout
        self.is_leader_transfer_target = True
        if self.device_ticks:
            # the tick fire site is device-owned; a leadership transfer is
            # an explicit request, so campaign immediately with the
            # transfer-target privileges intact
            self.election_tick = 0
            self.handle(Message(from_=self.node_id, type=MT.ELECTION))
        else:
            self.tick()
        if self.is_leader_transfer_target:
            self.is_leader_transfer_target = False

    # ------------------------------------------------------------------
    # candidate handlers
    # ------------------------------------------------------------------

    def handle_candidate_propose(self, m: Message) -> None:
        self.report_dropped_proposal(m)

    def handle_candidate_read_index(self, m: Message) -> None:
        self.report_dropped_read_index(m)

    # receiving Replicate/InstallSnapshot/Heartbeat at equal term implies a
    # leader exists for this term (raft paper §5.2 4th paragraph)
    def handle_candidate_replicate(self, m: Message) -> None:
        self.become_follower(self.term, m.from_)
        self.handle_replicate_message(m)

    def handle_candidate_install_snapshot(self, m: Message) -> None:
        self.become_follower(self.term, m.from_)
        self.handle_install_snapshot_message(m)

    def handle_candidate_heartbeat(self, m: Message) -> None:
        self.become_follower(self.term, m.from_)
        self.handle_heartbeat_message(m)

    def handle_candidate_request_vote_resp(self, m: Message) -> None:
        # reference raft.go:1965-1984
        if m.from_ in self.observers:
            return
        self.vote_trace.append(("rvr", m.from_, m.term, m.reject))
        count = self.handle_vote_resp(m.from_, m.reject)
        if self.offload is not None:
            # the device tallies; won/lost lands via node.offload_election
            self.offload.vote(self.cluster_id, m.from_, not m.reject)
            return
        if self.hier is not None:
            # hier vote rule (hier.py): quorum alone is not enough — the
            # granted set must also intersect every eligible domain's
            # possible sub-quorums.  `>=` instead of the classic `==`:
            # the tally can sit AT quorum while the intersection bound
            # waits on a later grant, so every resp must re-test.
            if count >= self.quorum() and self.hier_election_ok():
                self.become_leader()
                self.broadcast_replicate_message()
            elif count >= self.quorum():
                self.hier.note_election_hold()
            elif len(self.votes) - count == self.quorum():
                self.become_follower(self.term, NO_LEADER)
            return
        # 3rd paragraph section 5.2 of the raft paper
        if count == self.quorum():
            self.become_leader()
            self.broadcast_replicate_message()
        elif len(self.votes) - count == self.quorum():
            # etcd raft behavior, not in the raft paper
            self.become_follower(self.term, NO_LEADER)

    def hier_election_ok(self) -> bool:
        """True when the hier vote-intersection rule admits taking
        office with the current ``votes`` tally (trivially True with the
        plane off — the device offload path calls this before applying a
        `won` flag, hier-agnostic)."""
        if self.hier is None:
            return True
        return self.hier.election_ok(self.votes, self.voting_members())

    # ------------------------------------------------------------------
    # dropped request reporting
    # ------------------------------------------------------------------

    def report_dropped_config_change(self, e: Entry) -> None:
        self.dropped_entries.append(e)

    def report_dropped_proposal(self, m: Message) -> None:
        self.dropped_entries.extend(e.clone() for e in m.entries)
        if self.events is not None:
            self.events.proposal_dropped(
                self.cluster_id, self.node_id, m.entries
            )

    def report_dropped_read_index(self, m: Message) -> None:
        # record the ctx so the runtime can fail the pending read instead of
        # letting it sit until timeout (reference reportDroppedReadIndex)
        self.dropped_read_indexes.append(SystemCtx(low=m.hint, high=m.hint_high))
        if self.events is not None:
            self.events.read_index_dropped(self.cluster_id, self.node_id)


# ---------------------------------------------------------------------------
# handler table (reference initializeHandlerMap raft.go:2041-2102)
# ---------------------------------------------------------------------------

def _leader_msg_with_remote(f):
    def wrapper(r: Raft, m: Message) -> None:
        rp = r._get_remote_for_leader_message(m)
        if rp is None:
            return  # message from removed node
        f(r, m, rp)

    return wrapper


_COMMON = {
    MT.ELECTION: Raft.handle_node_election,
    MT.REQUEST_VOTE: Raft.handle_node_request_vote,
    MT.CONFIG_CHANGE_EVENT: Raft.handle_node_config_change,
    MT.LOCAL_TICK: Raft.handle_local_tick,
    MT.SNAPSHOT_RECEIVED: Raft.handle_restore_remote,
}

_HANDLERS: List[Dict[MessageType, Callable[[Raft, Message], None]]] = [
    {} for _ in range(NUM_STATES)
]

_HANDLERS[RaftState.FOLLOWER] = {
    **_COMMON,
    MT.PROPOSE: Raft.handle_follower_propose,
    MT.REPLICATE: Raft.handle_follower_replicate,
    MT.HEARTBEAT: Raft.handle_follower_heartbeat,
    MT.READ_INDEX: Raft.handle_follower_read_index,
    MT.LEADER_TRANSFER: Raft.handle_follower_leader_transfer,
    MT.READ_INDEX_RESP: Raft.handle_follower_read_index_resp,
    MT.INSTALL_SNAPSHOT: Raft.handle_follower_install_snapshot,
    MT.TIMEOUT_NOW: Raft.handle_follower_timeout_now,
}

_HANDLERS[RaftState.CANDIDATE] = {
    **_COMMON,
    MT.PROPOSE: Raft.handle_candidate_propose,
    MT.READ_INDEX: Raft.handle_candidate_read_index,
    MT.REPLICATE: Raft.handle_candidate_replicate,
    MT.INSTALL_SNAPSHOT: Raft.handle_candidate_install_snapshot,
    MT.HEARTBEAT: Raft.handle_candidate_heartbeat,
    MT.REQUEST_VOTE_RESP: Raft.handle_candidate_request_vote_resp,
}

_HANDLERS[RaftState.LEADER] = {
    **_COMMON,
    MT.LEADER_HEARTBEAT: Raft.handle_leader_heartbeat,
    MT.CHECK_QUORUM: Raft.handle_leader_check_quorum,
    MT.PROPOSE: Raft.handle_leader_propose,
    MT.READ_INDEX: Raft.handle_leader_read_index,
    MT.REPLICATE_RESP: _leader_msg_with_remote(Raft.handle_leader_replicate_resp),
    MT.HEARTBEAT_RESP: _leader_msg_with_remote(Raft.handle_leader_heartbeat_resp),
    MT.SNAPSHOT_STATUS: _leader_msg_with_remote(Raft.handle_leader_snapshot_status),
    MT.UNREACHABLE: _leader_msg_with_remote(Raft.handle_leader_unreachable),
    MT.LEADER_TRANSFER: _leader_msg_with_remote(Raft.handle_leader_transfer),
    MT.RATE_LIMIT: Raft.handle_leader_rate_limit,
}

_HANDLERS[RaftState.OBSERVER] = {
    MT.CONFIG_CHANGE_EVENT: Raft.handle_node_config_change,
    MT.LOCAL_TICK: Raft.handle_local_tick,
    MT.SNAPSHOT_RECEIVED: Raft.handle_restore_remote,
    MT.PROPOSE: Raft.handle_follower_propose,
    MT.REPLICATE: Raft.handle_follower_replicate,
    MT.HEARTBEAT: Raft.handle_follower_heartbeat,
    MT.INSTALL_SNAPSHOT: Raft.handle_follower_install_snapshot,
    MT.READ_INDEX: Raft.handle_follower_read_index,
    MT.READ_INDEX_RESP: Raft.handle_follower_read_index_resp,
}

_HANDLERS[RaftState.WITNESS] = {
    MT.CONFIG_CHANGE_EVENT: Raft.handle_node_config_change,
    MT.LOCAL_TICK: Raft.handle_local_tick,
    MT.SNAPSHOT_RECEIVED: Raft.handle_restore_remote,
    MT.REQUEST_VOTE: Raft.handle_node_request_vote,
    MT.REPLICATE: Raft.handle_follower_replicate,
    MT.HEARTBEAT: Raft.handle_follower_heartbeat,
    MT.INSTALL_SNAPSHOT: Raft.handle_follower_install_snapshot,
}
