"""Rate limiters bounding in-memory log growth.

Reference: ``internal/server/rate.go`` — a byte-count ``RateLimiter`` plus a
follower-aware ``InMemRateLimiter`` that rate-limits proposals based on the
max of the local and any follower's in-memory log size.
"""
from __future__ import annotations

from typing import Dict


class RateLimiter:
    """Byte-count limiter (reference ``rate.go:33-78``)."""

    __slots__ = ("size", "max_size")

    def __init__(self, max_size: int):
        self.size = 0
        self.max_size = max_size

    def enabled(self) -> bool:
        return self.max_size > 0

    def increase(self, sz: int) -> None:
        self.size += sz

    def decrease(self, sz: int) -> None:
        self.size = max(0, self.size - sz)

    def set(self, sz: int) -> None:
        self.size = sz

    def get(self) -> int:
        return self.size

    def rate_limited(self) -> bool:
        return self.enabled() and self.size > self.max_size


class InMemRateLimiter:
    """Follower-aware limiter (reference ``rate.go:81-198``)."""

    __slots__ = ("rl", "follower_sizes", "tick_count", "peers")

    # a follower report is considered stale after this many ticks
    FOLLOWER_GC_TICK = 3

    def __init__(self, max_size: int):
        self.rl = RateLimiter(max_size)
        self.follower_sizes: Dict[int, tuple] = {}
        self.tick_count = 0

    def enabled(self) -> bool:
        return self.rl.enabled()

    def tick(self) -> None:
        self.tick_count += 1

    def get_tick(self) -> int:
        return self.tick_count

    def increase(self, sz: int) -> None:
        self.rl.increase(sz)

    def decrease(self, sz: int) -> None:
        self.rl.decrease(sz)

    def set(self, sz: int) -> None:
        self.rl.set(sz)

    def get(self) -> int:
        return self.rl.get()

    def set_follower_state(self, node_id: int, sz: int) -> None:
        self.follower_sizes[node_id] = (sz, self.tick_count)

    def reset_follower_state(self) -> None:
        self.follower_sizes = {}

    def reset(self) -> None:
        self.rl.set(0)
        self.reset_follower_state()

    def rate_limited(self) -> bool:
        if not self.enabled():
            return False
        if self.rl.rate_limited():
            return True
        for sz, tick in self.follower_sizes.values():
            if self.tick_count - tick <= self.FOLLOWER_GC_TICK:
                if sz > self.rl.max_size:
                    return True
        return False
