"""ReadIndex protocol state (raft thesis §6.4, batched).

Reference: ``internal/raft/readindex.go`` — pending reads keyed by a 128-bit
``SystemCtx``, confirmed by quorum counting of heartbeat responses carrying
the ctx as a hint.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Set

from ..wire import SystemCtx


@dataclass(slots=True)
class ReadStatus:
    index: int = 0
    from_: int = 0
    ctx: SystemCtx = field(default_factory=SystemCtx)
    confirmed: Set[int] = field(default_factory=set)


class ReadIndex:
    __slots__ = ("pending", "queue")

    def __init__(self) -> None:
        self.pending: Dict[SystemCtx, ReadStatus] = {}
        self.queue: List[SystemCtx] = []

    def add_request(self, index: int, ctx: SystemCtx, from_: int) -> None:
        # reference readindex.go:43-68
        if ctx in self.pending:
            return
        if self.queue:
            p = self.pending.get(self.peep_ctx())
            if p is None:
                raise RuntimeError("inconsistent pending and queue")
            if index < p.index:
                raise RuntimeError(
                    f"index moved backward in readIndex, {index}:{p.index}"
                )
        self.queue.append(ctx)
        self.pending[ctx] = ReadStatus(index=index, from_=from_, ctx=ctx)

    def has_pending_request(self) -> bool:
        return len(self.queue) > 0

    def peep_ctx(self) -> SystemCtx:
        return self.queue[-1]

    def confirm(
        self, ctx: SystemCtx, from_: int, quorum: int
    ) -> List[ReadStatus]:
        # reference readindex.go:77-116: a confirmation of ctx releases it and
        # every request queued before it, all rewritten to ctx's index.
        p = self.pending.get(ctx)
        if p is None:
            return []
        p.confirmed.add(from_)
        if len(p.confirmed) + 1 < quorum:
            return []
        return self.release(ctx)

    def release(self, ctx: SystemCtx) -> List[ReadStatus]:
        """The queue-pop half of :meth:`confirm`: release ``ctx`` and every
        request queued before it, all rewritten to ``ctx``'s index.  The
        quorum counting is the caller's — the scalar echo tally above, or
        the device ``read_confirm`` kernel whose confirmed-slot egress the
        coordinator routes back here (``tpuquorum.py``); either way the
        released statuses and their indices are identical."""
        if ctx not in self.pending:
            return []
        done = 0
        cs: List[ReadStatus] = []
        for pctx in self.queue:
            done += 1
            s = self.pending.get(pctx)
            if s is None:
                raise RuntimeError("inconsistent pending and queue content")
            cs.append(s)
            if pctx == ctx:
                for v in cs:
                    if v.index > s.index:
                        raise RuntimeError("v.index > s.index is unexpected")
                    v.index = s.index
                self.queue = self.queue[done:]
                for v in cs:
                    del self.pending[v.ctx]
                if len(self.queue) != len(self.pending):
                    raise RuntimeError("inconsistent length")
                return cs
        return []
