"""Per-peer replication progress tracker.

Reference: ``internal/raft/remote.go`` — the etcd-derived flow-control state
machine with states Retry/Wait/Replicate/Snapshot tracking ``match``/``next``
indexes per remote peer.  The batched quorum engine mirrors exactly this state
as columns of its ``(nGroups, nPeers)`` tensors (state code, match, next),
so the semantics here are the single source of truth for both paths.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass


class RemoteState(enum.IntEnum):
    # reference remote.go:44-49
    RETRY = 0
    WAIT = 1
    REPLICATE = 2
    SNAPSHOT = 3


@dataclass(slots=True)
class Remote:
    """Progress of one remote peer (reference ``remote.go:62-68``)."""

    match: int = 0
    next: int = 0
    snapshot_index: int = 0
    state: RemoteState = RemoteState.RETRY
    active: bool = False

    def reset_snapshot(self) -> None:
        self.snapshot_index = 0

    def become_retry(self) -> None:
        # reference remote.go:80-88
        if self.state == RemoteState.SNAPSHOT:
            self.next = max(self.match + 1, self.snapshot_index + 1)
        else:
            self.next = self.match + 1
        self.reset_snapshot()
        self.state = RemoteState.RETRY

    def retry_to_wait(self) -> None:
        if self.state == RemoteState.RETRY:
            self.state = RemoteState.WAIT

    def wait_to_retry(self) -> None:
        if self.state == RemoteState.WAIT:
            self.state = RemoteState.RETRY

    def become_wait(self) -> None:
        self.become_retry()
        self.retry_to_wait()

    def become_replicate(self) -> None:
        self.next = self.match + 1
        self.reset_snapshot()
        self.state = RemoteState.REPLICATE

    def become_snapshot(self, index: int) -> None:
        self.reset_snapshot()
        self.snapshot_index = index
        self.state = RemoteState.SNAPSHOT

    def clear_pending_snapshot(self) -> None:
        self.snapshot_index = 0

    def try_update(self, index: int) -> bool:
        # reference remote.go:123-133
        if self.next < index + 1:
            self.next = index + 1
        if self.match < index:
            self.wait_to_retry()
            self.match = index
            return True
        return False

    def progress(self, last_index: int) -> None:
        # reference remote.go:135-143: called when entries were sent out
        if self.state == RemoteState.REPLICATE:
            self.next = last_index + 1
        elif self.state == RemoteState.RETRY:
            self.retry_to_wait()
        else:
            raise RuntimeError("unexpected remote state")

    def responded_to(self) -> None:
        # reference remote.go:145-153
        if self.state == RemoteState.RETRY:
            self.become_replicate()
        elif self.state == RemoteState.SNAPSHOT:
            if self.match >= self.snapshot_index:
                self.become_retry()

    def decrease_to(self, rejected: int, last: int) -> bool:
        # reference remote.go:155-171
        if self.state == RemoteState.REPLICATE:
            if rejected <= self.match:
                return False  # stale
            self.next = self.match + 1
            return True
        if self.next - 1 != rejected:
            return False  # stale
        self.wait_to_retry()
        self.next = max(1, min(rejected, last + 1))
        return True

    def is_paused(self) -> bool:
        return self.state in (RemoteState.WAIT, RemoteState.SNAPSHOT)

    def is_active(self) -> bool:
        return self.active

    def set_active(self) -> None:
        self.active = True

    def set_not_active(self) -> None:
        self.active = False

    def __str__(self) -> str:
        return (
            f"match:{self.match},next:{self.next},"
            f"state:{self.state.name},si:{self.snapshot_index}"
        )
