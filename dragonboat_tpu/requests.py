"""Pending-request tracking: the future/promise layer between user API calls
and the asynchronous engine.

Reference: ``requests.go`` — pooled ``RequestState`` futures with result
channels; ``pendingProposal`` sharded 16 ways on a random 64-bit key
(:446,:943); ``pendingReadIndex`` batching by ``SystemCtx`` (:457);
single-slot ``pendingConfigChange``/``pendingSnapshot``/
``pendingLeaderTransfer`` (:471-486); logical-clock GC of timed-out requests.
"""
from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Dict, List, Optional, Tuple

from .obs import trace as _trace
from .settings import Soft
from .statemachine import Result
from .wire import Entry, ReadyToRead, SystemCtx


class RequestError(Exception):
    pass


class ClusterNotFoundError(RequestError):
    pass


class ClusterAlreadyExistError(RequestError):
    pass


class ClusterNotReadyError(RequestError):
    pass


class ClusterClosedError(RequestError):
    pass


class SystemBusyError(RequestError):
    pass


class InvalidSessionError(RequestError):
    pass


class TimeoutError_(RequestError):
    pass


class CanceledError(RequestError):
    pass


class RejectedError(RequestError):
    pass


class InvalidOperationError(RequestError):
    """The request is not valid on this replica type — e.g. any
    proposal/read/config-change/snapshot/transfer on a WITNESS replica
    (reference ``ErrInvalidOperation``, node.go:352-442: witnesses vote
    and persist metadata but never serve user operations)."""


class PayloadTooBigError(RequestError):
    """Entry payload exceeds ``Config.max_in_mem_log_size`` (reference
    ``ErrPayloadTooBig``, node.go:363-367: an entry that cannot fit the
    in-memory log bound can never be appended)."""


class PendingConfigChangeExistError(RequestError):
    pass


class PendingSnapshotExistError(RequestError):
    pass


class PendingLeaderTransferExistError(RequestError):
    pass


class RequestResultCode(IntEnum):
    TIMEOUT = 0
    COMPLETED = 1
    TERMINATED = 2
    REJECTED = 3
    DROPPED = 4
    ABORTED = 5
    COMMITTED = 6


@dataclass
class RequestResult:
    code: RequestResultCode = RequestResultCode.TIMEOUT
    result: Result = field(default_factory=Result)
    snapshot_index: int = 0

    @property
    def completed(self) -> bool:
        return self.code == RequestResultCode.COMPLETED

    @property
    def rejected(self) -> bool:
        return self.code == RequestResultCode.REJECTED

    @property
    def timeout(self) -> bool:
        return self.code == RequestResultCode.TIMEOUT

    @property
    def terminated(self) -> bool:
        return self.code == RequestResultCode.TERMINATED

    @property
    def dropped(self) -> bool:
        return self.code == RequestResultCode.DROPPED


class RequestState:
    """Reference ``requests.go:267`` ``RequestState`` — a one-shot future."""

    __slots__ = (
        "key",
        "client_id",
        "series_id",
        "deadline",
        "_event",
        "_result",
        "read_index",
        "completed_at",
        "trace",
    )

    def __init__(self, key: int = 0, deadline: int = 0):
        self.key = key
        self.client_id = 0
        self.series_id = 0
        self.deadline = deadline
        self._event = threading.Event()
        self._result: Optional[RequestResult] = None
        self.read_index = 0
        #: perf_counter() at notify time — lets a pipelined client report
        #: the request's true completion latency instead of the (later)
        #: moment it got around to observing the result
        self.completed_at: Optional[float] = None
        #: request-trace token (ISSUE 9): None while tracing is off (the
        #: bit-identical default); with tracing on, a (tracer, t0)
        #: enqueue-timestamp token for non-sampled requests or an
        #: obs.trace.Trace for the sampled 1-in-N
        self.trace = None

    def notify(self, result: RequestResult) -> None:
        self.completed_at = time.perf_counter()
        self._result = result
        self._event.set()
        if self.trace is not None:
            _trace.request_done(self.trace, result)

    def wait(self, timeout: Optional[float] = None) -> RequestResult:
        if not self._event.wait(timeout):
            return RequestResult(code=RequestResultCode.TIMEOUT)
        assert self._result is not None
        return self._result

    def done(self) -> bool:
        return self._event.is_set()

    @property
    def result(self) -> Optional[RequestResult]:
        return self._result


class _LogicalClock:
    """Reference ``requests.go:216`` ``logicalClock``."""

    def __init__(self) -> None:
        self.tick = 0

    def advance(self) -> None:
        self.tick += 1


class PendingProposal:
    """Sharded proposal tracker (reference ``requests.go:446,943``)."""

    def __init__(self, shards: int = 0, rng: Optional[random.Random] = None):
        self.nshards = shards or Soft.pending_proposal_shards
        self._shards: List[Dict[int, RequestState]] = [
            {} for _ in range(self.nshards)
        ]
        self._locks = [threading.Lock() for _ in range(self.nshards)]
        self._clock = _LogicalClock()
        self._rng = rng or random.Random()
        self._stopped = False
        # earliest-deadline tracking: tick() skips the full scan until
        # something could actually have expired (it runs once per RTT for
        # EVERY group, so the scan-always version is hot-path cost).
        # Two fields to make the propose/tick race safe: _min_deadline is
        # owned by the scan; _pending_min accumulates deadlines published by
        # propose() since the last scan and is merged (never dropped) there.
        # A proposal inserted into an already-scanned shard mid-scan thus
        # stays visible to the fast-path check either way.
        self._min_deadline = 1 << 62
        self._pending_min = 1 << 62
        self._min_mu = threading.Lock()
        # client-completion egress sink (hostplane.EgressPool): when set,
        # ``applied`` hands the resolved future to the sink instead of
        # running ``rs.notify`` (the client-thread ``Event.set`` wakeup)
        # inline on the apply worker.  None (default) keeps the apply
        # path bit-identical to the pre-compartment build.
        self._egress = None

    def set_egress(self, sink) -> None:
        self._egress = sink

    def _next_key(self) -> int:
        return self._rng.getrandbits(64) or 1

    def propose(
        self, client_id: int, series_id: int, cmd: bytes, timeout_ticks: int
    ) -> Tuple[RequestState, Entry]:
        if self._stopped:
            raise ClusterClosedError()
        key = self._next_key()
        deadline = self._clock.tick + timeout_ticks
        rs = RequestState(key=key, deadline=deadline)
        rs.client_id = client_id
        rs.series_id = series_id
        shard = key % self.nshards
        with self._locks[shard]:
            self._shards[shard][key] = rs
        if deadline < self._pending_min:
            with self._min_mu:
                if deadline < self._pending_min:
                    self._pending_min = deadline
        entry = Entry(
            key=key, client_id=client_id, series_id=series_id, cmd=cmd
        )
        return rs, entry

    def propose_batch(
        self, client_id: int, series_id: int, cmds: List[bytes],
        timeout_ticks: int,
    ) -> Tuple[List[RequestState], List[Entry]]:
        """Track a burst of proposals in one pass.  Semantically identical
        to N ``propose`` calls (one RequestState + one Entry per command);
        amortizes the clock read, the deadline publication and — by
        grouping keys per shard — the tracker lock traffic.  The per-write
        Python cost of the propose path is a first-order term in end-to-end
        throughput once replication itself runs in the native fast lane."""
        if self._stopped:
            raise ClusterClosedError()
        deadline = self._clock.tick + timeout_ticks
        bits = self._rng.getrandbits
        states: List[RequestState] = []
        entries: List[Entry] = []
        by_shard: Dict[int, List[RequestState]] = {}
        for cmd in cmds:
            key = bits(64) or 1
            rs = RequestState(key=key, deadline=deadline)
            rs.client_id = client_id
            rs.series_id = series_id
            states.append(rs)
            entries.append(
                Entry(key=key, client_id=client_id, series_id=series_id, cmd=cmd)
            )
            by_shard.setdefault(key % self.nshards, []).append(rs)
        for shard, group in by_shard.items():
            with self._locks[shard]:
                d = self._shards[shard]
                for rs in group:
                    d[rs.key] = rs
        if deadline < self._pending_min:
            with self._min_mu:
                if deadline < self._pending_min:
                    self._pending_min = deadline
        return states, entries

    def register_batch(self, states: List[RequestState]) -> None:
        """Insert pre-created futures (hostplane ingress batcher): the
        client thread built the RequestStates without touching the tracker
        locks; the batcher registers them here — grouped per shard, one
        lock acquisition each — strictly before staging the entries, so
        completion can never miss the registration."""
        if self._stopped:
            for rs in states:
                rs.notify(RequestResult(code=RequestResultCode.TERMINATED))
            return
        by_shard: Dict[int, List[RequestState]] = {}
        min_deadline = 1 << 62
        for rs in states:
            by_shard.setdefault(rs.key % self.nshards, []).append(rs)
            if rs.deadline < min_deadline:
                min_deadline = rs.deadline
        for shard, group in by_shard.items():
            with self._locks[shard]:
                d = self._shards[shard]
                for rs in group:
                    d[rs.key] = rs
        if min_deadline < self._pending_min:
            with self._min_mu:
                if min_deadline < self._pending_min:
                    self._pending_min = min_deadline

    def applied(
        self,
        key: int,
        client_id: int,
        series_id: int,
        result: Result,
        rejected: bool,
    ) -> None:
        """Completion from the apply path (reference ``requests.go:1155``)."""
        shard = key % self.nshards
        with self._locks[shard]:
            rs = self._shards[shard].get(key)
            if rs is None:
                return
            if rs.client_id != client_id or rs.series_id != series_id:
                return
            del self._shards[shard][key]
        if rs.trace is not None:
            _trace.Tracer.mark(rs, "apply")
        code = (
            RequestResultCode.REJECTED if rejected else RequestResultCode.COMPLETED
        )
        egress = self._egress
        if egress is not None:
            egress(rs, RequestResult(code=code, result=result))
        else:
            rs.notify(RequestResult(code=code, result=result))

    def dropped(self, key: int) -> None:
        shard = key % self.nshards
        with self._locks[shard]:
            rs = self._shards[shard].pop(key, None)
        if rs is not None:
            rs.notify(RequestResult(code=RequestResultCode.DROPPED))

    def close(self) -> None:
        self._stopped = True
        for shard, lock in zip(self._shards, self._locks):
            with lock:
                for rs in shard.values():
                    rs.notify(RequestResult(code=RequestResultCode.TERMINATED))
                shard.clear()

    def has_pending(self) -> bool:
        """Unlocked emptiness probe (tick-lite sweep heuristic)."""
        return any(self._shards)

    def tick(self) -> None:
        self._clock.advance()
        now = self._clock.tick
        if now <= self._min_deadline and now <= self._pending_min:
            return
        new_min = 1 << 62
        timed_out = []
        for shard, lock in zip(self._shards, self._locks):
            with lock:
                for key, rs in list(shard.items()):
                    if rs.deadline < now:
                        timed_out.append(rs)
                        del shard[key]
                    elif rs.deadline < new_min:
                        new_min = rs.deadline
        with self._min_mu:
            # merge the scan result with anything propose() published since;
            # _pending_min is folded in (never discarded), so a proposal the
            # scan raced past cannot lose its timeout
            self._min_deadline = min(new_min, self._pending_min)
            self._pending_min = 1 << 62
        for rs in timed_out:
            rs.notify(RequestResult(code=RequestResultCode.TIMEOUT))


class PendingReadIndex:
    """ReadIndex batching tracker (reference ``requests.go:457,782``)."""

    def __init__(self, rng: Optional[random.Random] = None):
        self._mu = threading.Lock()
        self._rng = rng or random.Random()
        # requests waiting to be batched into the next ReadIndex
        self._pending: List[RequestState] = []
        # ctx → batch already submitted to raft
        self._batches: Dict[SystemCtx, List[RequestState]] = {}
        # confirmed (index known) but waiting for apply to catch up
        self._confirmed: List[Tuple[int, RequestState]] = []
        self._clock = _LogicalClock()
        self._stopped = False
        # completion egress sink (hostplane) — same contract as
        # PendingProposal._egress; None keeps notify inline
        self._egress = None
        # request tracer (ISSUE 9, set by NodeHost wiring): reads carry
        # no entry key, so their stage stamps ride the rs objects this
        # tracker already holds; None keeps every loop below untouched
        self._tracer = None

    def set_egress(self, sink) -> None:
        self._egress = sink

    def read(self, timeout_ticks: int) -> RequestState:
        if self._stopped:
            raise ClusterClosedError()
        rs = RequestState(deadline=self._clock.tick + timeout_ticks)
        with self._mu:
            self._pending.append(rs)
        return rs

    def peep(self) -> bool:
        # GIL-atomic read; polled every step round for every group
        return bool(self._pending)

    def has_pending(self) -> bool:
        """Unlocked emptiness probe (tick-lite sweep heuristic)."""
        return bool(self._pending or self._batches or self._confirmed)

    def next_ctx(self) -> SystemCtx:
        return SystemCtx(
            low=self._rng.getrandbits(64), high=self._rng.getrandbits(64) or 1
        )

    def take_pending(self, ctx: SystemCtx) -> bool:
        """Move queued requests into a submitted batch keyed by ``ctx``."""
        with self._mu:
            if not self._pending:
                return False
            batch = self._pending
            self._batches[ctx] = batch
            self._pending = []
        if self._tracer is not None:
            for rs in batch:
                if rs.trace is not None:
                    self._tracer.mark(rs, "raft_step")
        return True

    def pending_ctxs(self) -> List[SystemCtx]:
        """Contexts taken for confirmation but not yet ready — after a
        fast-lane eject these must be re-driven through the scalar
        protocol or their reads strand until timeout."""
        with self._mu:
            return list(self._batches.keys())

    def add_ready(self, readies: List[ReadyToRead]) -> None:
        """Raft confirmed these contexts at an index
        (reference ``requests.go:821``)."""
        if not readies:
            return
        tracer = self._tracer
        with self._mu:
            for r in readies:
                batch = self._batches.pop(r.system_ctx, None)
                if batch is None:
                    continue
                # lease-served readies (ISSUE 10) skipped the echo-quorum
                # round entirely; the trace shows the short path
                stage = "lease_read" if r.lease else "read_confirm"
                for rs in batch:
                    rs.read_index = r.index
                    self._confirmed.append((r.index, rs))
                    if tracer is not None and rs.trace is not None:
                        tracer.mark(rs, stage)

    def applied(self, applied_index: int) -> None:
        """Apply watermark moved; complete reads whose index is covered
        (reference ``requests.go:868``)."""
        done: List[RequestState] = []
        with self._mu:
            if not self._confirmed:
                return
            keep = []
            for idx, rs in self._confirmed:
                if idx <= applied_index:
                    done.append(rs)
                else:
                    keep.append((idx, rs))
            self._confirmed = keep
        egress = self._egress
        tracer = self._tracer
        for rs in done:
            if tracer is not None and rs.trace is not None:
                tracer.mark(rs, "apply")
            if egress is not None:
                egress(rs, RequestResult(code=RequestResultCode.COMPLETED))
            else:
                rs.notify(RequestResult(code=RequestResultCode.COMPLETED))

    def dropped(self, ctxs: List[SystemCtx]) -> None:
        with self._mu:
            batches = [self._batches.pop(c, None) for c in ctxs]
        for batch in batches:
            if batch:
                for rs in batch:
                    rs.notify(RequestResult(code=RequestResultCode.DROPPED))

    def close(self) -> None:
        self._stopped = True
        with self._mu:
            all_rs = list(self._pending)
            self._pending = []
            for batch in self._batches.values():
                all_rs.extend(batch)
            self._batches.clear()
            all_rs.extend(rs for _, rs in self._confirmed)
            self._confirmed = []
        for rs in all_rs:
            rs.notify(RequestResult(code=RequestResultCode.TERMINATED))

    def tick(self) -> None:
        self._clock.advance()
        now = self._clock.tick
        # fast path: nothing tracked (idle groups tick every RTT)
        if not (self._pending or self._batches or self._confirmed):
            return
        timed_out: List[RequestState] = []
        with self._mu:
            self._pending, expired = (
                [rs for rs in self._pending if rs.deadline >= now],
                [rs for rs in self._pending if rs.deadline < now],
            )
            timed_out.extend(expired)
            for ctx in list(self._batches):
                batch = self._batches[ctx]
                live = [rs for rs in batch if rs.deadline >= now]
                dead = [rs for rs in batch if rs.deadline < now]
                timed_out.extend(dead)
                if live:
                    self._batches[ctx] = live
                else:
                    del self._batches[ctx]
            keep = []
            for idx, rs in self._confirmed:
                if rs.deadline < now:
                    timed_out.append(rs)
                else:
                    keep.append((idx, rs))
            self._confirmed = keep
        for rs in timed_out:
            rs.notify(RequestResult(code=RequestResultCode.TIMEOUT))


class _SingleSlot:
    """Single-in-flight request trackers (reference ``requests.go:471-486``)."""

    exist_error = RequestError

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._pending: Optional[RequestState] = None
        self._payload: Optional[object] = None
        self._clock = _LogicalClock()
        self._stopped = False

    def request(self, payload, timeout_ticks: int) -> RequestState:
        if self._stopped:
            raise ClusterClosedError()
        with self._mu:
            if self._pending is not None:
                raise self.exist_error()
            rs = RequestState(
                key=random.getrandbits(64),
                deadline=self._clock.tick + timeout_ticks,
            )
            self._pending = rs
            self._payload = payload
            return rs

    def take(self):
        # lock-free empty check: this runs in every step round for every
        # group (node._handle_events) and is almost always empty; a plain
        # read is GIL-atomic and a racing request() just gets picked up on
        # the next round
        if self._payload is None:
            return None
        with self._mu:
            p, self._payload = self._payload, None
            return p

    def pending(self) -> Optional[RequestState]:
        if self._pending is None:
            return None
        with self._mu:
            return self._pending

    def notify(self, result: RequestResult) -> None:
        with self._mu:
            rs, self._pending = self._pending, None
            self._payload = None
        if rs is not None:
            rs.notify(result)

    def close(self) -> None:
        self._stopped = True
        self.notify(RequestResult(code=RequestResultCode.TERMINATED))

    def tick(self) -> None:
        self._clock.advance()
        if self._pending is None:
            return
        with self._mu:
            rs = self._pending
            if rs is not None and rs.deadline < self._clock.tick:
                self._pending = None
                self._payload = None
            else:
                rs = None
        if rs is not None:
            rs.notify(RequestResult(code=RequestResultCode.TIMEOUT))


class PendingConfigChange(_SingleSlot):
    exist_error = PendingConfigChangeExistError


class PendingSnapshot(_SingleSlot):
    exist_error = PendingSnapshotExistError


class PendingLeaderTransfer(_SingleSlot):
    exist_error = PendingLeaderTransferExistError
