"""Replicated state machine layer (reference ``internal/rsm/``).

Adapts the three public SM contracts to one managed interface, drives apply
batches with exactly-once client sessions, tracks applied membership, and
owns the versioned snapshot file format.
"""
from .adapters import (  # noqa: F401
    IManagedStateMachine,
    from_concurrent_sm,
    from_on_disk_sm,
    from_regular_sm,
)
from .membership import MembershipState  # noqa: F401
from .session import SessionManager  # noqa: F401
from .statemachine import StateMachine, SSMeta, SSRequest, SSReqType, Task  # noqa: F401
from .taskqueue import TaskQueue  # noqa: F401
