"""Uniform managed interface over the three public SM contracts.

Reference: ``internal/rsm/sm.go:27-386`` (adapter structs) and
``internal/rsm/native.go:55`` (``IManagedStateMachine``).  Each adapter
normalizes its contract to batch update + snapshot hooks so the
:class:`dragonboat_tpu.rsm.statemachine.StateMachine` manager never branches
on the user SM kind except where semantics genuinely differ (concurrent
snapshotting, on-disk open/sync).
"""
from __future__ import annotations

import abc
from typing import BinaryIO, List, Optional

from ..statemachine import (
    IConcurrentStateMachine,
    IOnDiskStateMachine,
    IStateMachine,
    Result,
    SMEntry,
    SnapshotFile,
    SnapshotFileCollection,
    StopChecker,
)
from ..wire import StateMachineType


class IManagedStateMachine(abc.ABC):
    """Reference ``native.go:55``."""

    sm_type: StateMachineType = StateMachineType.REGULAR

    @property
    def concurrent_snapshot(self) -> bool:
        return False

    @property
    def on_disk(self) -> bool:
        return False

    def open(self, stopc: StopChecker) -> int:
        """On-disk SMs return their last applied index; others 0."""
        return 0

    @abc.abstractmethod
    def update(self, entries: List[SMEntry]) -> List[SMEntry]: ...

    @abc.abstractmethod
    def lookup(self, query: object) -> object: ...

    def sync(self) -> None:
        pass

    def prepare_snapshot(self) -> object:
        return None

    @abc.abstractmethod
    def save_snapshot(
        self,
        ctx: object,
        w: BinaryIO,
        files: Optional[SnapshotFileCollection],
        stopc: StopChecker,
    ) -> None: ...

    @abc.abstractmethod
    def recover_from_snapshot(
        self, r: BinaryIO, files: List[SnapshotFile], stopc: StopChecker
    ) -> None: ...

    def close(self) -> None:
        pass


class RegularSM(IManagedStateMachine):
    """Reference ``sm.go`` ``RegularStateMachine``."""

    sm_type = StateMachineType.REGULAR

    def __init__(self, sm: IStateMachine):
        self.sm = sm

    def update(self, entries: List[SMEntry]) -> List[SMEntry]:
        for e in entries:
            e.result = self.sm.update(e.cmd) or Result()
        return entries

    def lookup(self, query: object) -> object:
        return self.sm.lookup(query)

    def save_snapshot(self, ctx, w, files, stopc) -> None:
        self.sm.save_snapshot(w, files, stopc)

    def recover_from_snapshot(self, r, files, stopc) -> None:
        self.sm.recover_from_snapshot(r, files, stopc)

    def close(self) -> None:
        self.sm.close()


class ConcurrentSM(IManagedStateMachine):
    """Reference ``sm.go`` ``ConcurrentStateMachine``."""

    sm_type = StateMachineType.CONCURRENT

    def __init__(self, sm: IConcurrentStateMachine):
        self.sm = sm

    @property
    def concurrent_snapshot(self) -> bool:
        return True

    def update(self, entries: List[SMEntry]) -> List[SMEntry]:
        return self.sm.update(entries)

    def lookup(self, query: object) -> object:
        return self.sm.lookup(query)

    def prepare_snapshot(self) -> object:
        return self.sm.prepare_snapshot()

    def save_snapshot(self, ctx, w, files, stopc) -> None:
        self.sm.save_snapshot(ctx, w, files, stopc)

    def recover_from_snapshot(self, r, files, stopc) -> None:
        self.sm.recover_from_snapshot(r, files, stopc)

    def close(self) -> None:
        self.sm.close()


class OnDiskSM(IManagedStateMachine):
    """Reference ``sm.go`` ``OnDiskStateMachine``."""

    sm_type = StateMachineType.ON_DISK

    def __init__(self, sm: IOnDiskStateMachine):
        self.sm = sm
        self._opened = False

    @property
    def concurrent_snapshot(self) -> bool:
        return True

    @property
    def on_disk(self) -> bool:
        return True

    def open(self, stopc: StopChecker) -> int:
        idx = self.sm.open(stopc)
        self._opened = True
        return idx

    def update(self, entries: List[SMEntry]) -> List[SMEntry]:
        if not self._opened:
            raise RuntimeError("update called before open")
        return self.sm.update(entries)

    def lookup(self, query: object) -> object:
        return self.sm.lookup(query)

    def sync(self) -> None:
        self.sm.sync()

    def prepare_snapshot(self) -> object:
        return self.sm.prepare_snapshot()

    def save_snapshot(self, ctx, w, files, stopc) -> None:
        # on-disk snapshots carry no external file collection: state streams
        # directly from the SM's own store (reference statemachine/disk.go)
        self.sm.save_snapshot(ctx, w, stopc)

    def recover_from_snapshot(self, r, files, stopc) -> None:
        self.sm.recover_from_snapshot(r, stopc)

    def close(self) -> None:
        self.sm.close()


def from_regular_sm(sm: IStateMachine) -> IManagedStateMachine:
    return RegularSM(sm)


def from_concurrent_sm(sm: IConcurrentStateMachine) -> IManagedStateMachine:
    return ConcurrentSM(sm)


def from_on_disk_sm(sm: IOnDiskStateMachine) -> IManagedStateMachine:
    return OnDiskSM(sm)
