"""ChunkWriter: stream a snapshot image directly into transport chunks.

Reference: ``internal/rsm/chunkwriter.go:35`` — on-disk SMs stream their
state to a slow follower without materializing a file; the byte stream is a
valid snapshot image (header + crc'd blocks) so the receiver's assembled
file can be opened by the normal :class:`SnapshotReader`.

Because the aggregate payload crc cannot be known before streaming starts,
streamed images set ``checksum_type = STREAMED`` in the header and rely on
the per-block crcs (the reference's v2 format solves the same problem with
tail checksums).  The total chunk count is equally unknown, so the final
chunk carries the ``LAST_CHUNK_COUNT`` sentinel.
"""
from __future__ import annotations

import struct
import zlib

from ..settings import Hard, Soft
from ..wire import Chunk, LAST_CHUNK_COUNT
from ..server.snapshotenv import snapshot_dir_name
from .snapshotio import (
    _BLOCK_HDR,
    _HEADER_CRC_OFF,
    _HEADER_FMT,
    BLOCK_SIZE,
    CKS_STREAMED,
    MAGIC,
    V2,
)


class ChunkWriter:
    """File-like writer emitting transport chunks (reference
    ``chunkwriter.go``).

    ``sink.receive(chunk) -> bool`` consumes chunks; the last one is marked
    with the ``LAST_CHUNK_COUNT`` sentinel so ``Chunk.is_last_chunk()`` is
    true on the receiving tracker.
    """

    def __init__(
        self,
        sink,
        meta,
        cluster_id: int,
        node_id: int,
        from_node_id: int,
        deployment_id: int,
    ):
        self.sink = sink
        self.meta = meta
        self.cluster_id = cluster_id
        self.node_id = node_id
        self.from_node_id = from_node_id
        self.deployment_id = deployment_id
        self._chunk_buf = bytearray()
        self._block_buf = bytearray()
        self._chunk_id = 0
        self._finalized = False
        self.total = 0
        self._write_header()

    # ---- snapshot-image framing ----

    def _write_header(self) -> None:
        header = bytearray(Hard.snapshot_header_size)
        _HEADER_FMT.pack_into(header, 0, MAGIC, V2, CKS_STREAMED, 0, 0, 0)
        hcrc = zlib.crc32(bytes(header[:_HEADER_CRC_OFF]))
        struct.pack_into("<I", header, _HEADER_CRC_OFF, hcrc)
        self._emit(bytes(header))

    def write_session(self, data: bytes) -> None:
        # streamed images carry no session store (on-disk SMs only)
        if data:
            raise ValueError("streamed snapshots cannot carry sessions")

    def write(self, data: bytes) -> int:
        self._block_buf += data
        self.total += len(data)
        while len(self._block_buf) >= BLOCK_SIZE:
            self._emit_block(self._block_buf[:BLOCK_SIZE])
            del self._block_buf[:BLOCK_SIZE]
        return len(data)

    def _emit_block(self, block) -> None:
        crc = zlib.crc32(bytes(block))
        self._emit(_BLOCK_HDR.pack(len(block), crc) + bytes(block))

    # ---- chunk framing ----

    def _emit(self, data: bytes) -> None:
        self._chunk_buf += data
        while len(self._chunk_buf) >= Soft.snapshot_chunk_size:
            self._send_chunk(
                bytes(self._chunk_buf[: Soft.snapshot_chunk_size]), False
            )
            del self._chunk_buf[: Soft.snapshot_chunk_size]

    def _make_chunk(self, data: bytes, last: bool) -> Chunk:
        c = Chunk(
            cluster_id=self.cluster_id,
            node_id=self.node_id,
            from_=self.from_node_id,
            chunk_id=self._chunk_id,
            chunk_size=len(data),
            chunk_count=LAST_CHUNK_COUNT if last else 0,
            data=data,
            index=self.meta.index,
            term=self.meta.term,
            membership=self.meta.membership,
            filepath=f"{snapshot_dir_name(self.meta.index)}.ss",
            deployment_id=self.deployment_id,
            file_chunk_id=self._chunk_id,
            file_chunk_count=0,
            on_disk_index=self.meta.on_disk_index,
        )
        return c

    def _send_chunk(self, data: bytes, last: bool) -> None:
        c = self._make_chunk(data, last)
        self._chunk_id += 1
        if not self.sink.receive(c):
            raise RuntimeError("chunk sink failed")

    def finalize(self) -> None:
        if self._finalized:
            return
        if self._block_buf:
            self._emit_block(self._block_buf)
            self._block_buf.clear()
        self._send_chunk(bytes(self._chunk_buf), True)
        self._chunk_buf.clear()
        self._finalized = True
