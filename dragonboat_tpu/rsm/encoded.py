"""Encoded entry payloads: versioned 1-byte header + optional compression.

Reference: ``internal/rsm/encoded.go:47-176``.  Entries proposed with a
non-empty command are stored as ``EntryType.ENCODED`` whose cmd is:

    |Version|CompressionFlag|SessionFlag|
    | 4Bits |     3Bits     |   1Bit    |      (1 header byte)

followed by the payload — raw bytes for no-compression, a snappy block
(which embeds its own uvarint uncompressed length) for snappy.
"""
from __future__ import annotations

from .. import dio
from ..wire import Entry, EntryType

EE_HEADER_SIZE = 1
EE_V0 = 0 << 4

EE_NO_COMPRESSION = 0 << 1
EE_SNAPPY = 1 << 1

EE_NO_SESSION = 0
EE_HAS_SESSION = 1

_VER_MASK = 15 << 4
_CT_MASK = 7 << 1
_SES_MASK = 1


def to_dio_compression_type(ct: int) -> dio.CompressionType:
    """config.CompressionType value → dio.CompressionType."""
    if ct == 0:
        return dio.CompressionType.NO_COMPRESSION
    if ct == 1:
        return dio.CompressionType.SNAPPY
    raise ValueError(f"unknown compression type {ct}")


def get_max_block_size(ct: int) -> int:
    return dio.max_block_len(to_dio_compression_type(ct))


def _header(version: int, cf: int, session: bool) -> int:
    return version | cf | (EE_HAS_SESSION if session else EE_NO_SESSION)


def parse_header(cmd) -> tuple:
    h = cmd[0]
    return h & _VER_MASK, h & _CT_MASK, bool(h & _SES_MASK)


def get_encoded_payload(ct: dio.CompressionType, cmd) -> bytes:
    """Reference ``GetEncodedPayload`` (v0)."""
    if not cmd:
        raise ValueError("empty payload")
    if ct == dio.CompressionType.NO_COMPRESSION:
        return bytes([_header(EE_V0, EE_NO_COMPRESSION, False)]) + bytes(cmd)
    if ct == dio.CompressionType.SNAPPY:
        return bytes([_header(EE_V0, EE_SNAPPY, False)]) + dio.compress_snappy_block(cmd)
    raise ValueError(f"unknown compression type {ct}")


def get_decoded_payload(cmd) -> bytes:
    """Reference ``getDecodedPayload``."""
    ver, ct, has_session = parse_header(cmd)
    if ver != EE_V0:
        raise ValueError(f"unknown encoded entry version {ver >> 4}")
    if has_session:
        raise ValueError("v0 cmd has session info")
    if ct == EE_NO_COMPRESSION:
        return bytes(cmd[EE_HEADER_SIZE:])
    if ct == EE_SNAPPY:
        return dio.decompress_snappy_block(cmd[EE_HEADER_SIZE:])
    raise ValueError(f"unknown compression flag {ct >> 1}")


def get_entry_payload(e: Entry) -> bytes:
    """Payload ready for the user SM (reference ``getEntryPayload``)."""
    if e.type in (EntryType.APPLICATION, EntryType.CONFIG_CHANGE):
        return e.cmd
    if e.type == EntryType.ENCODED:
        return get_decoded_payload(e.cmd)
    raise ValueError(f"unknown entry type {e.type}")
