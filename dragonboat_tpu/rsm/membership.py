"""Applied membership state.

Reference: ``internal/rsm/membership.go:56`` — the authoritative view of
addresses / observers / witnesses / removed ids plus the ConfigChangeId used
for ordered-config-change enforcement and add/remove dedup.
"""
from __future__ import annotations

import zlib
from typing import Optional

from ..config import Config
from ..logger import get_logger
from ..wire import ConfigChange, ConfigChangeType, Membership
from ..wire.codec import encode_membership

plog = get_logger("rsm")

CCT = ConfigChangeType


class MembershipState:
    """Reference ``membership.go`` ``membership``."""

    def __init__(self, cluster_id: int, node_id: int, ordered: bool):
        self.cluster_id = cluster_id
        self.node_id = node_id
        self.ordered = ordered
        self.members = Membership()

    # ---- snapshot plumbing ----

    def set(self, m: Membership) -> None:
        self.members = m.clone()

    def get(self) -> Membership:
        return self.members.clone()

    def hash(self) -> int:
        return zlib.crc32(encode_membership(self.members))

    # ---- application (reference membership.go:131-292) ----

    def is_empty(self) -> bool:
        return len(self.members.addresses) == 0

    def is_config_change_up_to_date(self, cc: ConfigChange) -> bool:
        if not self.ordered or cc.initialize:
            return True
        return self.members.config_change_id == cc.config_change_id

    def is_adding_removed_node(self, cc: ConfigChange) -> bool:
        if cc.type in (CCT.ADD_NODE, CCT.ADD_OBSERVER, CCT.ADD_WITNESS):
            return cc.node_id in self.members.removed
        return False

    def is_promoting_observer(self, cc: ConfigChange) -> bool:
        if cc.type != CCT.ADD_NODE:
            return False
        addr = self.members.observers.get(cc.node_id)
        return addr is not None and addr == cc.address

    def is_invalid_observer_promotion(self, cc: ConfigChange) -> bool:
        if cc.type != CCT.ADD_NODE:
            return False
        addr = self.members.observers.get(cc.node_id)
        return addr is not None and addr != cc.address

    def is_adding_existing_member(self, cc: ConfigChange) -> bool:
        # adding again with a different address is the dangerous case
        if cc.type == CCT.ADD_NODE:
            if self.is_promoting_observer(cc):
                return False
            if cc.node_id in self.members.addresses:
                return self.members.addresses[cc.node_id] != cc.address
            return cc.address in self.members.addresses.values()
        if cc.type == CCT.ADD_OBSERVER:
            if cc.node_id in self.members.observers:
                return self.members.observers[cc.node_id] != cc.address
            return (
                cc.address in self.members.addresses.values()
                or cc.address in self.members.observers.values()
            )
        if cc.type == CCT.ADD_WITNESS:
            if cc.node_id in self.members.witnesses:
                return True
            return cc.address in self.members.addresses.values()
        return False

    def is_adding_node_as_observer(self, cc: ConfigChange) -> bool:
        return cc.type == CCT.ADD_OBSERVER and cc.node_id in self.members.addresses

    def is_adding_node_as_witness(self, cc: ConfigChange) -> bool:
        return cc.type == CCT.ADD_WITNESS and (
            cc.node_id in self.members.addresses
            or cc.node_id in self.members.observers
        )

    def is_deleting_only_node(self, cc: ConfigChange) -> bool:
        return (
            cc.type == CCT.REMOVE_NODE
            and len(self.members.addresses) == 1
            and cc.node_id in self.members.addresses
        )

    def handle_config_change(self, cc: ConfigChange, index: int) -> bool:
        """Validate + apply; returns True when accepted
        (reference ``membership.go`` ``handleConfigChange``)."""
        accepted = (
            self.is_config_change_up_to_date(cc)
            and not self.is_adding_removed_node(cc)
            and not self.is_adding_existing_member(cc)
            and not self.is_invalid_observer_promotion(cc)
            and not self.is_adding_node_as_observer(cc)
            and not self.is_adding_node_as_witness(cc)
            and not self.is_deleting_only_node(cc)
        )
        if not accepted:
            plog.warning(
                "cluster %d rejected config change %s at index %d",
                self.cluster_id,
                cc,
                index,
            )
            return False
        self._apply(cc, index)
        return True

    def _apply(self, cc: ConfigChange, index: int) -> None:
        self.members.config_change_id = index
        if cc.type == CCT.ADD_NODE:
            self.members.observers.pop(cc.node_id, None)
            if cc.node_id in self.members.witnesses:
                raise RuntimeError("promoting a witness is not allowed")
            self.members.addresses[cc.node_id] = cc.address
        elif cc.type == CCT.ADD_OBSERVER:
            self.members.observers[cc.node_id] = cc.address
        elif cc.type == CCT.ADD_WITNESS:
            self.members.witnesses[cc.node_id] = cc.address
        elif cc.type == CCT.REMOVE_NODE:
            self.members.addresses.pop(cc.node_id, None)
            self.members.observers.pop(cc.node_id, None)
            self.members.witnesses.pop(cc.node_id, None)
            self.members.removed[cc.node_id] = True
        else:
            raise RuntimeError(f"unknown config change type {cc.type}")

    # ---- queries ----

    def local_node_removed(self) -> bool:
        # only an applied RemoveNode counts: a joining node legitimately has
        # no membership entry until its AddNode commits
        return self.node_id in self.members.removed

    @staticmethod
    def bootstrap(
        cluster_id: int, node_id: int, config: Config, addresses
    ) -> "MembershipState":
        m = MembershipState(cluster_id, node_id, config.ordered_config_change)
        for nid, addr in addresses.items():
            m.members.addresses[nid] = addr
        return m
