"""Server-side client session store: exactly-once apply semantics.

Reference: ``internal/rsm/session.go`` (per-session response cache keyed by
SeriesID), ``internal/rsm/lrusession.go`` (LRU over sessions, max 4096 =
``settings/hard.go:85``) and ``internal/rsm/sessionmanager.go``.  The whole
store serializes into every snapshot so all replicas evict identically.
"""
from __future__ import annotations

import zlib
from collections import OrderedDict
from typing import Dict, Optional, Tuple

from ..settings import Hard
from ..statemachine import Result
from ..wire.codec import _read_bytes, _read_uvarint, _write_bytes, _write_uvarint


class Session:
    """Reference ``internal/rsm/session.go:49``."""

    __slots__ = ("client_id", "responded_up_to", "history")

    def __init__(self, client_id: int):
        self.client_id = client_id
        self.responded_up_to = 0
        self.history: Dict[int, Result] = {}

    def add_response(self, series_id: int, result: Result) -> None:
        if series_id in self.history:
            raise RuntimeError("adding a duplicated response")
        self.history[series_id] = result

    def get_response(self, series_id: int) -> Tuple[Optional[Result], bool]:
        r = self.history.get(series_id)
        return r, r is not None

    def has_responded(self, series_id: int) -> bool:
        return series_id <= self.responded_up_to

    def clear_to(self, series_id: int) -> None:
        """Evict cached responses up to ``series_id`` inclusive (reference
        ``session.go`` ``clearTo``)."""
        if series_id <= self.responded_up_to:
            return
        if series_id == self.responded_up_to + 1:
            self.history.pop(series_id, None)
        else:
            for k in [k for k in self.history if k <= series_id]:
                del self.history[k]
        self.responded_up_to = series_id

    # deterministic serialization (order by series id)
    def save(self, buf: bytearray) -> None:
        _write_uvarint(buf, self.client_id)
        _write_uvarint(buf, self.responded_up_to)
        _write_uvarint(buf, len(self.history))
        for sid in sorted(self.history):
            r = self.history[sid]
            _write_uvarint(buf, sid)
            _write_uvarint(buf, r.value)
            _write_bytes(buf, r.data)

    @staticmethod
    def load(data: bytes, pos: int) -> Tuple["Session", int]:
        cid, pos = _read_uvarint(data, pos)
        s = Session(cid)
        s.responded_up_to, pos = _read_uvarint(data, pos)
        n, pos = _read_uvarint(data, pos)
        for _ in range(n):
            sid, pos = _read_uvarint(data, pos)
            val, pos = _read_uvarint(data, pos)
            d, pos = _read_bytes(data, pos)
            s.history[sid] = Result(value=val, data=d)
        return s, pos


class SessionManager:
    """LRU session store (reference ``lrusession.go:54`` +
    ``sessionmanager.go:27-135``)."""

    def __init__(self, max_sessions: int = 0):
        self._max = max_sessions or Hard.lru_max_session_count
        self._sessions: "OrderedDict[int, Session]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._sessions)

    # ---- registration (reference sessionmanager.go:49-88) ----

    def register_client_id(self, client_id: int) -> Result:
        if client_id in self._sessions:
            self._sessions.move_to_end(client_id)
            return Result(value=client_id)
        self._sessions[client_id] = Session(client_id)
        if len(self._sessions) > self._max:
            self._sessions.popitem(last=False)  # evict LRU
        return Result(value=client_id)

    def unregister_client_id(self, client_id: int) -> Result:
        if client_id not in self._sessions:
            return Result(value=0)
        del self._sessions[client_id]
        return Result(value=client_id)

    def client_registered(self, client_id: int) -> Optional[Session]:
        s = self._sessions.get(client_id)
        if s is not None:
            self._sessions.move_to_end(client_id)
        return s

    # ---- dedup (reference sessionmanager.go:90-135) ----

    def update_required(
        self, session: Session, series_id: int
    ) -> Tuple[Optional[Result], bool]:
        """Returns ``(cached_result, update_required)``."""
        if session.has_responded(series_id):
            return None, False  # already responded; result no longer cached
        cached, ok = session.get_response(series_id)
        if ok:
            return cached, False
        return None, True

    def add_response(self, session: Session, series_id: int, result: Result):
        session.add_response(series_id, result)

    # ---- snapshot serialization ----

    def save(self) -> bytes:
        buf = bytearray()
        _write_uvarint(buf, len(self._sessions))
        # LRU order must be preserved so evictions replay identically
        for s in self._sessions.values():
            s.save(buf)
        return bytes(buf)

    @staticmethod
    def load(data: bytes, max_sessions: int = 0) -> "SessionManager":
        sm = SessionManager(max_sessions)
        n, pos = _read_uvarint(data, 0)
        for _ in range(n):
            s, pos = Session.load(data, pos)
            sm._sessions[s.client_id] = s
        return sm

    def hash(self) -> int:
        """Cross-replica consistency hash (reference ``monkey.go`` session
        hash via ``GetSessionHash``)."""
        return zlib.crc32(self.save())
