"""Versioned snapshot file IO: header + crc-checked block payload.

Reference: ``internal/rsm/snapshotio.go`` (SnapshotWriter/Reader/Validator,
witness image, shrink) and ``internal/rsm/rw.go`` (v2 block writer with
per-block crc32).  Layout here:

    [1KB header][block]*[tail crc]
    header: magic(8) version(4) checksum_type(4) compression_type(4)
            session_size(8) payload_checksum(4) reserved... header_crc(4 @1020)
    block:  len(u32) crc32(u32) data[len]      (1MB data per block)

``session_size`` lets recovery split the payload into the session store image
and the user SM image without framing inside the payload.  Shrinking keeps
the header and replaces the payload with an empty image (reference
``snapshotio.go:443-516``), used by on-disk SMs whose state needs no replay.
"""
from __future__ import annotations

import os
import struct
import zlib
from typing import BinaryIO, List, Tuple

from .. import vfs
from ..settings import Hard
from ..wire import Snapshot, SnapshotFile

MAGIC = b"DBTPUSS1"
V2 = 2
BLOCK_SIZE = 1024 * 1024
_HEADER_FMT = struct.Struct("<8sIIIQI")  # magic, ver, cks, comp, session, payload_crc
_BLOCK_HDR = struct.Struct("<II")
_HEADER_CRC_OFF = 1020

EMPTY_PAYLOAD_CRC = 0

# checksum_type header values: DEFAULT has the aggregate payload crc in the
# header; STREAMED images (ChunkWriter) rely on per-block crcs because the
# aggregate cannot be known before streaming starts
CKS_DEFAULT = 0
CKS_STREAMED = 1


class SnapshotFormatError(ValueError):
    pass


class BlockWriter:
    """Buffers payload into crc'd blocks (reference ``rw.go:89-205``)."""

    def __init__(self, f: BinaryIO):
        self._f = f
        self._buf = bytearray()
        self._crc = 0  # running crc over block crcs
        self.total = 0

    def write(self, data: bytes) -> int:
        self._buf += data
        self.total += len(data)
        while len(self._buf) >= BLOCK_SIZE:
            self._flush_block(self._buf[:BLOCK_SIZE])
            del self._buf[:BLOCK_SIZE]
        return len(data)

    def _flush_block(self, block) -> None:
        crc = zlib.crc32(bytes(block))
        self._f.write(_BLOCK_HDR.pack(len(block), crc))
        self._f.write(bytes(block))
        self._crc = zlib.crc32(crc.to_bytes(4, "little"), self._crc)

    def flush(self) -> int:
        """Flush the final partial block; returns the payload checksum."""
        if self._buf:
            self._flush_block(self._buf)
            self._buf.clear()
        return self._crc


class BlockReader:
    """Streaming reader over crc'd blocks."""

    def __init__(self, f: BinaryIO):
        self._f = f
        self._pending = bytearray()
        self._crc = 0
        self._eof = False

    def _next_block(self) -> bool:
        hdr = self._f.read(_BLOCK_HDR.size)
        if len(hdr) < _BLOCK_HDR.size:
            self._eof = True
            return False
        ln, crc = _BLOCK_HDR.unpack(hdr)
        data = self._f.read(ln)
        if len(data) != ln or zlib.crc32(data) != crc:
            raise SnapshotFormatError("corrupted snapshot block")
        self._crc = zlib.crc32(crc.to_bytes(4, "little"), self._crc)
        self._pending += data
        return True

    def read(self, n: int = -1) -> bytes:
        while not self._eof and (n < 0 or len(self._pending) < n):
            self._next_block()
        if n < 0:
            out, self._pending = bytes(self._pending), bytearray()
        else:
            out = bytes(self._pending[:n])
            del self._pending[:n]
        return out

    def checksum(self) -> int:
        return self._crc


class SnapshotWriter:
    """Reference ``snapshotio.go:163`` ``SnapshotWriter``.

    With ``compression`` set (dio.CompressionType value, recorded in the
    header's compression_type field) the payload stream — session image and
    user SM image — is compressed before blocking; ``session_size`` always
    refers to UNCOMPRESSED bytes so recovery splits after decompression.
    """

    def __init__(self, path: str, fs: vfs.IFS = vfs.DEFAULT, compression: int = 0):
        from .. import dio

        self.path = path
        self._fs = fs
        self.compression = int(compression)
        self._f = fs.open(path, "wb")
        self._f.write(b"\0" * Hard.snapshot_header_size)  # placeholder
        self._bw = BlockWriter(self._f)
        self._out = (
            dio.Compressor(dio.CompressionType(self.compression), self._bw)
            if self.compression
            else self._bw
        )
        self.session_size = 0
        self._closed = False

    def write_session(self, data: bytes) -> None:
        self.session_size = len(data)
        self._out.write(data)

    def write(self, data: bytes) -> int:
        self._out.write(data)
        return len(data)

    def finalize(self) -> None:
        if self._out is not self._bw:
            self._out.close()  # flush the final compressed block
        payload_crc = self._bw.flush()
        header = bytearray(Hard.snapshot_header_size)
        _HEADER_FMT.pack_into(
            header, 0, MAGIC, V2, 0, self.compression, self.session_size, payload_crc
        )
        hcrc = zlib.crc32(bytes(header[:_HEADER_CRC_OFF]))
        struct.pack_into("<I", header, _HEADER_CRC_OFF, hcrc)
        self._f.flush()
        self._f.seek(0)
        self._f.write(bytes(header))
        self._fs.fsync(self._f)
        self._f.close()
        self._closed = True

    def abort(self) -> None:
        if not self._closed:
            self._f.close()
            try:
                self._fs.remove(self.path)
            except OSError:
                pass
            self._closed = True


def read_header(f: BinaryIO) -> Tuple[int, int, int, int, int]:
    """Returns (session_size, payload_crc, version, checksum_type,
    compression_type); validates the header crc."""
    header = f.read(Hard.snapshot_header_size)
    if len(header) != Hard.snapshot_header_size:
        raise SnapshotFormatError("truncated snapshot header")
    magic, ver, cks, comp, session_size, payload_crc = _HEADER_FMT.unpack_from(
        header, 0
    )
    if magic != MAGIC:
        raise SnapshotFormatError("bad snapshot magic")
    if ver != V2:
        raise SnapshotFormatError(f"unsupported snapshot version {ver}")
    (hcrc,) = struct.unpack_from("<I", header, _HEADER_CRC_OFF)
    if zlib.crc32(header[:_HEADER_CRC_OFF]) != hcrc:
        raise SnapshotFormatError("corrupted snapshot header")
    return session_size, payload_crc, ver, cks, comp


class SnapshotReader:
    """Reference ``snapshotio.go:272`` ``SnapshotReader``."""

    def __init__(self, path: str, fs: vfs.IFS = vfs.DEFAULT):
        from .. import dio

        self.path = path
        self._f = fs.open(path, "rb")
        (
            self.session_size,
            self.payload_crc,
            self.version,
            self.checksum_type,
            self.compression,
        ) = read_header(self._f)
        self._br = BlockReader(self._f)
        try:
            ct = dio.CompressionType(self.compression)
        except ValueError as e:
            # malformed-header class of error: callers (the snapshot
            # validator, recovery) expect SnapshotFormatError
            raise SnapshotFormatError(
                f"unknown compression type {self.compression}"
            ) from e
        self._in = (
            dio.Decompressor(ct, self._br) if self.compression else self._br
        )

    def read_session(self) -> bytes:
        return self._in.read(self.session_size)

    def read(self, n: int = -1) -> bytes:
        return self._in.read(n)

    def validate_payload(self) -> None:
        self._br.read(-1)  # drain; per-block crcs verified as a side effect
        if (
            self.checksum_type != CKS_STREAMED
            and self._br.checksum() != self.payload_crc
        ):
            raise SnapshotFormatError("snapshot payload checksum mismatch")

    def close(self) -> None:
        self._f.close()


def validate_snapshot_file(path: str, fs: vfs.IFS = vfs.DEFAULT) -> bool:
    """Reference ``snapshotio.go:392`` ``SnapshotValidator``."""
    try:
        r = SnapshotReader(path, fs)
        try:
            r.validate_payload()
        finally:
            r.close()
        return True
    except (OSError, SnapshotFormatError):
        return False


def shrink_snapshot(src: str, dst: str, fs: vfs.IFS = vfs.DEFAULT) -> None:
    """Strip the payload, keep sessions-empty image (reference
    ``snapshotio.go:443-516`` ``ShrinkSnapshot``): used when an on-disk SM
    restarts — its state needs no replay, only valid metadata."""
    r = SnapshotReader(src, fs)
    try:
        r.validate_payload()
    finally:
        r.close()
    w = SnapshotWriter(dst, fs)
    w.write_session(b"")
    w.finalize()


def write_witness_snapshot(path: str, fs: vfs.IFS = vfs.DEFAULT) -> None:
    """Tiny dummy image for witness replicas (reference
    ``snapshotio.go:133``)."""
    w = SnapshotWriter(path, fs)
    w.write_session(b"")
    w.finalize()


class FileCollection:
    """External snapshot file collection (reference ``internal/rsm/files.go``
    implementing ``sm.ISnapshotFileCollection``)."""

    def __init__(self, tmpdir: str, fs: vfs.IFS = vfs.DEFAULT):
        self.tmpdir = tmpdir
        self._fs = fs
        self.files: List[SnapshotFile] = []
        self._ids = set()

    def add_file(self, file_id: int, path: str, metadata: bytes) -> None:
        if file_id in self._ids:
            raise ValueError(f"duplicated external file id {file_id}")
        self._ids.add(file_id)
        self.files.append(
            SnapshotFile(file_id=file_id, filepath=path, metadata=metadata)
        )

    def prepare_files(self, ss: Snapshot) -> None:
        """Record collected files into the snapshot metadata with their
        final names (reference ``files.go`` ``PrepareFiles``)."""
        for f in self.files:
            final = os.path.join(
                os.path.dirname(ss.filepath) or self.tmpdir,
                f"external-file-{f.file_id}",
            )
            if self._fs.exists(f.filepath):
                self._fs.replace(f.filepath, final)
            size = self._fs.getsize(final) if self._fs.exists(final) else 0
            ss.files.append(
                SnapshotFile(
                    filepath=final,
                    file_size=size,
                    file_id=f.file_id,
                    metadata=f.metadata,
                )
            )
