"""StateMachine manager: drives the user SM from committed raft entries.

Reference: ``internal/rsm/statemachine.go`` — drains the task queue into
apply batches (:599-647), applies entries with exactly-once session dedup
(:883-977), applies config changes (:979), orchestrates snapshot save /
recover including the concurrent and on-disk variants (:552-814), and tracks
the ``onDiskInitIndex`` bookkeeping for on-disk SMs (:858-881).
"""
from __future__ import annotations

import enum
import threading
import zlib
from dataclasses import dataclass, field
from typing import List, Optional, Protocol, Tuple

from ..logger import get_logger
from ..statemachine import Result, SMEntry, StopChecker
from ..wire import (
    ConfigChange,
    Entry,
    EntryType,
    Membership,
    SERIES_ID_FOR_REGISTER,
    SERIES_ID_FOR_UNREGISTER,
    Snapshot,
    StateMachineType,
    config_change_from_entry,
)
from .adapters import IManagedStateMachine
from .membership import MembershipState
from .encoded import get_entry_payload
from .session import SessionManager

plog = get_logger("rsm")


class SSReqType(enum.IntEnum):
    """Snapshot request kinds (reference ``statemachine.go:71``)."""

    PERIODIC = 0
    USER_REQUESTED = 1
    EXPORTED = 2
    STREAMING = 3


@dataclass(slots=True)
class SSRequest:
    """Reference ``statemachine.go`` ``SSRequest``."""

    type: SSReqType = SSReqType.PERIODIC
    key: int = 0
    path: str = ""
    override_compaction_overhead: bool = False
    compaction_overhead: int = 0

    @property
    def exported(self) -> bool:
        return self.type == SSReqType.EXPORTED

    @property
    def streaming(self) -> bool:
        return self.type == SSReqType.STREAMING


@dataclass(slots=True)
class SSMeta:
    """Everything captured at snapshot time (reference ``statemachine.go:92``)."""

    from_index: int = 0
    index: int = 0
    term: int = 0
    on_disk_index: int = 0
    request: SSRequest = field(default_factory=SSRequest)
    membership: Membership = field(default_factory=Membership)
    session: bytes = b""
    ctx: object = None
    type: StateMachineType = StateMachineType.REGULAR
    compression: int = 0


@dataclass(slots=True)
class Task:
    """A unit of apply/snapshot work (reference ``statemachine.go:106``)."""

    cluster_id: int = 0
    node_id: int = 0
    index: int = 0
    entries: List[Entry] = field(default_factory=list)
    save: bool = False
    stream: bool = False
    # target replica of a stream task (Task.index stays a raft index)
    stream_to: int = 0
    recover: bool = False
    initial: bool = False
    new_node: bool = False
    ss: Optional[Snapshot] = None
    ss_request: SSRequest = field(default_factory=SSRequest)

    def is_snapshot_task(self) -> bool:
        return self.save or self.stream or self.recover

    @property
    def periodic_sync(self) -> bool:
        # reference Task.PeriodicSync: on-disk SM fsync tick
        return False


class INodeProxy(Protocol):
    """Callbacks from the apply loop into the node runtime (reference
    ``internal/rsm/statemachine.go`` ``INode``, implemented by ``node.go``)."""

    def node_ready(self) -> None: ...

    def apply_update(
        self,
        entry: Entry,
        result: Result,
        rejected: bool,
        ignored: bool,
        notify_read: bool,
    ) -> None: ...

    def apply_config_change(
        self, cc: ConfigChange, key: int, rejected: bool
    ) -> None: ...

    def restore_remotes(self, ss: Snapshot) -> None: ...

    def should_stop(self) -> bool: ...


class ISnapshotter(Protocol):
    """Snapshot file orchestration (reference ``statemachine.go:150``
    ``ISnapshotter``, implemented by the top-level ``snapshotter.go``)."""

    def save(self, savable, meta: SSMeta) -> Tuple[Snapshot, object]: ...

    def recover(self, recoverable, ss: Snapshot) -> None: ...

    def stream(
        self, streamable, meta: SSMeta, sink, to_node_id: int,
        deployment_id: int,
    ) -> None: ...

    def get_snapshot(self, index: int) -> Snapshot: ...

    def is_no_snapshot_error(self, e: Exception) -> bool: ...


class _CaptureSavable:
    """Savable facade over a native consistent capture
    (``natr_capture_sm``): writes the pre-serialized KV image in exactly
    the framing ``NativeKVStateMachine.save_snapshot`` uses, so the
    recovery side is the shared adapter path."""

    def __init__(self, kv_image: bytes) -> None:
        self._kv = kv_image

    def save_snapshot_payload(self, meta: "SSMeta", writer) -> None:
        writer.write_session(meta.session)
        writer.write(len(self._kv).to_bytes(8, "little") + self._kv)


class StateMachine:
    """Reference ``statemachine.go:162`` ``StateMachine``."""

    def __init__(
        self,
        managed: IManagedStateMachine,
        snapshotter: Optional[ISnapshotter],
        node: INodeProxy,
        cluster_id: int,
        node_id: int,
        ordered_config_change: bool = False,
        is_witness: bool = False,
        snapshot_compression: int = 0,
    ):
        self.managed = managed
        self.snapshotter = snapshotter
        self.node = node
        self.cluster_id = cluster_id
        self.node_id = node_id
        self.is_witness = is_witness
        self.snapshot_compression = snapshot_compression
        self.sessions = SessionManager()
        # native C-ABI SM (natsm.py): dedup against the SAME store the
        # enrolled native core applies through, so enroll/eject carries no
        # session hand-off and cross-plane session hashes agree
        user = getattr(managed, "sm", None)
        if getattr(user, "natsm_sess_handle", 0):
            from ..native.natsm import NativeSessionManager

            self.sessions = NativeSessionManager(user)
        self.members = MembershipState(cluster_id, node_id, ordered_config_change)
        self._mu = threading.RLock()
        # regular (non-concurrent) SMs must not be mutated while a snapshot
        # of them is being written: the apply path and the snapshot pool
        # serialize on this lock (reference statemachine.go:761 holds the
        # SM RLock for the whole regular save).  Concurrent/on-disk SMs
        # snapshot from a prepared context and skip it.
        self._update_mu = threading.RLock()
        # serializes whole snapshot save/recover operations of this SM
        # (see save() docstring); always acquired BEFORE _update_mu
        self._save_mu = threading.RLock()
        # watermarks (reference statemachine.go index/term fields)
        self.last_applied = 0
        self.last_applied_term = 0
        self.batched_last_applied = 0
        self.snapshot_index = 0
        # on-disk SM bookkeeping (reference :858-881)
        self.on_disk_init_index = 0
        self.on_disk_index = 0
        self.stopc = StopChecker()

    # ---- identity ----

    @property
    def sm_type(self) -> StateMachineType:
        return self.managed.sm_type

    @property
    def on_disk(self) -> bool:
        return self.managed.on_disk

    @property
    def concurrent_snapshot(self) -> bool:
        return self.managed.concurrent_snapshot

    # ---- lifecycle ----

    def open(self) -> int:
        """Open an on-disk SM; returns its persisted last-applied index
        (reference ``statemachine.go`` ``OpenOnDiskStateMachine``)."""
        idx = self.managed.open(self.stopc)
        with self._mu:
            self.on_disk_init_index = idx
            self.on_disk_index = idx
        return idx

    def offloaded(self) -> None:
        self.managed.close()

    # ---- watermarks ----

    def advance_applied_native(self, index: int, term: int) -> None:
        """Acknowledge entries applied by the NATIVE plane (fast lane +
        natsm): the shared SM instance already holds their effects; only
        the watermark moves here.  Monotonic — a lagging completion batch
        arriving after an eject-time catch-up must not regress it."""
        with self._mu:
            if index > self.last_applied:
                self.last_applied = index
                self.last_applied_term = max(self.last_applied_term, term)

    def get_last_applied(self) -> int:
        with self._mu:
            return self.last_applied

    def get_batched_last_applied(self) -> int:
        with self._mu:
            return self.batched_last_applied

    def set_batched_last_applied(self, index: int) -> None:
        with self._mu:
            self.batched_last_applied = index

    def get_snapshot_index(self) -> int:
        with self._mu:
            return self.snapshot_index

    # ---- read path ----

    def lookup(self, query: object) -> object:
        if self.stopc:
            raise RuntimeError("cluster stopped")
        return self.managed.lookup(query)

    def sync(self) -> None:
        self.managed.sync()

    # ---- apply path (reference Handle :599-647) ----

    def handle(self, tasks: List[Task]) -> Optional[Task]:
        """Apply normal tasks in order; stop at and return the first
        snapshot task (save/stream/recover) for the snapshot workers."""
        for t in tasks:
            if t.is_snapshot_task():
                # entries before it must already have been applied
                return t
            self._handle_apply_task(t)
        return None

    def _handle_apply_task(self, t: Task) -> None:
        if t.cluster_id != self.cluster_id or t.node_id != self.node_id:
            raise RuntimeError("task for a different node")
        if not t.entries:
            return
        self._handle_entries(t.entries)

    def _handle_entries(self, entries: List[Entry]) -> None:
        # batch consecutive plain updates; break out entries needing
        # individual treatment (reference handleBatch :935-977)
        batch: List[Tuple[Entry, SMEntry]] = []
        with self._mu:
            expected = self.last_applied + 1
        for e in entries:
            if e.index != expected:
                raise RuntimeError(
                    f"applying out-of-order entry {e.index}, want {expected}"
                )
            expected += 1
            if e.is_config_change():
                self._flush_batch(batch)
                self._handle_config_change(e)
            elif self.is_witness or e.is_empty():
                self._flush_batch(batch)
                self._handle_noop(e)
            elif not e.is_session_managed():
                if self._on_disk_skip(e):
                    self._flush_batch(batch)
                    self._advance(e, Result(), False, True, True)
                else:
                    batch.append((e, SMEntry(index=e.index, cmd=get_entry_payload(e))))
            else:
                self._flush_batch(batch)
                self._handle_session_entry(e)
        self._flush_batch(batch)

    def _on_disk_skip(self, e: Entry) -> bool:
        """Entries already covered by an on-disk SM's own store are not
        re-applied (reference ``shouldApplyEntry``/``onDiskInitIndex``)."""
        return self.on_disk and e.index <= self.on_disk_init_index

    def _flush_batch(self, batch: List[Tuple[Entry, SMEntry]]) -> None:
        if not batch:
            return
        sm_entries = [se for _, se in batch]
        with self._update_mu:
            results = self.managed.update(sm_entries)
        if len(results) != len(sm_entries):
            raise RuntimeError("update dropped entries")
        for (e, _), se in zip(batch, results):
            self._advance(e, se.result, False, False, True)
        batch.clear()

    def _handle_noop(self, e: Entry) -> None:
        self._advance(e, Result(), False, False, True)

    def _handle_config_change(self, e: Entry) -> None:
        cc = config_change_from_entry(e)
        accepted = self.members.handle_config_change(cc, e.index)
        with self._mu:
            self.last_applied = e.index
            self.last_applied_term = max(self.last_applied_term, e.term)
        self.node.apply_config_change(cc, e.key, not accepted)

    def _handle_session_entry(self, e: Entry) -> None:
        if self._on_disk_skip(e):
            self._advance(e, Result(), False, True, True)
            return
        if e.is_new_session_request():
            r = self.sessions.register_client_id(e.client_id)
            self._advance(e, r, r.value == 0, False, True)
            return
        if e.is_end_of_session_request():
            r = self.sessions.unregister_client_id(e.client_id)
            self._advance(e, r, r.value == 0, False, True)
            return
        session = self.sessions.client_registered(e.client_id)
        if session is None:
            # session not found: reject (reference handleUpdate :1029)
            self._advance(e, Result(), True, False, True)
            return
        if session.has_responded(e.series_id):
            self._advance(e, Result(), False, True, False)
            return
        cached, ok = session.get_response(e.series_id)
        if ok:
            self._advance(e, cached, False, False, True)
            return
        with self._update_mu:
            results = self.managed.update(
                [SMEntry(index=e.index, cmd=get_entry_payload(e))]
            )
        result = results[0].result
        session.add_response(e.series_id, result)
        if e.responded_to > 0:
            session.clear_to(e.responded_to)
        self._advance(e, result, False, False, True)

    def _advance(
        self,
        e: Entry,
        result: Result,
        rejected: bool,
        ignored: bool,
        notify_read: bool,
    ) -> None:
        with self._mu:
            self.last_applied = e.index
            self.last_applied_term = max(self.last_applied_term, e.term)
            if self.on_disk and not ignored:
                self.on_disk_index = e.index
        self.node.apply_update(e, result, rejected, ignored, notify_read)

    # ---- snapshot save (reference Save :552-814) ----

    def prepare_snapshot(self, req: SSRequest) -> SSMeta:
        """Capture a consistent snapshot point.  For concurrent/on-disk SMs
        this runs on the apply thread (updates paused); the actual save can
        then proceed concurrently with new updates."""
        with self._mu:
            meta = SSMeta(
                from_index=self.snapshot_index,
                index=self.last_applied,
                term=self.last_applied_term,
                on_disk_index=self.on_disk_index,
                request=req,
                membership=self.members.get(),
                session=b"" if (self.on_disk or self.is_witness) else self.sessions.save(),
                type=self.sm_type,
                compression=self.snapshot_compression,
            )
        if self.concurrent_snapshot:
            meta.ctx = self.managed.prepare_snapshot()
        return meta

    def save_snapshot_payload(self, meta: SSMeta, writer) -> None:
        """Write sessions + SM image through ``writer`` (used by the
        snapshotter while it owns the temp file)."""
        writer.write_session(meta.session)
        if not self.is_witness:
            self.managed.save_snapshot(meta.ctx, writer, None, self.stopc)

    def save(self, req: SSRequest) -> Tuple[Snapshot, object]:
        """Full snapshot save via the snapshotter.

        ``_save_mu`` serializes saves of this SM (a user-requested and a
        periodic save can otherwise run concurrently on two pool workers and
        clobber each other's identically-named temp dir — the reference
        serializes per group via the snapshotState single-slot handoff,
        ``snapshotstate.go:65``).  Regular SMs additionally hold
        ``_update_mu`` across BOTH the meta capture and the image write:
        capturing meta.index first and locking later would let applies land
        in between and the image would reflect state newer than its label —
        double-apply after recovery."""
        if self.snapshotter is None:
            raise RuntimeError("no snapshotter configured")
        with self._save_mu:
            if self.concurrent_snapshot or self.on_disk:
                meta = self._checked_meta(req)
                ss, env = self.snapshotter.save(self, meta)
            else:
                with self._update_mu:
                    meta = self._checked_meta(req)
                    ss, env = self.snapshotter.save(self, meta)
        with self._mu:
            if not req.exported and ss.index > self.snapshot_index:
                self.snapshot_index = ss.index
        return ss, env

    def save_from_capture(
        self,
        req: SSRequest,
        index: int,
        term: int,
        kv_image: bytes,
        session_image: bytes,
        membership=None,
    ) -> Tuple[Snapshot, object]:
        """Snapshot from a pre-captured consistent native image
        (``natr_capture_sm``): the native core serialized kv+sessions at
        exactly ``index`` under its group mutex, so — unlike :meth:`save`
        — no update lock is needed here and the fast lane keeps applying
        while the file is written.  The image framing matches
        ``NativeKVStateMachine.save_snapshot``, so recovery is the shared
        path.

        ``membership`` must be the view captured ATOMICALLY with
        ``index`` (the caller snapshots it before ``natr_capture_sm`` and
        falls back to the eject path if the config-change id moved —
        ``Node._try_capture_save``): reading live membership here would
        race a config-change apply landing between the native capture and
        this call, labeling the image with membership newer than its
        index (the reference captures both under one mutex,
        ``prepare_snapshot``).  ``None`` preserves the legacy live read
        for callers that hold applies off by construction."""
        if self.snapshotter is None:
            raise RuntimeError("no snapshotter configured")
        with self._save_mu:
            with self._mu:
                if index == 0 or index <= self.snapshot_index:
                    raise SnapshotIgnored("nothing new to snapshot")
                meta = SSMeta(
                    from_index=self.snapshot_index,
                    index=index,
                    term=term,
                    on_disk_index=0,
                    request=req,
                    membership=(
                        membership if membership is not None
                        else self.members.get()
                    ),
                    session=session_image,
                    type=self.sm_type,
                    compression=self.snapshot_compression,
                )
            ss, env = self.snapshotter.save(_CaptureSavable(kv_image), meta)
        with self._mu:
            if not req.exported and ss.index > self.snapshot_index:
                self.snapshot_index = ss.index
        return ss, env

    def stream(self, sink, to_node_id: int, deployment_id: int) -> None:
        """Stream this SM's state to a lagging follower (reference
        ``statemachine.go`` ``Stream``; on-disk SMs only).  The image is
        captured from a prepared context and written straight into the
        transport sink via the ChunkWriter — never materialized locally."""
        if self.snapshotter is None:
            raise RuntimeError("no snapshotter configured")
        # only the meta/ctx capture needs the save lock; the transfer
        # itself writes no local files and may take as long as the slowest
        # follower — holding _save_mu for it would stall periodic saves
        # and compaction (the reference streams concurrently with saves)
        with self._save_mu:
            meta = self.prepare_snapshot(SSRequest(type=SSReqType.STREAMING))
        self.snapshotter.stream(self, meta, sink, to_node_id, deployment_id)

    def _checked_meta(self, req: SSRequest) -> SSMeta:
        meta = self.prepare_snapshot(req)
        if meta.index < self.on_disk_init_index:
            raise SnapshotIgnored("nothing new to snapshot")
        if meta.index == 0 or (
            meta.from_index >= meta.index and not req.exported
        ):
            raise SnapshotIgnored("no progress since last snapshot")
        return meta

    # ---- snapshot recover (reference Recover :228-341) ----

    def recover(self, t: Task) -> Optional[Snapshot]:
        """Recover from the snapshot carried by ``t`` (install) or the newest
        local snapshot (restart).

        Lock order matches save(): ``_save_mu`` then ``_update_mu`` — an
        install arriving while a pool worker is still writing an image of
        this SM must not overwrite the state mid-serialization."""
        if self.snapshotter is None:
            raise RuntimeError("no snapshotter configured")
        ss = t.ss
        if ss is None or ss.is_empty():
            return None
        if ss.witness or ss.dummy:
            self._post_recover(ss)
            return ss
        if self.on_disk and ss.on_disk_index <= self.on_disk_init_index:
            # SM's own store already covers it; just adopt metadata
            self._post_recover(ss)
            return ss
        with self._save_mu:
            with self._update_mu:
                self.snapshotter.recover(self, ss)
        self._post_recover(ss)
        return ss

    def recover_from_payload(self, ss: Snapshot, reader) -> None:
        """Restore sessions + SM image from an open snapshot reader."""
        session_data = reader.read_session()
        if not (self.on_disk or self.is_witness):
            if hasattr(self.sessions, "recover_image"):
                # native-backed store: replace CONTENT in place — the
                # handle is shared with the enrolled native core, so
                # identity must survive recover (image format is byte-
                # compatible between the two managers)
                self.sessions.recover_image(session_data or b"\x00")
            else:
                self.sessions = (
                    SessionManager.load(session_data)
                    if session_data
                    else SessionManager()
                )
        if not ss.witness and not ss.dummy:
            self.managed.recover_from_snapshot(reader, list(ss.files), self.stopc)

    def _post_recover(self, ss: Snapshot) -> None:
        with self._mu:
            self.last_applied = max(self.last_applied, ss.index)
            self.last_applied_term = max(self.last_applied_term, ss.term)
            self.snapshot_index = max(self.snapshot_index, ss.index)
            if self.on_disk:
                self.on_disk_index = max(self.on_disk_index, ss.on_disk_index)
        self.members.set(ss.membership)
        self.node.restore_remotes(ss)

    # ---- consistency hashes (reference GetHash :578-596, monkey.go) ----

    def get_hash(self) -> int:
        data = self.sessions.save()
        h = zlib.crc32(data)
        with self._mu:
            h = zlib.crc32(
                self.last_applied.to_bytes(8, "little"), h
            )
        return zlib.crc32(self.members.hash().to_bytes(8, "little"), h)

    def get_session_hash(self) -> int:
        return self.sessions.hash()

    def get_membership_hash(self) -> int:
        return self.members.hash()

    def get_membership(self) -> Membership:
        return self.members.get()


class SnapshotIgnored(Exception):
    """Snapshot request skipped: no progress (reference ``ErrSnapshotIgnored``)."""
