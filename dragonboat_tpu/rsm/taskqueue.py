"""Apply task queue between step and apply workers.

Reference: ``internal/rsm/taskqueue.go:31-107`` — a mutex-protected slice
queue with a "busy" watermark (target length 1024, ``settings/soft.go:94``)
used for backpressure, drained in batches by the apply worker.
"""
from __future__ import annotations

import threading
from typing import List, Optional

from ..settings import Soft


class TaskQueue:
    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._tasks: List = []

    def enqueue(self, task) -> None:
        with self._mu:
            self._tasks.append(task)

    def get(self) -> Optional[object]:
        with self._mu:
            if not self._tasks:
                return None
            return self._tasks.pop(0)

    def get_all(self) -> List:
        with self._mu:
            tasks, self._tasks = self._tasks, []
            return tasks

    def size(self) -> int:
        with self._mu:
            return len(self._tasks)

    def more_entries_to_apply(self) -> bool:
        """Backpressure check (reference ``taskqueue.go`` ``MoreEntryToApply``)."""
        return self.size() < Soft.task_queue_target_length
