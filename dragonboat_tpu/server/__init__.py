"""Cross-cutting host runtime utilities (reference ``internal/server/``)."""
from .message import MessageQueue  # noqa: F401
from .partition import FixedPartitioner  # noqa: F401
from .snapshotenv import SSEnv, SSMode  # noqa: F401
