"""NodeHost directory management: layout, locking, compatibility checks.

Reference: ``internal/server/context.go:73-378`` — deployment-id based
directory layout under ``<node_host_dir>/<hostname>/<did>``, ``flock``-held
LOCK files so a second NodeHost on the same data directory fails fast, and
the ``dragonboat.ds`` flag file (``raftpb.RaftDataStatus``) recording the
owner address/hostname/deployment-id plus the hard-settings hash
(``internal/settings/hard.go:124-137``) so an incompatible change refuses
to open the store instead of corrupting it.
"""
from __future__ import annotations

import fcntl
import json
import os
import socket
import zlib
from typing import Dict, Optional

from ..settings import Hard
from .partition import FixedPartitioner

LOCK_FILENAME = "LOCK"
FLAG_FILENAME = "dragonboat-tpu.ds"
DEFAULT_CLUSTER_ID_MOD = 16
BIN_VER = 1  # on-disk LogDB binary format version


class ContextError(Exception):
    pass


class LockDirectoryError(ContextError):
    """Another live NodeHost holds the directory lock."""


class NotOwnerError(ContextError):
    """The directory belongs to a NodeHost with a different raft address."""


class HostnameChangedError(ContextError):
    pass


class DeploymentIDChangedError(ContextError):
    pass


class HardSettingsChangedError(ContextError):
    """A data-format-affecting (hard) setting differs from the one the
    directory was created with."""


class IncompatibleDataError(ContextError):
    pass


class ServerContext:
    """Reference ``server.Context``."""

    def __init__(self, nhconfig):
        self.nhconfig = nhconfig
        self.hostname = socket.gethostname() or "localhost"
        self.partitioner = FixedPartitioner(DEFAULT_CLUSTER_ID_MOD)
        self._flocks: Dict[str, object] = {}

    # ---- layout ----

    @staticmethod
    def _did_dirname(did: int) -> str:
        return f"{did:020d}"

    def _data_dirs(self):
        dir_ = self.nhconfig.node_host_dir
        lldir = getattr(self.nhconfig, "wal_dir", "") or dir_
        return dir_, lldir

    def get_logdb_dirs(self, did: int):
        """(data dir, low-latency WAL dir) for this deployment.

        The hostname is recorded in the flag file, NOT the path: embedding
        it in the layout would give a renamed host a fresh empty directory
        — silently discarding its log and vote record — and the
        HostnameChangedError check could never fire."""
        dir_, lldir = self._data_dirs()
        sub = self._did_dirname(did)
        return os.path.join(dir_, sub), os.path.join(lldir, sub)

    def get_snapshot_dir(self, did: int, cluster_id: int, node_id: int) -> str:
        part = self.partitioner.get_partition_id(cluster_id)
        return os.path.join(
            self.nhconfig.node_host_dir,
            self._did_dirname(did),
            f"snapshot-part-{part}",
            f"snapshot-{cluster_id}-{node_id}",
        )

    def create_nodehost_dir(self, did: int):
        dir_, lldir = self.get_logdb_dirs(did)
        os.makedirs(dir_, exist_ok=True)
        os.makedirs(lldir, exist_ok=True)
        return dir_, lldir

    def create_snapshot_dir(self, did: int, cluster_id: int, node_id: int) -> str:
        d = self.get_snapshot_dir(did, cluster_id, node_id)
        os.makedirs(d, exist_ok=True)
        return d

    # ---- locking (reference LockNodeHostDir / tryLockNodeHostDir) ----

    def lock_nodehost_dir(self) -> None:
        for d in set(self.get_logdb_dirs(self.nhconfig.get_deployment_id())):
            self._try_lock(d)

    def _try_lock(self, dirname: str) -> None:
        fp = os.path.join(dirname, LOCK_FILENAME)
        if fp in self._flocks:
            return
        f = open(fp, "a+")
        try:
            fcntl.flock(f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as e:
            f.close()
            raise LockDirectoryError(
                f"directory {dirname!r} is locked by another NodeHost"
            ) from e
        self._flocks[fp] = f

    # ---- compatibility flag file (reference checkNodeHostDir/check) ----

    def check_nodehost_dir(self, did: int, addr: str, logdb_type: str) -> None:
        for d in set(self.get_logdb_dirs(did)):
            self._check(d, did, addr, logdb_type)

    def _flag_path(self, dirname: str) -> str:
        return os.path.join(dirname, FLAG_FILENAME)

    def _check(self, dirname: str, did: int, addr: str, logdb_type: str) -> None:
        fp = self._flag_path(dirname)
        if not os.path.exists(fp):
            self._create_flag_file(dirname, did, addr, logdb_type)
            return
        s = self._read_flag_file(fp)
        same = lambda a, b: str(a).strip().lower() == str(b).strip().lower()
        if not same(s.get("address", ""), addr):
            raise NotOwnerError(
                f"{dirname!r} belongs to {s.get('address')!r}, not {addr!r}"
            )
        if s.get("hostname") and not same(s["hostname"], self.hostname):
            raise HostnameChangedError(
                f"hostname changed: {s['hostname']!r} -> {self.hostname!r}"
            )
        if s.get("deployment_id", 0) and s["deployment_id"] != did:
            raise DeploymentIDChangedError(
                f"deployment id changed: {s['deployment_id']} -> {did}"
            )
        if s.get("bin_ver") != BIN_VER:
            raise IncompatibleDataError(
                f"binary format {s.get('bin_ver')} != {BIN_VER}"
            )
        if s.get("hard_hash") != Hard.hash():
            raise HardSettingsChangedError(
                "hard settings changed since this directory was created"
            )

    def _create_flag_file(self, dirname: str, did: int, addr: str, logdb_type: str) -> None:
        payload = json.dumps(
            {
                "address": addr,
                "hostname": self.hostname,
                "deployment_id": did,
                "bin_ver": BIN_VER,
                "logdb_type": logdb_type,
                "hard_hash": Hard.hash(),
                "step_worker_count": Hard.step_engine_worker_count,
                "logdb_shard_count": Hard.logdb_pool_size,
                "max_session_count": Hard.lru_max_session_count,
                "entry_batch_size": Hard.logdb_entry_batch_size,
            },
            sort_keys=True,
        ).encode()
        crc = zlib.crc32(payload)
        tmp = self._flag_path(dirname) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(crc.to_bytes(4, "little") + payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._flag_path(dirname))

    @staticmethod
    def _read_flag_file(fp: str) -> dict:
        with open(fp, "rb") as f:
            raw = f.read()
        if len(raw) < 4 or zlib.crc32(raw[4:]) != int.from_bytes(raw[:4], "little"):
            raise IncompatibleDataError(f"corrupted flag file {fp!r}")
        return json.loads(raw[4:].decode())

    # ---- shutdown ----

    def stop(self) -> None:
        for fp, f in self._flocks.items():
            try:
                fcntl.flock(f.fileno(), fcntl.LOCK_UN)
                f.close()
            except OSError:
                pass
        self._flocks.clear()
