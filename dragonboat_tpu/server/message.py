"""Inbound raft message queue.

Reference: ``internal/server/message.go:24-172`` — a double-buffered queue
with a byte-size rate limit; snapshot messages use the ``MustAdd`` lane so a
full queue never drops an InstallSnapshot.
"""
from __future__ import annotations

import threading
from typing import List

from ..wire import Message, MessageType


class MessageQueue:
    def __init__(self, size: int, ch: bool = False, lazy_free_cycle: int = 0,
                 max_bytes: int = 0):
        self.size = size
        self.max_bytes = max_bytes
        self._mu = threading.Lock()
        self._left: List[Message] = []
        self._right: List[Message] = []
        self._use_left = True
        self._bytes = 0
        self._stopped = False
        del ch, lazy_free_cycle  # reference-compat args; unused host-side

    def _active(self) -> List[Message]:
        return self._left if self._use_left else self._right

    def add(self, m: Message) -> bool:
        with self._mu:
            if self._stopped:
                return False
            q = self._active()
            if len(q) >= self.size:
                return False
            if self.max_bytes:
                sz = sum(len(e.cmd) for e in m.entries)
                if self._bytes + sz > self.max_bytes:
                    return False
                self._bytes += sz
            q.append(m)
            return True

    def must_add(self, m: Message) -> bool:
        """Snapshot lane: never rejected by size limits (reference
        ``MustAdd``)."""
        with self._mu:
            if self._stopped:
                return False
            self._active().append(m)
            return True

    def get(self) -> List[Message]:
        """Swap buffers and return everything queued."""
        # lock-free empty fast path: the step loop polls this for every
        # group every round; list truthiness is GIL-atomic and a racing
        # add() is followed by a step_ready ping that triggers another round
        if not self._left and not self._right:
            return []
        with self._mu:
            q = self._active()
            self._use_left = not self._use_left
            out = list(q)
            q.clear()
            self._bytes = 0
            return out

    def close(self) -> None:
        with self._mu:
            self._stopped = True


def is_snapshot_message(m: Message) -> bool:
    return m.type == MessageType.INSTALL_SNAPSHOT
