"""Group→worker partitioners (reference ``internal/server/partition.go``)."""
from __future__ import annotations


class FixedPartitioner:
    """``clusterID % capacity`` (reference ``partition.go:22-45``)."""

    def __init__(self, capacity: int):
        self.capacity = capacity

    def get_partition_id(self, cluster_id: int) -> int:
        return cluster_id % self.capacity


class DoubleFixedPartitioner:
    """Reference ``partition.go:47-61``: stable under two capacities."""

    def __init__(self, capacity: int, workers: int):
        self.capacity = capacity
        self.workers = workers

    def get_partition_id(self, cluster_id: int) -> int:
        return (cluster_id % self.capacity) % self.workers
