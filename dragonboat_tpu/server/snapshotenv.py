"""Snapshot directory lifecycle.

Reference: ``internal/server/snapshotenv.go:116`` — every snapshot is built
in a mode-suffixed temp dir (``.generating`` for local saves,
``.receiving`` for streamed ones), fsync'd, then atomically renamed to the
final ``snapshot-{index:016X}`` dir containing a flag file with the snapshot
metadata.  Orphan/zombie dirs left by crashes are recognized by these
suffixes and garbage collected by the snapshotter.
"""
from __future__ import annotations

import enum
import os
import re
from typing import Optional

from .. import vfs
from ..wire import Snapshot
from ..wire.codec import decode_snapshot, encode_snapshot

GENERATING_SUFFIX = "generating"
RECEIVING_SUFFIX = "receiving"
SNAPSHOT_FLAG_FILE = "snapshot.message"
SNAPSHOT_DIR_RE = re.compile(r"^snapshot-([0-9A-F]{16})$")
TEMP_DIR_RE = re.compile(
    r"^snapshot-[0-9A-F]{16}(-[0-9A-F]+)?\.(generating|receiving)$"
)


class SSMode(enum.Enum):
    SNAPSHOT = GENERATING_SUFFIX  # created by the local SM save path
    RECEIVING = RECEIVING_SUFFIX  # streamed in from a remote replica


def snapshot_dir_name(index: int) -> str:
    return f"snapshot-{index:016X}"


def _fsync_dir(path: str, fs: vfs.IFS = vfs.DEFAULT) -> None:
    try:
        fs.fsync_dir(path)
    except OSError:
        return


class SSEnv:
    """Reference ``snapshotenv.go`` ``SSEnv``."""

    def __init__(
        self,
        root_dir: str,
        index: int,
        from_node_id: int,
        mode: SSMode,
        fs: vfs.IFS = vfs.DEFAULT,
    ):
        self.fs = fs
        self.root_dir = root_dir
        self.index = index
        final = snapshot_dir_name(index)
        self.final_dir = os.path.join(root_dir, final)
        if mode == SSMode.SNAPSHOT:
            tmp = f"{final}.{GENERATING_SUFFIX}"
        else:
            tmp = f"{final}-{from_node_id:X}.{RECEIVING_SUFFIX}"
        self.tmp_dir = os.path.join(root_dir, tmp)

    # ---- temp stage ----

    def create_tmp_dir(self) -> None:
        self.fs.makedirs(self.tmp_dir, exist_ok=False)
        _fsync_dir(self.root_dir, self.fs)

    def get_tmp_dir(self) -> str:
        return self.tmp_dir

    def get_final_dir(self) -> str:
        return self.final_dir

    def get_tmp_filepath(self) -> str:
        return os.path.join(self.tmp_dir, f"{snapshot_dir_name(self.index)}.ss")

    def get_filepath(self) -> str:
        return os.path.join(self.final_dir, f"{snapshot_dir_name(self.index)}.ss")

    def save_ss_metadata(self, ss: Snapshot) -> None:
        """Write the flag file into the temp dir (reference
        ``fileutil.CreateFlagFile``)."""
        flag = os.path.join(self.tmp_dir, SNAPSHOT_FLAG_FILE)
        data = encode_snapshot(ss)
        with self.fs.open(flag, "wb") as f:
            f.write(len(data).to_bytes(8, "little"))
            f.write(data)
            self.fs.fsync(f)
        _fsync_dir(self.tmp_dir, self.fs)

    # ---- finalize ----

    def finalize_snapshot(self) -> None:
        """Atomically promote temp → final (reference
        ``finalizeSnapshot``); raises FileExistsError if another replica
        already installed this index."""
        if self.fs.exists(self.final_dir):
            raise FileExistsError(self.final_dir)
        self.fs.replace(self.tmp_dir, self.final_dir)
        _fsync_dir(self.root_dir, self.fs)

    def has_flag_file(self) -> bool:
        return self.fs.exists(os.path.join(self.final_dir, SNAPSHOT_FLAG_FILE))

    def remove_flag_file(self) -> None:
        self.fs.remove(os.path.join(self.final_dir, SNAPSHOT_FLAG_FILE))

    def remove_tmp_dir(self) -> None:
        _rmtree(self.tmp_dir, self.fs)

    def remove_final_dir(self) -> None:
        _rmtree(self.final_dir, self.fs)


def read_ss_metadata(
    dirname: str, fs: vfs.IFS = vfs.DEFAULT
) -> Optional[Snapshot]:
    flag = os.path.join(dirname, SNAPSHOT_FLAG_FILE)
    try:
        with fs.open(flag, "rb") as f:
            n = int.from_bytes(f.read(8), "little")
            return decode_snapshot(f.read(n))
    except (OSError, ValueError):
        return None


def is_temp_snapshot_dir(name: str) -> bool:
    return TEMP_DIR_RE.match(name) is not None


def is_final_snapshot_dir(name: str) -> bool:
    return SNAPSHOT_DIR_RE.match(name) is not None


def snapshot_index_from_dir(name: str) -> int:
    m = SNAPSHOT_DIR_RE.match(name)
    if not m:
        raise ValueError(f"not a snapshot dir {name!r}")
    return int(m.group(1), 16)


def _rmtree(path: str, fs: vfs.IFS = vfs.DEFAULT) -> None:
    try:
        fs.rmtree(path)
    except OSError:
        pass
