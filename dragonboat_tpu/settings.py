"""Internal tunables, split Hard/Soft like the reference.

Reference: ``internal/settings/hard.go`` and ``internal/settings/soft.go``.
Hard settings affect on-disk data formats — changing them on an existing
deployment corrupts data, so a hash over them is persisted and re-checked on
open (reference ``hard.go:124-137``).  Soft settings are runtime tunables
overridable via a JSON file in the CWD (reference ``overwrite.go``).
"""
from __future__ import annotations

import json
import os
import zlib
from dataclasses import dataclass, fields


@dataclass
class HardSettings:
    """Data-format-affecting constants (reference ``hard.go:35-152``)."""

    step_engine_worker_count: int = 16
    logdb_pool_size: int = 16  # LogDB shard count
    lru_max_session_count: int = 4096
    logdb_entry_batch_size: int = 48
    # snapshot file header size in bytes (reference hard.go:99)
    snapshot_header_size: int = 1024

    def hash(self) -> int:
        """Stable hash persisted alongside data dirs to detect incompatible
        setting changes (reference ``hard.go:124-137``)."""
        payload = "|".join(
            f"{f.name}={getattr(self, f.name)}" for f in fields(self)
        ).encode()
        return zlib.crc32(payload)


@dataclass
class SoftSettings:
    """Runtime tunables (reference ``soft.go:17-217``)."""

    # engine
    step_engine_commit_worker_count: int = 16
    step_engine_apply_worker_count: int = 16
    step_engine_snapshot_worker_count: int = 64
    task_queue_target_length: int = 1024
    node_reload_millisecond: int = 200
    # raft
    max_entry_size: int = 2 * 1024 * 1024  # per Replicate msg / apply batch
    in_mem_entry_slice_size: int = 512
    min_entry_slice_free_size: int = 96
    in_mem_gc_timeout: int = 100
    unknown_region_size: int = 10
    # queues
    incoming_proposal_queue_length: int = 2048
    incoming_read_index_queue_length: int = 4096
    received_message_queue_length: int = 1024
    snapshot_status_push_delay_ms: int = 1000
    # transport
    send_queue_length: int = 2048
    max_message_batch_size: int = 64 * 1024 * 1024
    max_snapshot_connections: int = 64
    max_concurrent_streaming_snapshots: int = 128
    snapshot_chunk_size: int = 2 * 1024 * 1024
    snapshot_gc_tick: int = 30
    snapshot_chunk_timeout_tick: int = 900
    get_connected_timeout_second: int = 5
    # logdb
    logdb_compaction_interval_seconds: int = 60
    # nodehost
    sync_op_default_timeout_ms: int = 5000
    pending_proposal_shards: int = 16
    # tick-lite staleness bound: a node with native/device-owned raft
    # clocks and pending requests is woken at least once per this many
    # ticks so pending-request timeout GC runs (lazy tick delivery)
    lazy_tick_sweep_ticks: int = 4
    # batched quorum engine (new, TPU-specific)
    quorum_engine_max_peers: int = 8
    quorum_engine_block_groups: int = 1024

    # ReadIndex / quiesce
    quiesce_threshold_factor: int = 10


def _load_overrides(path: str, obj) -> None:
    if not os.path.exists(path):
        return
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return
    for k, v in data.items():
        if hasattr(obj, k) and isinstance(v, int):
            setattr(obj, k, v)


Hard = HardSettings()
Soft = SoftSettings()

# JSON override files, same mechanism as the reference's
# dragonboat-{hard,soft}-settings.json (reference overwrite.go, hard.go:50-57)
_load_overrides("dragonboat-tpu-hard-settings.json", Hard)
_load_overrides("dragonboat-tpu-soft-settings.json", Soft)
