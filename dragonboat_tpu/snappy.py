"""Pure-Python snappy block-format codec.

The reference compresses entry payloads and snapshot streams with google
snappy (``internal/utils/dio/io.go:26-36``, ``internal/rsm/encoded.go``).
No snappy binding is available in this image, so this module implements the
snappy *block format* directly from the public format description
(github.com/google/snappy, format_description.txt):

  preamble: uvarint length of the UNCOMPRESSED data, then a sequence of
  elements, each starting with a tag byte whose low 2 bits select:
    00  literal: len-1 in tag bits 2-7; 60/61/62/63 mean 1/2/3/4
        little-endian extra length bytes follow
    01  copy, 1-byte offset: length = 4 + ((tag>>2) & 0x7)  (4..11),
        offset = ((tag>>5) << 8) | next byte  (<= 2047)
    10  copy, 2-byte offset: length = 1 + (tag>>2) (1..64),
        offset = next two bytes little-endian
    11  copy, 4-byte offset: length = 1 + (tag>>2),
        offset = next four bytes little-endian

The compressor is a greedy single-pass matcher with a 4-byte hash table —
the same scheme as the C++ reference — emitting 2-byte-offset copies; the
decompressor accepts every tag form.  Output decompresses with any
conformant snappy implementation.
"""
from __future__ import annotations

import struct

_U16 = struct.Struct("<H")

MAX_BLOCK_LEN = (1 << 32) - 1


class SnappyError(ValueError):
    pass


def _write_uvarint(buf: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            buf.append(b | 0x80)
        else:
            buf.append(b)
            return


def _read_uvarint(data, pos: int):
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise SnappyError("truncated uvarint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise SnappyError("uvarint overflow")


def max_encoded_len(n: int) -> int:
    """Worst-case compressed size (mirrors snappy's MaxEncodedLen)."""
    return 32 + n + n // 6


def _emit_literal(out: bytearray, data, start: int, end: int) -> None:
    length = end - start
    while length > 0:
        chunk = min(length, 1 << 16)  # keep extra-length bytes at <= 2
        n = chunk - 1
        if n < 60:
            out.append(n << 2)
        elif n < (1 << 8):
            out.append(60 << 2)
            out.append(n)
        else:
            out.append(61 << 2)
            out += _U16.pack(n)
        out += data[start : start + chunk]
        start += chunk
        length -= chunk


def _emit_copy(out: bytearray, offset: int, length: int) -> None:
    # 2-byte-offset copies, length 4..64 per op (len 1..3 tail folded into
    # the final op by shrinking the previous one, as the C++ encoder does)
    while length >= 4:
        chunk = min(length, 64)
        if length - chunk in (1, 2, 3):
            chunk -= 4 - (length - chunk)  # leave >= 4 for the last op
        out.append(((chunk - 1) << 2) | 0x02)
        out += _U16.pack(offset)
        length -= chunk


def compress(data) -> bytes:
    """Snappy block-format compression."""
    data = bytes(data)
    n = len(data)
    out = bytearray()
    _write_uvarint(out, n)
    if n == 0:
        return bytes(out)
    if n < 4:
        _emit_literal(out, data, 0, n)
        return bytes(out)
    table = {}
    i = 0
    lit_start = 0
    limit = n - 3
    while i < limit:
        key = data[i : i + 4]
        j = table.get(key)
        table[key] = i
        if j is not None and i - j <= 0xFFFF:
            # extend the match forward
            length = 4
            max_len = n - i
            while (
                length < max_len and data[j + length] == data[i + length]
            ):
                length += 1
            _emit_literal(out, data, lit_start, i)
            _emit_copy(out, i - j, length)
            i += length
            lit_start = i
        else:
            i += 1
    _emit_literal(out, data, lit_start, n)
    return bytes(out)


def uncompressed_length(data) -> int:
    n, _ = _read_uvarint(data, 0)
    return n


def decompress(data) -> bytes:
    """Snappy block-format decompression (all tag forms)."""
    data = bytes(data)
    n, pos = _read_uvarint(data, 0)
    if n > MAX_BLOCK_LEN:
        raise SnappyError("declared length too large")
    out = bytearray()
    dlen = len(data)
    while pos < dlen:
        tag = data[pos]
        kind = tag & 0x03
        pos += 1
        if kind == 0:  # literal
            length = tag >> 2
            if length >= 60:
                extra = length - 59  # 1..4 bytes
                if pos + extra > dlen:
                    raise SnappyError("truncated literal length")
                length = int.from_bytes(data[pos : pos + extra], "little")
                pos += extra
            length += 1
            if pos + length > dlen:
                raise SnappyError("truncated literal")
            out += data[pos : pos + length]
            pos += length
            continue
        if kind == 1:  # copy, 1-byte offset
            length = 4 + ((tag >> 2) & 0x07)
            if pos >= dlen:
                raise SnappyError("truncated copy1")
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:  # copy, 2-byte offset
            length = 1 + (tag >> 2)
            if pos + 2 > dlen:
                raise SnappyError("truncated copy2")
            offset = _U16.unpack_from(data, pos)[0]
            pos += 2
        else:  # copy, 4-byte offset
            length = 1 + (tag >> 2)
            if pos + 4 > dlen:
                raise SnappyError("truncated copy4")
            offset = int.from_bytes(data[pos : pos + 4], "little")
            pos += 4
        if offset == 0 or offset > len(out):
            raise SnappyError("invalid copy offset")
        # overlapping copies are byte-at-a-time semantics
        start = len(out) - offset
        if offset >= length:
            out += out[start : start + length]
        else:
            for k in range(length):
                out.append(out[start + k])
    if len(out) != n:
        raise SnappyError(f"length mismatch: got {len(out)}, want {n}")
    return bytes(out)
