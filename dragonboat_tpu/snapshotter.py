"""Snapshotter: snapshot directory/record lifecycle for one replica.

Reference: ``snapshotter.go`` — owns the per-node snapshot root dir,
produces snapshots through :class:`SSEnv` temp dirs, commits records to the
LogDB, keeps the 3 newest snapshots (``snapshotter.go:34``), shrinks old
images and garbage-collects orphaned dirs left behind by crashes.
Implements the RSM layer's ``ISnapshotter`` contract.
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

from . import vfs
from .logger import get_logger
from .rsm.snapshotio import SnapshotReader, SnapshotWriter, shrink_snapshot
from .rsm.statemachine import SSMeta
from .server.snapshotenv import (
    SSEnv,
    SSMode,
    _rmtree,
    is_final_snapshot_dir,
    is_temp_snapshot_dir,
    snapshot_index_from_dir,
)
from .wire import Snapshot

plog = get_logger("snapshotter")

SNAPSHOTS_TO_KEEP = 3


class NoSnapshotError(Exception):
    pass


class Snapshotter:
    """Reference ``snapshotter.go:57``."""

    def __init__(
        self,
        root_dir: str,
        cluster_id: int,
        node_id: int,
        logdb,
        fs: vfs.IFS = vfs.DEFAULT,
    ):
        self.root_dir = root_dir
        self.cluster_id = cluster_id
        self.node_id = node_id
        self.logdb = logdb
        self.fs = fs
        fs.makedirs(root_dir, exist_ok=True)

    # ---- ISnapshotter ----

    def save(self, savable, meta: SSMeta) -> Tuple[Snapshot, SSEnv]:
        """Write a snapshot image into a temp dir (reference
        ``snapshotter.go:103-150`` ``Save``).  Exported snapshots land in
        the user-provided directory instead of the node's snapshot root
        (reference custom-SSEnv path for ``Exported`` requests) and are
        never recorded in the LogDB."""
        root = self.root_dir
        if meta.request is not None and meta.request.exported:
            if not meta.request.path:
                raise ValueError("exported snapshot request without a path")
            root = meta.request.path
        env = SSEnv(root, meta.index, self.node_id, SSMode.SNAPSHOT, self.fs)
        env.remove_tmp_dir()
        env.create_tmp_dir()
        path = env.get_tmp_filepath()
        # writer construction is inside the cleanup scope: __init__ already
        # writes the header placeholder, and a fault there (ErrorFS write
        # injection, ENOSPC) must not leak the .generating temp dir
        # (tests/test_rsm.py fault table caught exactly this)
        w = None
        try:
            w = SnapshotWriter(path, self.fs, compression=meta.compression)
            savable.save_snapshot_payload(meta, w)
            w.finalize()
        except Exception:
            if w is not None:
                w.abort()
            env.remove_tmp_dir()
            raise
        ss = Snapshot(
            filepath=env.get_filepath(),
            file_size=self.fs.getsize(path),
            index=meta.index,
            term=meta.term,
            membership=meta.membership,
            cluster_id=self.cluster_id,
            type=meta.type,
            on_disk_index=meta.on_disk_index,
            witness=False,
        )
        env.save_ss_metadata(ss)
        return ss, env

    def commit(self, ss: Snapshot, env: SSEnv) -> None:
        """Promote temp → final and record in the LogDB (reference
        ``snapshotter.go:181`` ``Commit``)."""
        env.finalize_snapshot()
        self.logdb.save_snapshot(self.cluster_id, self.node_id, ss)

    def recover(self, recoverable, ss: Snapshot) -> None:
        """Reference ``snapshotter.go`` recover path: open + validate the
        image and hand the payload to the RSM."""
        r = SnapshotReader(ss.filepath, self.fs)
        try:
            recoverable.recover_from_payload(ss, r)
        finally:
            r.close()

    def stream(
        self, streamable, meta: SSMeta, sink, to_node_id: int,
        deployment_id: int,
    ) -> None:
        from .rsm.chunkwriter import ChunkWriter

        cw = ChunkWriter(
            sink, meta, self.cluster_id, to_node_id, self.node_id,
            deployment_id,
        )
        streamable.save_snapshot_payload(meta, cw)
        cw.finalize()

    def get_snapshot(self, index: int = 0) -> Snapshot:
        snapshots = self.logdb.list_snapshots(self.cluster_id, self.node_id)
        if index == 0:
            if not snapshots:
                raise NoSnapshotError()
            return snapshots[-1]
        for ss in snapshots:
            if ss.index == index:
                return ss
        raise NoSnapshotError()

    def get_most_recent_snapshot(self) -> Optional[Snapshot]:
        snapshots = self.logdb.list_snapshots(self.cluster_id, self.node_id)
        return snapshots[-1] if snapshots else None

    def is_no_snapshot_error(self, e: Exception) -> bool:
        return isinstance(e, NoSnapshotError)

    # ---- retention / GC ----

    def compact(self, keep: int = SNAPSHOTS_TO_KEEP) -> None:
        """Drop all but the ``keep`` newest snapshot records + dirs
        (reference ``snapshotter.go`` ``Compact``)."""
        snapshots = self.logdb.list_snapshots(self.cluster_id, self.node_id)
        for ss in snapshots[:-keep] if keep else snapshots:
            self.logdb.delete_snapshot(self.cluster_id, self.node_id, ss.index)
            self._remove_snapshot_dir(ss.index)

    def shrink(self, shrink_to: int) -> None:
        """Shrink images older than ``shrink_to`` (reference
        ``snapshotter.go`` ``Shrink``) — used by on-disk SMs whose old full
        images are dead weight."""
        for ss in self.logdb.list_snapshots(self.cluster_id, self.node_id):
            if ss.index > shrink_to or ss.witness or ss.dummy:
                continue
            if not self.fs.exists(ss.filepath):
                continue
            tmp = ss.filepath + ".shrinking"
            shrink_snapshot(ss.filepath, tmp, self.fs)
            self.fs.replace(tmp, ss.filepath)

    def process_orphans(self) -> None:
        """Remove temp dirs and unrecorded final dirs left by crashes
        (reference ``snapshotter.go:393-408`` ``ProcessOrphans``)."""
        recorded = {
            ss.index
            for ss in self.logdb.list_snapshots(self.cluster_id, self.node_id)
        }
        try:
            names = self.fs.listdir(self.root_dir)
        except OSError:
            return
        for name in names:
            full = os.path.join(self.root_dir, name)
            if is_temp_snapshot_dir(name):
                plog.info("removing orphaned temp dir %s", full)
                _rmtree(full, self.fs)
            elif is_final_snapshot_dir(name):
                if snapshot_index_from_dir(name) not in recorded:
                    plog.info("removing unrecorded snapshot dir %s", full)
                    _rmtree(full, self.fs)

    def _remove_snapshot_dir(self, index: int) -> None:
        env = SSEnv(self.root_dir, index, self.node_id, SSMode.SNAPSHOT, self.fs)
        env.remove_final_dir()

