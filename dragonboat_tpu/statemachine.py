"""Public user state machine interfaces.

Reference: ``statemachine/rsm.go`` (``IStateMachine``),
``statemachine/concurrent.go`` (``IConcurrentStateMachine``) and
``statemachine/disk.go:59`` (``IOnDiskStateMachine``).  Applications implement
one of the three contracts; the RSM layer adapts them to a uniform managed
interface (:mod:`dragonboat_tpu.rsm.adapters`).
"""
from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import BinaryIO, List, Optional, Tuple


@dataclass(slots=True)
class Result:
    """Outcome of an update (reference ``statemachine/rsm.go`` ``Result``)."""

    value: int = 0
    data: bytes = b""

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Result)
            and self.value == other.value
            and self.data == other.data
        )


@dataclass(slots=True)
class SMEntry:
    """An entry handed to the state machine (reference ``statemachine/rsm.go``
    ``Entry``)."""

    index: int = 0
    cmd: bytes = b""
    result: Result = field(default_factory=Result)


class SnapshotFileCollection(abc.ABC):
    """Collects external files into a snapshot (reference
    ``statemachine/rsm.go`` ``ISnapshotFileCollection``)."""

    @abc.abstractmethod
    def add_file(self, file_id: int, path: str, metadata: bytes) -> None: ...


@dataclass(slots=True)
class SnapshotFile:
    """An external file restored with a snapshot (reference
    ``statemachine/rsm.go`` ``SnapshotFile``)."""

    file_id: int = 0
    filepath: str = ""
    metadata: bytes = b""


class SnapshotStopped(Exception):
    """Raised by SM snapshot ops when the node is being stopped
    (reference ``statemachine/rsm.go`` ``ErrSnapshotStopped``)."""


class SnapshotAborted(Exception):
    """Raised by user SMs to abort a snapshot operation."""


class IStateMachine(abc.ABC):
    """The regular (in-memory, serialized-access) SM
    (reference ``statemachine/rsm.go:184``)."""

    @abc.abstractmethod
    def update(self, cmd: bytes) -> Result: ...

    @abc.abstractmethod
    def lookup(self, query: object) -> object: ...

    @abc.abstractmethod
    def save_snapshot(
        self,
        w: BinaryIO,
        files: SnapshotFileCollection,
        done: "StopChecker",
    ) -> None: ...

    @abc.abstractmethod
    def recover_from_snapshot(
        self,
        r: BinaryIO,
        files: List[SnapshotFile],
        done: "StopChecker",
    ) -> None: ...

    def close(self) -> None:
        pass


class IConcurrentStateMachine(abc.ABC):
    """Concurrent-snapshot SM (reference ``statemachine/concurrent.go``):
    update batches are serialized, but snapshotting runs concurrently with
    updates using the state captured by ``prepare_snapshot``."""

    @abc.abstractmethod
    def update(self, entries: List[SMEntry]) -> List[SMEntry]: ...

    @abc.abstractmethod
    def lookup(self, query: object) -> object: ...

    @abc.abstractmethod
    def prepare_snapshot(self) -> object: ...

    @abc.abstractmethod
    def save_snapshot(
        self,
        ctx: object,
        w: BinaryIO,
        files: SnapshotFileCollection,
        done: "StopChecker",
    ) -> None: ...

    @abc.abstractmethod
    def recover_from_snapshot(
        self,
        r: BinaryIO,
        files: List[SnapshotFile],
        done: "StopChecker",
    ) -> None: ...

    def close(self) -> None:
        pass


class IOnDiskStateMachine(abc.ABC):
    """On-disk SM (reference ``statemachine/disk.go:59``): state lives in the
    SM's own durable store; raft log replay resumes from ``open()``'s index
    and snapshots stream state directly between replicas."""

    @abc.abstractmethod
    def open(self, stopc) -> int:
        """Open existing state; returns the index of the last applied entry."""

    @abc.abstractmethod
    def update(self, entries: List[SMEntry]) -> List[SMEntry]: ...

    @abc.abstractmethod
    def lookup(self, query: object) -> object: ...

    @abc.abstractmethod
    def sync(self) -> None: ...

    @abc.abstractmethod
    def prepare_snapshot(self) -> object: ...

    @abc.abstractmethod
    def save_snapshot(self, ctx: object, w: BinaryIO, done: "StopChecker") -> None: ...

    @abc.abstractmethod
    def recover_from_snapshot(self, r: BinaryIO, done: "StopChecker") -> None: ...

    def close(self) -> None:
        pass


class StopChecker:
    """Polled cancellation flag passed to snapshot operations (plays the role
    of the reference's ``<-chan struct{}``)."""

    __slots__ = ("_stopped",)

    def __init__(self) -> None:
        self._stopped = False

    def stop(self) -> None:
        self._stopped = True

    def __bool__(self) -> bool:
        return self._stopped

    def check(self) -> None:
        if self._stopped:
            raise SnapshotStopped()


# factory signatures (reference nodehost.go StartCluster's factory args)
CreateStateMachineFunc = "Callable[[int, int], IStateMachine]"
CreateConcurrentStateMachineFunc = "Callable[[int, int], IConcurrentStateMachine]"
CreateOnDiskStateMachineFunc = "Callable[[int, int], IOnDiskStateMachine]"


def __getattr__(name):
    # Lazy re-export of the device-resident KV state machine (devsm,
    # ISSUE 11): registering one with Config.device_kv on the tpu engine
    # moves the group's apply plane into the fused device program.  Lazy
    # because devsm imports numpy/ops machinery this interface module
    # must not pull in for plain host SM users.
    if name == "DeviceKVStateMachine":
        from .devsm.machine import DeviceKVStateMachine

        return DeviceKVStateMachine
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
