"""Shared full-stack probe harness (used by the driver's multichip
dry-run hook and the sharding test suite — one copy, so the election-wait
and propose protocol cannot drift between them).

Reference analog: ``internal/tests`` ships the fake SMs every test layer
reuses; this module plays the same role for the in-process 3-NodeHost
stack shape.
"""
from __future__ import annotations

import time

from . import Config, NodeHostConfig, Result
from .config import ExpertConfig
from .nodehost import NodeHost
from .transport import ChanRouter, ChanTransport


class CounterSM:
    """Minimal counter state machine for stack probes.

    Process-spawnable (ISSUE 12): living in an importable module — not
    a bench/test ``__main__`` — lets the hostproc apply tier rebuild it
    inside a worker from its ``module:qualname`` spec."""

    __hostproc_spawnable__ = True

    def __init__(self, cluster_id, node_id):
        self.v = 0

    def update(self, cmd):
        self.v += 1
        return Result(value=self.v)

    def lookup(self, query):
        return self.v

    def save_snapshot(self, w, files, done):
        w.write(self.v.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, files, done):
        self.v = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def run_sharded_stack_check(
    n_devices: int,
    groups: int = 16,
    writes_per_group: int = 5,
    rtt_ms: int = 20,
    election_wait_s: float = 90.0,
) -> int:
    """3 in-process NodeHosts (chan transport) whose quorum engines are
    group-sharded over ``n_devices`` (``ExpertConfig.engine_mesh_devices``):
    real coordinator registration/staging/rounds, device-tick elections,
    and ``writes_per_group`` committed proposals per group.  Returns the
    total committed write count; raises on any failure.

    ``n_devices`` is capped at the host's core count: each mesh shard
    carries its own dispatch-stream thread, and this check builds THREE
    coordinators, so 8 virtual shards on a 2-vCPU CI box means 24
    dispatch threads thrashing 2 cores — measured 386s vs 12s for the
    identical check at one stream per core.  Wide-mesh coverage (8
    shards, single engine) lives in tests/test_mesh_dispatch.py and the
    bench mesh_axis rung, which don't triple the stream count."""
    import os

    from .ops.sharding import GROUP_AXIS

    n_devices = min(n_devices, max(2, os.cpu_count() or 2))
    while groups % n_devices:
        n_devices -= 1

    router = ChanRouter()
    addrs = {i: f"mc{i}:1" for i in (1, 2, 3)}
    cids = list(range(500, 500 + groups))
    nhs = []
    try:
        for i in (1, 2, 3):
            nhs.append(NodeHost(NodeHostConfig(
                node_host_dir=":memory:", rtt_millisecond=rtt_ms,
                raft_address=addrs[i],
                raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                    s, rh, ch, router=router
                ),
                expert=ExpertConfig(
                    quorum_engine="tpu", engine_block_groups=groups,
                    engine_mesh_devices=n_devices,
                ),
            )))
        for nh in nhs:
            # defensive: SingleDeviceSharding has no .spec, and the
            # coordinator silently falls back to unsharded on 1-device
            # hosts — fail with the diagnostic, not an AttributeError
            spec = getattr(
                nh.quorum_coordinator.eng.dev.match.sharding, "spec", None
            )
            assert spec and spec[0] == GROUP_AXIS, (
                f"engine not group-sharded: {spec}"
            )
        for i, nh in enumerate(nhs, 1):
            for cid in cids:
                nh.start_cluster(addrs, False, CounterSM, Config(
                    cluster_id=cid, node_id=i, election_rtt=10,
                    heartbeat_rtt=1,
                ))
        deadline = time.time() + election_wait_s
        led = {}
        while len(led) < len(cids) and time.time() < deadline:
            for cid in cids:
                if cid in led:
                    continue
                for nh in nhs:
                    lid, ok = nh.get_leader_id(cid)
                    if ok:
                        led[cid] = nhs[lid - 1]
                        break
            time.sleep(0.02)
        assert len(led) == len(cids), (
            f"sharded-stack elections: {len(led)}/{len(cids)}"
        )
        total = 0
        for cid, leader in led.items():
            s = leader.get_noop_session(cid)
            for k in range(writes_per_group):
                r = leader.sync_propose(s, b"x", timeout=10.0)
                assert r.value == k + 1
                total += 1
        return total
    finally:
        for nh in nhs:
            nh.stop()
