"""Operational tools.

Reference: ``tools/`` — ``ImportSnapshot`` quorum-loss repair
(``tools/import.go:130``) and the ``checkdisk`` write-throughput probe
(``tools/checkdisk/main.go``).
"""
from .importsnap import import_snapshot  # noqa: F401
