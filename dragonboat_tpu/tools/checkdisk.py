"""checkdisk: write-throughput probe for the LogDB + pipeline.

Reference: ``tools/checkdisk/main.go:98`` — spins many single-replica raft
groups on ONE NodeHost and measures sustained proposal throughput, telling
you what the local disk + engine pipeline can do before any networking is
involved.

Usage:
    python -m dragonboat_tpu.tools.checkdisk --groups 48 --seconds 5 \
        --payload 16 [--dir /path/on/target/disk]

Omitting ``--dir`` probes the in-memory backend (pipeline ceiling).
"""
from __future__ import annotations

import argparse
import json
import threading
import time

from ..config import Config, NodeHostConfig
from ..nodehost import NodeHost
from ..statemachine import IStateMachine, Result
from ..transport import ChanRouter, ChanTransport


class _NoopSM(IStateMachine):
    """Counting no-op SM (plays the reference's tests.NoOP role)."""

    def __init__(self, cluster_id, node_id):
        self.count = 0

    def update(self, cmd):
        self.count += 1
        return Result(value=self.count)

    def lookup(self, query):
        return self.count

    def save_snapshot(self, w, files, done):
        w.write(self.count.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, files, done):
        self.count = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def run(
    groups: int = 48,
    seconds: float = 5.0,
    payload: int = 16,
    dirname: str = "",
    client_threads: int = 8,
) -> dict:
    router = ChanRouter()
    addr = "checkdisk:1"
    nhc = NodeHostConfig(
        node_host_dir=dirname or ":memory:",
        rtt_millisecond=50,
        raft_address=addr,
        raft_rpc_factory=lambda src, rh, ch: ChanTransport(
            src, rh, ch, router=router
        ),
    )
    nh = NodeHost(nhc)
    results = {"writes": 0}
    try:
        for cid in range(1, groups + 1):
            nh.start_cluster(
                {1: addr},
                False,
                _NoopSM,
                Config(
                    cluster_id=cid,
                    node_id=1,
                    election_rtt=10,
                    heartbeat_rtt=1,
                    snapshot_entries=0,
                ),
            )
        # wait for every group to elect itself
        deadline = time.time() + 10
        for cid in range(1, groups + 1):
            while time.time() < deadline:
                _, ok = nh.get_leader_id(cid)
                if ok:
                    break
                time.sleep(0.005)
        cmd = b"x" * payload
        stop_at = time.time() + seconds
        counts = [0] * client_threads
        errors = [0] * client_threads

        def client(tid: int) -> None:
            # each thread round-robins its own slice of groups
            my = [c for c in range(1, groups + 1) if c % client_threads == tid % client_threads]
            if not my:
                my = [1]
            sessions = {c: nh.get_noop_session(c) for c in my}
            i = 0
            while time.time() < stop_at:
                cid = my[i % len(my)]
                i += 1
                try:
                    nh.sync_propose(sessions[cid], cmd, timeout=5.0)
                    counts[tid] += 1
                except Exception:
                    errors[tid] += 1

        threads = [
            threading.Thread(target=client, args=(t,), daemon=True)
            for t in range(client_threads)
        ]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=seconds + 30)
        elapsed = time.time() - t0
        writes = sum(counts)
        results = {
            "metric": "checkdisk_writes_per_sec",
            "value": round(writes / elapsed, 1),
            "unit": "writes/s",
            "writes": writes,
            "errors": sum(errors),
            "elapsed_s": round(elapsed, 3),
            "groups": groups,
            "payload": payload,
            "backend": nh.logdb.name(),
            "client_threads": client_threads,
        }
    finally:
        nh.stop()
    return results


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--groups", type=int, default=48)
    p.add_argument("--seconds", type=float, default=5.0)
    p.add_argument("--payload", type=int, default=16)
    p.add_argument("--dir", default="")
    p.add_argument("--threads", type=int, default=8)
    args = p.parse_args()
    out = run(
        groups=args.groups,
        seconds=args.seconds,
        payload=args.payload,
        dirname=args.dir,
        client_threads=args.threads,
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
