"""ImportSnapshot: rebuild a quorum-lost raft group from an exported image.

Reference: ``tools/import.go:130-218`` ``ImportSnapshot``.  Disaster
recovery flow: while the cluster still had quorum somebody exported a
snapshot (``NodeHost.sync_request_snapshot(..., export_path=...)``); after
quorum loss, EVERY surviving/replacement member runs
:func:`import_snapshot` against its own NodeHost dir with the SAME new
membership map and its own node id, then restarts the group normally.
The snapshot's membership is overwritten with the new map, so the
restarted group forms a quorum among exactly those members.

What the import writes (mirroring the reference):
- the snapshot image copied into the NodeHost's snapshot dir layout with
  a rewritten metadata flag file (``imported=True``, membership = new map,
  ``config_change_id = snapshot index``)
- the LogDB bootstrap record for (cluster, node) carrying the new map
- the snapshot record + raft ``State{term, commit=index}`` so replay
  starts from the image
- any pre-existing snapshot records for the node are dropped
"""
from __future__ import annotations

import os
import shutil
from dataclasses import replace
from typing import Dict

from .. import vfs
from ..config import NodeHostConfig
from ..logdb import open_logdb
from ..logger import get_logger
from ..rsm.snapshotio import validate_snapshot_file
from ..server.snapshotenv import (
    SSEnv,
    SSMode,
    read_ss_metadata,
    snapshot_dir_name,
)
from ..wire import Bootstrap, Membership, Snapshot, State, Update

plog = get_logger("tools")


def _host_dir(nhconfig: NodeHostConfig) -> str:
    # must match the ServerContext deployment-id layout the NodeHost uses
    # (server/context.py get_logdb_dirs)
    from ..server.context import ServerContext

    ctx = ServerContext(nhconfig)
    data_dir, _ = ctx.get_logdb_dirs(nhconfig.get_deployment_id())
    return data_dir


def _snapshot_dir(nhconfig: NodeHostConfig, cluster_id: int, node_id: int) -> str:
    # must match NodeHost.snapshot_dir layout (ServerContext)
    from ..server.context import ServerContext

    ctx = ServerContext(nhconfig)
    return ctx.get_snapshot_dir(
        nhconfig.get_deployment_id(), cluster_id, node_id
    )


def import_snapshot(
    nhconfig: NodeHostConfig,
    src_dir: str,
    members: Dict[int, str],
    node_id: int,
) -> Snapshot:
    """Import the exported snapshot in ``src_dir`` for ``node_id``.

    ``members`` is the complete post-repair membership
    ``{node_id: raft_address}``; ``node_id`` must be one of them and its
    address must equal ``nhconfig.raft_address``
    (reference ``tools/import.go:139-166`` validations).
    """
    nhconfig.validate()
    nhconfig.prepare()
    if node_id not in members:
        raise ValueError(f"node {node_id} not in the new membership")
    if members[node_id] != nhconfig.raft_address:
        raise ValueError(
            f"node {node_id} address {members[node_id]!r} != "
            f"NodeHost raft address {nhconfig.raft_address!r}"
        )
    ss = read_ss_metadata(src_dir)
    if ss is None:
        raise ValueError(f"no exported snapshot metadata in {src_dir!r}")
    src_image = os.path.join(src_dir, f"{snapshot_dir_name(ss.index)}.ss")
    if not os.path.exists(src_image):
        raise FileNotFoundError(src_image)
    if not validate_snapshot_file(src_image):
        raise ValueError(f"corrupted snapshot image {src_image!r}")
    for nid in ss.membership.witnesses:
        if nid in members:
            raise ValueError(f"witness {nid} cannot be a voting member")

    cluster_id = ss.cluster_id
    # rewritten record: new membership, imported marker
    # (reference import.go getProcessedSnapshotRecord)
    membership = Membership(
        config_change_id=ss.index,
        addresses=dict(members),
    )
    dst_root = _snapshot_dir(nhconfig, cluster_id, node_id)
    vfs.DEFAULT.makedirs(dst_root, exist_ok=True)
    env = SSEnv(dst_root, ss.index, node_id, SSMode.SNAPSHOT)
    env.remove_tmp_dir()
    env.remove_final_dir()
    env.create_tmp_dir()
    dst_image = env.get_tmp_filepath()
    shutil.copyfile(src_image, dst_image)
    imported = replace(
        ss,
        filepath=env.get_filepath(),
        file_size=os.path.getsize(dst_image),
        membership=membership,
        imported=True,
        files=list(ss.files),
    )
    # external files travel with the image dir
    for f in ss.files:
        src_f = os.path.join(src_dir, os.path.basename(f.filepath))
        if os.path.exists(src_f):
            shutil.copyfile(
                src_f, os.path.join(env.get_tmp_dir(), os.path.basename(f.filepath))
            )
    env.save_ss_metadata(imported)
    env.finalize_snapshot()

    db = open_logdb(
        os.path.join(_host_dir(nhconfig), "logdb"),
        shards=nhconfig.logdb_config.shards,
    )
    try:
        # drop stale snapshot records (reference import.go:200-207)
        for old in db.list_snapshots(cluster_id, node_id):
            db.delete_snapshot(cluster_id, node_id, old.index)
        db.save_bootstrap_info(
            cluster_id, node_id, Bootstrap(addresses=dict(members), join=False)
        )
        db.save_snapshot(cluster_id, node_id, imported)
        db.save_raft_state(
            [
                Update(
                    cluster_id=cluster_id,
                    node_id=node_id,
                    state=State(term=ss.term, vote=0, commit=ss.index),
                )
            ]
        )
    finally:
        db.close()
    plog.info(
        "imported snapshot idx=%d for cluster=%d node=%d, members=%s",
        ss.index,
        cluster_id,
        node_id,
        members,
    )
    return imported
