"""TPU quorum plugin: routes the live runtime's hot path through the
batched device engine.

This is the plugin boundary BASELINE.json's north star calls
``plugin/tpuquorum`` (selected via ``ExpertConfig.quorum_engine``): with it
enabled, the per-group scalar work the reference does inside
``processSteps`` — ReplicateResp ack tallying, matchIndex quorum reduction
(``raft.go:888-909`` ``tryCommit``) and candidate vote tallying
(``raft.go:1062-1080``) — is staged as compact event batches and computed
for ALL groups in one fused device dispatch per coordinator round
(:mod:`dragonboat_tpu.ops`).  With it disabled, nothing below runs and the
scalar path is untouched.

Division of labor (SURVEY.md §7 design pivot):
- dense 99% paths on device: ack ingest (scatter-max), per-group
  kth-largest commit reduction, vote tally vs quorum
- rare paths stay scalar on host and re-sync their row: leadership
  transitions, membership change, snapshot restore, index rebase
- commit/election *effects* are applied back under each node's raftMu
  with the scalar guards intact (``log.try_commit(q, term)`` re-checks the
  term rule), so a stale device result is rejected, never applied

Determinism: the device commit index is the same ``kth_largest(match)``
the scalar sort computes, and the term guard is re-applied scalar-side —
commit outputs are bit-identical to the pure-scalar path (differential
tests in ``tests/test_tpuquorum.py`` + ``tests/test_ops_quorum.py``).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, TYPE_CHECKING

from . import obs as _obs
from .logger import get_logger

if TYPE_CHECKING:
    from .node import Node

plog = get_logger("tpuquorum")


class TpuQuorumCoordinator:
    """Owns the device engine; one round = one fused dispatch.

    All staging methods are called from raft under the owning node's
    raftMu; the coordinator serializes engine access with its own lock.
    The round thread applies commit/election results back through
    ``Node.offload_commit`` / ``Node.offload_election``.
    """

    def __init__(
        self,
        capacity: int = 1024,
        n_peers: int = 8,
        interval_s: float = 0.002,
        drive_ticks: bool = True,
        mesh_devices: int = 0,
        drive_reads: bool = True,
        warm_fused: bool = False,
        compilation_cache_dir: Optional[str] = None,
        telem: bool = False,
    ):
        from .ops.engine import (
            WARM_K_BUCKETS,
            BatchedQuorumEngine,
            enable_persistent_compilation_cache,
            k_bucket,
        )

        # mesh-sharded dispatch plane (ExpertConfig.engine_mesh_devices,
        # ops/mesh.py): no data ever flows BETWEEN groups, so N mesh
        # devices run N independent single-device per-shard engines —
        # each shard owns a contiguous group partition with its OWN
        # concurrent dispatch stream and per-shard dispatch lock.  This
        # replaced the GSPMD-partitioned single engine whose every
        # dispatch was an all-device rendezvous serialized process-wide
        # by the old _MULTIDEV_MU class lock (zero dispatch concurrency
        # from mesh hardware); the GSPMD path remains available by
        # constructing BatchedQuorumEngine(sharding=...) directly.
        mesh_n = 0  # effective shard count (0 = unsharded)
        mesh_devs = None
        if mesh_devices > 1:
            import jax

            devs = jax.devices()
            n = min(mesh_devices, len(devs))
            if n > 1:
                capacity = ((capacity + n - 1) // n) * n
                mesh_devs = devs[:n]
                mesh_n = n
                plog.info(
                    "quorum engine mesh-sharded over %d devices "
                    "(%d rows, %d per shard)", n, capacity, capacity // n,
                )
        self.mesh_devices = mesh_n
        # persistent XLA compilation cache (ISSUE 7): enabled BEFORE any
        # program compiles so even the single-round warm misses persist;
        # the directory is versioned by kernel-source hash inside
        # enable_persistent_compilation_cache.  Env fallback lets ops
        # point every process at a shared cache without config plumbing.
        if compilation_cache_dir is None:
            compilation_cache_dir = (
                os.environ.get("DBTPU_COMPILATION_CACHE") or None
            )
        self.compilation_cache_dir = None
        if compilation_cache_dir:
            try:
                self.compilation_cache_dir = (
                    enable_persistent_compilation_cache(compilation_cache_dir)
                )
            except OSError as e:
                plog.warning("compilation cache unavailable: %r", e)
        if mesh_n > 1:
            from .ops.mesh import MeshQuorumEngine

            self.eng = MeshQuorumEngine(
                capacity, n_peers, event_cap=max(4 * capacity, 4096),
                devices=mesh_devs, device_ticks=drive_ticks,
            )
        else:
            self.eng = BatchedQuorumEngine(
                capacity, n_peers, event_cap=max(4 * capacity, 4096),
                device_ticks=drive_ticks,
            )
        self.capacity = capacity
        # adaptive K-round batching (ISSUE 7 tentpole): once the warmup
        # pass has compiled the padded fused program set, the round
        # thread replays tick backlogs as ONE fused dispatch of up to
        # fused_k_max rounds; until then (and whenever a round carries
        # votes) it stays on the single-round path
        self._k_bucket = k_bucket
        # the deficit cap IS the largest warmed program: a bigger cap
        # would silently drop the ticks past the pad clamp
        self.fused_k_max = max(WARM_K_BUCKETS)
        self.fused_dispatches = 0
        # auto-warm only ticking engines: the fused live path is
        # tick-deficit replay, meaningless without drive_ticks.  Mesh
        # coordinators warm too — each shard's program set is
        # single-device (no collectives, no rendezvous), walked
        # sequentially off the round thread by the facade's niced
        # background warmer (ops/mesh.py warmup_fused).
        self._warm_requested = warm_fused and drive_ticks
        # device-tick mode: the per-tick firing decisions (election due,
        # heartbeat due, check-quorum window) come from the device tick
        # kernel; registered nodes set raft.device_ticks accordingly
        self.drive_ticks = drive_ticks
        # device read plane (ISSUE 3): ReadIndex heartbeat-echo quorum
        # counting batches into the same single-round dispatch; the
        # scalar ReadIndex stays the pending bookkeeping and the releaser
        self.drive_reads = drive_reads
        # per-group FIFO of device-staged read ctxs: cid -> list of
        # (slot, low, high, term) in staging order.  Confirmation of a
        # slot releases its ctx through the scalar prefix release, which
        # also frees every EARLIER ctx — their engine slots are cancelled
        # here.  Guarded by _mu (round thread + drain).
        self._read_pending: Dict[int, list] = {}
        # batched device-plane lease tracking (ISSUE 10, lease.LeaseTable):
        # created by the first registered read_lease group; the drain loop
        # folds the heartbeat-ack ops it is ALREADY walking into a
        # per-round tally — lease-coverage introspection across thousands
        # of groups with no extra host pass and no raftMu.  Advisory only:
        # the serving authority is each group's scalar LeaderLease.
        self.lease_table = None
        # observability: ctxs confirmed BY THE DEVICE plane vs echoes that
        # fell back to the scalar tally (overflow/stale) — the read-plane
        # tests assert the device actually served the load
        self.read_confirms = 0
        self.read_fallbacks = 0
        # device state machine plane (devsm, ISSUE 11; DevKVPlane):
        # created by the FIRST DeviceKVStateMachine registration
        # (NodeHost.start_cluster with Config.device_kv).  None keeps the
        # round loop bit-identical — every hook below gates on it.
        self.devsm = None
        # cost-driven placement cadence (mesh only): the round thread
        # runs at most one bounded rebalance pass per interval
        self._rebalance_interval = 1.0
        self._next_rebalance = time.monotonic() + self._rebalance_interval
        # monotonically increasing tick sequence written ONLY by the tick
        # thread; the round compares against the last value it consumed, so
        # a tick arriving mid-round is never lost (no lock needed: single
        # writer, single reader)
        self._tick_seq = 0
        self._tick_seen = 0
        self._nodes: Dict[int, "Node"] = {}
        self._mu = threading.RLock()
        # staging is decoupled from the engine lock: raft step workers only
        # append under this micro-lock and NEVER wait on an in-flight
        # device dispatch — a blocked step worker delays heartbeats and
        # provokes spurious elections (the same reason the reference sends
        # Replicate before fsync, execengine.go:954-961)
        self._stage_mu = threading.Lock()
        self._staged: list = []
        # per-round leader-contact dedup: one election-clock reset per
        # group per round is sufficient and idempotent; without this a
        # follower ingesting tens of thousands of Replicates per second
        # would stage one event slot per message
        self._contacted: set = set()
        self._pending = threading.Event()
        self._stopped = threading.Event()
        self._interval = interval_s
        # compartmentalized host plane (hostplane.py, wired by NodeHost
        # when ExpertConfig.host_compartments is on): the round fan-out
        # below then flags offload effects with wake=False and coalesces
        # the engine step wakeups to ONE per touched group per round —
        # the coordinator feeds the same batched-wakeup tier the ingress
        # batcher uses.  None keeps the per-effect wakeups (bit-identical
        # pre-compartment behavior).
        self.hostplane = None
        # device-plane observability (ISSUE 5): OFF by default, gated on
        # `is not None` everywhere (the engine's overhead contract); the
        # module latch covers tests/bench, NodeHostConfig.enable_metrics
        # covers the live stack (nodehost.py wiring)
        self._obs = None
        # cross-plane request tracer (obs/trace.py, ISSUE 9; set by
        # NodeHost): the round fan-out stamps "device_round" on the
        # in-flight traces of every group whose commit/read-confirm this
        # round released, linking the engine's dispatch span seq.  None
        # keeps the round loop bit-identical.
        self.tracer = None
        # replication attribution (obs/replattr.py, ISSUE 14; set by
        # NodeHost with the tracer): device-plane commits link the
        # staged-round ack block's dispatch span into their attribution
        # records, so a closed record names the round that released it.
        # None keeps the round loop bit-identical.
        self.replattr = None
        # device capacity & profiling plane (obs/devprof.py, ISSUE 15;
        # attached by NodeHost when device_profile > 0).  None keeps the
        # engine's _devprof latch down and the dispatch path bit-identical.
        self.devprof = None
        # device telemetry fold (ISSUE 20, kernels.telem_fold; NodeHost
        # wires NodeHostConfig.health_aggregate here): flipped BEFORE
        # warmup starts so the warmed fused programs already include the
        # fold — a late enable_telem still works but pays one recompile
        # per variant on next use (the late-devsm precedent).
        if telem:
            self.eng.enable_telem()
        if _obs.enabled():
            self.enable_obs()
        if self._warm_requested:
            self.start_warmup()
        self._thread = threading.Thread(
            target=self._round_main, name="tpuquorum", daemon=True
        )
        self._thread.start()

    def start_warmup(self, force: bool = False):
        """Kick off the engine's background AOT warm-compile (idempotent;
        see ``BatchedQuorumEngine.warmup_fused``).  NodeHost calls this
        AFTER wiring observability so the warmup spans/metrics land in
        the host's registry; until the readiness latch flips, every
        round uses the already-compiled single-round programs — a
        proposal never waits on XLA.

        Mesh-sharded coordinators warm too: the facade's background
        walker compiles each shard's SINGLE-DEVICE program set
        sequentially (no collectives, so the historical multi-device
        first-compile rendezvous wedge cannot recur), and the
        ``fused_ready`` readiness latch flips only once every shard
        finished — until then fused-eligible rounds record
        ``fuse_skip="mesh_warmup"``.

        No-op (returns None) on a tickless coordinator unless ``force``:
        the fused live path is tick-deficit replay."""
        if not force and not self.drive_ticks:
            return None
        return self.eng.warmup_fused()

    @property
    def warmup_stats(self) -> dict:
        """The engine's warm-compile record (programs, wall seconds,
        persistent-cache hits/misses, error)."""
        return self.eng.warmup_stats

    def enable_obs(self, recorder=None, registry=None, stall_ms=None):
        """Attach round-loop + engine instruments: coordinator spans and
        ``dragonboat_coord_*`` families here, ``dragonboat_device_*`` on
        the engine, node offload counters on registered nodes — all into
        one registry so ``write_health_metrics`` exposes the whole device
        plane.  ``stall_ms`` overrides the recorder's stall threshold
        (the round-gate watchdog's trip point).  A repeat call with no
        recorder/registry is a no-op; explicit arguments REBIND (the
        engine's ``enable_obs`` note: a latch-attached coordinator must
        not swallow NodeHost's later registry wiring)."""
        if self._obs is None or recorder is not None or registry is not None:
            from .obs.instruments import CoordObs

            eng_obs = self.eng.enable_obs(recorder, registry)
            self._obs = CoordObs(eng_obs.recorder, registry=registry)
            with self._mu:
                for node in self._nodes.values():
                    node.obs_registry = self._obs.registry
        if stall_ms is not None:
            self._obs.recorder.stall_ms = float(stall_ms)
        return self._obs

    @property
    def flight_recorder(self):
        """The attached flight recorder (None while obs is off)."""
        return self._obs.recorder if self._obs is not None else None

    def enable_devprof(self, devprof):
        """Attach the device capacity & profiling plane (ISSUE 15,
        obs/devprof.py; NodeHost wires it when
        ``NodeHostConfig.device_profile`` > 0): binds the DevProf to the
        engine (flipping its ``_devprof`` latch — sampled device-time
        estimation, padding-waste accounting, the HBM ledger) and hands
        it this coordinator so its snapshots can reach the devsm plane's
        shadow residency."""
        devprof.coord = self
        devprof.bind_engine(self.eng)
        self.devprof = devprof
        return devprof

    def enable_telem(self, topk: Optional[int] = None) -> None:
        """Flip the engine's device telemetry fold (ISSUE 20,
        ``kernels.telem_fold``): every subsequent fused/dense/sparse
        dispatch egresses a fixed-size health aggregate (commit-lag
        histogram, per-state counts, stalled count, slot occupancy,
        on-device top-K worst groups).  One-way, like ``enable_devprof``;
        prefer the ``telem=True`` constructor kwarg so the warmed program
        set already includes the fold."""
        self.eng.enable_telem(topk)

    @property
    def telem_enabled(self) -> bool:
        return self.eng.telem_enabled

    def telem_snapshot(self) -> Optional[dict]:
        """Latest harvested device telemetry aggregate (None until the
        first telem-on dispatch lands; mesh coordinators merge per-shard
        folds host-side).  Passive: the dict refreshes only when rounds
        dispatch, and carries ``seq``/``mono`` so the health sampler can
        tell a fresh fold from a stale one on an idle engine."""
        return self.eng.telem_snapshot()

    def registered_cids(self) -> set:
        """Cluster ids currently registered on the device engine (the
        aggregate health sampler's coverage set: these groups are
        watched by the telemetry fold, everything else keeps the
        per-group raft_mu walk).  Snapshot under the coordinator lock —
        callers cache it keyed on the membership signature."""
        with self._mu:
            return set(self._nodes)

    def health_snapshot(self) -> dict:
        """Round-loop health for the cluster health sampler (ISSUE 13):
        staged-op backlog, registered groups, warmup readiness and the
        read-plane tallies — all lock-free or micro-locked reads, never
        the engine lock (a sampler must not queue behind a dispatch)."""
        with self._stage_mu:
            staged = len(self._staged)
        d = {
            "groups": len(self._nodes),
            "staged": staged,
            "tick_deficit": self._tick_seq - self._tick_seen,
            "fused_ready": bool(self.eng.fused_ready),
            "fused_dispatches": self.fused_dispatches,
            "read_confirms": self.read_confirms,
            "read_fallbacks": self.read_fallbacks,
        }
        lt = self.lease_table
        if lt is not None:
            d["lease_groups_held"] = lt.held_count(self._tick_seen)
        if self.mesh_devices > 1:
            # per-shard placement/cost view (mesh dispatch plane): group
            # counts, dispatch-cost EMA and per-shard warm readiness,
            # plus the lifetime migration count — the shard_imbalance
            # health detector keys off these
            d["shards"] = self.eng.shard_stats()
            d["migrations"] = self.eng.migrations
        return d

    # ------------------------------------------------------------------
    # node lifecycle
    # ------------------------------------------------------------------

    def register(self, node: "Node") -> None:
        """Add the node's group and sync its current raft state into the
        row.  Called after Peer.launch with the raft lock held."""
        with self._mu:
            self._nodes[node.cluster_id] = node
            self._sync_row_locked(node)
            if self.drive_reads:
                node.peer.raft.device_reads = True
            if self._obs is not None:
                node.obs_registry = self._obs.registry

    def unregister(self, cluster_id: int) -> None:
        if self.devsm is not None:
            self.devsm.unregister(cluster_id)
        with self._mu:
            self._nodes.pop(cluster_id, None)
            self._read_pending.pop(cluster_id, None)
            if self.lease_table is not None:
                self.lease_table.remove(cluster_id)
            if cluster_id in self.eng.groups:
                self.eng.remove_group(cluster_id)

    def devsm_plane(self):
        """The device state machine plane, created on first use
        (``NodeHost.start_cluster`` registration path)."""
        if self.devsm is None:
            from .devsm.plane import DevKVPlane

            self.devsm = DevKVPlane(self)
            # the ENGINE egress hook is the single delivery channel for
            # KV read captures: it fires on every harvest that carried
            # one — including rare-path internal harvests (row syncs,
            # transitions) whose results the round loop never sees and
            # which would otherwise strand parked readers until timeout
            self.eng.kv_egress_hook = self.devsm.deliver
        return self.devsm

    def devsm_force_release(self, cluster_id: int) -> bool:
        """Actuation surface for the recovery plane (obs/recovery.py,
        ISSUE 17): force-release the group's device binding so a
        bind/unbind loop stops burning uploads — reads fall back to the
        gated host shadow and the bind re-arms only on the next
        leadership transition.  Returns True when the group was tracked
        (something to release)."""
        plane = self.devsm
        if plane is None or not plane.tracks(cluster_id):
            return False
        plane.on_unbind(cluster_id)
        return True

    def _sync_row_locked(self, node: "Node") -> None:
        """(Re)build the group's row from scalar raft state — the rare-path
        resync used at registration and after membership changes."""
        r = node.peer.raft
        cid = r.cluster_id
        self._read_pending.pop(cid, None)
        if r.lease is not None:
            # (re)configure the advisory lease row from scalar state —
            # quorum/duration track membership changes through the same
            # resync path the engine row rides
            if self.lease_table is None:
                from .lease import LeaseTable

                self.lease_table = LeaseTable()
            self.lease_table.configure(
                cid, r.quorum(), r.lease.duration, r.node_id,
                voters=list(r.remotes) + list(r.witnesses),
            )
        if cid in self.eng.groups:
            self.eng.remove_group(cid)
        voters = sorted(set(r.remotes))
        witnesses = tuple(sorted(r.witnesses))
        observers = tuple(sorted(r.observers))
        if r.node_id not in r.remotes and r.node_id not in r.witnesses and (
            r.node_id not in r.observers
        ):
            # a joining node knows no membership yet (it learns from the
            # log); register a self-only row until membership_changed
            # resyncs it
            voters = sorted(set(voters) | {r.node_id})
        self.eng.add_group(
            cid,
            node_ids=voters,
            self_id=r.node_id,
            election_timeout=r.election_timeout,
            heartbeat_timeout=r.heartbeat_timeout,
            # per-replica seeded randomized timeout (scalar raft's own),
            # so co-hosted replicas don't fire elections in lockstep
            rand_timeout=r.randomized_election_timeout,
            check_quorum=r.check_quorum,
            witnesses=witnesses,
            observers=observers,
        )
        if r.hier is not None:
            # hier geometry (ISSUE 18) is membership-like: the near mask
            # and sub-quorum cardinality follow the voter set and this
            # replica's static domain, not the row's role, so the
            # registration/resync rebuild is the only push site — the
            # staged leader/candidate/follower transitions leave it
            # untouched exactly like the membership columns.  The fused
            # rule only ever widens q on leader rows (kernels._finish_step
            # has_hier twin of Raft._hier_try_commit).
            from .raft.hier import sub_quorum_size

            near = r.hier.near_voters(set(voters) | set(witnesses))
            self.eng.set_hier(
                cid, near, sub_quorum_size(len(near)) if near else 0
            )
        if r.is_leader():
            self.eng.set_leader(
                cid,
                term=r.term,
                term_start=self._term_start(r),
                last_index=r.log.last_index(),
            )
            # replay known match state so commit picks up where scalar was
            for nid, rp in list(r.remotes.items()) + list(r.witnesses.items()):
                if rp.match > 0:
                    self.eng.ack(cid, nid, rp.match)
            if self.devsm is not None and self.devsm.tracks(cid):
                # a resync on a standing leader re-arms the devsm bind at
                # the current log tail (the drain's resync op unbound it)
                self.devsm.on_leader(cid, r.log.last_index())
        elif r.is_candidate():
            self.eng.set_candidate(cid, term=r.term)
            for nid, granted in r.votes.items():
                self.eng.vote(cid, nid, granted)
        else:
            self.eng.set_follower(cid, term=r.term)

    @staticmethod
    def _term_start(r) -> int:
        """First index of the leader's current term — the floor below which
        counting-based commit is forbidden (raft paper p8).  O(1): the
        leader records the index of its promotion noop
        (``raft.term_start_index``); the scan fallback covers only rows
        synced from state predating the attribute (never in practice)."""
        if r.term_start_index > 0:
            return r.term_start_index
        idx = r.log.last_index()
        first = r.log.first_index()
        while idx >= first:
            try:
                if r.log.term(idx) != r.term:
                    return idx + 1
            except Exception:
                return idx + 1
            idx -= 1
        return idx + 1

    # ------------------------------------------------------------------
    # staging hooks (called from raft under the node's raftMu)
    # ------------------------------------------------------------------

    def _stage(self, op) -> None:
        with self._stage_mu:
            self._staged.append(op)
        self._pending.set()

    def ack(self, cluster_id: int, node_id: int, index: int) -> None:
        self._stage(("ack", cluster_id, node_id, index))

    def vote(self, cluster_id: int, node_id: int, granted: bool) -> None:
        self._stage(("vote", cluster_id, node_id, granted))

    def heartbeat_resp(self, cluster_id: int, node_id: int) -> None:
        self._stage(("hbresp", cluster_id, node_id))

    def leader_contact(self, cluster_id: int) -> None:
        if cluster_id in self._contacted:
            return
        with self._stage_mu:
            if cluster_id in self._contacted:
                return
            self._contacted.add(cluster_id)
            self._staged.append(("contact", cluster_id))
        self._pending.set()

    def set_randomized_timeout(self, cluster_id: int, timeout: int) -> None:
        self._stage(("randto", cluster_id, timeout))

    def read_stage(
        self, cluster_id: int, committed: int, low: int, high: int, term: int
    ) -> None:
        """A leader accepted a ReadIndex ctx (``handle_leader_read_index``
        under raftMu): stage it into the group's pending-read slot,
        captured at scalar raft's own committed watermark."""
        self._stage(("rstage", cluster_id, committed, low, high, term))

    def read_ack_hint(
        self, cluster_id: int, node_id: int, low: int, high: int
    ) -> None:
        """A heartbeat response echoed a ReadIndex hint: joins the ctx's
        pending-read slot; the device row-sum decides the quorum."""
        self._stage(("rack", cluster_id, node_id, low, high))

    def stage_sm_ops(self, cluster_id: int, ops) -> None:
        """A devsm leader appended application entries
        (``raft.append_entries`` under raftMu): hand their ``(index,
        payload)`` pairs to the device state machine plane — the apply
        fold consumes them the round their commit lands."""
        self._stage(("kvops", cluster_id, ops))

    def set_leader(
        self, cluster_id: int, term: int, term_start: int, last_index: int
    ) -> None:
        self._stage(("leader", cluster_id, term, term_start, last_index))

    def set_candidate(self, cluster_id: int, term: int) -> None:
        self._stage(("candidate", cluster_id, term))

    def set_follower(self, cluster_id: int, term: int) -> None:
        self._stage(("follower", cluster_id, term))

    def membership_changed(self, cluster_id: int) -> None:
        self._stage(("resync", cluster_id))

    def request_tick(self) -> None:
        """One RTT elapsed: the next round runs the device tick kernel
        (called from the NodeHost tick worker, once per tick for ALL
        groups — the device ticks rows in lockstep)."""
        self._tick_seq += 1
        self._pending.set()

    def _drain_locked(self) -> list:
        """Apply staged ops to the engine in staging order (so a
        transition's queued-event purge still covers exactly the events
        staged before it).  Returns the cids needing a row recovery —
        recovery takes node.raft_mu, and the lock order everywhere else is
        raft_mu -> coord._mu (register's contract), so acquiring raft_mu
        HERE (under _mu) deadlocks against fast_eject -> register (seen
        live in the tpu+fastlane chaos run); the caller recovers after
        releasing _mu."""
        with self._stage_mu:
            ops, self._staged = self._staged, []
            self._contacted.clear()
        recover = []
        lt = self.lease_table
        lease_acks: Dict[int, set] = {}
        # bulk-pull every row a transition below will mutate: one device
        # gather per field for the whole set, instead of ~20 single-row
        # reads inside each set_* call (the dominant cost of election
        # bursts at 4k+ groups)
        sync_rows = []
        for op in ops:
            if op[0] in ("leader", "candidate", "follower", "randto"):
                gi = self.eng.groups.get(op[1])
                if gi is not None:
                    sync_rows.append(gi.row)
        if sync_rows:
            self.eng.sync_rows(sync_rows)
        for op in ops:
            kind, cid = op[0], op[1]
            if cid not in self.eng.groups:
                continue
            try:
                if kind == "ack":
                    self.eng.ack(cid, op[2], op[3])
                elif kind == "vote":
                    self.eng.vote(cid, op[2], op[3])
                elif kind == "hbresp":
                    self.eng.heartbeat_resp(cid, op[2])
                    if lt is not None and lt.tracks(cid):
                        # lease tally rides the op walk already in flight
                        lease_acks.setdefault(cid, set()).add(op[2])
                elif kind == "contact":
                    self.eng.leader_contact(cid)
                elif kind == "randto":
                    self.eng.set_randomized_timeout(cid, op[2])
                elif kind == "rstage":
                    try:
                        slot = self.eng.stage_read(cid, count=1, index=op[2])
                    except RuntimeError:
                        # every pending-read slot holds an unconfirmed
                        # batch: leave this ctx to the scalar fallback
                        # (its echoes arrive as unknown-ctx racks below)
                        pass
                    else:
                        self._read_pending.setdefault(cid, []).append(
                            (slot, op[3], op[4], op[5])
                        )
                elif kind == "rack":
                    node_id, low, high = op[2], op[3], op[4]
                    slot = None
                    for sl, lo, hi, _t in self._read_pending.get(cid, ()):
                        if lo == low and hi == high:
                            slot = sl
                            break
                    if slot is not None:
                        self.eng.read_ack(cid, node_id, slot)
                    else:
                        # ctx not device-tracked (slot overflow, stale or
                        # already-confirmed echo): scalar tally under
                        # raftMu — confirm() on an unknown ctx is a no-op
                        self.read_fallbacks += 1
                        node = self._nodes.get(cid)
                        if node is not None:
                            node.offload_read_echo(node_id, low, high)
                elif kind == "kvops":
                    if self.devsm is not None:
                        self.devsm.handle_ops(cid, op[2])
                elif kind == "leader":
                    self._read_pending.pop(cid, None)
                    if lt is not None:
                        lt.drop(cid)
                    self.eng.set_leader(
                        cid, term=op[2], term_start=op[3], last_index=op[4]
                    )
                    if self.devsm is not None:
                        self.devsm.on_leader(cid, op[4])
                elif kind == "candidate":
                    self._read_pending.pop(cid, None)
                    if lt is not None:
                        lt.drop(cid)
                    self.eng.set_candidate(cid, term=op[2])
                    if self.devsm is not None:
                        self.devsm.on_unbind(cid)
                elif kind == "follower":
                    self._read_pending.pop(cid, None)
                    if lt is not None:
                        lt.drop(cid)
                    self.eng.set_follower(cid, term=op[2])
                    if self.devsm is not None:
                        self.devsm.on_unbind(cid)
                else:  # resync
                    self._read_pending.pop(cid, None)
                    if lt is not None:
                        lt.drop(cid)
                    if self.devsm is not None:
                        self.devsm.on_unbind(cid)
                    recover.append(cid)
            except (ValueError, KeyError):
                # unknown peer slot / index past the rebase window: rebuild
                # the row from scalar state (rare)
                recover.append(cid)
        if lt is not None and lease_acks:
            lt.note_round(lease_acks, self._tick_seen)
        return recover

    def _recover_row(self, cluster_id: int) -> None:
        """Rebuild a row from scalar state.  Lock order: raft_mu FIRST,
        then _mu (matching register/fast_eject) — never call under _mu."""
        node = self._nodes.get(cluster_id)
        if node is None:
            return
        with node.raft_mu:
            if node.peer is None:
                return
            with self._mu:
                if cluster_id not in self.eng.groups:
                    return
                try:
                    self.eng.rebase(cluster_id)
                except Exception:
                    pass
                self._sync_row_locked(node)

    # ------------------------------------------------------------------
    # the round
    # ------------------------------------------------------------------

    def _round_main(self) -> None:
        # Deprioritize this thread (Linux per-thread niceness, default +5,
        # DBTPU_ENGINE_NICE overrides, 0 disables).  The round thread is
        # a batch amortizer — a delayed round just batches more events —
        # but its dispatches (and the jax runtime work they trigger)
        # compete with the raft/transport threads for cycles on a
        # core-starved box: the e2e A/B's bimodal throughput (a ~6.6k
        # w/s mode whenever the scheduler favored this thread; PERF.md
        # round-5 §3) hit 3 of 8 un-niced runs and 0 of 6 niced ones
        # (validated at both +10 and this +5 default; mean up ~22%).
        # On an idle machine niceness changes nothing — a niced thread
        # with a free core still runs immediately.
        import os as _os

        try:
            nice = int(_os.environ.get("DBTPU_ENGINE_NICE", "5"))
        except ValueError:
            plog.warning("malformed DBTPU_ENGINE_NICE; using default 5")
            nice = 5
        if nice:
            try:
                _os.setpriority(
                    _os.PRIO_PROCESS, threading.get_native_id(), nice
                )
            except (OSError, AttributeError) as e:
                # the perf fix silently not applying must be attributable
                # (the bimodal slow mode would return with no clue)
                plog.warning("engine round-thread nice failed: %r", e)
        while not self._stopped.is_set():
            fired = self._pending.wait(timeout=self._interval)
            if self._stopped.is_set():
                return
            if fired:
                self._pending.clear()
            try:
                self._round()
            except Exception:
                plog.exception("tpu quorum round failed")

    def _round(self) -> None:
        recover: list = []
        try:
            self._round_inner(recover)
        finally:
            if recover:
                # rare-path row rebuilds, OUTSIDE _mu (lock order: raft_mu
                # then _mu); the recovered rows step next round
                for cid in dict.fromkeys(recover):
                    self._recover_row(cid)
                self._pending.set()

    def _round_inner(self, recover: list) -> None:
        obs = self._obs
        t0 = time.perf_counter() if obs is not None else 0.0
        gate = None
        n_ops = 0
        k_rounds = 1
        fused = False
        fuse_skip = None
        with self._mu:
            seq = self._tick_seq
            # catch up missed ticks (a slow round — tunneled dispatch,
            # contended host — can span several host ticks; the scalar
            # path replays every LOCAL_TICK the same way).  Fused-ready
            # rounds replay up to fused_k_max ticks in ONE dispatch;
            # before warmup completes the cap stays at 4 so the per-step
            # fallback can't turn a stall into a dispatch storm.
            fused_ok = self.drive_ticks and self.eng.fused_ready
            cap = self.fused_k_max if fused_ok else 4
            deficit = min(seq - self._tick_seen, cap) if self.drive_ticks else 0
            do_tick = deficit > 0
            self._tick_seen = seq
            if obs is not None:
                n_ops = len(self._staged)  # racy read, gauge-grade
            recover.extend(self._drain_locked())
            if self.devsm is not None:
                # advance pending devsm binds (host apply catching the
                # promotion watermark completes them)
                self.devsm.poll()
            has_acks = bool(
                self.eng._acks or self.eng._ack_blocks or self.eng._votes
            )
            # staged read ctxs / heartbeat echoes must dispatch even
            # on an otherwise-quiet round: with drive_ticks off (or
            # a quiet group) nothing else would ever flush them and
            # the pending ReadIndex would hang until client timeout
            has_reads = self.eng._reads_pending()
            # ... and so must staged devsm entry ops / KV read captures
            # (a parked lookup is waiting on exactly one dispatch)
            has_kv = self.eng._kv_pending()
            # dirty-only rounds (row registrations, transition
            # replays with no queued events) need no dispatch when
            # ticks drive regular rounds anyway: the upload
            # piggybacks on the next event/tick round.  Bulk
            # registration of thousands of groups otherwise
            # interleaves a dispatch between every few registers.
            dirty_gate = bool(self.eng._dirty and not self.drive_ticks)
            if not (do_tick or has_acks or has_reads or has_kv or dirty_gate):
                return
            if obs is not None:
                gate = "+".join(
                    name
                    for name, hit in (
                        ("tick", do_tick), ("acks", has_acks),
                        ("reads", has_reads), ("kv", has_kv),
                        ("dirty", dirty_gate),
                    )
                    if hit
                )
            # Adaptive K-round batching (ISSUE 7 tentpole).  The fused
            # K-round program (step_rounds, the ladder's workhorse) was
            # once measured here and reverted because each first-use XLA
            # compile of a fused variant cost 0.5-4s and stalled
            # proposals behind it; the warmup pass killed the stall
            # instead of the feature — AOT warm-compile of the padded
            # (K,G,P) program set at enable time, persisted across
            # restarts by the XLA compilation cache.  Policy:
            #   - quiet rounds (deficit <= 1) keep the single-round
            #     program — identical dispatch, identical latency;
            #   - a tick backlog replays as ONE fused dispatch: the
            #     staged events ride round 0 and the remaining deficit
            #     ticks run as event-free padding rounds (tick_rounds),
            #     padded to the nearest warm K bucket so the whole
            #     adaptive range reuses len(buckets) compiled programs;
            #   - rounds carrying VOTES fall back to the single-round
            #     path (elections want the fastest round, not a batched
            #     one — and the fused vote variant is deliberately not
            #     warmed);
            #   - until warmup completes, the per-step replay below
            #     keeps using the already-compiled single-round
            #     programs, so a proposal NEVER waits on XLA
            #     (fuse_skip span field: "warmup"/"votes").
            # Semantics are unchanged either way: epoch filters resolve
            # at dispatch exactly like the single-round path (no round
            # is sealed mid-drain), and a deficit-K block is precisely
            # the old step + (K-1) tick replays in one program
            # (differential: tests/test_live_fused.py).
            has_votes = bool(self.eng._votes)
            # the coordinator itself never stages in-program recycles
            # (membership changes resync through the host rare path),
            # but a hybrid caller driving stage_recycle/begin_round on
            # this engine could leave churn in the backlog — and the
            # warmed program set deliberately excludes the has_churn
            # variant, so fusing it would reintroduce the first-use
            # compile stall this PR exists to kill
            has_churn = bool(self.eng._churn or self.eng._round_blocks)
            # a kv-carrying block needs the has_kv fused variants warmed
            # (warmup_devsm, kicked at plane registration) — until then
            # kv rounds take the already-compiling dense single-round
            # path instead of stalling a fused dispatch behind XLA.
            # BUFFERED device ents force the fold too (the engine runs
            # has_kv on every dispatch while any op awaits its commit —
            # see _kv_ents_buffered), so they gate fusing the same way
            kv_unwarmed = (
                has_kv or self.eng._kv_ents_buffered()
            ) and not self.eng.kv_fused_ready
            read_confirms: list = []
            if deficit > 1:
                if not fused_ok:
                    # distinguish a mesh coordinator's per-shard program
                    # sets still warming from the single-device case —
                    # the readiness latch is all-shards-ready
                    fuse_skip = (
                        "mesh_warmup" if self.mesh_devices > 1 else "warmup"
                    )
                elif has_votes:
                    fuse_skip = "votes"
                elif has_churn:
                    fuse_skip = "churn"
                elif kv_unwarmed:
                    fuse_skip = "devsm"
            if (
                fused_ok and deficit > 1 and not has_votes
                and not has_churn and not kv_unwarmed
            ):
                fused = True
                k_rounds = deficit
                # guarantee >= 1 round even on a pure tick-catch-up
                # round with nothing staged
                self.eng.begin_round()
                res = self.eng.step_rounds(
                    do_tick=True,
                    pad_rounds_to=self._k_bucket(deficit),
                    tick_rounds=deficit,
                )
                self.fused_dispatches += 1
                self._collect_read_confirms(res, read_confirms)
            else:
                # per-step replay keeps the historical 4-tick cap even
                # when the fused gate (votes, warmup) bounced a deeper
                # backlog here: one skipped fuse must not become a
                # 16-dispatch storm (excess ticks are swallowed, exactly
                # as the old cap swallowed them)
                deficit = min(deficit, 4)
                res = self.eng.step(do_tick=do_tick)
                self._collect_read_confirms(res, read_confirms)
                for _ in range(deficit - 1):  # replay remaining ticks
                    extra = self.eng.step(do_tick=True)
                    res.commit.update(extra.commit)
                    self._collect_read_confirms(extra, read_confirms)
                    for field in (
                        "won", "lost", "elect", "heartbeat", "demote"
                    ):
                        merged = set(getattr(res, field))
                        merged.update(getattr(extra, field))
                        setattr(res, field, list(merged))
        # (devsm KV read captures were already delivered by the engine's
        # kv_egress_hook inside each harvest — see devsm_plane())
        # confirmed-read releases, OUTSIDE _mu like the commit callbacks:
        # the node re-checks leader/term under raftMu and releases through
        # the scalar ReadIndex prefix pop (indices identical to the pure
        # scalar path — tests/test_read_confirm.py).  With the host plane
        # attached, effects are flagged with wake=False and the step
        # wakeups coalesce to one per touched group at the end of the
        # round (hostplane.wake_nodes) — a commit+tick+read round for one
        # group costs one CV notify instead of three.
        tracer = self.tracer
        if tracer is not None and (res.commit or read_confirms):
            # stamp the device round BEFORE the offload fan-out (the
            # apply stamp must sort after this one), linking the span
            # seq of the dispatch that served this round.  The common
            # round has no read confirms — iterate res.commit's keys
            # directly instead of building a merged set (this block is
            # on the round thread, the tpu path's bottleneck)
            seq = self.eng.last_span_seq
            if read_confirms:
                cids = set(res.commit)
                cids.update(c for c, _l, _h, _t in read_confirms)
            else:
                cids = res.commit
            tracer.mark_clusters(cids, seq if seq >= 0 else None)
        replattr = self.replattr
        if replattr is not None and res.commit:
            # device-plane commit attribution (ISSUE 14): link THIS
            # round's dispatch span into the groups' open commit records
            # before the offload fan-out closes them under raftMu — the
            # closed record then cites the same span the request trace
            # links via mark_clusters above
            seq = self.eng.last_span_seq
            if seq >= 0:
                for cid in res.commit:
                    replattr.note_device_round(cid, seq)
        hp = self.hostplane
        touched: dict = {}
        # wake_kw stays EMPTY without the host plane so duck-typed test
        # nodes that predate the wake kwarg keep working unchanged
        wake_kw: dict = {} if hp is None else {"wake": False}
        for cid, low, high, term in read_confirms:
            node = self._nodes.get(cid)
            if node is not None:
                node.offload_read_confirm(low, high, term, **wake_kw)
                if hp is not None:
                    touched[cid] = node
        for cid, q in res.commit.items():
            node = self._nodes.get(cid)
            if node is not None:
                node.offload_commit(q, **wake_kw)
                if hp is not None:
                    touched[cid] = node
        # device tick flags: election due / heartbeat due / check-quorum
        # demote — applied through the scalar handlers under raftMu with
        # all guards intact (stale flags are rejected there)
        if do_tick:
            for cid in res.elect:
                node = self._nodes.get(cid)
                if node is not None:
                    node.offload_tick_elect(**wake_kw)
                    if hp is not None:
                        touched[cid] = node
            for cid in res.heartbeat:
                node = self._nodes.get(cid)
                if node is not None:
                    node.offload_tick_heartbeat(**wake_kw)
                    if hp is not None:
                        touched[cid] = node
            for cid in res.demote:
                node = self._nodes.get(cid)
                if node is not None:
                    node.offload_tick_demote(**wake_kw)
                    if hp is not None:
                        touched[cid] = node
        if hp is not None and touched:
            hp.wake_nodes(touched.values())
        # tag election outcomes with the term the row held when the round
        # ran: during long dispatches (first jit compile, busy host) the
        # scalar side may have restarted the campaign at a higher term, and
        # a stale won-flag must never promote a later-term candidate that
        # lacks a quorum at that term
        won_terms = {}
        lost_terms = {}
        with self._mu:
            for cid in res.won:
                gi = self.eng.groups.get(cid)
                if gi is not None:
                    won_terms[cid] = int(self.eng._read("term", gi.row))
            for cid in res.lost:
                gi = self.eng.groups.get(cid)
                if gi is not None:
                    lost_terms[cid] = int(self.eng._read("term", gi.row))
        for cid, term in won_terms.items():
            node = self._nodes.get(cid)
            if node is not None:
                node.offload_election(True, term)
        for cid, term in lost_terms.items():
            node = self._nodes.get(cid)
            if node is not None:
                node.offload_election(False, term)
        if obs is not None:
            if self.lease_table is not None:
                # advisory lease-coverage gauge (dragonboat_lease_groups_
                # held), refreshed from the drain-fed table — device-plane
                # lease introspection with zero raftMu traffic
                self.lease_table.publish(obs.registry, self._tick_seen)
            # the recorder's stall check on wall_ms IS the round-gate
            # watchdog: a round outlasting stall_ms (wedged dispatch,
            # first-compile storm, tunnel stall) auto-dumps the ring
            # with this span as the trigger
            obs.round(
                wall_ms=(time.perf_counter() - t0) * 1e3,
                gate=gate,
                ops=n_ops,
                deficit=deficit,
                commits=len(res.commit),
                reads_confirmed=len(read_confirms),
                read_fallbacks=self.read_fallbacks,
                staged_depth=len(self._staged),
                k_rounds=k_rounds,
                fused=fused,
                fuse_skip=fuse_skip,
            )
        # cost-driven placement (mesh dispatch plane): a time-gated
        # rebalance pass on dispatched rounds only — quiet coordinators
        # have no load to balance.  Runs under _mu like every other
        # engine access; the pass is bounded (one migration) and bails
        # unless the shard cost EMAs actually skew.
        if self.mesh_devices > 1:
            now = time.monotonic()
            if now >= self._next_rebalance:
                self._next_rebalance = now + self._rebalance_interval
                try:
                    with self._mu:
                        self.eng.maybe_rebalance()
                except Exception:
                    plog.exception("mesh rebalance failed")

    def _collect_read_confirms(self, res, out: list) -> None:
        """Map confirmed-read egress slots back to their ctxs (under _mu).

        A confirmed slot releases its ctx AND — through the scalar prefix
        release — every ctx staged before it; the earlier ctxs' engine
        slots are cancelled here so they don't leak until a transition
        purge.  Ctxs no longer tracked (a transition purged the group's
        FIFO after the dispatch was staged) drop silently: the node-side
        term guard would reject them anyway."""
        if res.read_cids is None or not len(res.read_cids):
            return
        for cid, slot, _index, _count in res.reads:
            fifo = self._read_pending.get(cid)
            if not fifo:
                continue
            pos = next(
                (i for i, e in enumerate(fifo) if e[0] == slot), None
            )
            if pos is None:
                continue
            _slot, low, high, term = fifo[pos]
            for e in fifo[:pos]:  # prefix-released scalar-side
                try:
                    self.eng.cancel_read(cid, e[0])
                except (ValueError, KeyError):
                    pass
            del fifo[: pos + 1]
            self.read_confirms += 1
            out.append((cid, low, high, term))

    def flush(self) -> None:
        """Run one round synchronously (tests)."""
        self._round()

    def stop(self) -> None:
        self._stopped.set()
        self.eng.cancel_warmup()
        self._pending.set()
        self._thread.join(timeout=5)
        stop_streams = getattr(self.eng, "stop", None)
        if stop_streams is not None:  # mesh facade: join shard streams
            stop_streams()
