"""Transport layer (reference ``internal/transport/``).

Message plane: per-remote queued senders with batching + circuit breakers.
Snapshot plane: chunked transfers on dedicated connections.  Wire modules
are pluggable (``IRaftRPC``): framed TCP with optional mutual TLS, or the
in-memory chan transport for single-process clusters and tests.
"""
from .chan import ChanRouter, ChanTransport, DEFAULT_ROUTER  # noqa: F401
from .chunks import Chunks  # noqa: F401
from .latency import LatencyInjector, crossdomain  # noqa: F401
from .registry import Registry  # noqa: F401
from .rpc import IConnection, IRaftRPC, ISnapshotConnection, TransportError  # noqa: F401
from .tcp import TCPTransport  # noqa: F401
from .transport import CircuitBreaker, Transport, create_transport  # noqa: F401
