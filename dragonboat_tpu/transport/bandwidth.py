"""Token-bucket bandwidth limiter for snapshot traffic.

Reference: ``internal/transport/tcp.go:430-437`` — snapshot chunk sends go
through a juju/ratelimit token bucket sized by
``NodeHostConfig.MaxSnapshotSendBytesPerSecond`` so bulk snapshot transfer
cannot starve the raft message plane.
"""
from __future__ import annotations

import threading
import time


class TokenBucket:
    """Classic token bucket: ``rate`` bytes/second, burst of one second."""

    def __init__(self, rate: int):
        self.rate = max(0, int(rate))
        self._mu = threading.Lock()
        self._tokens = float(self.rate)
        self._last = time.monotonic()

    def take(self, n: int, stop=None) -> None:
        """Block until ``n`` tokens are available (no-op when unlimited).

        Requests larger than one second's burst are clamped — a 2MB chunk
        against a 1MB/s cap waits ~1s instead of forever.  ``stop`` is an
        optional Event-like; once set the wait aborts (the caller's own
        stop/failure handling then takes over instead of this thread
        sitting in a throttle sleep after shutdown)."""
        if self.rate <= 0:
            return
        n = min(n, self.rate)
        while True:
            if stop is not None and stop.is_set():
                return
            with self._mu:
                now = time.monotonic()
                self._tokens = min(
                    float(self.rate), self._tokens + (now - self._last) * self.rate
                )
                self._last = now
                if self._tokens >= n:
                    self._tokens -= n
                    return
                missing = n - self._tokens
            time.sleep(min(0.2, missing / self.rate))
