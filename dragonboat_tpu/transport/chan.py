"""In-memory channel transport: deterministic single-process wire layer.

Reference: ``plugin/chan/chan.go`` — the test transport selected by the
memfs builds; also the template for pluggable transports.  A process-global
router maps addresses to receive handlers; chaos hooks (partitions, drops)
mirror the reference's monkey-test hooks (``monkey.go:184-213``).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Set, Tuple

from ..wire import Chunk, MessageBatch
from .rpc import (
    ChunkHandler,
    IConnection,
    IRaftRPC,
    ISnapshotConnection,
    RequestHandler,
    TransportError,
)


class ChanRouter:
    """Process-global address → handler registry."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._handlers: Dict[str, Tuple[RequestHandler, ChunkHandler]] = {}
        self._partitioned: Set[Tuple[str, str]] = set()
        self._drop_hook: Optional[Callable[[MessageBatch], bool]] = None
        self._delay_hook: Optional[Callable[[str, str], float]] = None

    def register(self, addr: str, rh: RequestHandler, ch: ChunkHandler) -> None:
        with self._mu:
            self._handlers[addr] = (rh, ch)

    def unregister(self, addr: str) -> None:
        with self._mu:
            self._handlers.pop(addr, None)

    def resolve(self, addr: str):
        with self._mu:
            return self._handlers.get(addr)

    # ---- chaos hooks ----

    def partition(self, a: str, b: str) -> None:
        """Symmetric partition between two addresses."""
        with self._mu:
            self._partitioned.add((a, b))
            self._partitioned.add((b, a))

    def heal(self, a: str = "", b: str = "") -> None:
        with self._mu:
            if not a:
                self._partitioned.clear()
            else:
                self._partitioned.discard((a, b))
                self._partitioned.discard((b, a))

    def is_partitioned(self, src: str, dst: str) -> bool:
        with self._mu:
            return (src, dst) in self._partitioned

    def set_drop_hook(self, hook) -> None:
        """hook(batch) -> True to drop (reference
        ``SetTransportDropBatchHook`` ``monkey.go:82``)."""
        with self._mu:
            self._drop_hook = hook

    def should_drop(self, batch: MessageBatch) -> bool:
        with self._mu:
            hook = self._drop_hook
        return hook(batch) if hook else False

    def set_delay_hook(self, hook) -> None:
        """hook(src, dst) -> one-way seconds to sleep before delivery
        (ISSUE 10 latency classes; a ``LatencyInjector.delay`` bound
        method fits directly).  Delivery runs on the per-remote sender
        thread, so the sleep delays that link only.  None clears."""
        with self._mu:
            self._delay_hook = hook

    def delivery_delay(self, src: str, dst: str) -> float:
        with self._mu:
            hook = self._delay_hook
        return hook(src, dst) if hook else 0.0


DEFAULT_ROUTER = ChanRouter()


class _ChanConnection(IConnection):
    def __init__(self, rpc: "ChanTransport", target: str):
        self.rpc = rpc
        self.target = target

    def send_message_batch(self, batch: MessageBatch) -> None:
        self.rpc.deliver(self.target, batch)

    def close(self) -> None:
        pass


class _ChanSnapshotConnection(ISnapshotConnection):
    def __init__(self, rpc: "ChanTransport", target: str):
        self.rpc = rpc
        self.target = target

    def send_chunk(self, chunk: Chunk) -> None:
        self.rpc.deliver_chunk(self.target, chunk)

    def close(self) -> None:
        pass


class ChanTransport(IRaftRPC):
    """Reference ``plugin/chan/chan.go`` ``ChanTransport``."""

    def __init__(
        self,
        source_address: str,
        request_handler: RequestHandler,
        chunk_handler: ChunkHandler,
        router: Optional[ChanRouter] = None,
    ):
        self.source_address = source_address
        self.request_handler = request_handler
        self.chunk_handler = chunk_handler
        self.router = router or DEFAULT_ROUTER
        self._started = False

    def name(self) -> str:
        return "chan-transport"

    def start(self) -> None:
        self.router.register(
            self.source_address, self.request_handler, self.chunk_handler
        )
        self._started = True

    def stop(self) -> None:
        if self._started:
            self.router.unregister(self.source_address)
            self._started = False

    def _check(self, target: str):
        if self.router.is_partitioned(self.source_address, target):
            raise TransportError(f"partitioned from {target}")
        h = self.router.resolve(target)
        if h is None:
            raise TransportError(f"no handler registered at {target}")
        return h

    def get_connection(self, target: str) -> IConnection:
        self._check(target)
        return _ChanConnection(self, target)

    def get_snapshot_connection(self, target: str) -> ISnapshotConnection:
        self._check(target)
        return _ChanSnapshotConnection(self, target)

    def deliver(self, target: str, batch: MessageBatch) -> None:
        if self.router.should_drop(batch):
            return
        d = self.router.delivery_delay(self.source_address, target)
        if d > 0:
            # runs on the Transport per-remote sender thread: the sleep
            # models this link's one-way latency only (latency.py)
            import time

            time.sleep(d)
        for m in batch.requests:
            if m.trace is not None:
                # replication-trace carriage parity with the TCP wire
                # (ISSUE 14): a framed wire DECODES a fresh ReplTrace on
                # the receiver, so the sender never observes the
                # receiver's stamps until they ride back on the ack.
                # The in-proc wire hands the sender's objects across
                # directly — clone the context at the delivery boundary
                # so both wires stamp an isolated copy (the trace=None
                # latch keeps this loop at one attribute check per
                # message otherwise).
                m.trace = m.trace.clone()
        rh, _ = self._check(target)
        rh(batch)

    def deliver_chunk(self, target: str, chunk: Chunk) -> None:
        _, ch = self._check(target)
        if not ch(chunk):
            raise TransportError(f"chunk rejected by {target}")
