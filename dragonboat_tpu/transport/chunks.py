"""Inbound snapshot chunk tracker.

Reference: ``internal/transport/chunks.go`` — tracks in-flight inbound
snapshots (max 128), validates chunk ordering, writes the image into a
``.receiving`` temp dir, and on completion converts the finished set into a
local ``InstallSnapshot`` message handed to the message router.  Stale
transfers are garbage collected on ticks.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from ..logger import get_logger
from ..settings import Soft
from ..server.snapshotenv import SSEnv, SSMode
from ..wire import Chunk, Message, MessageBatch, MessageType, Snapshot, SnapshotFile

plog = get_logger("transport")


@dataclass
class _Track:
    first_chunk: Chunk
    env: SSEnv
    next_chunk_id: int = 0
    tick: int = 0
    file: Optional[object] = None
    file_path: str = ""
    files: list = field(default_factory=list)  # completed external SnapshotFiles
    validate_current: Optional[SnapshotFile] = None


class Chunks:
    """Reference ``chunks.go:69`` ``Chunks``."""

    def __init__(
        self,
        deployment_id: int,
        snapshot_dir_fn: Callable[[int, int], str],
        message_handler: Callable[[MessageBatch], None],
        source_address: str = "",
        on_received: Optional[Callable[[int, int, int, int], None]] = None,
    ):
        self.deployment_id = deployment_id
        self.snapshot_dir_fn = snapshot_dir_fn
        self.message_handler = message_handler
        self.source_address = source_address
        self.on_received = on_received
        self._mu = threading.Lock()
        self._tracked: Dict[str, _Track] = {}
        self._tick = 0

    @staticmethod
    def key(c: Chunk) -> str:
        return f"{c.cluster_id}:{c.node_id}:{c.from_}"

    def add_chunk(self, c: Chunk) -> bool:
        """Reference ``chunks.go:103`` ``AddChunk``; returns False to poison
        the connection."""
        if c.deployment_id != self.deployment_id:
            return False
        with self._mu:
            return self._add_locked(c)

    def _add_locked(self, c: Chunk) -> bool:
        k = self.key(c)
        t = self._tracked.get(k)
        if c.chunk_id == 0:
            if t is not None:
                self._drop(k)
            if len(self._tracked) >= Soft.max_concurrent_streaming_snapshots:
                plog.warning("too many concurrent inbound snapshots")
                return False
            t = self._start_track(c)
            if t is None:
                return False
            self._tracked[k] = t
        elif t is None:
            plog.warning("ignored out-of-band chunk %d for %s", c.chunk_id, k)
            return False
        elif c.chunk_id != t.next_chunk_id:
            plog.warning(
                "unexpected chunk %d (want %d) for %s",
                c.chunk_id,
                t.next_chunk_id,
                k,
            )
            self._drop(k)
            return False
        try:
            self._save_chunk(t, c)
        except OSError as e:
            plog.error("failed to save chunk for %s: %s", k, e)
            self._drop(k)
            return False
        t.next_chunk_id = c.chunk_id + 1
        t.tick = self._tick
        if c.is_last_chunk():
            try:
                msg = self._finalize(t, c)
            except (OSError, FileExistsError) as e:
                plog.error("failed to finalize snapshot for %s: %s", k, e)
                self._drop(k)
                return False
            del self._tracked[k]
            if self.on_received is not None:
                self.on_received(c.cluster_id, c.node_id, c.index, c.from_)
            self.message_handler(
                MessageBatch(
                    requests=[msg],
                    deployment_id=self.deployment_id,
                    source_address=self.source_address,
                )
            )
        return True

    def _start_track(self, c: Chunk) -> Optional[_Track]:
        root = self.snapshot_dir_fn(c.cluster_id, c.node_id)
        if not root:
            plog.error("no snapshot dir for %d:%d", c.cluster_id, c.node_id)
            return None
        os.makedirs(root, exist_ok=True)
        env = SSEnv(root, c.index, c.from_, SSMode.RECEIVING)
        env.remove_tmp_dir()
        env.create_tmp_dir()
        return _Track(first_chunk=c, env=env, tick=self._tick)

    def _open_file(self, t: _Track, name: str):
        if t.file is not None:
            t.file.close()
        t.file_path = os.path.join(t.env.get_tmp_dir(), os.path.basename(name))
        t.file = open(t.file_path, "wb")

    def _save_chunk(self, t: _Track, c: Chunk) -> None:
        if c.file_chunk_id == 0:
            if c.has_file_info:
                # finishing previous file, starting an external one
                t.files.append(
                    SnapshotFile(
                        filepath=os.path.join(
                            t.env.get_tmp_dir(),
                            os.path.basename(c.file_info.filepath),
                        ),
                        file_size=c.file_info.file_size,
                        file_id=c.file_info.file_id,
                        metadata=c.file_info.metadata,
                    )
                )
                self._open_file(t, c.file_info.filepath)
            else:
                self._open_file(t, c.filepath)
        assert t.file is not None
        t.file.write(c.data)
        if c.is_last_file_chunk():
            t.file.flush()
            os.fsync(t.file.fileno())
            t.file.close()
            t.file = None

    def _finalize(self, t: _Track, last: Chunk) -> Message:
        if t.file is not None:
            # streamed transfers don't frame per-file boundaries; close on
            # the sentinel-marked last chunk
            t.file.flush()
            os.fsync(t.file.fileno())
            t.file.close()
            t.file = None
        first = t.first_chunk
        final_dir = t.env.get_final_dir()
        main_path = os.path.join(final_dir, os.path.basename(first.filepath))
        files = [
            SnapshotFile(
                filepath=os.path.join(final_dir, os.path.basename(f.filepath)),
                file_size=f.file_size,
                file_id=f.file_id,
                metadata=f.metadata,
            )
            for f in t.files
        ]
        ss = Snapshot(
            filepath=main_path,
            file_size=first.file_size,
            index=first.index,
            term=first.term,
            membership=first.membership,
            files=files,
            cluster_id=first.cluster_id,
            on_disk_index=first.on_disk_index,
            witness=first.witness,
        )
        t.env.save_ss_metadata(ss)
        try:
            t.env.finalize_snapshot()
        except FileExistsError:
            # the same snapshot was already received and promoted (an
            # earlier transfer's install message may have been lost); the
            # image on disk is identical, so delivering the install message
            # again is the idempotent repair — raft rejects it if stale
            t.env.remove_tmp_dir()
        del last
        # m.term stays 0: chunk.term is the snapshot point's ENTRY term and
        # must not be stamped on the message — the receiver's raft would
        # drop it as an old-term message (reference toMessage
        # chunks.go:375-407 builds the message without a term)
        return Message(
            type=MessageType.INSTALL_SNAPSHOT,
            to=first.node_id,
            from_=first.from_,
            cluster_id=first.cluster_id,
            snapshot=ss,
        )

    def _drop(self, k: str) -> None:
        t = self._tracked.pop(k, None)
        if t is not None:
            if t.file is not None:
                t.file.close()
            t.env.remove_tmp_dir()

    def tick(self) -> None:
        """GC stale transfers (reference ``chunks.go`` tick-based timeout)."""
        with self._mu:
            self._tick += 1
            stale = [
                k
                for k, t in self._tracked.items()
                if self._tick - t.tick > Soft.snapshot_chunk_timeout_tick
            ]
            for k in stale:
                plog.warning("inbound snapshot %s timed out", k)
                self._drop(k)

    def close(self) -> None:
        with self._mu:
            for k in list(self._tracked):
                self._drop(k)
