"""Per-transfer snapshot streaming job + Sink.

Reference: ``internal/transport/job.go:43-248`` — each outbound snapshot
stream gets its own job with a dedicated snapshot connection and a bounded
chunk queue; the ``Sink`` is handed to the on-disk state machine's save
path (via the RSM ChunkWriter) so the image streams straight onto the wire
without ever being materialized as a local file.
"""
from __future__ import annotations

import queue
import threading
from typing import Optional

from ..logger import get_logger
from ..wire import Chunk, POISON_CHUNK_COUNT

plog = get_logger("transport")

STREAMING_CHAN_LENGTH = 16


class Sink:
    """Reference ``job.go:43`` ``Sink``: receive(chunk) -> accepted."""

    def __init__(self, job: "StreamJob"):
        self._j = job

    def receive(self, chunk: Chunk) -> bool:
        return self._j.add_chunk(chunk)

    def stop(self) -> None:
        self._j.add_chunk(Chunk(chunk_count=POISON_CHUNK_COUNT))

    @property
    def cluster_id(self) -> int:
        return self._j.cluster_id

    @property
    def to_node_id(self) -> int:
        return self._j.node_id


class StreamJob:
    """One streaming transfer: owns the connection + the sender thread."""

    def __init__(
        self,
        rpc,
        addr: str,
        cluster_id: int,
        node_id: int,
        on_done,  # Callable[[int, int, bool], None] (cid, nid, failed)
        bucket=None,  # optional bandwidth TokenBucket
    ):
        self.rpc = rpc
        self.addr = addr
        self.cluster_id = cluster_id
        self.node_id = node_id
        self._on_done = on_done
        self._bucket = bucket
        self._q: "queue.Queue[Chunk]" = queue.Queue(
            maxsize=STREAMING_CHAN_LENGTH
        )
        self._failed = threading.Event()
        self._done = threading.Event()
        self._thread = threading.Thread(
            target=self._main, name=f"stream-job-{addr}", daemon=True
        )
        self._thread.start()

    def add_chunk(self, chunk: Chunk) -> bool:
        """Producer side (ChunkWriter via Sink).  False once the job has
        failed — the writer aborts the stream.  The poison chunk (abort)
        is always accepted: it only flips the failure flag, so a full
        queue or an already-failed job cannot block the abort."""
        if chunk.chunk_count == POISON_CHUNK_COUNT:
            self._failed.set()
            return True
        if self._failed.is_set() or self._done.is_set():
            return False
        try:
            self._q.put(chunk, timeout=30.0)
            return True
        except queue.Full:
            self._failed.set()
            return False

    def _main(self) -> None:
        failed = False
        conn = None
        sent_any = False
        try:
            conn = self.rpc.get_snapshot_connection(self.addr)
            while True:
                try:
                    c = self._q.get(timeout=1.0)
                except queue.Empty:
                    if self._failed.is_set():
                        failed = True
                        break
                    continue
                if self._failed.is_set():
                    failed = True
                    break
                if self._bucket is not None:
                    self._bucket.take(
                        c.chunk_size or len(c.data), stop=self._failed
                    )
                    if self._failed.is_set():
                        failed = True
                        break
                conn.send_chunk(c)
                sent_any = True
                if c.is_last_chunk():
                    break
        except Exception as e:  # noqa: BLE001 — connection/stream failure
            plog.warning("stream job to %s failed: %s", self.addr, e)
            failed = True
            self._failed.set()
        finally:
            if conn is not None:
                try:
                    conn.close()
                except Exception:
                    pass
            self._done.set()
        self._on_done(self.cluster_id, self.node_id, failed or not sent_any)

    def join(self, timeout: float = 10.0) -> None:
        self._thread.join(timeout=timeout)
