"""Per-peer RTT latency classes: cross-domain link injection (ISSUE 10).

The hierarchical/cross-domain tier (ROADMAP item 4, CD-Raft / "Fast Raft
for Hierarchical Consensus" in PAPERS.md) needs groups that span simulated
high-RTT domains.  This module is the injection surface, in the
``monkey.py`` router-hook style: nothing below mutates production behavior
unless a harness installs an injector.

Model: every transport address belongs to a **domain**; links between
domains carry a configurable one-way delay (``classes`` maps class names
to seconds; intra-domain traffic is free).  An explicit per-pair override
supports asymmetric paths.

Two hook points, covering both wire modules with one mechanism:

- ``Transport.latency`` (transport.py): the per-remote sender thread
  sleeps the link's one-way delay before each batch send.  Because each
  remote has its OWN queue+thread, the sleep delays that link only, and
  messages arriving during the sleep coalesce into the same batch — the
  link gains latency, not a bandwidth collapse.  This covers the TCP and
  the in-proc chan wire identically (chan delivery runs on the same
  sender thread).
- ``ChanRouter.set_delay_hook`` (chan.py): the direct-router variant for
  harnesses that bypass ``Transport`` (mirrors ``set_drop_hook``).

Wire it with :func:`dragonboat_tpu.monkey.set_latency`.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

#: built-in one-way delay classes (seconds); override/extend per injector
DEFAULT_CLASSES: Dict[str, float] = {
    "local": 0.0,        # same host / same rack
    "near": 0.0002,      # same datacenter
    "metro": 0.002,      # same metro region
    "far": 0.02,         # cross-region (40ms RTT)
    "wan": 0.04,         # cross-continent (80ms RTT)
}


class LatencyInjector:
    """Address → domain assignment plus inter-domain one-way delays."""

    def __init__(self, classes: Optional[Dict[str, float]] = None):
        self._mu = threading.Lock()
        self.classes = dict(DEFAULT_CLASSES)
        if classes:
            self.classes.update(classes)
        self._domain: Dict[str, str] = {}
        self._link: Dict[frozenset, float] = {}
        self._pair: Dict[Tuple[str, str], float] = {}

    def assign(self, addr: str, domain: str) -> "LatencyInjector":
        """Place a transport address in a domain (chainable)."""
        with self._mu:
            self._domain[addr] = domain
        return self

    def link(self, dom_a: str, dom_b: str, cls) -> "LatencyInjector":
        """Set the symmetric one-way delay between two domains; ``cls``
        is a class name (``"far"``) or a plain seconds float."""
        d = self.classes[cls] if isinstance(cls, str) else float(cls)
        with self._mu:
            self._link[frozenset((dom_a, dom_b))] = d
        return self

    def set_pair(self, src: str, dst: str, seconds: float) -> "LatencyInjector":
        """Asymmetric per-address override (takes precedence)."""
        with self._mu:
            self._pair[(src, dst)] = float(seconds)
        return self

    def delay(self, src: str, dst: str) -> float:
        """One-way delay for a batch from ``src`` to ``dst`` (seconds)."""
        with self._mu:
            d = self._pair.get((src, dst))
            if d is not None:
                return d
            da, db = self._domain.get(src), self._domain.get(dst)
            if da is None or db is None or da == db:
                return 0.0
            return self._link.get(frozenset((da, db)), 0.0)

    # ---- introspection (ISSUE 14 satellite) ----

    def domain_of(self, addr: str) -> Optional[str]:
        """The domain an address was assigned to (None when unassigned)
        — the replication-attribution plane labels peer rows with this
        instead of bare node ids (``ReplAttr.class_of``)."""
        with self._mu:
            return self._domain.get(addr)

    def peer_class(self, src: str, dst: str) -> Optional[str]:
        """Effective latency class of the ``src``→``dst`` link, as seen
        from ``src`` (ISSUE 18 bugfix): attribution used to label peers
        by static domain only, so a near peer behind a ``set_pair``
        asymmetric override still classified "near" while its acks
        crawled over an injected slow link — closer/laggard rows lied.
        When either direction carries a pair override, classify the
        worse measured one-way delay through :meth:`class_name` instead;
        otherwise fall back to the static domain label."""
        with self._mu:
            has_override = (src, dst) in self._pair or (dst, src) in self._pair
        if has_override:
            worst = max(self.delay(src, dst), self.delay(dst, src))
            cls = self.class_name(worst)
            if cls is not None:
                return cls
        return self.domain_of(dst)

    def class_name(self, seconds: float) -> Optional[str]:
        """The latency-class name whose one-way delay matches (nearest;
        None when no class is within 20%)."""
        best = None
        with self._mu:
            for name, d in self.classes.items():
                err = abs(d - seconds)
                if best is None or err < best[0]:
                    best = (err, name, d)
        if best is None:
            return None
        err, name, d = best
        if seconds == 0.0:
            return name if d == 0.0 else None
        return name if err <= 0.2 * max(seconds, 1e-9) else None

    def health_snapshot(self) -> dict:
        """``health_snapshot()``-style introspection (the plane
        accessors' contract, obs/health.py): the full domain map so
        attribution rows and ``run_crossdomain`` can label peers by
        latency class instead of bare node ids."""
        with self._mu:
            links = {
                "|".join(sorted(k)): {
                    "one_way_s": v,
                    "cls": None,
                }
                for k, v in self._link.items()
            }
            out = {
                "classes": dict(self.classes),
                "domains": dict(self._domain),
                "links": links,
                "pair_overrides": {
                    f"{s}->{d}": v for (s, d), v in self._pair.items()
                },
            }
        for lk in out["links"].values():
            lk["cls"] = self.class_name(lk["one_way_s"])
        return out


def crossdomain(
    near_addrs, far_addrs, one_way="far", classes=None
) -> LatencyInjector:
    """Two-domain convenience builder: ``near_addrs`` in domain A,
    ``far_addrs`` in domain B, ``one_way`` delay (class name or seconds)
    between them — the asymmetric-RTT shape the cross-domain bench rung
    drives."""
    inj = LatencyInjector(classes=classes)
    for a in near_addrs:
        inj.assign(a, "A")
    for a in far_addrs:
        inj.assign(a, "B")
    inj.link("A", "B", one_way)
    return inj
