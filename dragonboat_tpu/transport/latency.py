"""Per-peer RTT latency classes: cross-domain link injection (ISSUE 10).

The hierarchical/cross-domain tier (ROADMAP item 4, CD-Raft / "Fast Raft
for Hierarchical Consensus" in PAPERS.md) needs groups that span simulated
high-RTT domains.  This module is the injection surface, in the
``monkey.py`` router-hook style: nothing below mutates production behavior
unless a harness installs an injector.

Model: every transport address belongs to a **domain**; links between
domains carry a configurable one-way delay (``classes`` maps class names
to seconds; intra-domain traffic is free).  An explicit per-pair override
supports asymmetric paths.

Two hook points, covering both wire modules with one mechanism:

- ``Transport.latency`` (transport.py): the per-remote sender thread
  sleeps the link's one-way delay before each batch send.  Because each
  remote has its OWN queue+thread, the sleep delays that link only, and
  messages arriving during the sleep coalesce into the same batch — the
  link gains latency, not a bandwidth collapse.  This covers the TCP and
  the in-proc chan wire identically (chan delivery runs on the same
  sender thread).
- ``ChanRouter.set_delay_hook`` (chan.py): the direct-router variant for
  harnesses that bypass ``Transport`` (mirrors ``set_drop_hook``).

Wire it with :func:`dragonboat_tpu.monkey.set_latency`.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

#: built-in one-way delay classes (seconds); override/extend per injector
DEFAULT_CLASSES: Dict[str, float] = {
    "local": 0.0,        # same host / same rack
    "near": 0.0002,      # same datacenter
    "metro": 0.002,      # same metro region
    "far": 0.02,         # cross-region (40ms RTT)
    "wan": 0.04,         # cross-continent (80ms RTT)
}


class LatencyInjector:
    """Address → domain assignment plus inter-domain one-way delays."""

    def __init__(self, classes: Optional[Dict[str, float]] = None):
        self._mu = threading.Lock()
        self.classes = dict(DEFAULT_CLASSES)
        if classes:
            self.classes.update(classes)
        self._domain: Dict[str, str] = {}
        self._link: Dict[frozenset, float] = {}
        self._pair: Dict[Tuple[str, str], float] = {}

    def assign(self, addr: str, domain: str) -> "LatencyInjector":
        """Place a transport address in a domain (chainable)."""
        with self._mu:
            self._domain[addr] = domain
        return self

    def link(self, dom_a: str, dom_b: str, cls) -> "LatencyInjector":
        """Set the symmetric one-way delay between two domains; ``cls``
        is a class name (``"far"``) or a plain seconds float."""
        d = self.classes[cls] if isinstance(cls, str) else float(cls)
        with self._mu:
            self._link[frozenset((dom_a, dom_b))] = d
        return self

    def set_pair(self, src: str, dst: str, seconds: float) -> "LatencyInjector":
        """Asymmetric per-address override (takes precedence)."""
        with self._mu:
            self._pair[(src, dst)] = float(seconds)
        return self

    def delay(self, src: str, dst: str) -> float:
        """One-way delay for a batch from ``src`` to ``dst`` (seconds)."""
        with self._mu:
            d = self._pair.get((src, dst))
            if d is not None:
                return d
            da, db = self._domain.get(src), self._domain.get(dst)
            if da is None or db is None or da == db:
                return 0.0
            return self._link.get(frozenset((da, db)), 0.0)


def crossdomain(
    near_addrs, far_addrs, one_way="far", classes=None
) -> LatencyInjector:
    """Two-domain convenience builder: ``near_addrs`` in domain A,
    ``far_addrs`` in domain B, ``one_way`` delay (class name or seconds)
    between them — the asymmetric-RTT shape the cross-domain bench rung
    drives."""
    inj = LatencyInjector(classes=classes)
    for a in near_addrs:
        inj.assign(a, "A")
    for a in far_addrs:
        inj.assign(a, "B")
    inj.link("A", "B", one_way)
    return inj
