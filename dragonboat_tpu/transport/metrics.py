"""Transport metrics counters.

Reference: ``internal/transport/metrics.go:21`` ``transportMetrics`` — the
same counter family, written into the shared Prometheus-text
MetricsRegistry (``dragonboat_tpu.events``) so ``write_health_metrics``
and the health plane's live ``/metrics`` endpoint expose them alongside
the per-raft-node metrics.

ISSUE 14 satellite: every family is **described** (``# HELP``) and
**zero-registered** at construction — a scrape distinguishes "transport
idle" (families at zero) from "metrics wired elsewhere" (families
absent), and the exposition's HELP-before-TYPE invariant holds for the
``dragonboat_transport_*`` families from the first scrape (round-trip
tested in tests/test_events.py).  The original reference set (messages,
snapshots, drops) grows batch/byte counters for both directions and the
snapshot chunk counters the chunked send plane was not reporting.
"""
from __future__ import annotations

from typing import Optional

from ..events import DEFAULT_REGISTRY, MetricsRegistry

_T = "dragonboat_transport_"

#: ``# HELP`` text per family (the obs/instruments.py discipline)
_HELP = {
    _T + "message_sent": "raft messages handed to remote connections",
    _T + "message_dropped": "raft messages dropped at a full send queue",
    _T + "message_received": "raft messages accepted from remote hosts",
    _T + "message_receive_dropped": "inbound raft messages dropped "
    "(deployment-id mismatch or injected partition)",
    _T + "message_connection_failed": "per-remote sender connections "
    "that failed (dial error, send error, breaker trip)",
    _T + "snapshot_sent": "snapshot transfers completed to remote hosts",
    _T + "snapshot_dropped": "snapshot sends dropped before transfer",
    _T + "snapshot_received": "snapshot transfers completed from remote "
    "hosts",
    _T + "snapshot_connection_failed": "snapshot transfer connections "
    "that failed",
    _T + "batch_sent_total": "message batches handed to remote "
    "connections (messages coalesce per batch)",
    _T + "batch_received_total": "message batches accepted from remote "
    "hosts",
    _T + "bytes_sent_total": "approximate payload bytes handed to "
    "remote connections (entry-size accounting, the batching cap's "
    "own measure)",
    _T + "bytes_received_total": "approximate payload bytes accepted "
    "from remote hosts",
    _T + "snapshot_chunk_sent_total": "snapshot chunks written to "
    "transfer connections",
    _T + "snapshot_chunk_received_total": "snapshot chunks accepted "
    "from remote hosts",
}


class TransportMetrics:
    """Reference ``newTransportMetrics`` counter set plus the ISSUE 14
    batch/byte/chunk extensions."""

    NAMES = tuple(_HELP)

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry or DEFAULT_REGISTRY
        r = self.registry
        for name, text in _HELP.items():
            r.describe(name, text)
            r.counter_add(name, 0)

    def _add(self, name: str, n: int = 1) -> None:
        self.registry.counter_add(name, n)

    def message_sent(self, n: int = 1) -> None:
        self._add(_T + "message_sent", n)

    def message_dropped(self, n: int = 1) -> None:
        self._add(_T + "message_dropped", n)

    def message_received(self, n: int = 1) -> None:
        self._add(_T + "message_received", n)

    def message_receive_dropped(self, n: int = 1) -> None:
        self._add(_T + "message_receive_dropped", n)

    def message_connection_failed(self, n: int = 1) -> None:
        self._add(_T + "message_connection_failed", n)

    def snapshot_sent(self, n: int = 1) -> None:
        self._add(_T + "snapshot_sent", n)

    def snapshot_dropped(self, n: int = 1) -> None:
        self._add(_T + "snapshot_dropped", n)

    def snapshot_received(self, n: int = 1) -> None:
        self._add(_T + "snapshot_received", n)

    def snapshot_connection_failed(self, n: int = 1) -> None:
        self._add(_T + "snapshot_connection_failed", n)

    # ---- ISSUE 14 satellite: batches / bytes / snapshot chunks ----

    def batch_sent(self, nbytes: int) -> None:
        self._add(_T + "batch_sent_total", 1)
        if nbytes:
            self._add(_T + "bytes_sent_total", nbytes)

    def batch_received(self, nbytes: int) -> None:
        self._add(_T + "batch_received_total", 1)
        if nbytes:
            self._add(_T + "bytes_received_total", nbytes)

    def snapshot_chunks_sent(self, n: int) -> None:
        if n:
            self._add(_T + "snapshot_chunk_sent_total", n)

    def snapshot_chunks_received(self, n: int = 1) -> None:
        self._add(_T + "snapshot_chunk_received_total", n)

    def value(self, name: str) -> float:
        return self.registry.counter_value(name)
