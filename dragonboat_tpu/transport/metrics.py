"""Transport metrics counters.

Reference: ``internal/transport/metrics.go:21`` ``transportMetrics`` — the
same counter family, written into the shared Prometheus-text
MetricsRegistry (``dragonboat_tpu.events``) so ``write_health_metrics``
exposes them alongside the per-raft-node metrics.
"""
from __future__ import annotations

from typing import Optional

from ..events import DEFAULT_REGISTRY, MetricsRegistry


class TransportMetrics:
    """Reference ``newTransportMetrics`` counter set."""

    NAMES = (
        "dragonboat_transport_message_sent",
        "dragonboat_transport_message_dropped",
        "dragonboat_transport_message_received",
        "dragonboat_transport_message_receive_dropped",
        "dragonboat_transport_message_connection_failed",
        "dragonboat_transport_snapshot_sent",
        "dragonboat_transport_snapshot_dropped",
        "dragonboat_transport_snapshot_received",
        "dragonboat_transport_snapshot_connection_failed",
    )

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry or DEFAULT_REGISTRY

    def _add(self, name: str, n: int = 1) -> None:
        self.registry.counter_add(name, n)

    def message_sent(self, n: int = 1) -> None:
        self._add("dragonboat_transport_message_sent", n)

    def message_dropped(self, n: int = 1) -> None:
        self._add("dragonboat_transport_message_dropped", n)

    def message_received(self, n: int = 1) -> None:
        self._add("dragonboat_transport_message_received", n)

    def message_receive_dropped(self, n: int = 1) -> None:
        self._add("dragonboat_transport_message_receive_dropped", n)

    def message_connection_failed(self, n: int = 1) -> None:
        self._add("dragonboat_transport_message_connection_failed", n)

    def snapshot_sent(self, n: int = 1) -> None:
        self._add("dragonboat_transport_snapshot_sent", n)

    def snapshot_dropped(self, n: int = 1) -> None:
        self._add("dragonboat_transport_snapshot_dropped", n)

    def snapshot_received(self, n: int = 1) -> None:
        self._add("dragonboat_transport_snapshot_received", n)

    def snapshot_connection_failed(self, n: int = 1) -> None:
        self._add("dragonboat_transport_snapshot_connection_failed", n)

    def value(self, name: str) -> float:
        return self.registry.counter_value(name)
