"""Node address registry.

Reference: ``internal/transport/nodes.go`` — ``(clusterID, nodeID) → address``
resolution for the send path, plus reverse lookup for unreachable events.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple


class Registry:
    """Reference ``nodes.go:48`` ``Nodes``."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._addr: Dict[Tuple[int, int], str] = {}
        # addresses learned from incoming batches' source_address — lets a
        # joining node reply before membership is applied (reference
        # nodes.go AddRemoteAddress)
        self._remote: Dict[Tuple[int, int], str] = {}

    def add(self, cluster_id: int, node_id: int, address: str) -> None:
        with self._mu:
            existing = self._addr.get((cluster_id, node_id))
            if existing is not None and existing != address:
                raise ValueError(
                    f"inconsistent address for ({cluster_id},{node_id}): "
                    f"{existing} vs {address}"
                )
            self._addr[(cluster_id, node_id)] = address

    def add_remote(self, cluster_id: int, node_id: int, address: str) -> None:
        # skip the write (and the lock) when the entry is unchanged — this
        # runs once per received message batch on the hot path
        key = (cluster_id, node_id)
        if self._remote.get(key) == address:
            return
        with self._mu:
            self._remote[key] = address

    def remove(self, cluster_id: int, node_id: int) -> None:
        with self._mu:
            self._addr.pop((cluster_id, node_id), None)

    def remove_cluster(self, cluster_id: int) -> None:
        with self._mu:
            for k in [k for k in self._addr if k[0] == cluster_id]:
                del self._addr[k]

    def resolve(self, cluster_id: int, node_id: int) -> Optional[str]:
        # lock-free read: dict get is atomic under the GIL and both maps are
        # only ever mutated to newer values; the send path calls this once
        # per message so a mutex here is measurable contention
        addr = self._addr.get((cluster_id, node_id))
        if addr is None:
            addr = self._remote.get((cluster_id, node_id))
        return addr

    def reverse_resolve(self, address: str) -> List[Tuple[int, int]]:
        with self._mu:
            return [k for k, v in self._addr.items() if v == address]
