"""Pluggable wire layer contracts.

Reference: ``raftio/rpc.go:90`` — ``IRaftRPC`` with separate message and
snapshot-chunk planes; implementations here are the in-memory chan transport
(:mod:`dragonboat_tpu.transport.chan`) and framed TCP
(:mod:`dragonboat_tpu.transport.tcp`).
"""
from __future__ import annotations

import abc
from typing import Callable, List

from ..wire import Chunk, MessageBatch

# receive-side callbacks (reference raftio/rpc.go RequestHandler/ChunkHandler)
RequestHandler = Callable[[MessageBatch], None]
ChunkHandler = Callable[[Chunk], bool]


class TransportError(Exception):
    pass


class IConnection(abc.ABC):
    """One established outbound message channel (reference
    ``raftio/rpc.go`` ``IConnection``)."""

    @abc.abstractmethod
    def send_message_batch(self, batch: MessageBatch) -> None: ...

    @abc.abstractmethod
    def close(self) -> None: ...


class ISnapshotConnection(abc.ABC):
    """One outbound snapshot chunk stream (reference
    ``raftio/rpc.go`` ``ISnapshotConnection``)."""

    @abc.abstractmethod
    def send_chunk(self, chunk: Chunk) -> None: ...

    @abc.abstractmethod
    def close(self) -> None: ...


class IRaftRPC(abc.ABC):
    """Reference ``raftio/rpc.go:90`` ``IRaftRPC``."""

    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def start(self) -> None: ...

    @abc.abstractmethod
    def stop(self) -> None: ...

    @abc.abstractmethod
    def get_connection(self, target: str) -> IConnection: ...

    @abc.abstractmethod
    def get_snapshot_connection(self, target: str) -> ISnapshotConnection: ...
