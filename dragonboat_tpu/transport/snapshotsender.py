"""Snapshot chunk splitting and sending.

Reference: ``internal/transport/snapshot.go:186-292`` (``splitSnapshotMessage``)
and ``internal/transport/job.go`` — a snapshot transfer is its own connection
streaming 2MB chunks: main image file first, then each external file, with
``file_chunk_id/count`` framing and ``has_file_info`` on each external file's
first chunk.
"""
from __future__ import annotations

import threading
from typing import List

from ..wire import Chunk, Message, SnapshotFile
from .rpc import ISnapshotConnection


def _file_chunks(
    path: str, size: int, chunk_size: int
) -> List[tuple]:
    """(offset, length) pairs covering ``size`` bytes."""
    if size == 0:
        return [(0, 0)]
    out = []
    off = 0
    while off < size:
        out.append((off, min(chunk_size, size - off)))
        off += chunk_size
    del path
    return out


def split_snapshot_message(
    m: Message, deployment_id: int, chunk_size: int
) -> List[Chunk]:
    """Plan the chunk sequence; data is loaded lazily at send time."""
    ss = m.snapshot
    files = [SnapshotFile(filepath=ss.filepath, file_size=ss.file_size)]
    files.extend(ss.files)
    chunks: List[Chunk] = []
    total = sum(
        len(_file_chunks(f.filepath, f.file_size, chunk_size)) for f in files
    )
    chunk_id = 0
    for fidx, f in enumerate(files):
        plan = _file_chunks(f.filepath, f.file_size, chunk_size)
        for fcid, (off, ln) in enumerate(plan):
            c = Chunk(
                cluster_id=m.cluster_id,
                node_id=m.to,
                from_=m.from_,
                chunk_id=chunk_id,
                chunk_size=ln,
                chunk_count=total,
                index=ss.index,
                # the ENTRY term of the snapshot point, NOT the raft term of
                # the carrying message (reference snapshot.go:211 uses
                # msg.Snapshot.Term): the receiver rebuilds the Snapshot from
                # chunk fields and a raft-term stamp here corrupts the log's
                # term(ss.index) after restore — probes from the real leader
                # then mismatch forever and replication livelocks
                term=ss.term,
                membership=ss.membership,
                filepath=f.filepath,
                file_size=f.file_size,
                deployment_id=deployment_id,
                file_chunk_id=fcid,
                file_chunk_count=len(plan),
                on_disk_index=ss.on_disk_index,
                witness=ss.witness,
            )
            if fidx > 0 and fcid == 0:
                c.has_file_info = True
                c.file_info = SnapshotFile(
                    filepath=f.filepath,
                    file_size=f.file_size,
                    file_id=f.file_id,
                    metadata=f.metadata,
                )
            c.data = (off, ln)  # placeholder filled by the sender
            chunks.append(c)
            chunk_id += 1
    return chunks


def load_chunk_data(c: Chunk) -> Chunk:
    off, ln = c.data
    if ln == 0:
        c.data = b""
        return c
    with open(c.filepath, "rb") as f:
        f.seek(off)
        data = f.read(ln)
    if len(data) != ln:
        raise RuntimeError(f"short read on {c.filepath}")
    c.data = data
    return c


def send_snapshot_chunks(
    conn: ISnapshotConnection,
    chunks: List[Chunk],
    stopped: threading.Event,
    bucket=None,
) -> None:
    for c in chunks:
        if stopped.is_set():
            raise RuntimeError("transport stopped")
        loaded = load_chunk_data(c)
        if bucket is not None:
            # snapshot bandwidth cap (reference tcp.go:430-437)
            bucket.take(loaded.chunk_size or len(loaded.data), stop=stopped)
        if stopped.is_set():
            raise RuntimeError("transport stopped")
        conn.send_chunk(loaded)
