"""Framed TCP wire module with optional mutual TLS.

Reference: ``internal/transport/tcp.go`` — magic ``0xAE7D``, fixed header
{method, payload size, payload crc32, header crc32}, method 100 for raft
message batches and 200 for snapshot chunks, mutual-TLS via config
(``tcp.go:582-595``), poison-drain on connection close (``tcp.go:122-147``).

Frame layout here: ``magic(2) method(2) size(8) payload_crc(4) header_crc(4)``
followed by the payload bytes (codec-encoded MessageBatch or Chunk).
"""
from __future__ import annotations

import socket
import ssl
import struct
import threading
import zlib
from typing import Optional

from ..logger import get_logger
from ..wire.codec import (
    decode_chunk,
    decode_message_batch,
    encode_chunk,
    encode_message_batch,
)
from .rpc import (
    ChunkHandler,
    IConnection,
    IRaftRPC,
    ISnapshotConnection,
    RequestHandler,
    TransportError,
)

plog = get_logger("transport")

MAGIC = 0xAE7D
RAFT_METHOD = 100
SNAPSHOT_METHOD = 200
POISON_METHOD = 999
_HDR = struct.Struct(">HHQII")
MAX_PAYLOAD = 1 << 30


def _send_frame(sock, method: int, payload: bytes) -> None:
    pcrc = zlib.crc32(payload)
    hdr_wo_crc = struct.pack(">HHQI", MAGIC, method, len(payload), pcrc)
    hcrc = zlib.crc32(hdr_wo_crc)
    sock.sendall(hdr_wo_crc + struct.pack(">I", hcrc) + payload)


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        d = sock.recv(n - len(buf))
        if not d:
            raise ConnectionError("peer closed")
        buf += d
    return bytes(buf)


def _recv_frame(sock):
    hdr = _recv_exact(sock, _HDR.size)
    magic, method, size, pcrc, hcrc = _HDR.unpack(hdr)
    if magic != MAGIC:
        raise TransportError("bad magic")
    if zlib.crc32(hdr[:-4]) != hcrc:
        raise TransportError("corrupted frame header")
    if size > MAX_PAYLOAD:
        raise TransportError("oversized frame")
    payload = _recv_exact(sock, size)
    if zlib.crc32(payload) != pcrc:
        raise TransportError("corrupted frame payload")
    return method, payload


class TCPConnection(IConnection):
    """Reference ``tcp.go:351`` ``TCPConnection``."""

    def __init__(self, sock):
        self.sock = sock

    def send_message_batch(self, batch) -> None:
        _send_frame(self.sock, RAFT_METHOD, encode_message_batch(batch))

    def close(self) -> None:
        try:
            _send_frame(self.sock, POISON_METHOD, b"")
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class TCPSnapshotConnection(ISnapshotConnection):
    """Reference ``tcp.go:396``."""

    def __init__(self, sock):
        self.sock = sock

    def send_chunk(self, chunk) -> None:
        _send_frame(self.sock, SNAPSHOT_METHOD, encode_chunk(chunk))

    def close(self) -> None:
        try:
            _send_frame(self.sock, POISON_METHOD, b"")
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class TCPTransport(IRaftRPC):
    """Reference ``tcp.go:409`` ``TCPTransport``."""

    def __init__(
        self,
        source_address: str,
        request_handler: RequestHandler,
        chunk_handler: ChunkHandler,
        listen_address: str = "",
        mutual_tls: bool = False,
        ca_file: str = "",
        cert_file: str = "",
        key_file: str = "",
        connect_timeout: float = 5.0,
    ):
        self.source_address = source_address
        self.request_handler = request_handler
        self.chunk_handler = chunk_handler
        self.listen_address = listen_address or source_address
        self.mutual_tls = mutual_tls
        self.ca_file, self.cert_file, self.key_file = ca_file, cert_file, key_file
        self.connect_timeout = connect_timeout
        self._listener: Optional[socket.socket] = None
        self._stopped = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        # optional raw-payload hook (the native replication fast lane,
        # fastlane.py): called with each RAFT_METHOD payload BEFORE
        # decoding; returns the leftover payload for the normal path, or
        # None when fully consumed natively
        self.raw_handler = None
        # optional stream hook (preferred when set): an object with
        # stream_open() -> handle, stream_feed(handle, bytes) ->
        # [(method, payload)...], stream_close(handle).  The recv loop
        # reads large chunks and the native core reassembles/consumes
        # frames without per-frame Python overhead.
        self.raw_stream = None
        # optional fd takeover hook (fastest): takeover_fd(fd) -> bool.
        # Plain (non-TLS) accepted connections are handed to a native
        # reader thread entirely — the GIL never touches the inbound
        # fast plane; leftover frames surface via the fast-lane pump.
        self.takeover_fd = None

    def name(self) -> str:
        return "tcp-transport"

    # ---- TLS ----

    def _server_ctx(self) -> Optional[ssl.SSLContext]:
        if not self.mutual_tls:
            return None
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_file, self.key_file)
        ctx.load_verify_locations(self.ca_file)
        ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx

    def _client_ctx(self) -> Optional[ssl.SSLContext]:
        if not self.mutual_tls:
            return None
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_cert_chain(self.cert_file, self.key_file)
        ctx.load_verify_locations(self.ca_file)
        ctx.check_hostname = False
        return ctx

    # ---- server side ----

    def start(self) -> None:
        host, _, port = self.listen_address.rpartition(":")
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((host, int(port)))
        ls.listen(128)
        ls.settimeout(0.5)
        self._listener = ls
        self._accept_thread = threading.Thread(
            target=self._accept_main, name=f"tcp-accept-{self.listen_address}",
            daemon=True,
        )
        self._accept_thread.start()

    def _accept_main(self) -> None:
        ctx = self._server_ctx()
        assert self._listener is not None
        while not self._stopped.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            if ctx is not None:
                try:
                    conn = ctx.wrap_socket(conn, server_side=True)
                except ssl.SSLError as e:
                    plog.warning("TLS handshake failed: %s", e)
                    conn.close()
                    continue
            elif self.takeover_fd is not None:
                # native reader owns the fd from here (fast lane)
                import os as _os

                fd = conn.detach()
                try:
                    if not self.takeover_fd(fd):
                        _os.close(fd)
                except Exception:
                    plog.exception("fd takeover failed")
                    try:
                        _os.close(fd)
                    except OSError:
                        pass
                continue
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()

    def _serve_conn(self, conn) -> None:
        """Reference ``tcp.go:515`` ``serveConn``."""
        stream = self.raw_stream
        if stream is not None:
            return self._serve_conn_stream(conn, stream)
        try:
            conn.settimeout(60.0)
            while not self._stopped.is_set():
                method, payload = _recv_frame(conn)
                if method == POISON_METHOD:
                    return
                if method == RAFT_METHOD:
                    raw = self.raw_handler
                    if raw is not None:
                        payload = raw(payload)
                        if payload is None:
                            continue
                    self.request_handler(decode_message_batch(payload))
                elif method == SNAPSHOT_METHOD:
                    if not self.chunk_handler(decode_chunk(payload)):
                        return
                else:
                    plog.warning("unknown method %d", method)
                    return
        except (ConnectionError, TransportError, socket.timeout, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_conn_stream(self, conn, stream) -> None:
        """Bulk-recv variant (native fast lane): large reads, frame
        reassembly + CRC + fast-path consumption in C; only leftovers
        surface here."""
        h = stream.stream_open()
        try:
            conn.settimeout(60.0)
            while not self._stopped.is_set():
                data = conn.recv(1 << 20)
                if not data:
                    return
                for method, payload in stream.stream_feed(h, data):
                    if method == POISON_METHOD:
                        return
                    if method == RAFT_METHOD:
                        self.request_handler(decode_message_batch(payload))
                    elif method == SNAPSHOT_METHOD:
                        if not self.chunk_handler(decode_chunk(payload)):
                            return
                    else:  # 0xFFFF framing/CRC error or unknown method
                        plog.warning("stream error/unknown method %d", method)
                        return
        except (ConnectionError, TransportError, socket.timeout, OSError):
            pass
        finally:
            stream.stream_close(h)
            try:
                conn.close()
            except OSError:
                pass

    # ---- client side ----

    def _dial(self, target: str):
        host, _, port = target.rpartition(":")
        sock = socket.create_connection(
            (host, int(port)), timeout=self.connect_timeout
        )
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        ctx = self._client_ctx()
        if ctx is not None:
            sock = ctx.wrap_socket(sock, server_hostname=host)
        return sock

    def get_connection(self, target: str) -> TCPConnection:
        try:
            return TCPConnection(self._dial(target))
        except OSError as e:
            raise TransportError(f"dial {target}: {e}") from e

    def get_snapshot_connection(self, target: str) -> TCPSnapshotConnection:
        try:
            return TCPSnapshotConnection(self._dial(target))
        except OSError as e:
            raise TransportError(f"dial {target}: {e}") from e

    def stop(self) -> None:
        self._stopped.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2)
